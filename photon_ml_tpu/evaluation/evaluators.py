"""Evaluators: AUC / RMSE / log-loss / Poisson-loss / squared-loss.

Reference counterparts: ``Evaluator``, ``AreaUnderROCCurveEvaluator``,
``RMSEEvaluator``, ``LogisticLossEvaluator``, ``PoissonLossEvaluator``,
``SquaredLossEvaluator``, ``EvaluatorType``, ``EvaluationResults``
(photon-api ``com.linkedin.photon.ml.evaluation`` [expected paths, mount
unavailable — see SURVEY.md]).  Sharded per-entity variants
(``MultiEvaluator``) live in ``photon_ml_tpu.evaluation.sharded``.

All metrics are pure jittable functions of ``(scores, labels, weights,
mask)`` flat arrays.  AUC — a ranking metric the reference computes with
Spark's BinaryClassificationMetrics over sorted score buckets — is an
O(n log n) sort + cumulative-sum program here: ranks via ``argsort``,
tie groups averaged by segment mean, no host round-trip, so validation
runs on-device between coordinate-descent iterations.
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp

Array = jax.Array


class EvaluatorType(str, enum.Enum):
    AUC = "AUC"
    RMSE = "RMSE"
    LOGISTIC_LOSS = "LOGISTIC_LOSS"
    POISSON_LOSS = "POISSON_LOSS"
    SQUARED_LOSS = "SQUARED_LOSS"

    @property
    def larger_is_better(self) -> bool:
        return self == EvaluatorType.AUC


def _masked_weights(weights: Array | None, mask: Array | None, n: int) -> Array:
    w = jnp.ones((n,)) if weights is None else weights
    if mask is not None:
        w = w * mask
    return w


def auc(
    scores: Array,
    labels: Array,
    weights: Array | None = None,
    mask: Array | None = None,
) -> Array:
    """Weighted, tie-aware area under the ROC curve.

    AUC = P(score⁺ > score⁻) + ½·P(score⁺ = score⁻) over weighted
    positive/negative pairs.  Computed by sorting once and giving every
    example its tie-averaged weighted rank:

        AUC = (Σ_{i∈pos} w_i·r̄_i − W⁺·(W⁺+1)/2-analog) / (W⁺·W⁻)

    generalized to weights via cumulative weight sums; masked examples get
    weight 0 and sort wherever they like without affecting the result.
    """
    n = scores.shape[0]
    w = _masked_weights(weights, mask, n)
    y = labels

    order = jnp.argsort(scores)
    s_sorted = scores[order]
    w_sorted = w[order]
    wy_sorted = (w * y)[order]

    # Tie-group ids: positions where the sorted score strictly increases.
    new_group = jnp.concatenate(
        [jnp.ones((1,), jnp.int32),
         (s_sorted[1:] != s_sorted[:-1]).astype(jnp.int32)]
    )
    gid = jnp.cumsum(new_group) - 1  # [n] group index per sorted position

    # Weighted "rank" of each tie group = (weight below group) + ½·(weight
    # within group): the average position of the group's mass.
    cw = jnp.cumsum(w_sorted)
    group_total = jax.ops.segment_sum(w_sorted, gid, num_segments=n)
    group_end = jax.ops.segment_max(cw, gid, num_segments=n)
    group_rank = group_end - 0.5 * group_total  # [n] (per group id)

    # Σ over positives of their group rank (weighted).
    pos_rank_sum = jnp.sum(wy_sorted * group_rank[gid])

    w_pos = jnp.sum(w * y)
    w_neg = jnp.sum(w * (1.0 - y))
    # pos-vs-pos pairs contribute w_pos²/2 (each positive's rank counts
    # positive mass below it + half its own); subtract to keep pos-vs-neg.
    numer = pos_rank_sum - 0.5 * w_pos * w_pos
    denom = w_pos * w_neg
    return jnp.where(denom > 0.0, numer / denom, 0.5)


def rmse(
    scores: Array,
    labels: Array,
    weights: Array | None = None,
    mask: Array | None = None,
) -> Array:
    n = scores.shape[0]
    w = _masked_weights(weights, mask, n)
    se = w * (scores - labels) ** 2
    return jnp.sqrt(jnp.sum(se) / jnp.maximum(jnp.sum(w), 1e-30))


def logistic_loss(
    scores: Array,
    labels: Array,
    weights: Array | None = None,
    mask: Array | None = None,
) -> Array:
    """Mean weighted logistic loss of raw *margins* (not probabilities),
    matching the reference's LogisticLossEvaluator."""
    n = scores.shape[0]
    w = _masked_weights(weights, mask, n)
    z, y = scores, labels
    ll = jnp.maximum(z, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(z))) - y * z
    return jnp.sum(w * ll) / jnp.maximum(jnp.sum(w), 1e-30)


def poisson_loss(
    scores: Array,
    labels: Array,
    weights: Array | None = None,
    mask: Array | None = None,
) -> Array:
    n = scores.shape[0]
    w = _masked_weights(weights, mask, n)
    z, y = scores, labels
    pl = jnp.exp(jnp.minimum(z, 30.0)) - y * z
    return jnp.sum(w * pl) / jnp.maximum(jnp.sum(w), 1e-30)


def squared_loss(
    scores: Array,
    labels: Array,
    weights: Array | None = None,
    mask: Array | None = None,
) -> Array:
    n = scores.shape[0]
    w = _masked_weights(weights, mask, n)
    return jnp.sum(w * 0.5 * (scores - labels) ** 2) / jnp.maximum(
        jnp.sum(w), 1e-30
    )


_EVALUATOR_FNS = {
    EvaluatorType.AUC: auc,
    EvaluatorType.RMSE: rmse,
    EvaluatorType.LOGISTIC_LOSS: logistic_loss,
    EvaluatorType.POISSON_LOSS: poisson_loss,
    EvaluatorType.SQUARED_LOSS: squared_loss,
}


def evaluate(
    evaluator: EvaluatorType,
    scores: Array,
    labels: Array,
    weights: Array | None = None,
    mask: Array | None = None,
) -> Array:
    """Dispatch an ``EvaluatorType`` (reference ``Evaluator.evaluate``).

    ``scores`` are raw margins for AUC/loss evaluators and mean-space
    predictions for RMSE/squared loss, matching the reference's
    per-evaluator score conventions.
    """
    return _EVALUATOR_FNS[evaluator](scores, labels, weights, mask)


def better_than(evaluator: EvaluatorType, a: Array, b: Array) -> Array:
    """Model-selection ordering (reference ``Evaluator.betterThan``)."""
    return a > b if evaluator.larger_is_better else a < b
