"""Sharded (per-entity) evaluators: metric per entity, averaged.

Reference counterparts: ``MultiEvaluator``,
``AreaUnderROCCurveMultiEvaluator``, ``PrecisionAtKMultiEvaluator``
(photon-api ``com.linkedin.photon.ml.evaluation`` [expected paths, mount
unavailable — see SURVEY.md §2.6]) — used for per-query/per-user ranking
quality in GAME validation.

The reference groups scores by entity id with a shuffle and computes the
metric per group on executors.  Here grouping reuses the GAME entity
ETL (``group_by_entity`` + padded blocks) and the metric is **vmapped
over entity rows** — per-entity AUCs for tens of thousands of entities
are one device program, no shuffle, no host loop.

Entities that cannot support the metric (single-class for AUC, empty
for precision@k) are excluded from the average, matching the reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.evaluation.evaluators import auc
from photon_ml_tpu.game.dataset import group_by_entity, scatter_to_blocks

Array = jax.Array


def _to_blocks(values: np.ndarray, grouping) -> list[jnp.ndarray]:
    return [jnp.asarray(b) for b in scatter_to_blocks(grouping, values)]


def sharded_auc(
    scores: np.ndarray,
    labels: np.ndarray,
    entity_ids: np.ndarray,
    weights: np.ndarray | None = None,
) -> float:
    """Mean per-entity AUC over entities with both classes present."""
    grouping = group_by_entity(np.asarray(entity_ids))
    scores = np.asarray(scores, np.float32)
    labels = np.asarray(labels, np.float32)
    weights = (np.ones_like(scores) if weights is None
               else np.asarray(weights, np.float32))

    total, count = 0.0, 0
    for s_blk, y_blk, w_blk, m_blk in zip(
        _to_blocks(scores, grouping),
        _to_blocks(labels, grouping),
        _to_blocks(weights, grouping),
        _to_blocks(np.ones_like(scores), grouping),
    ):
        per_entity = jax.vmap(auc)(s_blk, y_blk, w_blk, m_blk)
        wm = np.asarray(w_blk * m_blk)
        yv = np.asarray(y_blk)
        has_pos = ((yv > 0.5) & (wm > 0)).any(axis=1)
        has_neg = ((yv < 0.5) & (wm > 0)).any(axis=1)
        valid = has_pos & has_neg
        total += float(np.asarray(per_entity)[valid].sum())
        count += int(valid.sum())
    return total / count if count else 0.5


def sharded_precision_at_k(
    scores: np.ndarray,
    labels: np.ndarray,
    entity_ids: np.ndarray,
    k: int,
) -> float:
    """Mean per-entity precision@k (reference ``PrecisionAtKMultiEvaluator``).

    Per entity: fraction of positives among its k highest-scored
    examples (fewer than k examples → use all of them).
    """
    grouping = group_by_entity(np.asarray(entity_ids))
    scores = np.asarray(scores, np.float32)
    labels = np.asarray(labels, np.float32)

    def per_entity_prec(s_row, y_row, m_row):
        cap = s_row.shape[0]
        kk = min(k, cap)
        s_masked = jnp.where(m_row > 0, s_row, -jnp.inf)
        _, top_idx = jax.lax.top_k(s_masked, kk)
        picked_mask = m_row[top_idx]                # 0 for padding picks
        picked_labels = y_row[top_idx] * picked_mask
        denom = jnp.minimum(jnp.sum(m_row), float(kk))
        return jnp.sum(picked_labels) / jnp.maximum(denom, 1.0)

    total, count = 0.0, 0
    ones = np.ones_like(scores)
    for s_blk, y_blk, m_blk in zip(
        _to_blocks(scores, grouping),
        _to_blocks(labels, grouping),
        _to_blocks(ones, grouping),
    ):
        vals = jax.vmap(per_entity_prec)(s_blk, y_blk, m_blk)
        nonempty = np.asarray(m_blk).sum(axis=1) > 0
        total += float(np.asarray(vals)[nonempty].sum())
        count += int(nonempty.sum())
    return total / count if count else 0.0
