"""Evaluators: AUC/RMSE/loss metrics + sharded per-entity variants.

Reference: photon-api ``com.linkedin.photon.ml.evaluation`` (SURVEY.md
§2.6 — expected paths, mount unavailable).
"""

from photon_ml_tpu.evaluation.sharded import (
    sharded_auc,
    sharded_precision_at_k,
)
from photon_ml_tpu.evaluation.evaluators import (
    EvaluatorType,
    auc,
    better_than,
    evaluate,
    logistic_loss,
    poisson_loss,
    rmse,
    squared_loss,
)
from photon_ml_tpu.evaluation.streaming import (
    StreamingAUC,
    StreamingMeanLoss,
    StreamingRMSE,
    make_streaming_evaluator,
)

__all__ = [
    "EvaluatorType",
    "StreamingAUC",
    "StreamingMeanLoss",
    "StreamingRMSE",
    "auc",
    "better_than",
    "evaluate",
    "logistic_loss",
    "make_streaming_evaluator",
    "poisson_loss",
    "rmse",
    "squared_loss",
    "sharded_auc",
    "sharded_precision_at_k",
]
