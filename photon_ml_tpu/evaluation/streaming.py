"""Streaming (chunk-accumulated) evaluators for the scoring pipeline.

Reference counterpart: the reference evaluates scored data with Spark's
``BinaryClassificationMetrics`` over an RDD — a distributed fold that
never holds the dataset on one machine (SURVEY.md §2.6).  The one-shot
evaluators in ``evaluation.evaluators`` are the opposite: pure device
programs over resident ``[n]`` arrays, which is exactly right between
coordinate-descent iterations but wrong for the streaming scoring
pipeline (ISSUE 4), where margins exist one chunk at a time and the
whole point is that nothing ``[n]``-sized stays live.

Every metric here is a fold over chunks:

- **Mean losses / RMSE** (logistic, Poisson, squared, RMSE): exact —
  the metric is ``Σ w·f(score, y) / Σ w`` and both sums accumulate in
  float64 across chunks (the one-shot evaluators reduce in float32 on
  device, so agreement is to float tolerance, not bit-exact).
- **AUC**: rank-based, so it cannot be folded exactly in O(1) state.
  ``StreamingAUC`` buffers raw chunks while the running row count is
  below ``exact_below`` (the exactness fallback: small datasets get the
  one-shot answer exactly); past the threshold it collapses the buffer
  into a fixed-bin weighted histogram of ``sigmoid(score)`` — a
  monotone squash, so ranks (hence AUC) are preserved up to binning —
  and accumulates per-bin positive/negative weight from then on.  The
  histogram AUC gives every within-bin pair the tie credit ½, so the
  error is bounded by half the probability mass of same-bin
  cross-class pairs: ≤ 1/(2·n_bins) of the pair mass per bin in the
  worst case (documented tolerance ~1e-3 at the default 8192 bins;
  exact when scores are distinct across bins).
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.evaluation.evaluators import EvaluatorType

# Histogram resolution / exactness threshold defaults (StreamingAUC).
AUC_BINS = 8192
AUC_EXACT_BELOW = 1_000_000


def _as64(a) -> np.ndarray:
    return np.asarray(a, np.float64)


class StreamingMeanLoss:
    """Σ w·loss(score, y) / Σ w accumulated in float64 over chunks.

    ``kind``: "logistic" | "poisson" | "squared" — the same formulas as
    the one-shot evaluators, over raw margins."""

    def __init__(self, kind: str):
        self.kind = kind
        self._num = 0.0
        self._den = 0.0

    def update(self, scores, labels, weights) -> None:
        z, y, w = _as64(scores), _as64(labels), _as64(weights)
        if self.kind == "logistic":
            ll = np.maximum(z, 0.0) + np.log1p(np.exp(-np.abs(z))) - y * z
        elif self.kind == "poisson":
            ll = np.exp(np.minimum(z, 30.0)) - y * z
        elif self.kind == "squared":
            ll = 0.5 * (z - y) ** 2
        else:
            raise ValueError(f"unknown loss kind {self.kind!r}")
        self._num += float(np.sum(w * ll))
        self._den += float(np.sum(w))

    def result(self) -> float:
        return self._num / max(self._den, 1e-30)


class StreamingRMSE:
    """sqrt(Σ w·(score−y)² / Σ w) over chunks (float64)."""

    def __init__(self):
        self._num = 0.0
        self._den = 0.0

    def update(self, scores, labels, weights) -> None:
        s, y, w = _as64(scores), _as64(labels), _as64(weights)
        self._num += float(np.sum(w * (s - y) ** 2))
        self._den += float(np.sum(w))

    def result(self) -> float:
        return float(np.sqrt(self._num / max(self._den, 1e-30)))


class StreamingAUC:
    """Weighted AUC over chunks: exact below ``exact_below`` rows,
    fixed-bin histogram (tie-aware, monotone-squashed scores) above.

    State: either the raw buffered chunks (exact regime) or two
    ``[n_bins]`` float64 weight histograms — never both past the
    threshold, so memory is O(min(n, exact_below) + n_bins)."""

    def __init__(self, n_bins: int = AUC_BINS,
                 exact_below: int = AUC_EXACT_BELOW):
        self.n_bins = int(n_bins)
        self.exact_below = int(exact_below)
        self._rows = 0
        self._buf: list | None = []          # exact regime
        self._w_pos: np.ndarray | None = None
        self._w_neg: np.ndarray | None = None
        self.exact = True

    def _bin(self, scores: np.ndarray) -> np.ndarray:
        # Monotone squash to (0, 1): AUC is rank-based, so any strictly
        # increasing map preserves it; sigmoid bounds the bin domain
        # without needing a min/max pre-pass over the stream.
        p = 1.0 / (1.0 + np.exp(-_as64(scores)))
        return np.minimum((p * self.n_bins).astype(np.int64),
                          self.n_bins - 1)

    def _to_histogram(self) -> None:
        self._w_pos = np.zeros(self.n_bins, np.float64)
        self._w_neg = np.zeros(self.n_bins, np.float64)
        self.exact = False
        buf, self._buf = self._buf, None
        for s, y, w in buf:
            self._accumulate(s, y, w)

    def _accumulate(self, scores, labels, weights) -> None:
        b = self._bin(scores)
        y, w = _as64(labels), _as64(weights)
        self._w_pos += np.bincount(b, weights=w * y,
                                   minlength=self.n_bins)
        self._w_neg += np.bincount(b, weights=w * (1.0 - y),
                                   minlength=self.n_bins)

    def update(self, scores, labels, weights) -> None:
        scores = np.asarray(scores, np.float32)
        labels = np.asarray(labels, np.float32)
        weights = np.asarray(weights, np.float32)
        self._rows += len(scores)
        if self._buf is not None and self._rows <= self.exact_below:
            self._buf.append((scores.copy(), labels.copy(),
                              weights.copy()))
            return
        if self._buf is not None:
            self._buf.append((scores, labels, weights))
            self._to_histogram()
        else:
            self._accumulate(scores, labels, weights)

    def result(self) -> float:
        if self._buf is not None:
            # Exact regime: the ONE-SHOT evaluator over the buffer — the
            # fallback is literally the resident answer.
            import jax.numpy as jnp

            from photon_ml_tpu.evaluation.evaluators import auc

            if not self._buf:
                return 0.5
            s = np.concatenate([b[0] for b in self._buf])
            y = np.concatenate([b[1] for b in self._buf])
            w = np.concatenate([b[2] for b in self._buf])
            return float(auc(jnp.asarray(s), jnp.asarray(y),
                             jnp.asarray(w)))
        w_pos, w_neg = self._w_pos, self._w_neg
        total_pos = float(w_pos.sum())
        total_neg = float(w_neg.sum())
        if total_pos <= 0.0 or total_neg <= 0.0:
            return 0.5
        # Per bin: every positive in the bin outranks the negative mass
        # below it and ties (½ credit) the negative mass within it.
        neg_below = np.concatenate(([0.0], np.cumsum(w_neg)[:-1]))
        num = float(np.sum(w_pos * (neg_below + 0.5 * w_neg)))
        return num / (total_pos * total_neg)


class _EvaluatorAdapter:
    """Binds one ``EvaluatorType`` to its streaming metric and its score
    convention (margins vs mean-space predictions — the same
    per-evaluator choice the one-shot driver path makes)."""

    def __init__(self, ev: EvaluatorType, metric, use_predictions: bool):
        self.type = ev
        self.metric = metric
        self.use_predictions = use_predictions

    def update(self, margins, predictions, labels, weights) -> None:
        scores = predictions if self.use_predictions else margins
        self.metric.update(scores, labels, weights)

    def result(self) -> float:
        return float(self.metric.result())


def make_streaming_evaluator(
    ev: EvaluatorType,
    auc_bins: int = AUC_BINS,
    auc_exact_below: int = AUC_EXACT_BELOW,
) -> _EvaluatorAdapter:
    """Streaming counterpart of ``evaluation.evaluate`` dispatch."""
    if ev == EvaluatorType.AUC:
        return _EvaluatorAdapter(
            ev, StreamingAUC(auc_bins, auc_exact_below), False)
    if ev == EvaluatorType.RMSE:
        return _EvaluatorAdapter(ev, StreamingRMSE(), True)
    if ev == EvaluatorType.LOGISTIC_LOSS:
        return _EvaluatorAdapter(ev, StreamingMeanLoss("logistic"), False)
    if ev == EvaluatorType.POISSON_LOSS:
        return _EvaluatorAdapter(ev, StreamingMeanLoss("poisson"), False)
    if ev == EvaluatorType.SQUARED_LOSS:
        return _EvaluatorAdapter(ev, StreamingMeanLoss("squared"), True)
    raise ValueError(f"no streaming evaluator for {ev!r}")
