"""Streaming score sinks: chunked writers for scoring output.

Reference counterpart: ``ScoringResultAvro`` output written by Spark
executors as partitioned container files — no single process ever
builds the whole output in memory (SURVEY.md §2.8).  Before ISSUE 4
the scoring driver did exactly that: ``np.savez`` of full ``[n]``
arrays, and an Avro writer that built one Python dict PER ROW and fed
a generic per-record encoder.  Both sinks here consume finished chunks
as the streaming pipeline produces them, so output memory is bounded
by one chunk:

- ``NpzScoreSink`` — the ``.npz`` contract (``scores`` /
  ``predictions`` / ``labels``), written incrementally: each member is
  a preallocated ``.npy`` memmap (chunk writes are file-backed page
  cache, not anonymous RSS), zipped STORED into the final ``.npz`` at
  close (streamed copy; ``np.load`` reads it like any savez output,
  and the chunk store's mmap loader can map it back).
- ``AvroScoreSink`` — an Avro object container with ONE BLOCK PER
  CHUNK: records are encoded by a schema-specific batch encoder
  (zigzag longs + little-endian doubles straight from the arrays)
  instead of per-row dict construction + recursive generic dispatch.
  The output is byte-compatible with ``SCORING_RESULT_SCHEMA`` (the
  round-trip test reads it back through the generic reader).
"""

from __future__ import annotations

import io
import os
import struct
import zipfile

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.io.avro import MAGIC, SYNC_SIZE, Schema, write_long
from photon_ml_tpu.io.avro_schemas import SCORING_RESULT_SCHEMA


class NpzScoreSink:
    """Incremental ``.npz`` writer for the scoring driver's output
    contract.  ``write(lo, hi, ...)`` may arrive in any order (ranges
    must tile [0, n)); ``close()`` assembles the zip."""

    _MEMBERS = ("scores", "predictions", "labels")

    def __init__(self, path: str, n: int):
        self.path = path
        self.n = int(n)
        self._tmp = {}
        self._mm = {}
        self._failed = False
        for name in self._MEMBERS:
            tmp = path + f".{name}.tmp.npy"
            self._mm[name] = np.lib.format.open_memmap(
                tmp, mode="w+", dtype=np.float32, shape=(self.n,))
            self._tmp[name] = tmp
        self._written = 0

    def write(self, lo: int, hi: int, margins, predictions,
              labels, ids: dict | None = None) -> None:
        del ids   # the npz contract carries no entity-id columns
        try:
            self._mm["scores"][lo:hi] = np.asarray(margins, np.float32)
            self._mm["predictions"][lo:hi] = np.asarray(predictions,
                                                        np.float32)
            self._mm["labels"][lo:hi] = np.asarray(labels, np.float32)
        except BaseException:
            # A failed chunk write (shape mismatch, I/O error on a
            # member) poisons the sink: close() must refuse to
            # assemble the zip instead of publishing rows this chunk
            # never landed (ISSUE 9 satellite — no torn container).
            self._failed = True
            raise
        self._written += hi - lo
        telemetry.count("sink.rows_written", hi - lo)

    def close(self) -> None:
        if self._failed or self._written != self.n:
            self._cleanup()
            raise ValueError(
                f"npz sink: {self._written} of {self.n} rows written"
                + (" (a chunk write failed)" if self._failed else ""))
        for mm in self._mm.values():
            mm.flush()
        self._mm.clear()
        tmp_zip = self.path + ".tmp"
        try:
            with zipfile.ZipFile(tmp_zip, "w", zipfile.ZIP_STORED) as zf:
                for name in self._MEMBERS:
                    zf.write(self._tmp[name], arcname=name + ".npy")
            os.replace(tmp_zip, self.path)
        finally:
            try:
                os.remove(tmp_zip)
            except OSError:  # photon-lint: disable=swallowed-exception (tmp already os.replace'd or never created)
                pass
            self._cleanup()

    def _cleanup(self) -> None:
        self._mm.clear()
        for tmp in self._tmp.values():
            try:
                os.remove(tmp)
            except OSError:  # photon-lint: disable=swallowed-exception (idempotent cleanup; member tmp may already be gone)
                pass

    def abort(self) -> None:
        self._cleanup()


def _encode_scoring_block(uids, predictions, labels, ids: dict) -> bytes:
    """One Avro block's worth of ``ScoringResultAvro`` records, encoded
    by direct struct packing in schema field order (uid long,
    predictionScore double, label union[null,double], ids map<string>).

    ``ids``: entity-key → [rows] integer array (stringified per the
    driver's convention).  The per-row work is this loop and nothing
    else — no dicts, no recursive schema dispatch."""
    out = io.BytesIO()
    w = out.write
    preds = np.asarray(predictions, np.float64)
    labs = None if labels is None else np.asarray(labels, np.float64)
    id_items = [(k.encode("utf-8"), np.asarray(v)) for k, v in ids.items()]
    pack_d = struct.Struct("<d").pack
    for j, uid in enumerate(np.asarray(uids, np.int64)):
        write_long(out, int(uid))
        w(pack_d(preds[j]))
        if labs is None:
            w(b"\x00")                       # union branch 0: null
        else:
            w(b"\x02")                       # union branch 1 (zigzag 1)
            w(pack_d(labs[j]))
        if id_items:
            write_long(out, len(id_items))
            for key, col in id_items:
                write_long(out, len(key))
                w(key)
                sval = str(int(col[j])).encode("utf-8")
                write_long(out, len(sval))
                w(sval)
        w(b"\x00")                           # map terminator
    return out.getvalue()


class AvroScoreSink:
    """Avro object-container sink: one container block per chunk.

    The container header/sync framing matches ``io.avro
    .write_container``; blocks may arrive in any order (each is
    self-delimited), deflate-compressed by default like the reference's
    output files."""

    def __init__(self, path: str, ids_keys: tuple = (),
                 codec: str = "deflate",
                 schema: Schema = SCORING_RESULT_SCHEMA):
        import zlib

        if codec not in ("null", "deflate"):
            raise ValueError(f"unsupported codec {codec!r}")
        self._zlib = zlib
        self.path = path
        self.codec = codec
        self.ids_keys = tuple(ids_keys)
        self._sync = os.urandom(SYNC_SIZE)
        self._tmp = path + ".tmp"
        self._f = open(self._tmp, "wb")
        self._f.write(MAGIC)
        from photon_ml_tpu.io.avro import _META_SCHEMA, _encode

        _encode(_META_SCHEMA, _META_SCHEMA.root,
                {"avro.schema": schema.to_json().encode(),
                 "avro.codec": codec.encode()}, self._f)
        self._f.write(self._sync)
        self.records_written = 0
        self.blocks_written = 0
        self._failed = False

    def write(self, lo: int, hi: int, margins, predictions,
              labels, ids: dict | None = None) -> None:
        del margins   # the Avro record carries mean-space scores only
        count = hi - lo
        if count <= 0:
            return
        ids = ids or {}
        if self.ids_keys:
            # The declared keys fix the emitted id-map contents and
            # order (deterministic blocks regardless of caller dict
            # ordering).
            ids = {k: ids[k] for k in self.ids_keys}
        payload = _encode_scoring_block(
            np.arange(lo, hi, dtype=np.int64), predictions, labels, ids)
        if self.codec == "deflate":
            c = self._zlib.compressobj(wbits=-15)
            payload = c.compress(payload) + c.flush()
        block_start = self._f.tell()
        try:
            write_long(self._f, count)
            write_long(self._f, len(payload))
            self._f.write(payload)
            self._f.write(self._sync)
        except BaseException:
            # Torn-block rollback (ISSUE 9 satellite): truncate back to
            # the last block boundary so the container stays valid, and
            # poison the sink — close() refuses to publish short data.
            self._failed = True
            try:
                self._f.seek(block_start)
                self._f.truncate()
            except (OSError, ValueError):  # photon-lint: disable=swallowed-exception (rollback is best-effort on a failing file; the sink is poisoned and close() aborts)
                pass
            raise
        self.records_written += count
        self.blocks_written += 1
        telemetry.count("sink.rows_written", count)
        telemetry.count("sink.avro_blocks")
        telemetry.count("sink.bytes_written", len(payload))

    def close(self) -> None:
        if self._failed:
            self.abort()
            raise ValueError(
                "avro sink: a block write failed upstream; the partial "
                f"container {self._tmp!r} was removed instead of being "
                "published short")
        self._f.close()
        os.replace(self._tmp, self.path)

    def abort(self) -> None:
        self._f.close()
        try:
            os.remove(self._tmp)
        except OSError:  # photon-lint: disable=swallowed-exception (idempotent abort; tmp may already be gone)
            pass
