"""GameModel persistence: save/load a model directory.

Reference counterpart: ``ModelProcessingUtils`` writing per-coordinate
``BayesianLinearModelAvro`` files to HDFS (photon-api
``com.linkedin.photon.ml.io`` [expected paths, mount unavailable — see
SURVEY.md §2.4/§3.1]).

Layout: ``<dir>/metadata.json`` (task, coordinate kinds/shards) +
``<dir>/<coordinate>.npz`` (fixed: means/variances; random: per-bucket
coefficient blocks + the entity-level grouping index + projection
feature ids).  npz is the fast native checkpoint format (zero-copy
arrays, exact round trip of the padded block layout); for interchange
with reference pipelines, ``export_model_avro`` additionally writes
per-coordinate ``BayesianLinearModelAvro`` container files keyed by
(name, term) via the stdlib Avro codec in ``io.avro``.

Checkpoint manifest (ISSUE 12 satellite): ``save_game_model``
additionally writes ``model_manifest.npz`` — the WHOLE model as ONE
atomically-replaced file encoded with the reliability checkpoint's
state-tree codec (``flatten_tree`` + ``atomic_savez``), so

- the model server and the batch drivers share one loading path
  (``load_game_model`` prefers the manifest when present, falls back
  to the legacy metadata.json layout otherwise), and
- the manifest is the HOT-SWAP unit: one ``os.replace`` makes the new
  model visible, a reader can never observe a torn multi-file write,
  and a corrupt manifest raises cleanly (the server keeps the previous
  good model; see ``serving.server``).
"""

from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.dataset import EntityGrouping
from photon_ml_tpu.game.projector import SubspaceProjection
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import TaskType

# One-file model manifest: the checkpoint state-tree codec's load unit
# and the serving tier's hot-swap unit.
MODEL_MANIFEST_FILE = "model_manifest.npz"
MODEL_MANIFEST_SCHEMA = 1


def model_manifest_path(model_dir: str) -> str:
    return os.path.join(model_dir, MODEL_MANIFEST_FILE)


def _model_tree(model: GameModel, task: TaskType) -> dict:
    """GameModel → checkpoint state tree (flatten_tree-encodable)."""
    coords: dict = {}
    for name, comp in model.models.items():
        if isinstance(comp, FixedEffectModel):
            coords[name] = {
                "kind": "FIXED_EFFECT",
                "feature_shard": comp.feature_shard,
                "intercept": bool(comp.intercept),
                "means": np.asarray(comp.coefficients.means),
                "variances": (
                    None if comp.coefficients.variances is None
                    else np.asarray(comp.coefficients.variances)),
            }
        elif isinstance(comp, RandomEffectModel):
            g = comp.grouping
            coords[name] = {
                "kind": "RANDOM_EFFECT",
                "feature_shard": comp.feature_shard,
                "entity_key": comp.entity_key,
                "global_dim": (comp.projection.global_dim
                               if comp.projection else None),
                "grouping": {
                    "entity_ids": np.asarray(g.entity_ids),
                    "entity_counts": np.asarray(g.entity_counts),
                    "entity_bucket": np.asarray(g.entity_bucket),
                    "entity_slot": np.asarray(g.entity_slot),
                    "capacities": [int(c) for c in g.capacities],
                    "n_entities": [int(c) for c in g.n_entities],
                },
                "blocks": [np.asarray(b)
                           for b in comp.coefficient_blocks],
                "variance_blocks": (
                    None if comp.variance_blocks is None
                    else [np.asarray(b) for b in comp.variance_blocks]),
                "proj_feature_ids": (
                    None if comp.projection is None
                    else [np.asarray(f)
                          for f in comp.projection.feature_ids]),
            }
        else:
            raise TypeError(f"unknown component model {type(comp)}")
    return {"task": task.value, "coordinates": coords}


def _model_from_tree(tree: dict) -> tuple[GameModel, TaskType]:
    task = TaskType(tree["task"])
    models: dict = {}
    for name, c in tree["coordinates"].items():
        if c["kind"] == "FIXED_EFFECT":
            models[name] = FixedEffectModel(
                coefficients=Coefficients(
                    means=jnp.asarray(c["means"]),
                    variances=(None if c["variances"] is None
                               else jnp.asarray(c["variances"]))),
                feature_shard=c["feature_shard"],
                intercept=bool(c["intercept"]),
            )
        elif c["kind"] == "RANDOM_EFFECT":
            g = c["grouping"]
            grouping = EntityGrouping(
                n_examples=0,  # example-level maps are training state
                entity_ids=g["entity_ids"],
                entity_counts=g["entity_counts"],
                entity_bucket=g["entity_bucket"],
                entity_slot=g["entity_slot"],
                capacities=[int(x) for x in g["capacities"]],
                n_entities=[int(x) for x in g["n_entities"]],
                example_bucket=np.empty(0, np.int64),
                example_row=np.empty(0, np.int64),
                example_col=np.empty(0, np.int64),
            )
            projection = None
            if c["proj_feature_ids"] is not None:
                projection = SubspaceProjection(
                    feature_ids=list(c["proj_feature_ids"]),
                    global_dim=int(c["global_dim"]),
                )
            models[name] = RandomEffectModel(
                coefficient_blocks=[jnp.asarray(b)
                                    for b in c["blocks"]],
                grouping=grouping,
                feature_shard=c["feature_shard"],
                variance_blocks=(
                    None if c["variance_blocks"] is None
                    else [jnp.asarray(b)
                          for b in c["variance_blocks"]]),
                projection=projection,
                entity_key=c["entity_key"],
            )
        else:
            raise ValueError(f"unknown coordinate kind {c['kind']!r}")
    return GameModel(models=models), task


def save_model_manifest(model: GameModel, task: TaskType,
                        out_dir: str) -> str:
    """Write the one-file checkpoint manifest (atomic tmp +
    ``os.replace`` — the hot-swap publish primitive).  Returns its
    path."""
    from photon_ml_tpu.cache.plan_cache import atomic_savez
    from photon_ml_tpu.reliability.checkpoint import flatten_tree

    os.makedirs(out_dir, exist_ok=True)
    tree_meta, arrays = flatten_tree(_model_tree(model, task))
    path = model_manifest_path(out_dir)
    atomic_savez(path, {"kind": "game_model",
                        "schema": MODEL_MANIFEST_SCHEMA,
                        "tree": tree_meta}, arrays)
    return path


def load_model_manifest(model_dir: str) -> tuple[GameModel, TaskType]:
    """Load a model from ``<model_dir>/model_manifest.npz``.  Raises on
    a missing/corrupt/mismatched file — the server's swap watcher
    catches and keeps the previous good model."""
    from photon_ml_tpu.reliability.checkpoint import unflatten_tree

    path = model_manifest_path(model_dir)
    with np.load(path, allow_pickle=False) as z:
        if "__meta__" not in z.files:
            raise ValueError(f"model manifest {path}: no __meta__ "
                             "member (not an atomic_savez file)")
        meta = json.loads(bytes(np.asarray(z["__meta__"])).decode())
        arrays = {key: np.asarray(z[key]) for key in z.files
                  if key != "__meta__"}
    if meta.get("kind") != "game_model":
        raise ValueError(f"model manifest {path}: kind "
                         f"{meta.get('kind')!r} != 'game_model'")
    if meta.get("schema") != MODEL_MANIFEST_SCHEMA:
        raise ValueError(f"model manifest {path}: schema "
                         f"{meta.get('schema')!r} != "
                         f"{MODEL_MANIFEST_SCHEMA}")
    return _model_from_tree(unflatten_tree(meta["tree"], arrays))


def save_game_model(model: GameModel, task: TaskType, out_dir: str) -> None:
    os.makedirs(out_dir, exist_ok=True)
    meta = {"task_type": task.value, "coordinates": {}}
    for name, comp in model.models.items():
        path = os.path.join(out_dir, f"{name}.npz")
        if isinstance(comp, FixedEffectModel):
            meta["coordinates"][name] = {
                "kind": "FIXED_EFFECT", "feature_shard": comp.feature_shard,
                "intercept": comp.intercept,
            }
            arrs = {"means": np.asarray(comp.coefficients.means)}
            if comp.coefficients.variances is not None:
                arrs["variances"] = np.asarray(comp.coefficients.variances)
            np.savez(path, **arrs)
        elif isinstance(comp, RandomEffectModel):
            meta["coordinates"][name] = {
                "kind": "RANDOM_EFFECT", "feature_shard": comp.feature_shard,
                "entity_key": comp.entity_key,
                "n_buckets": len(comp.coefficient_blocks),
                "projected": comp.projection is not None,
                "global_dim": (comp.projection.global_dim
                               if comp.projection else None),
            }
            g = comp.grouping
            arrs = {
                "entity_ids": g.entity_ids,
                "entity_counts": g.entity_counts,
                "entity_bucket": g.entity_bucket,
                "entity_slot": g.entity_slot,
                "capacities": np.asarray(g.capacities),
                "n_entities": np.asarray(g.n_entities),
            }
            for b, blk in enumerate(comp.coefficient_blocks):
                arrs[f"block_{b}"] = np.asarray(blk)
            if comp.variance_blocks is not None:
                for b, blk in enumerate(comp.variance_blocks):
                    arrs[f"variance_block_{b}"] = np.asarray(blk)
            if comp.projection is not None:
                for b, fids in enumerate(comp.projection.feature_ids):
                    arrs[f"proj_feature_ids_{b}"] = fids
            np.savez(path, **arrs)
        else:
            raise TypeError(f"unknown component model {type(comp)}")
    with open(os.path.join(out_dir, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=2)
    # The manifest goes LAST: its atomic replace is the publish signal
    # a serving hot-swap watcher polls, and every file it could point a
    # legacy-path reader at already exists by now.
    save_model_manifest(model, task, out_dir)


def load_game_model(model_dir: str) -> tuple[GameModel, TaskType]:
    """Load a model directory: the one-file checkpoint manifest when
    present (the serving/batch shared path), else the legacy
    metadata.json + per-coordinate npz layout."""
    if os.path.exists(model_manifest_path(model_dir)):
        return load_model_manifest(model_dir)
    with open(os.path.join(model_dir, "metadata.json")) as f:
        meta = json.load(f)
    task = TaskType(meta["task_type"])
    models = {}
    for name, info in meta["coordinates"].items():
        data = np.load(os.path.join(model_dir, f"{name}.npz"))
        if info["kind"] == "FIXED_EFFECT":
            models[name] = FixedEffectModel(
                coefficients=Coefficients(
                    means=jnp.asarray(data["means"]),
                    variances=(jnp.asarray(data["variances"])
                               if "variances" in data else None),
                ),
                feature_shard=info["feature_shard"],
                intercept=bool(info.get("intercept", False)),
            )
        else:
            n_buckets = int(info["n_buckets"])
            grouping = EntityGrouping(
                n_examples=0,  # example-level maps are training-run state
                entity_ids=data["entity_ids"],
                entity_counts=data["entity_counts"],
                entity_bucket=data["entity_bucket"],
                entity_slot=data["entity_slot"],
                capacities=[int(c) for c in data["capacities"]],
                n_entities=[int(c) for c in data["n_entities"]],
                example_bucket=np.empty(0, np.int64),
                example_row=np.empty(0, np.int64),
                example_col=np.empty(0, np.int64),
            )
            projection = None
            if info.get("projected"):
                projection = SubspaceProjection(
                    feature_ids=[data[f"proj_feature_ids_{b}"]
                                 for b in range(n_buckets)],
                    global_dim=int(info["global_dim"]),
                )
            variance_blocks = None
            if f"variance_block_0" in data:
                variance_blocks = [
                    jnp.asarray(data[f"variance_block_{b}"])
                    for b in range(n_buckets)
                ]
            models[name] = RandomEffectModel(
                coefficient_blocks=[jnp.asarray(data[f"block_{b}"])
                                    for b in range(n_buckets)],
                grouping=grouping,
                feature_shard=info["feature_shard"],
                variance_blocks=variance_blocks,
                projection=projection,
                entity_key=info.get("entity_key"),
            )
    return GameModel(models=models), task


def export_model_avro(
    model: GameModel,
    task: TaskType,
    feature_maps: dict,
    out_dir: str,
) -> list[str]:
    """Write per-coordinate ``BayesianLinearModelAvro`` container files.

    Reference parity (``ModelProcessingUtils.saveGameModelToHDFS``):
    coefficients are keyed by (name, term) so the file is portable
    across feature-index rebuilds.  Fixed effect → one record; random
    effect → one record per entity (``modelId`` = entity id), in the
    reference's per-entity Bayesian-linear-model layout.

    ``feature_maps``: feature shard → IndexMap (must cover every shard
    the model references; the intercept column the estimator appends is
    emitted as name="(INTERCEPT)").
    """
    from photon_ml_tpu.io.avro_schemas import write_model_avro
    from photon_ml_tpu.io.avro import write_container
    from photon_ml_tpu.io.avro_schemas import bayesian_linear_model_schema

    os.makedirs(out_dir, exist_ok=True)
    written = []

    def keyer(imap, dim):
        def index_to_key(i):
            if i >= len(imap):          # estimator-appended intercept
                return ("(INTERCEPT)", "")
            return imap.feature_at(i)
        return index_to_key

    for name, comp in model.models.items():
        path = os.path.join(out_dir, f"{name}.avro")
        if isinstance(comp, FixedEffectModel):
            imap = feature_maps[comp.feature_shard]
            means = np.asarray(comp.coefficients.means)
            variances = (
                None if comp.coefficients.variances is None
                else np.asarray(comp.coefficients.variances)
            )
            write_model_avro(
                path, name, means, keyer(imap, means.size),
                variances=variances, loss_function=task.value,
            )
        elif isinstance(comp, RandomEffectModel):
            imap = feature_maps[comp.feature_shard]

            def records():
                for eid in np.asarray(comp.grouping.entity_ids):
                    w = comp.global_coefficients_for(int(eid))
                    if w is None:
                        continue
                    idx = np.nonzero(w)[0]
                    k = keyer(imap, w.size)
                    yield {
                        "modelId": str(int(eid)),
                        "modelClass": "",
                        "lossFunction": task.value,
                        "means": [
                            {"name": k(int(i))[0], "term": k(int(i))[1],
                             "value": float(w[i])} for i in idx
                        ],
                        "variances": None,
                    }

            write_container(path, bayesian_linear_model_schema(), records())
        else:
            raise TypeError(f"unknown component model {type(comp)}")
        written.append(path)
    return written
