"""GameModel persistence: save/load a model directory.

Reference counterpart: ``ModelProcessingUtils`` writing per-coordinate
``BayesianLinearModelAvro`` files to HDFS (photon-api
``com.linkedin.photon.ml.io`` [expected paths, mount unavailable — see
SURVEY.md §2.4/§3.1]).

Layout: ``<dir>/metadata.json`` (task, coordinate kinds/shards) +
``<dir>/<coordinate>.npz`` (fixed: means/variances; random: per-bucket
coefficient blocks + the entity-level grouping index + projection
feature ids).  npz is the fast native checkpoint format (zero-copy
arrays, exact round trip of the padded block layout); for interchange
with reference pipelines, ``export_model_avro`` additionally writes
per-coordinate ``BayesianLinearModelAvro`` container files keyed by
(name, term) via the stdlib Avro codec in ``io.avro``.
"""

from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.dataset import EntityGrouping
from photon_ml_tpu.game.projector import SubspaceProjection
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import TaskType


def save_game_model(model: GameModel, task: TaskType, out_dir: str) -> None:
    os.makedirs(out_dir, exist_ok=True)
    meta = {"task_type": task.value, "coordinates": {}}
    for name, comp in model.models.items():
        path = os.path.join(out_dir, f"{name}.npz")
        if isinstance(comp, FixedEffectModel):
            meta["coordinates"][name] = {
                "kind": "FIXED_EFFECT", "feature_shard": comp.feature_shard,
                "intercept": comp.intercept,
            }
            arrs = {"means": np.asarray(comp.coefficients.means)}
            if comp.coefficients.variances is not None:
                arrs["variances"] = np.asarray(comp.coefficients.variances)
            np.savez(path, **arrs)
        elif isinstance(comp, RandomEffectModel):
            meta["coordinates"][name] = {
                "kind": "RANDOM_EFFECT", "feature_shard": comp.feature_shard,
                "entity_key": comp.entity_key,
                "n_buckets": len(comp.coefficient_blocks),
                "projected": comp.projection is not None,
                "global_dim": (comp.projection.global_dim
                               if comp.projection else None),
            }
            g = comp.grouping
            arrs = {
                "entity_ids": g.entity_ids,
                "entity_counts": g.entity_counts,
                "entity_bucket": g.entity_bucket,
                "entity_slot": g.entity_slot,
                "capacities": np.asarray(g.capacities),
                "n_entities": np.asarray(g.n_entities),
            }
            for b, blk in enumerate(comp.coefficient_blocks):
                arrs[f"block_{b}"] = np.asarray(blk)
            if comp.variance_blocks is not None:
                for b, blk in enumerate(comp.variance_blocks):
                    arrs[f"variance_block_{b}"] = np.asarray(blk)
            if comp.projection is not None:
                for b, fids in enumerate(comp.projection.feature_ids):
                    arrs[f"proj_feature_ids_{b}"] = fids
            np.savez(path, **arrs)
        else:
            raise TypeError(f"unknown component model {type(comp)}")
    with open(os.path.join(out_dir, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=2)


def load_game_model(model_dir: str) -> tuple[GameModel, TaskType]:
    with open(os.path.join(model_dir, "metadata.json")) as f:
        meta = json.load(f)
    task = TaskType(meta["task_type"])
    models = {}
    for name, info in meta["coordinates"].items():
        data = np.load(os.path.join(model_dir, f"{name}.npz"))
        if info["kind"] == "FIXED_EFFECT":
            models[name] = FixedEffectModel(
                coefficients=Coefficients(
                    means=jnp.asarray(data["means"]),
                    variances=(jnp.asarray(data["variances"])
                               if "variances" in data else None),
                ),
                feature_shard=info["feature_shard"],
                intercept=bool(info.get("intercept", False)),
            )
        else:
            n_buckets = int(info["n_buckets"])
            grouping = EntityGrouping(
                n_examples=0,  # example-level maps are training-run state
                entity_ids=data["entity_ids"],
                entity_counts=data["entity_counts"],
                entity_bucket=data["entity_bucket"],
                entity_slot=data["entity_slot"],
                capacities=[int(c) for c in data["capacities"]],
                n_entities=[int(c) for c in data["n_entities"]],
                example_bucket=np.empty(0, np.int64),
                example_row=np.empty(0, np.int64),
                example_col=np.empty(0, np.int64),
            )
            projection = None
            if info.get("projected"):
                projection = SubspaceProjection(
                    feature_ids=[data[f"proj_feature_ids_{b}"]
                                 for b in range(n_buckets)],
                    global_dim=int(info["global_dim"]),
                )
            variance_blocks = None
            if f"variance_block_0" in data:
                variance_blocks = [
                    jnp.asarray(data[f"variance_block_{b}"])
                    for b in range(n_buckets)
                ]
            models[name] = RandomEffectModel(
                coefficient_blocks=[jnp.asarray(data[f"block_{b}"])
                                    for b in range(n_buckets)],
                grouping=grouping,
                feature_shard=info["feature_shard"],
                variance_blocks=variance_blocks,
                projection=projection,
                entity_key=info.get("entity_key"),
            )
    return GameModel(models=models), task


def export_model_avro(
    model: GameModel,
    task: TaskType,
    feature_maps: dict,
    out_dir: str,
) -> list[str]:
    """Write per-coordinate ``BayesianLinearModelAvro`` container files.

    Reference parity (``ModelProcessingUtils.saveGameModelToHDFS``):
    coefficients are keyed by (name, term) so the file is portable
    across feature-index rebuilds.  Fixed effect → one record; random
    effect → one record per entity (``modelId`` = entity id), in the
    reference's per-entity Bayesian-linear-model layout.

    ``feature_maps``: feature shard → IndexMap (must cover every shard
    the model references; the intercept column the estimator appends is
    emitted as name="(INTERCEPT)").
    """
    from photon_ml_tpu.io.avro_schemas import write_model_avro
    from photon_ml_tpu.io.avro import write_container
    from photon_ml_tpu.io.avro_schemas import bayesian_linear_model_schema

    os.makedirs(out_dir, exist_ok=True)
    written = []

    def keyer(imap, dim):
        def index_to_key(i):
            if i >= len(imap):          # estimator-appended intercept
                return ("(INTERCEPT)", "")
            return imap.feature_at(i)
        return index_to_key

    for name, comp in model.models.items():
        path = os.path.join(out_dir, f"{name}.avro")
        if isinstance(comp, FixedEffectModel):
            imap = feature_maps[comp.feature_shard]
            means = np.asarray(comp.coefficients.means)
            variances = (
                None if comp.coefficients.variances is None
                else np.asarray(comp.coefficients.variances)
            )
            write_model_avro(
                path, name, means, keyer(imap, means.size),
                variances=variances, loss_function=task.value,
            )
        elif isinstance(comp, RandomEffectModel):
            imap = feature_maps[comp.feature_shard]

            def records():
                for eid in np.asarray(comp.grouping.entity_ids):
                    w = comp.global_coefficients_for(int(eid))
                    if w is None:
                        continue
                    idx = np.nonzero(w)[0]
                    k = keyer(imap, w.size)
                    yield {
                        "modelId": str(int(eid)),
                        "modelClass": "",
                        "lossFunction": task.value,
                        "means": [
                            {"name": k(int(i))[0], "term": k(int(i))[1],
                             "value": float(w[i])} for i in idx
                        ],
                        "variances": None,
                    }

            write_container(path, bayesian_linear_model_schema(), records())
        else:
            raise TypeError(f"unknown component model {type(comp)}")
        written.append(path)
    return written
