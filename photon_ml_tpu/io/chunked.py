"""Chunked (bounded-memory) dataset ingestion.

Reference counterpart: the reference never materializes a dataset on one
host — Spark streams HDFS splits through executors (``AvroDataReader``
per-partition iterators, photon-api ``com.linkedin.photon.ml.io``
[expected paths, mount unavailable — see SURVEY.md]).  A single-host TPU
ETL must instead bound its own peak memory: these readers stream the
file in fixed-size byte windows, canonicalize each window into a compact
``SparseRows`` chunk (CSR arrays, no per-row Python objects), and
assemble with one final concatenation — peak host RSS is
final-dataset-size + one window, never a multiple of the dataset.

The window parser is the same native C++ tokenizer / numpy
canonicalization the whole-file reader uses, so chunked and whole-file
reads are byte-for-byte identical (tested in ``tests/test_data_io.py``).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from photon_ml_tpu.data.sparse_rows import SparseRows


def _iter_byte_windows(path: str, chunk_bytes: int) -> Iterator[bytes]:
    """Yield file contents in windows split at line boundaries."""
    with open(path, "rb") as f:
        carry = b""
        while True:
            block = f.read(chunk_bytes)
            if not block:
                if carry.strip():
                    yield carry
                return
            block = carry + block
            cut = block.rfind(b"\n")
            if cut < 0:
                carry = block
                continue
            yield block[: cut + 1]
            carry = block[cut + 1:]


def iter_libsvm_chunks(
    path: str,
    chunk_bytes: int = 64 << 20,
    n_features: int | None = None,
    zero_based: bool = False,
) -> Iterator[tuple[SparseRows, np.ndarray]]:
    """Stream a LIBSVM file as (SparseRows, raw labels) chunks.

    Labels are NOT {-1,+1}→{0,1} remapped here (that decision needs the
    whole file's label set); ``read_libsvm_chunked`` applies it at
    assembly, callers doing true out-of-core passes apply their own.
    """
    from photon_ml_tpu.io.libsvm import parse_libsvm_bytes

    for window in _iter_byte_windows(path, chunk_bytes):
        yield parse_libsvm_bytes(window, n_features=n_features,
                                 zero_based=zero_based, where=path)


def read_libsvm_chunked(
    path: str,
    n_features: int | None = None,
    zero_based: bool = False,
    binary_labels_to_01: bool = True,
    chunk_bytes: int = 64 << 20,
) -> tuple[SparseRows, np.ndarray, int]:
    """``io.libsvm.read_libsvm`` semantics with windowed peak memory."""
    parts: list[SparseRows] = []
    label_parts: list[np.ndarray] = []
    for rows, labels in iter_libsvm_chunks(
        path, chunk_bytes=chunk_bytes, n_features=n_features,
        zero_based=zero_based,
    ):
        parts.append(rows)
        label_parts.append(labels)
    from photon_ml_tpu.io.libsvm import map_binary_labels

    rows = SparseRows.concat(parts)
    y = (np.concatenate(label_parts) if label_parts
         else np.zeros(0, np.float32))
    dim = n_features if n_features is not None else rows.max_col + 1
    if binary_labels_to_01:
        y = map_binary_labels(y)
    return rows, y, dim


def iter_jsonl_chunks(path: str, chunk_records: int = 100_000
                      ) -> Iterator[list]:
    """Stream parsed JSONL records in bounded batches (the structured-
    format analogue; ``io.dataset.read_game_dataset`` consumes whole
    files, drivers with --chunked ETL consume this)."""
    import json

    batch: list = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            batch.append(json.loads(line))
            if len(batch) >= chunk_records:
                yield batch
                batch = []
    if batch:
        yield batch
