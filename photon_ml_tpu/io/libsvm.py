"""LIBSVM-format reader: the rebuild's a1a-class data path.

Reference counterpart: ``AvroDataReader`` (photon-api
``com.linkedin.photon.ml.io`` [expected path, mount unavailable — see
SURVEY.md]) — the reference ingests Avro; its canonical small fixtures
(a1a, heart-scale) are LIBSVM files converted to Avro.  The rebuild reads
LIBSVM natively for parity fixtures and benchmarking; structured
(Avro-equivalent) ingestion lives in ``photon_ml_tpu.io.dataset``.

Output is host-side numpy (rows of (col_ids, values) + labels), which
``make_sparse_batch`` / ``make_dense_batch`` turn into device-resident
static-shape batches — the one host→HBM hop, after which training never
touches the host again.
"""

from __future__ import annotations

import numpy as np


def read_libsvm(
    path: str,
    n_features: int | None = None,
    zero_based: bool = False,
    binary_labels_to_01: bool = True,
) -> tuple[list[tuple[np.ndarray, np.ndarray]], np.ndarray, int]:
    """Parse a LIBSVM file → (rows, labels, dim).

    Args:
      path: file path. Lines: ``label idx:val idx:val ...`` (# comments ok).
      n_features: feature-space width; inferred as max index + 1 if None.
      zero_based: whether indices in the file start at 0 (LIBSVM default
        is 1-based, e.g. a1a).
      binary_labels_to_01: map {-1,+1} labels to {0,1} (the reference's
        binary-classification label convention).

    Returns:
      rows: per-example (col_ids int32[], values float32[]) with column
        ids deduplicated (duplicate indices summed, as SparseBatch
        requires unique ids per row).
      labels: float32 [n].
      dim: feature-space width.
    """
    native = _read_libsvm_native(
        path, n_features, zero_based, binary_labels_to_01
    )
    if native is not None:
        return native

    rows: list[tuple[np.ndarray, np.ndarray]] = []
    labels: list[float] = []
    max_idx = -1
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            base = 0 if zero_based else 1
            idxs, vals = [], []
            for tok in parts[1:]:
                i_str, v_str = tok.split(":")
                i = int(i_str) - base
                if i < 0:
                    raise ValueError(
                        f"{path}: feature index below {base} "
                        f"(zero_based={zero_based})"
                    )
                idxs.append(i)
                vals.append(float(v_str))
            c = np.asarray(idxs, np.int32)
            v = np.asarray(vals, np.float32)
            if n_features is not None and len(c):
                # Features outside the declared space (e.g. test-set
                # indices a model never saw) are dropped, never allowed
                # to dot into out-of-range coefficients.
                keep = c < n_features
                c, v = c[keep], v[keep]
            if len(c):
                max_idx = max(max_idx, int(c.max()))
                if len(np.unique(c)) != len(c):
                    # Sum duplicate indices so SparseBatch's unique-ids
                    # invariant holds.
                    c, inv = np.unique(c, return_inverse=True)
                    v = np.bincount(inv, weights=v).astype(np.float32)
            order = np.argsort(c)
            rows.append((c[order], v[order]))

    dim = n_features if n_features is not None else max_idx + 1
    y = np.asarray(labels, np.float32)
    if binary_labels_to_01 and set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y + 1.0) / 2.0
    return rows, y, dim


def _read_libsvm_native(
    path: str,
    n_features: int | None,
    zero_based: bool,
    binary_labels_to_01: bool,
):
    """C++ tokenizer path (photon_ml_tpu.native); None → Python fallback.

    Post-processing (base conversion, out-of-space clipping, duplicate
    summing, per-row sort) stays here in vectorized numpy so both paths
    share one semantics definition."""
    from photon_ml_tpu.native import libsvm_parse_native, native_available

    if not native_available():
        return None
    with open(path, "rb") as f:
        data = f.read()
    parsed = libsvm_parse_native(data)
    if parsed is None:
        return None
    labels, row_ptr, cols, vals, _ = parsed
    base = 0 if zero_based else 1
    cols = cols.astype(np.int64) - base
    if cols.size and cols.min() < 0:
        raise ValueError(
            f"{path}: feature index below {base} (zero_based={zero_based})"
        )
    max_idx = -1
    rows: list[tuple[np.ndarray, np.ndarray]] = []
    for i in range(len(labels)):
        c = cols[row_ptr[i]:row_ptr[i + 1]].astype(np.int32)
        v = vals[row_ptr[i]:row_ptr[i + 1]]
        if n_features is not None and len(c):
            keep = c < n_features
            c, v = c[keep], v[keep]
        if len(c):
            max_idx = max(max_idx, int(c.max()))
            if len(np.unique(c)) != len(c):
                c, inv = np.unique(c, return_inverse=True)
                v = np.bincount(inv, weights=v).astype(np.float32)
        order = np.argsort(c)
        rows.append((c[order], v[order]))
    dim = n_features if n_features is not None else max_idx + 1
    y = np.asarray(labels, np.float32)
    if binary_labels_to_01 and set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y + 1.0) / 2.0
    return rows, y, dim


def write_libsvm(
    path: str,
    rows: list[tuple[np.ndarray, np.ndarray]],
    labels: np.ndarray,
    zero_based: bool = False,
) -> None:
    """Inverse of ``read_libsvm`` (fixture generation / round-trip tests)."""
    off = 0 if zero_based else 1
    with open(path, "w") as f:
        for (c, v), y in zip(rows, labels):
            feats = " ".join(f"{int(i) + off}:{val:g}" for i, val in zip(c, v))
            f.write(f"{y:g} {feats}\n".rstrip() + "\n")
