"""LIBSVM-format reader: the rebuild's a1a-class data path.

Reference counterpart: ``AvroDataReader`` (photon-api
``com.linkedin.photon.ml.io`` [expected path, mount unavailable — see
SURVEY.md]) — the reference ingests Avro; its canonical small fixtures
(a1a, heart-scale) are LIBSVM files converted to Avro.  The rebuild reads
LIBSVM natively for parity fixtures and benchmarking; structured
(Avro-equivalent) ingestion lives in ``photon_ml_tpu.io.dataset``.

Output is host-side ``SparseRows`` (CSR arrays) + numpy labels, which
``make_sparse_batch`` / ``make_dense_batch`` turn into device-resident
static-shape batches — the one host→HBM hop, after which training never
touches the host again.

``parse_libsvm_bytes`` is the single parse-and-canonicalize definition:
``read_libsvm`` is the whole-file case, ``io.chunked`` feeds it byte
windows — both therefore share one semantics (comment stripping, base
conversion, out-of-space clipping, duplicate summing, per-row sort).
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.data.sparse_rows import SparseRows


def parse_libsvm_bytes(
    data: bytes,
    n_features: int | None = None,
    zero_based: bool = False,
    where: str = "<bytes>",
) -> tuple[SparseRows, np.ndarray]:
    """LIBSVM text bytes → (canonical SparseRows, raw float32 labels).

    Uses the native C++ tokenizer when available; the Python tokenizer
    is the fallback.  Either way canonicalization (sort within row, sum
    duplicate ids, drop ``col >= n_features``) happens in ONE vectorized
    ``SparseRows.from_flat`` pass.
    """
    from photon_ml_tpu.native import libsvm_parse_native, native_available

    base = 0 if zero_based else 1
    if native_available():
        parsed = libsvm_parse_native(data)
        if parsed is not None:
            labels, row_ptr, cols, vals, _ = parsed
            cols = cols.astype(np.int64) - base
            if cols.size and cols.min() < 0:
                raise ValueError(
                    f"{where}: feature index below {base} "
                    f"(zero_based={zero_based})"
                )
            rows = SparseRows.from_flat(row_ptr.astype(np.int64), cols,
                                        vals, clip_dim=n_features)
            return rows, np.asarray(labels, np.float32)

    counts: list[int] = []
    idxs: list[int] = []
    vs: list[float] = []
    labels_l: list[float] = []
    for line in data.decode().splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        labels_l.append(float(parts[0]))
        cnt = 0
        for tok in parts[1:]:
            i_str, v_str = tok.split(":")
            i = int(i_str) - base
            if i < 0:
                raise ValueError(
                    f"{where}: feature index below {base} "
                    f"(zero_based={zero_based})"
                )
            idxs.append(i)
            vs.append(float(v_str))
            cnt += 1
        counts.append(cnt)
    indptr = np.zeros(len(counts) + 1, np.int64)
    np.cumsum(np.asarray(counts, np.int64), out=indptr[1:])
    rows = SparseRows.from_flat(indptr, np.asarray(idxs, np.int64),
                                np.asarray(vs, np.float64),
                                clip_dim=n_features)
    return rows, np.asarray(labels_l, np.float32)


def map_binary_labels(y: np.ndarray) -> np.ndarray:
    """{-1,+1} labels → {0,1} when the label set is exactly that
    (the reference's binary-classification convention)."""
    if set(np.unique(y)) <= {-1.0, 1.0}:
        return ((y + 1.0) / 2.0).astype(np.float32)
    return y


def read_libsvm(
    path: str,
    n_features: int | None = None,
    zero_based: bool = False,
    binary_labels_to_01: bool = True,
) -> tuple[SparseRows, np.ndarray, int]:
    """Parse a LIBSVM file → (rows, labels, dim).

    Args:
      path: file path. Lines: ``label idx:val idx:val ...`` (# comments ok).
      n_features: feature-space width; inferred as max index + 1 if None.
        Features outside the declared space (e.g. test-set indices a
        model never saw) are dropped, never allowed to dot into
        out-of-range coefficients.
      zero_based: whether indices in the file start at 0 (LIBSVM default
        is 1-based, e.g. a1a).
      binary_labels_to_01: map {-1,+1} labels to {0,1}.

    Returns:
      rows: ``SparseRows`` (CSR-backed; indexes/iterates as per-example
        (col_ids int32[], values float32[]) pairs) with column ids
        deduplicated (duplicate indices summed, as SparseBatch requires
        unique ids per row).
      labels: float32 [n].
      dim: feature-space width.
    """
    with open(path, "rb") as f:
        data = f.read()
    rows, y = parse_libsvm_bytes(data, n_features=n_features,
                                 zero_based=zero_based, where=path)
    dim = n_features if n_features is not None else rows.max_col + 1
    if binary_labels_to_01:
        y = map_binary_labels(y)
    return rows, y, dim


def write_libsvm(
    path: str,
    rows,
    labels: np.ndarray,
    zero_based: bool = False,
) -> None:
    """Inverse of ``read_libsvm`` (fixture generation / round-trip tests)."""
    off = 0 if zero_based else 1
    with open(path, "w") as f:
        for (c, v), y in zip(rows, labels):
            feats = " ".join(f"{int(i) + off}:{val:g}" for i, val in zip(c, v))
            f.write(f"{y:g} {feats}\n".rstrip() + "\n")
