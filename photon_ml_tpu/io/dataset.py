"""Structured training/scoring data: name-term-value records → GameDataset.

Reference counterparts: ``AvroDataReader``, ``AvroDataWriter``,
``TrainingExampleAvro`` and the name-term-value feature records
(photon-api ``com.linkedin.photon.ml.io``/``photon-avro-schemas``
[expected paths, mount unavailable — see SURVEY.md §2.4]).

The reference ingests Avro container files whose records carry label /
weight / offset, per-shard lists of ``{name, term, value}`` features,
and string random-effect ids.  No Avro library is baked into this
environment, so the wire format here is JSON-lines with the same record
shape — same schema, different container:

    {"label": 1.0, "weight": 1.0, "offset": 0.0,
     "features": {"global": [["age", "", 0.5], ["geo", "us", 1.0]]},
     "ids": {"userId": "u42"}}

Feature entries may be ``[name, term, value]`` triples or
``{"name":, "term":, "value":}`` objects (Avro-record parity).  All
string→int resolution happens here, once, on the host: device code only
ever sees the int32/float32 arrays of ``GameDataset``.
"""

from __future__ import annotations

import json

import numpy as np

from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.io.index_map import IndexMap, IndexMapBuilder, feature_key


def _iter_records(path: str):
    """Yield structured records from JSONL or Avro (by extension/magic):
    the two containers carry the same record shape, so everything
    downstream (index building, ETL) is format-blind."""
    if _is_avro(path):
        from photon_ml_tpu.io.avro_schemas import iter_avro_dataset

        yield from iter_avro_dataset(path)
        return
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)


def _is_avro(path: str) -> bool:
    if path.endswith(".avro"):
        return True
    try:
        with open(path, "rb") as f:
            return f.read(4) == b"Obj\x01"
    except OSError:  # photon-lint: disable=swallowed-exception (unreadable file is simply not detected as Avro; the real read errors loudly)
        return False


def _feature_entries(entries):
    """Yield (name, term, value) from triples or Avro-style dicts."""
    for e in entries:
        if isinstance(e, dict):
            yield e["name"], e.get("term", ""), float(e["value"])
        else:
            name, term, value = e
            yield name, term, float(value)


def build_index_maps(
    path: str,
    feature_shards: list[str] | None = None,
    entity_keys: list[str] | None = None,
) -> tuple[dict, dict]:
    """Scan a JSONL dataset and build feature/entity index maps.

    The rebuild's ``FeatureIndexingDriver`` core (reference §3.4): one
    pass collecting distinct (name, term) per shard and distinct entity
    ids per key, frozen into deterministic sorted-order maps.
    """
    f_builders: dict = {}
    e_builders: dict = {}
    for rec in _iter_records(path):
        for shard, entries in rec.get("features", {}).items():
            if feature_shards is not None and shard not in feature_shards:
                continue
            b = f_builders.setdefault(shard, IndexMapBuilder())
            for name, term, _ in _feature_entries(entries):
                b.put_feature(name, term)
        for key, eid in rec.get("ids", {}).items():
            if entity_keys is not None and key not in entity_keys:
                continue
            e_builders.setdefault(key, IndexMapBuilder()).put(str(eid))
    return (
        {s: b.build() for s, b in f_builders.items()},
        {k: b.build() for k, b in e_builders.items()},
    )


def detect_format(path: str, declared: str = "auto") -> str:
    """Shared input-format resolution for the training/scoring drivers."""
    if declared != "auto":
        return declared
    if path.endswith((".jsonl", ".json", ".ndjson")):
        return "jsonl"
    if _is_avro(path):
        return "avro"
    return "libsvm"


def read_game_dataset(
    path: str,
    feature_maps: dict,
    entity_maps: dict | None = None,
    dense_shards: tuple[str, ...] | list[str] = (),
    skip_unindexed: bool = True,
    extend_entity_maps: bool = False,
) -> GameDataset:
    """Read JSONL records into a host-side ``GameDataset``.

    Args:
      feature_maps: shard → IndexMap; features absent from the map are
        dropped (``skip_unindexed=True``, the reference's behavior for
        out-of-vocabulary features at scoring time) or raise.
      entity_maps: entity key → IndexMap.  Entity ids absent from the
        map are handled per ``extend_entity_maps``:
        - True (training): the id is APPENDED to the map in place, so
          the map the driver persists stays the single source of truth
          for id → index resolution;
        - False (scoring): the id maps to the -1 sentinel, which the
          transformer scores as 0 (reference cold-start semantics).
          Fresh dense indices are never invented here — they could
          alias a trained entity's index (silently scoring with the
          wrong entity's coefficients).
      dense_shards: shards materialized as dense [n, d] float arrays
        (small per-entity shards); all others stay sparse row lists.
    """
    from photon_ml_tpu.data.sparse_rows import SparseRows

    entity_maps = entity_maps or {}
    labels, weights, offsets = [], [], []
    # Flat per-shard accumulators (counts/cols/vals) — record parsing is
    # inherently a Python loop, but per-example numpy arrays are not:
    # the arrays are materialized ONCE per shard at the end.
    shard_acc: dict = {s: ([], [], []) for s in feature_maps}
    id_cols: dict = {k: [] for k in entity_maps}

    for rec in _iter_records(path):
        labels.append(float(rec.get("label", 0.0)))
        weights.append(float(rec.get("weight", 1.0)))
        offsets.append(float(rec.get("offset", 0.0)))
        feats = rec.get("features", {})
        for shard, imap in feature_maps.items():
            counts, idxs, vals = shard_acc[shard]
            cnt = 0
            for name, term, value in _feature_entries(feats.get(shard, [])):
                i = imap.get(feature_key(name, term))
                if i < 0:
                    if skip_unindexed:
                        continue
                    raise KeyError(
                        f"feature ({name!r}, {term!r}) not in shard "
                        f"{shard!r} index map"
                    )
                idxs.append(i)
                vals.append(value)
                cnt += 1
            counts.append(cnt)
        ids = rec.get("ids", {})
        for key, imap in entity_maps.items():
            eid = str(ids.get(key, ""))
            i = imap.get(eid)
            if i < 0 and extend_entity_maps:
                i = len(imap)
                imap.index[eid] = i
            id_cols[key].append(i)

    n = len(labels)
    features: dict = {}
    for shard, (counts, idxs, vals) in shard_acc.items():
        dim = len(feature_maps[shard])
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(np.asarray(counts, np.int64), out=indptr[1:])
        rows = SparseRows.from_flat(
            indptr, np.asarray(idxs, np.int64), np.asarray(vals, np.float64)
        )
        features[shard] = (rows.to_dense(dim) if shard in dense_shards
                          else rows)

    w = np.asarray(weights, np.float32)
    o = np.asarray(offsets, np.float32)
    return GameDataset(
        labels=np.asarray(labels, np.float32),
        features=features,
        entity_ids={k: np.asarray(v, np.int64) for k, v in id_cols.items()},
        weights=None if np.all(w == 1.0) else w,
        offsets=None if np.all(o == 0.0) else o,
        feature_dims={s: len(m) for s, m in feature_maps.items()},
    )


def write_game_dataset(
    path: str,
    labels: np.ndarray,
    features: dict,
    ids: dict | None = None,
    weights: np.ndarray | None = None,
    offsets: np.ndarray | None = None,
    feature_names: dict | None = None,
) -> None:
    """Write records back to JSONL (fixture generation, round-trips).

    ``features`` values are dense [n, d] arrays or sparse row lists;
    ``feature_names[shard]`` optionally gives index → name strings
    (defaults to ``f<i>``).
    """
    n = len(labels)
    with open(path, "w") as f:
        for r in range(n):
            rec: dict = {"label": float(labels[r])}
            if weights is not None:
                rec["weight"] = float(weights[r])
            if offsets is not None:
                rec["offset"] = float(offsets[r])
            rec["features"] = {}
            for shard, data in features.items():
                names = (feature_names or {}).get(shard)
                if isinstance(data, np.ndarray):
                    nz = np.nonzero(data[r])[0]
                    entries = [(names[i] if names else f"f{i}", "",
                                float(data[r, i])) for i in nz]
                else:
                    c, v = data[r]
                    entries = [(names[i] if names else f"f{i}", "",
                                float(val)) for i, val in zip(c, v)]
                rec["features"][shard] = entries
            if ids:
                rec["ids"] = {k: str(col[r]) for k, col in ids.items()}
            f.write(json.dumps(rec) + "\n")
