"""Data I/O: LIBSVM/CSV readers, feature indexing, model serialization.

Reference: photon-api ``com.linkedin.photon.ml.io`` (SURVEY.md §2.4 —
expected paths, mount unavailable).
"""

from photon_ml_tpu.io.chunked import (
    iter_jsonl_chunks,
    iter_libsvm_chunks,
    read_libsvm_chunked,
)
from photon_ml_tpu.io.libsvm import read_libsvm, write_libsvm

__all__ = [
    "iter_jsonl_chunks",
    "iter_libsvm_chunks",
    "read_libsvm",
    "read_libsvm_chunked",
    "write_libsvm",
]
