"""Avro binary format: stdlib-only codec + object-container-file I/O.

Reference counterparts: the Avro (Java) dependency plus
``AvroDataReader`` / ``AvroDataWriter`` / ``AvroUtils`` (photon-api
``com.linkedin.photon.ml.io.avro`` [expected paths, mount unavailable —
see SURVEY.md §2.4]).  The reference's on-disk interchange format — for
training data, scoring output, and saved models — is Avro object
container files.  No Avro library is baked into this environment, so
this module implements the wire format directly from the Avro 1.x
specification (zigzag varint longs, little-endian floats, length-
prefixed bytes/strings, block-encoded arrays/maps, union = index +
value, container = magic / metadata map / sync-marker-delimited deflate
or null blocks).  That keeps the rebuild byte-compatible with reference
pipelines: files written here are readable by any Avro implementation
and vice versa.

Scope (documented subset): all primitive types, record / enum / fixed /
array / map / union named types, recursive name references, ``null`` and
``deflate`` codecs, and schema RESOLUTION (Avro spec §"Schema
Resolution"): ``read_container(path, reader_schema=...)`` decodes with
the container's embedded writer schema and resolves each datum to the
caller's reader schema — writer-only fields are skipped, reader-only
fields take their defaults, primitives promote (int→long→float→double,
string↔bytes), unions resolve branch-wise, renamed fields/types match
through reader aliases — so files written by evolved reference
pipelines stay readable.

This is host-side ETL: nothing here touches jax.  Device code only ever
sees the int32/float32 arrays produced downstream (``io.dataset``).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Iterable, Iterator

MAGIC = b"Obj\x01"
SYNC_SIZE = 16
_PRIMITIVES = {
    "null", "boolean", "int", "long", "float", "double", "bytes", "string"
}


# ---------------------------------------------------------------------------
# Schema handling
# ---------------------------------------------------------------------------


class Schema:
    """A parsed Avro schema: the JSON structure plus a named-type registry
    so ``{"type": "X"}`` references resolve during encode/decode."""

    def __init__(self, source: "str | dict | list"):
        if isinstance(source, str):
            src = source.strip()
            source = json.loads(src) if src and src[0] in "[{\"" else src
        self.names: dict[str, dict] = {}
        self.root = self._collect(source)

    def _collect(self, s: Any) -> Any:
        """Walk the schema, registering named types (record/enum/fixed)."""
        if isinstance(s, str):
            return s
        if isinstance(s, list):
            return [self._collect(b) for b in s]
        t = s.get("type")
        if t in ("record", "error"):
            self.names[s["name"]] = s
            for f in s["fields"]:
                f["type"] = self._collect(f["type"])
            return s
        if t in ("enum", "fixed"):
            self.names[s["name"]] = s
            return s
        if t == "array":
            s["items"] = self._collect(s["items"])
            return s
        if t == "map":
            s["values"] = self._collect(s["values"])
            return s
        if isinstance(t, (dict, list)):
            # {"type": {...}} wrapper
            return self._collect(t)
        return s

    def resolve(self, s: Any) -> Any:
        """Dereference a by-name type reference."""
        if isinstance(s, str) and s not in _PRIMITIVES:
            return self.names[s]
        return s

    def to_json(self) -> str:
        return json.dumps(self.root)


# ---------------------------------------------------------------------------
# Binary encoding (Avro spec §"Binary Encoding")
# ---------------------------------------------------------------------------


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(out: BinaryIO, n: int) -> None:
    z = _zigzag(n)
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.write(bytes((b | 0x80,)))
        else:
            out.write(bytes((b,)))
            return


def read_long(inp: BinaryIO) -> int:
    shift, acc = 0, 0
    while True:
        (b,) = inp.read(1)
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return _unzigzag(acc)
        shift += 7


def _encode(schema: Schema, s: Any, datum: Any, out: BinaryIO) -> None:
    s = schema.resolve(s)
    if isinstance(s, list):                       # union
        for i, branch in enumerate(s):
            if _union_match(schema, branch, datum):
                write_long(out, i)
                _encode(schema, branch, datum, out)
                return
        raise TypeError(f"datum {datum!r} matches no union branch {s!r}")
    t = s if isinstance(s, str) else s["type"]
    if t == "null":
        return
    if t == "boolean":
        out.write(b"\x01" if datum else b"\x00")
    elif t in ("int", "long"):
        write_long(out, int(datum))
    elif t == "float":
        out.write(struct.pack("<f", float(datum)))
    elif t == "double":
        out.write(struct.pack("<d", float(datum)))
    elif t == "bytes":
        write_long(out, len(datum))
        out.write(datum)
    elif t == "string":
        raw = datum.encode("utf-8")
        write_long(out, len(raw))
        out.write(raw)
    elif t == "record":
        for f in s["fields"]:
            name = f["name"]
            if name in datum:
                value = datum[name]
            elif "default" in f:
                value = f["default"]
            else:
                raise KeyError(
                    f"record {s['name']!r}: field {name!r} missing and "
                    "has no default"
                )
            _encode(schema, f["type"], value, out)
    elif t == "enum":
        out.write(b"")
        write_long(out, s["symbols"].index(datum))
    elif t == "fixed":
        if len(datum) != s["size"]:
            raise ValueError(f"fixed {s['name']}: want {s['size']} bytes")
        out.write(datum)
    elif t == "array":
        if datum:
            write_long(out, len(datum))
            for item in datum:
                _encode(schema, s["items"], item, out)
        write_long(out, 0)
    elif t == "map":
        if datum:
            write_long(out, len(datum))
            for k, v in datum.items():
                _encode(schema, "string", k, out)
                _encode(schema, s["values"], v, out)
        write_long(out, 0)
    else:
        raise TypeError(f"unsupported schema {s!r}")


def _union_match(schema: Schema, branch: Any, datum: Any) -> bool:
    branch = schema.resolve(branch)
    t = branch if isinstance(branch, str) else branch["type"]
    if t == "null":
        return datum is None
    if t == "boolean":
        return isinstance(datum, bool)
    if t in ("int", "long"):
        return isinstance(datum, int) and not isinstance(datum, bool)
    if t in ("float", "double"):
        return isinstance(datum, (int, float)) and not isinstance(datum, bool)
    if t == "string":
        return isinstance(datum, str)
    if t in ("bytes", "fixed"):
        return isinstance(datum, (bytes, bytearray))
    if t == "record":
        return isinstance(datum, dict)
    if t == "map":
        return isinstance(datum, dict)
    if t == "array":
        return isinstance(datum, (list, tuple))
    if t == "enum":
        return isinstance(datum, str)
    return False


def _decode(schema: Schema, s: Any, inp: BinaryIO) -> Any:
    s = schema.resolve(s)
    if isinstance(s, list):                       # union
        return _decode(schema, s[read_long(inp)], inp)
    t = s if isinstance(s, str) else s["type"]
    if t == "null":
        return None
    if t == "boolean":
        return inp.read(1) == b"\x01"
    if t in ("int", "long"):
        return read_long(inp)
    if t == "float":
        return struct.unpack("<f", inp.read(4))[0]
    if t == "double":
        return struct.unpack("<d", inp.read(8))[0]
    if t == "bytes":
        return inp.read(read_long(inp))
    if t == "string":
        return inp.read(read_long(inp)).decode("utf-8")
    if t == "record":
        return {f["name"]: _decode(schema, f["type"], inp)
                for f in s["fields"]}
    if t == "enum":
        return s["symbols"][read_long(inp)]
    if t == "fixed":
        return inp.read(s["size"])
    if t == "array":
        out = []
        while True:
            count = read_long(inp)
            if count == 0:
                return out
            if count < 0:                         # block with byte size
                read_long(inp)
                count = -count
            for _ in range(count):
                out.append(_decode(schema, s["items"], inp))
    if t == "map":
        out = {}
        while True:
            count = read_long(inp)
            if count == 0:
                return out
            if count < 0:
                read_long(inp)
                count = -count
            for _ in range(count):
                k = inp.read(read_long(inp)).decode("utf-8")
                out[k] = _decode(schema, s["values"], inp)
    raise TypeError(f"unsupported schema {s!r}")


# ---------------------------------------------------------------------------
# Schema resolution (Avro spec §"Schema Resolution"): decode with the
# WRITER schema's wire layout, produce data shaped by the READER schema.
# ---------------------------------------------------------------------------

_PROMOTIONS = {
    ("int", "long"), ("int", "float"), ("int", "double"),
    ("long", "float"), ("long", "double"), ("float", "double"),
    ("string", "bytes"), ("bytes", "string"),
}


def _type_of(s: Any) -> str:
    return s if isinstance(s, str) else s["type"]


def _schemas_match(wschema: "Schema", ws: Any, rschema: "Schema",
                   rs: Any) -> bool:
    """Can writer schema ``ws`` resolve to reader schema ``rs``?
    (Shallow per spec — container element mismatches surface as errors
    during decode, like reference implementations.)"""
    ws = wschema.resolve(ws)
    rs = rschema.resolve(rs)
    if isinstance(ws, list) or isinstance(rs, list):
        return True   # union resolution happens per-datum at decode
    wt, rt = _type_of(ws), _type_of(rs)
    if wt == rt:
        if wt in ("record", "enum", "fixed"):
            # Named types match on unqualified name — or when the
            # reader declares the writer's name as an alias (spec
            # §Aliases), mirroring _decode_resolved: without this a
            # renamed type nested inside a reader union failed
            # resolution that succeeds outside a union.
            wn = ws["name"].rsplit(".", 1)[-1]
            rn = rs["name"].rsplit(".", 1)[-1]
            if wn != rn and wn not in (
                    a.rsplit(".", 1)[-1] for a in rs.get("aliases", ())):
                return False
            if wt == "fixed":
                return ws["size"] == rs["size"]
        return True
    return (wt, rt) in _PROMOTIONS


def _promote(value: Any, wt: str, rt: str) -> Any:
    if rt in ("float", "double") and wt in ("int", "long", "float"):
        return float(value)
    if wt == "string" and rt == "bytes":
        return value.encode("utf-8") if isinstance(value, str) else value
    if wt == "bytes" and rt == "string":
        return value.decode("utf-8") if isinstance(value, bytes) else value
    return value


def _default_datum(rschema: "Schema", rs: Any, default: Any) -> Any:
    """A reader field's JSON default → runtime datum (spec: bytes/fixed
    defaults are JSON strings of latin-1 code points; union defaults
    conform to the FIRST branch)."""
    rs = rschema.resolve(rs)
    if isinstance(rs, list):
        return _default_datum(rschema, rs[0], default)
    t = _type_of(rs)
    if t in ("bytes", "fixed") and isinstance(default, str):
        return default.encode("latin-1")
    if t == "record":
        return {
            f["name"]: _default_datum(
                rschema, f["type"],
                default.get(f["name"], f.get("default")))
            for f in rs["fields"]
        }
    if t == "array":
        return [_default_datum(rschema, rs["items"], d) for d in default]
    if t == "map":
        return {k: _default_datum(rschema, rs["values"], v)
                for k, v in default.items()}
    return default


def _skip(schema: Schema, s: Any, inp: BinaryIO) -> None:
    """Decode-and-discard a writer-only value (spec: skipped fields)."""
    _decode(schema, s, inp)


def _decode_resolved(wschema: Schema, ws: Any, rschema: Schema, rs: Any,
                     inp: BinaryIO) -> Any:
    ws = wschema.resolve(ws)
    rs = rschema.resolve(rs)
    if isinstance(ws, list):
        # Writer union: the wire carries the branch index; resolve the
        # actual branch against the reader schema.
        return _decode_resolved(wschema, ws[read_long(inp)], rschema, rs,
                                inp)
    if isinstance(rs, list):
        # Reader union, writer not: first reader branch that matches.
        for branch in rs:
            if _schemas_match(wschema, ws, rschema, branch):
                return _decode_resolved(wschema, ws, rschema, branch, inp)
        raise TypeError(
            f"writer schema {ws!r} matches no reader union branch {rs!r}")
    wt, rt = _type_of(ws), _type_of(rs)
    if wt != rt and (wt, rt) not in _PROMOTIONS:
        raise TypeError(
            f"cannot resolve writer {wt!r} to reader {rt!r}")
    if wt == rt and wt in ("enum", "fixed"):
        # Spec: named types resolve only when (unqualified) names match
        # — or the reader declares the writer's name as an alias; fixed
        # additionally requires equal sizes.  A silent fall-through
        # here would yield writer-shaped bytes under a reader contract
        # that promises something else (review finding).
        wn = ws["name"].rsplit(".", 1)[-1]
        rn = rs["name"].rsplit(".", 1)[-1]
        if wn != rn and wn not in (
                a.rsplit(".", 1)[-1] for a in rs.get("aliases", ())):
            raise TypeError(
                f"{wt} name mismatch: writer {wn!r}, reader {rn!r}")
        if wt == "fixed" and ws["size"] != rs["size"]:
            raise TypeError(
                f"fixed {wn!r} size mismatch: writer {ws['size']}, "
                f"reader {rs['size']}")
    if wt == "record":
        wn = ws["name"].rsplit(".", 1)[-1]
        rn = rs["name"].rsplit(".", 1)[-1]
        if wn != rn and wn not in (
                a.rsplit(".", 1)[-1] for a in rs.get("aliases", ())):
            raise TypeError(f"record name mismatch: writer {wn}, "
                            f"reader {rn}")
        r_fields = {f["name"]: f for f in rs["fields"]}
        # Reader field aliases (spec §Aliases): a renamed field matches
        # the writer data under its OLD name.
        r_alias = {a: f for f in rs["fields"]
                   for a in f.get("aliases", ())}
        out = {}
        for f in ws["fields"]:        # wire order = writer field order
            rf = r_fields.pop(f["name"], None)
            if rf is None:
                rf = r_alias.get(f["name"])
                if rf is not None:
                    r_fields.pop(rf["name"], None)
            if rf is None:
                _skip(wschema, f["type"], inp)
            else:
                out[rf["name"]] = _decode_resolved(
                    wschema, f["type"], rschema, rf["type"], inp)
        for name, rf in r_fields.items():   # reader-only → defaults
            if "default" not in rf:
                raise TypeError(
                    f"record {rs['name']!r}: reader field {name!r} "
                    "absent from writer data and has no default")
            out[name] = _default_datum(rschema, rf["type"], rf["default"])
        return out
    if wt == "enum":
        symbol = ws["symbols"][read_long(inp)]
        if symbol not in rs["symbols"]:
            if "default" in rs:       # Avro 1.9+ enum default
                return rs["default"]
            raise TypeError(
                f"enum symbol {symbol!r} not in reader symbols")
        return symbol
    if wt == "array":
        out = []
        while True:
            count = read_long(inp)
            if count == 0:
                return out
            if count < 0:
                read_long(inp)
                count = -count
            for _ in range(count):
                out.append(_decode_resolved(
                    wschema, ws["items"], rschema, rs["items"], inp))
    if wt == "map":
        out = {}
        while True:
            count = read_long(inp)
            if count == 0:
                return out
            if count < 0:
                read_long(inp)
                count = -count
            for _ in range(count):
                k = inp.read(read_long(inp)).decode("utf-8")
                out[k] = _decode_resolved(
                    wschema, ws["values"], rschema, rs["values"], inp)
    value = _decode(wschema, ws, inp)
    return _promote(value, wt, rt)


def decode_datum_resolved(wschema: Schema, rschema: Schema,
                          raw: bytes) -> Any:
    """Decode writer-layout bytes into reader-schema-shaped data."""
    return _decode_resolved(wschema, wschema.root, rschema, rschema.root,
                            io.BytesIO(raw))


def encode_datum(schema: Schema, datum: Any) -> bytes:
    buf = io.BytesIO()
    _encode(schema, schema.root, datum, buf)
    return buf.getvalue()


def decode_datum(schema: Schema, raw: bytes) -> Any:
    return _decode(schema, schema.root, io.BytesIO(raw))


# ---------------------------------------------------------------------------
# Object container files (Avro spec §"Object Container Files")
# ---------------------------------------------------------------------------

_META_SCHEMA = Schema({"type": "map", "values": "bytes"})


def write_container(
    path: str,
    schema: "Schema | str | dict",
    records: Iterable[Any],
    codec: str = "deflate",
    records_per_block: int = 4096,
) -> int:
    """Write records to an Avro object container file; returns count."""
    if not isinstance(schema, Schema):
        schema = Schema(schema)
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported codec {codec!r}")
    sync = os.urandom(SYNC_SIZE)
    total = 0
    with open(path, "wb") as f:
        f.write(MAGIC)
        _encode(
            _META_SCHEMA,
            _META_SCHEMA.root,
            {
                "avro.schema": schema.to_json().encode(),
                "avro.codec": codec.encode(),
            },
            f,
        )
        f.write(sync)

        block = io.BytesIO()
        in_block = 0

        def flush():
            nonlocal in_block
            if not in_block:
                return
            payload = block.getvalue()
            if codec == "deflate":
                # Avro deflate = raw DEFLATE stream (no zlib wrapper).
                c = zlib.compressobj(wbits=-15)
                payload = c.compress(payload) + c.flush()
            write_long(f, in_block)
            write_long(f, len(payload))
            f.write(payload)
            f.write(sync)
            block.seek(0)
            block.truncate()
            in_block = 0

        for rec in records:
            _encode(schema, schema.root, rec, block)
            in_block += 1
            total += 1
            if in_block >= records_per_block:
                flush()
        flush()
    return total


def read_container(
    path: str,
    reader_schema: "Schema | str | dict | None" = None,
) -> tuple[Schema, Iterator[Any]]:
    """Open an Avro object container file → (writer schema, record iter).

    With ``reader_schema``, each record is RESOLVED writer→reader
    (schema evolution): data written under an older/newer schema decodes
    into the caller's shape — writer-only fields skipped, reader-only
    fields defaulted, primitives promoted (Avro spec §"Schema
    Resolution").  The returned schema is still the writer's (callers
    inspecting the file's own layout keep working).
    """
    if reader_schema is not None and not isinstance(reader_schema, Schema):
        reader_schema = Schema(reader_schema)
    f = open(path, "rb")
    if f.read(4) != MAGIC:
        f.close()
        raise ValueError(f"{path}: not an Avro object container file")
    meta = _decode(_META_SCHEMA, _META_SCHEMA.root, f)
    schema = Schema(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        f.close()
        raise ValueError(f"{path}: unsupported codec {codec!r}")
    sync = f.read(SYNC_SIZE)

    def records() -> Iterator[Any]:
        with f:
            while True:
                head = f.read(1)
                if not head:
                    return
                f.seek(-1, 1)
                count = read_long(f)
                size = read_long(f)
                payload = f.read(size)
                if codec == "deflate":
                    payload = zlib.decompress(payload, wbits=-15)
                if f.read(SYNC_SIZE) != sync:
                    raise ValueError(f"{path}: sync marker mismatch")
                buf = io.BytesIO(payload)
                if reader_schema is None:
                    for _ in range(count):
                        yield _decode(schema, schema.root, buf)
                else:
                    for _ in range(count):
                        yield _decode_resolved(
                            schema, schema.root, reader_schema,
                            reader_schema.root, buf)

    return schema, records()
