"""Photon-parity Avro schemas + GameDataset/model adapters.

Reference counterparts: the generated records of ``photon-avro-schemas``
— ``TrainingExampleAvro``, ``ScoringResultAvro``,
``BayesianLinearModelAvro``, ``NameTermValueAvro``,
``FeatureSummarizationResultAvro`` (``photon-avro-schemas/src/main/avro``
[expected paths, mount unavailable — see SURVEY.md §2.4]) and the flexible
GAME data schema read by ``AvroDataReader`` (feature *bags* as
``array<FeatureAvro>`` fields named per feature shard, random-effect ids
as string fields).

The adapters below translate between these records and the framework's
host-side record shape (``io.dataset``'s ``{"label", "weight", "offset",
"features": {bag: [(name, term, value), ...]}, "ids": {key: id}}``), so
the JSONL and Avro paths share one index-resolution/ETL pipeline.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator

import numpy as np

from photon_ml_tpu.io.avro import Schema, read_container, write_container

NAME_TERM_VALUE = {
    "type": "record",
    "name": "NameTermValueAvro",
    "namespace": "photon_ml_tpu.avro",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "value", "type": "double"},
    ],
}


def training_example_schema(
    feature_bags: Iterable[str] = ("features",),
    id_fields: Iterable[str] = (),
) -> Schema:
    """The flexible GAME training-record schema: one ``array<FeatureAvro>``
    field per feature bag, one nullable string field per entity id."""
    fields: list[dict] = [
        {"name": "label", "type": "double"},
        {"name": "weight", "type": "double", "default": 1.0},
        {"name": "offset", "type": "double", "default": 0.0},
    ]
    first = True
    for bag in feature_bags:
        items = NAME_TERM_VALUE if first else "NameTermValueAvro"
        first = False
        fields.append({
            "name": bag,
            "type": {"type": "array", "items": items},
            "default": [],
        })
    for key in id_fields:
        fields.append({
            "name": key, "type": ["null", "string"], "default": None
        })
    return Schema({
        "type": "record",
        "name": "TrainingExampleAvro",
        "namespace": "photon_ml_tpu.avro",
        "fields": fields,
    })


SCORING_RESULT_SCHEMA = Schema({
    "type": "record",
    "name": "ScoringResultAvro",
    "namespace": "photon_ml_tpu.avro",
    "fields": [
        {"name": "uid", "type": "long"},
        {"name": "predictionScore", "type": "double"},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "ids", "type": {"type": "map", "values": "string"},
         "default": {}},
    ],
})


def bayesian_linear_model_schema() -> Schema:
    """Saved-model record: (name, term)-keyed means and optional
    variances — the reference's ``BayesianLinearModelAvro`` shape, which
    is what makes saved models portable across feature-index rebuilds."""
    return Schema({
        "type": "record",
        "name": "BayesianLinearModelAvro",
        "namespace": "photon_ml_tpu.avro",
        "fields": [
            {"name": "modelId", "type": "string"},
            {"name": "modelClass", "type": "string", "default": ""},
            {"name": "lossFunction", "type": "string", "default": ""},
            {"name": "means",
             "type": {"type": "array", "items": NAME_TERM_VALUE}},
            {"name": "variances",
             "type": ["null",
                      {"type": "array", "items": "NameTermValueAvro"}],
             "default": None},
        ],
    })


# ---------------------------------------------------------------------------
# Record-shape adapters (Avro <-> io.dataset record dicts)
# ---------------------------------------------------------------------------


def avro_to_dataset_record(
    rec: dict,
    feature_bags: Iterable[str],
    id_fields: Iterable[str],
) -> dict:
    out: dict[str, Any] = {
        "label": rec.get("label", 0.0),
        "weight": rec.get("weight", 1.0),
        "offset": rec.get("offset", 0.0),
        "features": {
            bag: [(e["name"], e.get("term", ""), e["value"])
                  for e in rec.get(bag, [])]
            for bag in feature_bags
        },
    }
    ids = {k: rec[k] for k in id_fields if rec.get(k) is not None}
    if ids:
        out["ids"] = ids
    return out


def dataset_record_to_avro(
    rec: dict,
    feature_bags: Iterable[str],
    id_fields: Iterable[str],
) -> dict:
    out: dict[str, Any] = {
        "label": float(rec.get("label", 0.0)),
        "weight": float(rec.get("weight", 1.0)),
        "offset": float(rec.get("offset", 0.0)),
    }
    feats = rec.get("features", {})
    for bag in feature_bags:
        out[bag] = [
            {"name": n, "term": t, "value": float(v)}
            for n, t, v in _triples(feats.get(bag, []))
        ]
    ids = rec.get("ids", {})
    for key in id_fields:
        out[key] = str(ids[key]) if key in ids else None
    return out


def _triples(entries):
    for e in entries:
        if isinstance(e, dict):
            yield e["name"], e.get("term", ""), e["value"]
        else:
            yield e


def iter_avro_dataset(
    path: str,
    feature_bags: Iterable[str] | None = None,
    id_fields: Iterable[str] | None = None,
) -> Iterator[dict]:
    """Iterate an Avro training file as ``io.dataset``-shaped records.

    Bags/id fields default to introspection of the writer schema: every
    ``array``-typed field is a feature bag, every (nullable) string field
    is an entity id.
    """
    schema, records = read_container(path)
    if feature_bags is None or id_fields is None:
        bags, ids = [], []
        for f in schema.root["fields"]:
            t = schema.resolve(f["type"])
            if isinstance(t, dict) and t.get("type") == "array":
                bags.append(f["name"])
            elif f["name"] not in ("label", "weight", "offset"):
                branches = t if isinstance(t, list) else [t]
                if "string" in branches:
                    ids.append(f["name"])
        feature_bags = bags if feature_bags is None else feature_bags
        id_fields = ids if id_fields is None else id_fields
    for rec in records:
        yield avro_to_dataset_record(rec, feature_bags, id_fields)


def write_avro_dataset(
    path: str,
    records: Iterable[dict],
    feature_bags: Iterable[str] = ("features",),
    id_fields: Iterable[str] = (),
    codec: str = "deflate",
) -> int:
    """Write ``io.dataset``-shaped records as ``TrainingExampleAvro``."""
    feature_bags = list(feature_bags)
    id_fields = list(id_fields)
    schema = training_example_schema(feature_bags, id_fields)
    return write_container(
        path,
        schema,
        (dataset_record_to_avro(r, feature_bags, id_fields)
         for r in records),
        codec=codec,
    )


# ---------------------------------------------------------------------------
# Model I/O (BayesianLinearModelAvro)
# ---------------------------------------------------------------------------


def write_model_avro(
    path: str,
    model_id: str,
    means: np.ndarray,
    index_to_key,
    variances: np.ndarray | None = None,
    loss_function: str = "",
    sparse: bool = True,
) -> None:
    """Save coefficients keyed by (name, term) — reference model format.

    ``index_to_key(i)`` → ``(name, term)`` for feature index i (the
    feature IndexMap's inverse).  ``sparse=True`` drops exact zeros, as
    the reference does for L1 models.
    """
    means = np.asarray(means)
    idx = np.nonzero(means)[0] if sparse else np.arange(means.size)

    def ntv(values):
        out = []
        for i in idx:
            name, term = index_to_key(int(i))
            out.append({
                "name": name, "term": term, "value": float(values[i])
            })
        return out

    rec = {
        "modelId": model_id,
        "modelClass": "",
        "lossFunction": loss_function,
        "means": ntv(means),
        "variances": None if variances is None else ntv(
            np.asarray(variances)),
    }
    write_container(path, bayesian_linear_model_schema(), [rec])


def read_model_avro(
    path: str,
    key_to_index,
    dim: int,
) -> tuple[str, np.ndarray, np.ndarray | None]:
    """Load a BayesianLinearModelAvro → (model_id, means[dim], variances).

    ``key_to_index(name, term)`` → feature index (or a negative sentinel
    for unknown keys, which are skipped — reference behavior when the
    index map evolved since the model was trained).
    """
    _, records = read_container(path)
    rec = next(iter(records))
    means = np.zeros(dim, np.float32)
    for e in rec["means"]:
        i = key_to_index(e["name"], e.get("term", ""))
        if i is not None and i >= 0:
            means[i] = e["value"]
    variances = None
    if rec.get("variances") is not None:
        variances = np.zeros(dim, np.float32)
        for e in rec["variances"]:
            i = key_to_index(e["name"], e.get("term", ""))
            if i is not None and i >= 0:
                variances[i] = e["value"]
    return rec["modelId"], means, variances
