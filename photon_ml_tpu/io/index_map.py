"""Feature and entity index maps: string keys → dense integer indices.

Reference counterparts: ``IndexMap``, ``PalDBIndexMap``,
``PalDBIndexMapBuilder`` (photon-api
``com.linkedin.photon.ml.index`` [expected paths, mount unavailable —
see SURVEY.md §2.4]).  The reference maps ``(name, term)`` feature keys
to vector indices via off-heap PalDB stores, one per feature shard, and
tags examples with string random-effect entity ids.

TPU translation: the JVM needed an off-heap mmap store to keep
multi-million-entry maps off the garbage-collected heap; a Python dict
on the ETL host has no such constraint, so the store is a plain
sorted-key JSON file per shard — deterministic, diffable, and loadable
anywhere.  Device code never sees strings: all indexing happens once on
the host, producing the int32 arrays the static-shape batches consume.
"""

from __future__ import annotations

import dataclasses
import json
import os

# The reference joins (name, term) with a NUL-ish delimiter; use one
# that cannot appear in Avro name/term strings we care about.
_DELIM = "\x1f"


def feature_key(name: str, term: str = "") -> str:
    return f"{name}{_DELIM}{term}" if term else name


@dataclasses.dataclass
class IndexMap:
    """Immutable key → index map (features of one shard, or entity ids)."""

    index: dict  # str key → int

    def __len__(self) -> int:
        return len(self.index)

    def __contains__(self, key: str) -> bool:
        return key in self.index

    def get(self, key: str, default: int = -1) -> int:
        return self.index.get(key, default)

    def get_feature(self, name: str, term: str = "", default: int = -1) -> int:
        return self.index.get(feature_key(name, term), default)

    def names(self) -> list[str]:
        """Keys in index order (index i → names()[i])."""
        out = [""] * len(self.index)
        for k, i in self.index.items():
            out[i] = k
        return out

    def feature_at(self, i: int) -> tuple[str, str]:
        """Inverse of ``get_feature``: index → (name, term)."""
        key = self.names()[i]
        name, sep, term = key.partition(_DELIM)
        return (name, term) if sep else (key, "")

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.index, f, indent=0, sort_keys=True)

    @staticmethod
    def load(path: str) -> "IndexMap":
        with open(path) as f:
            return IndexMap(index=json.load(f))


class IndexMapBuilder:
    """Accumulate keys across a data scan, then freeze to an IndexMap.

    Indices are assigned by sorted key order at build time (not first-seen
    order), so the map is deterministic regardless of record order — the
    property the reference gets from its partition-then-sort indexing
    driver (§3.4).
    """

    def __init__(self):
        self._keys: set[str] = set()

    def put(self, key: str) -> None:
        self._keys.add(key)

    def put_feature(self, name: str, term: str = "") -> None:
        self._keys.add(feature_key(name, term))

    def build(self) -> IndexMap:
        return IndexMap(index={k: i for i, k in enumerate(sorted(self._keys))})


# ---------------------------------------------------------------------------
# Directory layout: one JSON per feature shard + one per entity key,
# the rebuild's equivalent of "one PalDB store per (shard, partition)".
# ---------------------------------------------------------------------------

def save_index_maps(
    out_dir: str,
    feature_maps: dict,
    entity_maps: dict | None = None,
) -> None:
    os.makedirs(out_dir, exist_ok=True)
    meta = {
        "feature_shards": sorted(feature_maps),
        "entity_keys": sorted(entity_maps or {}),
    }
    with open(os.path.join(out_dir, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=2)
    for shard, imap in feature_maps.items():
        imap.save(os.path.join(out_dir, f"features.{shard}.json"))
    for key, imap in (entity_maps or {}).items():
        imap.save(os.path.join(out_dir, f"entities.{key}.json"))


def load_index_maps(in_dir: str) -> tuple[dict, dict]:
    with open(os.path.join(in_dir, "metadata.json")) as f:
        meta = json.load(f)
    feature_maps = {
        shard: IndexMap.load(os.path.join(in_dir, f"features.{shard}.json"))
        for shard in meta["feature_shards"]
    }
    entity_maps = {
        key: IndexMap.load(os.path.join(in_dir, f"entities.{key}.json"))
        for key in meta["entity_keys"]
    }
    return feature_maps, entity_maps
