"""Checkpoint/resume: atomic run-state snapshots across the pipeline.

Reference counterpart: the reference's only recovery points are whole
saved models (``ModelOutputMode`` + warm-start re-load; SURVEY §5.4) —
Spark's lineage re-execution covers everything finer.  A jax_graft
rebuild has no lineage layer, and TPU slices fail as a unit, so
checkpoint/restart IS the failure-recovery story.  Round 9 snapshots
the run at three granularities:

- **CD level** (``save_cd`` / ``save_cd_partial``): completed-sweep
  count, the position WITHIN a sweep (which coordinates already
  trained this sweep), per-coordinate coefficients, the per-coordinate
  score planes plus the running total (restoring scores makes a
  resumed run's offsets *bitwise* equal to the uninterrupted run's),
  streamed-RE retirement/runtime state, and the accumulated
  history/validation record.
- **Solver level** (``maybe_save_solver``): the host-driven streaming
  L-BFGS / OWL-QN loop state — coefficients, value, gradient, the
  (s, y, ρ) memory pairs (swept: the full masked-lane buffers), the
  tracker planes — every ``every_solver_iters`` iterations, so a kill
  mid-solve resumes mid-solve instead of repaying the whole sweep
  sequence.  Labels are scoped by the CD loop (iteration ×
  coordinate), so a restored run can only ever adopt state from its
  own position.
- **Stage level** (``save_stage``): named auxiliary state — the
  batched λ-sweep's lane matrix between CD sweeps, the tuner's
  per-round proposal/observation history.

Format: one uncompressed ``.npz`` per snapshot via the plan cache's
``atomic_savez`` (tmp + ``os.replace`` — readers never see a torn
file), a JSON ``__meta__`` manifest, and a ``latest`` text pointer.
The CD-level layout is a superset of ``utils.checkpoint``'s
(``<name>__flat`` / ``<name>__block_<b>`` / ``<name>__score`` keys),
so pre-existing consumers keep reading the new files.  Any unreadable
snapshot degrades to the previous good one with a warning — a corrupt
checkpoint must cost one checkpoint interval, never the run.
"""

from __future__ import annotations

import contextlib
import glob
import json
import logging
import os
import re
import threading

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.utils.checkpoint import _flatten, _unflatten, _NpzView

logger = logging.getLogger(__name__)

# Snapshot schema version: rides in every manifest; a mismatch is a
# clean "no checkpoint" miss, never a crash.
CHECKPOINT_SCHEMA = 1

# Reserved npz-key prefix for state-tree arrays (kept disjoint from the
# utils.checkpoint coefficient/score key scheme, whose parser splits on
# the LAST "__" and skips unknown kinds).
_TREE_PREFIX = "__x__"


# ---------------------------------------------------------------------------
# State-tree codec: nested dict/list/scalars/arrays → (JSON meta, arrays)
# ---------------------------------------------------------------------------


def flatten_tree(tree) -> tuple[dict, dict]:
    """Encode a nested state tree (dict[str]/list/tuple/None/bool/int/
    float/str leaves + numpy/jax array leaves) as a JSON-able manifest
    plus a flat ``{key: ndarray}`` dict ready for ``atomic_savez``."""
    arrays: dict = {}

    def enc(node):
        if node is None:
            return {"k": "none"}
        if isinstance(node, bool):
            return {"k": "b", "v": bool(node)}
        if isinstance(node, int) and not isinstance(node, np.generic):
            return {"k": "i", "v": int(node)}
        if isinstance(node, float) and not isinstance(node, np.generic):
            return {"k": "f", "v": float(node)}
        if isinstance(node, str):
            return {"k": "s", "v": node}
        if isinstance(node, dict):
            for key in node:
                if not isinstance(key, str):
                    raise TypeError(
                        f"checkpoint tree keys must be str, got {key!r}")
            return {"k": "d", "v": {key: enc(v)
                                    for key, v in node.items()}}
        if isinstance(node, (list, tuple)):
            return {"k": "l", "v": [enc(v) for v in node]}
        # Array-ish leaf: numpy, numpy scalar, or a device array —
        # pulled to host once (checkpoints are a planned D2H copy).
        a = np.asarray(node)
        key = f"a{len(arrays)}"
        arrays[key] = a
        return {"k": "a", "ref": key}

    return enc(tree), arrays


def unflatten_tree(meta: dict, arrays) -> object:
    """Inverse of ``flatten_tree``; array leaves come back as host
    numpy (callers re-place on device as needed)."""
    k = meta["k"]
    if k == "none":
        return None
    if k in ("b", "i", "f", "s"):
        return meta["v"]
    if k == "d":
        return {key: unflatten_tree(v, arrays)
                for key, v in meta["v"].items()}
    if k == "l":
        return [unflatten_tree(v, arrays) for v in meta["v"]]
    if k == "a":
        return np.asarray(arrays[meta["ref"]])
    raise ValueError(f"unknown checkpoint tree node kind {k!r}")


def _slug(label: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "_", label)


def _load_npz_manifest(path: str):
    """(manifest dict, {key: array}) for an ``atomic_savez`` file, or
    None when absent/unreadable — a checkpoint read can never crash a
    run (degrade to the previous good snapshot instead)."""
    if not os.path.exists(path):
        return None
    try:
        with np.load(path, allow_pickle=False) as z:
            if "__meta__" not in z.files:
                # Pre-reliability utils.checkpoint file (plain np.savez,
                # no manifest) — not corruption.  CD loads fall back to
                # the legacy decoder; solver/stage loads treat it as a
                # miss either way.
                logger.info("checkpoint %s: no manifest (legacy format)",
                            path)
                return None
            meta = json.loads(bytes(np.asarray(z["__meta__"])).decode())
            arrays = {key: np.asarray(z[key]) for key in z.files
                      if key != "__meta__"}
        if meta.get("schema") != CHECKPOINT_SCHEMA:
            logger.warning("checkpoint %s: schema %r != %d; ignoring",
                           path, meta.get("schema"), CHECKPOINT_SCHEMA)
            return None
        return meta, arrays
    except Exception as e:
        logger.warning("checkpoint %s unreadable (%r); ignoring", path, e)
        return None


class RunCheckpointer:
    """One training run's checkpoint directory + cadence policy.

    ``every_sweeps``: CD sweep-boundary snapshot cadence (1 = every
    completed sweep; the final sweep always snapshots).
    ``every_solver_iters``: streaming-solver iteration cadence for
    mid-solve snapshots (0 = off — sweep boundaries only).  Nonzero
    also enables mid-sweep coordinate-boundary snapshots, so a
    multi-coordinate CD resumes at the exact coordinate it died in.

    Thread contract: snapshots are written from the main (driver)
    thread only; the scope stack is plain state.  ``session`` exposes
    the checkpointer to the streaming solvers the same way telemetry
    exposes its session — deep library code cannot thread a handle
    through every call.
    """

    def __init__(self, ckpt_dir: str, every_sweeps: int = 1,
                 every_solver_iters: int = 0, run_logger=None,
                 resume: bool = False):
        if every_sweeps < 1:
            raise ValueError("every_sweeps must be >= 1")
        if every_solver_iters < 0:
            raise ValueError("every_solver_iters must be >= 0")
        self.dir = ckpt_dir
        self.every_sweeps = int(every_sweeps)
        self.every_solver_iters = int(every_solver_iters)
        # True only when THIS run was launched to resume: mid-solve
        # state from a previous process is adopted solely then — a
        # fresh run into a dirty checkpoint dir (crashed predecessor,
        # changed config) must never silently inherit a stale solver
        # loop (review finding).  CD/stage restores are resume-gated at
        # their call sites for the same reason.
        self.resume = bool(resume)
        self._log = run_logger
        self._scope: list[str] = []
        self._claimed = False

    # -- shared write/read plumbing -----------------------------------------

    def _claim_dir(self) -> None:
        """A FRESH run claims its checkpoint directory at first write:
        pre-existing snapshots (an older run's ``cd_iter_*`` /
        ``stage_*`` / ``solver_*`` files — possibly a different config
        or dataset, and the manifests carry no run identity) are
        removed, so a later ``--resume`` can only ever adopt state THIS
        run wrote.  The ``resume=`` gate covers solver-state reads; this
        covers the files a resumed successor would glob (review
        finding)."""
        removed = 0
        for pattern in ("cd_iter_*.npz", "solver_*.npz", "stage_*.npz"):
            for path in glob.glob(os.path.join(self.dir, pattern)):
                try:
                    os.remove(path)
                    removed += 1
                except OSError:  # photon-lint: disable=swallowed-exception (racing cleanup; stale file is superseded below anyway)
                    pass
        for path in (os.path.join(self.dir, "latest"),
                     self._partial_path):
            try:
                os.remove(path)
                removed += 1
            except OSError:  # photon-lint: disable=swallowed-exception (file may not exist — nothing to claim)
                pass
        if removed:
            logger.info("checkpoint dir %s: fresh run removed %d stale "
                        "snapshot file(s) from a previous run",
                        self.dir, removed)
            self._event("checkpoint_dir_claimed", removed=removed)

    def _write(self, path: str, manifest: dict, arrays: dict,
               kind: str) -> None:
        from photon_ml_tpu.cache.plan_cache import atomic_savez

        if not self._claimed:
            self._claimed = True
            if not self.resume:
                self._claim_dir()
        manifest = {"schema": CHECKPOINT_SCHEMA, **manifest}
        atomic_savez(path, manifest, arrays)
        telemetry.count("reliability.checkpoints_saved")
        try:
            telemetry.count("reliability.checkpoint_bytes",
                            os.path.getsize(path))
        except OSError:  # photon-lint: disable=swallowed-exception (best-effort size metric; racing cleanup)
            pass
        if self._log is not None:
            self._log.event("checkpoint_saved", level=kind, path=path)

    def _event(self, kind: str, **fields) -> None:
        if self._log is not None:
            self._log.event(kind, **fields)

    # -- CD level ------------------------------------------------------------

    def _cd_path(self, iteration: int) -> str:
        return os.path.join(self.dir, f"cd_iter_{iteration}.npz")

    @property
    def _partial_path(self) -> str:
        return os.path.join(self.dir, "cd_partial.npz")

    def _cd_payload(self, iteration: int, coord_pos: int, coefs: dict,
                    scores: dict, re_state: dict | None,
                    extra: dict | None) -> tuple[dict, dict]:
        arrays = _flatten(coefs)
        for name, s in (scores or {}).items():
            arrays[f"{name}__score"] = np.asarray(s)
        tree_meta, tree_arrays = flatten_tree(
            {"re_state": re_state or {}, "extra": extra or {}})
        for key, a in tree_arrays.items():
            arrays[_TREE_PREFIX + key] = a
        manifest = {"kind": "cd", "iteration": int(iteration),
                    "coord_pos": int(coord_pos), "tree": tree_meta}
        return manifest, arrays

    def save_cd(self, iteration: int, coefs: dict, scores: dict,
                re_state: dict | None = None,
                extra: dict | None = None) -> str:
        """Sweep-boundary snapshot after completed (1-based) CD
        iteration ``iteration``.  Also purges solver/partial state —
        every mid-solve file is now superseded."""
        os.makedirs(self.dir, exist_ok=True)
        path = self._cd_path(iteration)
        manifest, arrays = self._cd_payload(iteration, 0, coefs, scores,
                                            re_state, extra)
        self._write(path, manifest, arrays, "cd")
        # ``latest`` stays a plain integer: the utils.checkpoint loader
        # (and its pinned tests) read the same pointer.
        tmp = os.path.join(self.dir, "latest.tmp")
        with open(tmp, "w") as f:
            f.write(str(int(iteration)))
        os.replace(tmp, os.path.join(self.dir, "latest"))
        self._clear_transient()
        return path

    def maybe_save_cd(self, iteration: int, coefs: dict, scores: dict,
                      re_state: dict | None = None,
                      extra: dict | None = None,
                      final: bool = False) -> str | None:
        """Cadence-gated ``save_cd``: every ``every_sweeps`` completed
        sweeps, plus always on the final sweep."""
        if final or iteration % self.every_sweeps == 0:
            return self.save_cd(iteration, coefs, scores,
                                re_state=re_state, extra=extra)
        return None

    def save_cd_partial(self, iteration: int, coord_pos: int, coefs: dict,
                        scores: dict, re_state: dict | None = None,
                        extra: dict | None = None) -> str:
        """Mid-sweep snapshot: ``coord_pos`` update-sequence entries of
        sweep ``iteration + 1`` are complete.  One file, atomically
        replaced — the partial plane never accumulates."""
        os.makedirs(self.dir, exist_ok=True)
        manifest, arrays = self._cd_payload(
            iteration, coord_pos, coefs, scores, re_state, extra)
        self._write(self._partial_path, manifest, arrays, "cd_partial")
        return self._partial_path

    @property
    def mid_sweep_enabled(self) -> bool:
        return self.every_solver_iters > 0

    def _decode_cd(self, loaded) -> dict:
        manifest, arrays = loaded
        reserved = {"__meta__"}
        scores = {key.rsplit("__", 1)[0]: arrays[key]
                  for key in arrays if key.endswith("__score")}
        coef_arrays = {key: a for key, a in arrays.items()
                       if key not in reserved
                       and not key.endswith("__score")
                       and not key.startswith(_TREE_PREFIX)}
        tree_arrays = {key[len(_TREE_PREFIX):]: a
                       for key, a in arrays.items()
                       if key.startswith(_TREE_PREFIX)}
        tree = unflatten_tree(manifest["tree"], tree_arrays)
        return {
            "iteration": int(manifest["iteration"]),
            "coord_pos": int(manifest.get("coord_pos", 0)),
            "coefs": _unflatten(_NpzView(coef_arrays)),
            "scores": scores,
            "re_state": tree.get("re_state") or {},
            "extra": tree.get("extra") or {},
        }

    def _load_legacy_cd(self, path: str, iteration: int) -> dict | None:
        """Decode a pre-reliability ``utils.checkpoint`` snapshot (plain
        ``np.savez`` — coefficient/score keys, no ``__meta__``
        manifest), so ``--resume`` into a directory checkpointed by the
        previous release restores the run instead of silently
        restarting at sweep 0."""
        try:
            with np.load(path, allow_pickle=False) as z:
                if "__meta__" in z.files:
                    return None  # new format; handled by the manifest path
                arrays = {key: np.asarray(z[key]) for key in z.files}
        except Exception as e:
            logger.warning("checkpoint %s unreadable (%r); ignoring",
                           path, e)
            return None
        scores = {key.rsplit("__", 1)[0]: arrays[key]
                  for key in arrays if key.endswith("__score")}
        coefs = _unflatten(_NpzView({k: a for k, a in arrays.items()
                                     if not k.endswith("__score")}))
        logger.info("checkpoint %s: restored legacy-format snapshot "
                    "(iteration %d)", path, iteration)
        return {"iteration": int(iteration), "coord_pos": 0,
                "coefs": coefs, "scores": scores,
                "re_state": {}, "extra": {}}

    def load_latest_cd(self) -> dict | None:
        """Most advanced readable CD snapshot (partial beats its own
        sweep boundary; corrupt files degrade to the previous good
        one), or None.  Keys: iteration, coord_pos, coefs, scores,
        re_state, extra."""
        candidates: list[tuple[int, int, str]] = []
        latest = os.path.join(self.dir, "latest")
        if os.path.exists(latest):
            try:
                with open(latest) as f:
                    k = int(f.read().strip())
                candidates.append((k, 0, self._cd_path(k)))
            except (OSError, ValueError) as e:
                logger.warning("checkpoint latest pointer unreadable "
                               "(%r); scanning %s", e, self.dir)
        # Fallback scan: every sweep-boundary file on disk (covers a
        # torn/corrupt pointer AND a corrupt newest snapshot).
        for path in glob.glob(os.path.join(self.dir, "cd_iter_*.npz")):
            m = re.match(r"cd_iter_(\d+)\.npz$", os.path.basename(path))
            if m:
                candidates.append((int(m.group(1)), 0, path))
        loaded_partial = _load_npz_manifest(self._partial_path)
        best: dict | None = None
        if loaded_partial is not None:
            best = self._decode_cd(loaded_partial)

        def key(st: dict) -> tuple[int, int]:
            return (st["iteration"], st["coord_pos"])

        seen: set[str] = set()
        # Boundaries newest-first; the first LOADABLE one dominates all
        # older boundaries, so the scan stops there (a corrupt newest
        # file degrades to the next-newest — one interval lost, not the
        # run).
        for k, _pos, path in sorted(candidates, reverse=True):
            if path in seen:
                continue
            seen.add(path)
            if best is not None and (k, 0) <= key(best):
                break
            loaded = _load_npz_manifest(path)
            st = (self._decode_cd(loaded) if loaded is not None
                  else self._load_legacy_cd(path, k))
            if st is None:
                continue
            if best is None or key(st) > key(best):
                best = st
            break
        if best is not None:
            telemetry.count("reliability.resumes")
            self._event("checkpoint_resume",
                        iteration=best["iteration"],
                        coord_pos=best["coord_pos"])
        return best

    # -- solver level --------------------------------------------------------

    @contextlib.contextmanager
    def scope(self, *parts: str):
        """Position context for solver labels: the CD loop pushes
        (iteration, coordinate) so a resumed run can only adopt solver
        state from its own position."""
        self._scope.extend(str(p) for p in parts)
        try:
            yield self
        finally:
            del self._scope[len(self._scope) - len(parts):]

    def solver_label(self, label: str) -> str:
        return "/".join([*self._scope, label or "solve"])

    def _solver_path(self, label: str) -> str:
        return os.path.join(self.dir, f"solver_{_slug(label)}.npz")

    def maybe_save_solver(self, label: str, it: int, state: dict) -> bool:
        """Cadence-gated mid-solve snapshot (``every_solver_iters``;
        0 disables).  ``state`` is a checkpoint tree; ``it`` rides in
        it so restore re-enters the loop at the right iteration."""
        if (self.every_solver_iters <= 0
                or it % self.every_solver_iters != 0):
            return False
        os.makedirs(self.dir, exist_ok=True)
        tree_meta, arrays = flatten_tree({"it": int(it), **state})
        self._write(self._solver_path(label),
                    {"kind": "solver", "label": label, "tree": tree_meta},
                    arrays, "solver")
        return True

    def load_solver(self, label: str) -> dict | None:
        if not self.resume:
            return None
        loaded = _load_npz_manifest(self._solver_path(label))
        if loaded is None:
            return None
        manifest, arrays = loaded
        if manifest.get("label") != label:
            return None
        state = unflatten_tree(manifest["tree"], arrays)
        telemetry.count("reliability.solver_resumes")
        self._event("checkpoint_solver_resume", label=label,
                    iteration=int(state.get("it", 0)))
        return state

    def clear_solver(self, label: str) -> None:
        try:
            os.remove(self._solver_path(label))
        except OSError:  # photon-lint: disable=swallowed-exception (file may never have been written at this cadence)
            pass

    def _clear_transient(self) -> None:
        """Drop mid-solve and mid-sweep files a sweep-boundary snapshot
        supersedes."""
        for path in glob.glob(os.path.join(self.dir, "solver_*.npz")):
            try:
                os.remove(path)
            except OSError:  # photon-lint: disable=swallowed-exception (racing writer; stale file is label-gated anyway)
                pass
        try:
            os.remove(self._partial_path)
        except OSError:  # photon-lint: disable=swallowed-exception (no partial snapshot at this boundary)
            pass

    # -- stage level (swept lanes, tuner history) ----------------------------

    def _stage_path(self, name: str) -> str:
        return os.path.join(self.dir, f"stage_{_slug(name)}.npz")

    def save_stage(self, name: str, tree: dict) -> str:
        os.makedirs(self.dir, exist_ok=True)
        tree_meta, arrays = flatten_tree(tree)
        path = self._stage_path(name)
        self._write(path, {"kind": "stage", "name": name,
                           "tree": tree_meta}, arrays, f"stage:{name}")
        return path

    def load_stage(self, name: str) -> dict | None:
        loaded = _load_npz_manifest(self._stage_path(name))
        if loaded is None:
            return None
        manifest, arrays = loaded
        if manifest.get("name") != name:
            return None
        return unflatten_tree(manifest["tree"], arrays)

    def clear_stage(self, name: str) -> None:
        try:
            os.remove(self._stage_path(name))
        except OSError:  # photon-lint: disable=swallowed-exception (stage may never have been saved)
            pass


# ---------------------------------------------------------------------------
# Active-session plumbing (the telemetry pattern): the streaming solvers
# are deep library code that cannot thread a checkpointer through every
# call — they consult the active session instead.
# ---------------------------------------------------------------------------

_ACTIVE: list[RunCheckpointer] = []
_ACTIVE_LOCK = threading.Lock()


def active() -> RunCheckpointer | None:
    """The innermost active checkpointer, or None."""
    with _ACTIVE_LOCK:
        return _ACTIVE[-1] if _ACTIVE else None


@contextlib.contextmanager
def session(ck: RunCheckpointer | None):
    """Expose ``ck`` to ``active()`` for the block; None yields a
    no-op (callers never branch on checkpointing-enabled)."""
    if ck is None:
        yield None
        return
    with _ACTIVE_LOCK:
        _ACTIVE.append(ck)
    try:
        yield ck
    finally:
        with _ACTIVE_LOCK:
            _ACTIVE.remove(ck)
