"""Bounded exponential-backoff retry for transient I/O.

Reference counterpart: Spark's task-retry policy (``spark.task
.maxFailures``) — the platform layer that turns a flaky disk read into
a retried task instead of a dead job.  The rebuild's equivalent is this
ONE helper, used by the chunk store's load and spill paths: bounded
attempts, exponential backoff, and telemetry so retries are visible
(``store.retries`` counts every retried attempt, ``store.gave_up``
every exhausted budget) and waits are heartbeat-visible in the run log
(a backoff sleep must look like a deliberate wait, not a hang).

Classification is deliberately narrow: only OSErrors whose errno is in
``TRANSIENT_ERRNOS`` retry.  ENOSPC is a capacity fact (retrying
cannot help — the caller raises one actionable error), ENOENT is a
lineage fact (the chunk store rebuilds), corruption (ValueError /
BadZipFile) is a content fact (rebuild).  Deterministic backoff — no
RNG jitter — so fault-matrix runs reproduce exactly.
"""

from __future__ import annotations

import errno
import logging
import time

from photon_ml_tpu import telemetry

logger = logging.getLogger(__name__)

# Retry budget defaults (overridable per call site).
IO_ATTEMPTS = 3
IO_BASE_DELAY_S = 0.05
IO_MAX_DELAY_S = 2.0

# OSError errnos worth retrying: device/transport hiccups that a
# bounded backoff can outlive.  Capacity (ENOSPC), permission (EACCES/
# EROFS/EPERM), and existence (ENOENT) errors are excluded — retrying
# cannot change them.
TRANSIENT_ERRNOS = frozenset({
    errno.EIO, errno.EINTR, errno.EAGAIN, errno.EBUSY, errno.ETIMEDOUT,
    errno.ENFILE, errno.EMFILE, errno.ESTALE,
})


def is_transient(e: BaseException) -> bool:
    return isinstance(e, OSError) and e.errno in TRANSIENT_ERRNOS


def run_with_retries(fn, label: str, attempts: int = IO_ATTEMPTS,
                     base_delay_s: float = IO_BASE_DELAY_S,
                     max_delay_s: float = IO_MAX_DELAY_S,
                     retriable=is_transient,
                     retry_counter: str = "store.retries",
                     gave_up_counter: str = "store.gave_up"):
    """Run ``fn()`` with up to ``attempts`` tries.

    Non-retriable errors propagate immediately; a retriable error on
    the last attempt counts ``gave_up_counter`` and propagates — the
    caller decides whether a degradation (rebuild) or an actionable
    error follows.  Backoff doubles per attempt, capped."""
    attempts = max(1, int(attempts))
    for attempt in range(attempts):
        try:
            return fn()
        except BaseException as e:
            if not retriable(e):
                raise
            if attempt == attempts - 1:
                telemetry.count(gave_up_counter)
                logger.warning("%s: giving up after %d attempts (%r)",
                               label, attempts, e)
                raise
            delay = min(base_delay_s * (2.0 ** attempt), max_delay_s)
            telemetry.count(retry_counter)
            telemetry.heartbeat("io-retry", label=label,
                               attempt=attempt + 1,
                               delay_s=round(delay, 3), error=repr(e))
            logger.warning("%s: attempt %d/%d failed (%r); retrying in "
                           "%.3fs", label, attempt + 1, attempts, e,
                           delay)
            time.sleep(delay)
