"""Deterministic fault injection at the streaming tier's seams.

The fault matrix (ISSUE 9) asserts that every failure the out-of-core
pipeline can meet — corrupt chunk, deleted chunk, slow read, transient
read error, ENOSPC on spill, prefetcher/sink thread death, device_put
failure — ends in a bounded retry, a documented degradation, or ONE
actionable error: never a hang, never a torn output.  That contract is
only testable if the faults are INJECTABLE, deterministically, at the
seams where they occur in production:

- ``store.load`` — fired in ``ChunkStore._load`` per read attempt.
- ``store.spill`` — fired in ``ChunkStore.put`` per write attempt.
- ``prefetch.load`` / ``prefetch.place`` — fired on the prefetch
  thread around the disk-read and device_put stages.
- ``sink.write`` — fired on the score sink-writer thread per chunk.

The serving fault matrix (ISSUE 13) adds the request-path seams:

- ``serve.store_load`` — fired in ``EntityServeStore`` per chunk read
  on the scoring hot path (slow store, transient/persistent I/O →
  retries then fixed-effect-only degradation).
- ``serve.dispatch`` — fired in ``ScoringEngine.score_batch`` before
  the fused device dispatch (wedged/failing device → answered error
  for the whole batch, never a hang).
- ``serve.manifest_load`` — fired in ``ModelServer._load_engine``
  with the manifest path (corrupt/torn swap → keep previous model).
- ``serve.replica_healthz`` — fired in the fleet supervisor's probe
  (flaky/wedged health probe → unhealthy-replica restart policy).

The multi-host training fault matrix (ISSUE 16) adds:

- ``fleet.reduce`` — fired in ``FleetReducer.reduce`` before each
  cross-host reduction, with ``seq=<reduce sequence number>`` context
  (the ``kill`` kind here simulates a host dying mid-sweep: peers hold
  at the chunk barrier and the restarted host replays from its
  per-host checkpoint, answered by the coordinator's done-cache).

A ``FaultInjector`` holds a list of ``Fault`` specs, each targeting a
site's Nth occurrence (per-site occurrence counters under one lock, so
multi-threaded sites count deterministically given a deterministic
visit order).  ``seeded_plan`` derives occurrence indices from an RNG
seed — the "chaos schedule" form — while tests mostly pin exact
occurrences.  With no injector installed the seam is a module-global
None check: zero overhead on the production hot path.
"""

from __future__ import annotations

import contextlib
import dataclasses
import errno
import logging
import os
import threading
import time

from photon_ml_tpu import telemetry

logger = logging.getLogger(__name__)

KINDS = ("error", "io_error", "enospc", "slow", "corrupt_file",
         "delete_file", "kill")


class InjectedFault(RuntimeError):
    """A deliberately injected hard failure (thread-death class)."""


@dataclasses.dataclass(frozen=True)
class Fault:
    """One planned fault: site × occurrence window × effect.

    ``at`` is the 0-based occurrence index of ``site`` at which the
    fault first fires; ``count`` consecutive occurrences fire (a
    persistent fault = large count).  ``delay_s`` applies to ``slow``;
    ``message`` rides in raised errors."""

    site: str
    kind: str
    at: int = 0
    count: int = 1
    delay_s: float = 0.05
    message: str = "injected fault"

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"fault kind {self.kind!r} not in {KINDS}")


class FaultInjector:
    """Executes a fault plan at ``fire`` call sites."""

    def __init__(self, faults: list[Fault]):
        self._by_site: dict[str, list[Fault]] = {}
        for f in faults:
            self._by_site.setdefault(f.site, []).append(f)
        self._lock = threading.Lock()
        self._hits: dict[str, int] = {}
        self.fired: list[tuple[str, str, int]] = []  # (site, kind, occ)

    def occurrences(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def fire(self, site: str, path: str | None = None, **ctx) -> None:
        faults = self._by_site.get(site)
        with self._lock:
            n = self._hits.get(site, 0)
            self._hits[site] = n + 1
        if not faults:
            return
        for f in faults:
            if not f.at <= n < f.at + f.count:
                continue
            with self._lock:
                self.fired.append((site, f.kind, n))
            telemetry.count("reliability.faults_injected")
            logger.info("fault injected: %s/%s at occurrence %d (%s)",
                        site, f.kind, n, ctx or path or "")
            self._apply(f, site, path)

    @staticmethod
    def _apply(f: Fault, site: str, path: str | None) -> None:
        if f.kind == "slow":
            time.sleep(f.delay_s)
        elif f.kind == "error":
            raise InjectedFault(f"{f.message} [site={site}]")
        elif f.kind == "io_error":
            raise OSError(errno.EIO, f"{f.message} [site={site}]", path)
        elif f.kind == "enospc":
            raise OSError(errno.ENOSPC,
                          f"No space left on device ({f.message})", path)
        elif f.kind == "corrupt_file":
            if path and os.path.exists(path):
                with open(path, "r+b") as fh:
                    fh.write(b"CORRUPTED-BY-FAULT-PLAN")
        elif f.kind == "delete_file":
            if path and os.path.exists(path):
                os.remove(path)
        elif f.kind == "kill":
            # Simulated host death (fleet fault matrix): the process dies
            # without flushing or unwinding, exactly like an OOM-kill or a
            # preempted VM.  Peers must survive the barrier stall and the
            # restarted host must resume from its per-host checkpoint.
            import signal

            os.kill(os.getpid(), signal.SIGKILL)


def seeded_plan(seed: int, site_kinds: dict[str, str],
                horizon: int = 32) -> FaultInjector:
    """Deterministic seeded plan: one fault per (site, kind) entry at
    an RNG-drawn occurrence in [0, horizon) — same seed, same plan,
    everywhere."""
    import numpy as np

    rng = np.random.default_rng(seed)
    faults = [Fault(site=site, kind=kind,
                    at=int(rng.integers(0, max(1, horizon))))
              for site, kind in sorted(site_kinds.items())]
    return FaultInjector(faults)


# ---------------------------------------------------------------------------
# Module-global installation (the seam contract: one None check when
# injection is off — the production path must not pay for testability).
# ---------------------------------------------------------------------------

_INJECTOR: FaultInjector | None = None


def fire(site: str, path: str | None = None, **ctx) -> None:
    """The seam call.  No-op unless an injector is installed."""
    inj = _INJECTOR
    if inj is not None:
        inj.fire(site, path=path, **ctx)


def install(inj: FaultInjector | None) -> None:
    global _INJECTOR
    _INJECTOR = inj


@contextlib.contextmanager
def injected(inj: FaultInjector):
    """Install ``inj`` for the block (tests)."""
    install(inj)
    try:
        yield inj
    finally:
        install(None)
