"""Reliability tier: checkpoint/resume, fault injection, bounded-retry
I/O (ISSUE 9).

ROADMAP item 1 targets a 1.5e8-example multi-host fit — hours of wall
clock on a mesh — and until this round any SIGKILL, ENOSPC, dead
prefetcher thread, or corrupt chunk lost the entire run; the only
recovery machinery was the chunk store's lineage rebuild and a
``thread_exception`` forensic event.  Snap ML's hierarchical pipeline
and the Spark function-minimization reference (PAPERS.md) both
presuppose the PLATFORM's re-execution/fault-tolerance layer; a
jax_graft rebuild has to supply its own:

- ``reliability.checkpoint`` — atomic, content-addressed run-state
  snapshots (CD loop position, coefficients, streaming-solver state,
  RE retirement sets, λ-sweep lane state, tuner history) on a
  configurable cadence, with ``--resume`` on the training driver
  restoring mid-fit.
- ``reliability.faults`` — a deterministic, seeded fault plan injected
  at the chunk-store / prefetcher / sink seams, driving the pytest
  fault matrix: every injected fault must end in a bounded retry, a
  documented degradation, or ONE actionable error — never a hang or a
  torn output.
- ``reliability.retry`` — bounded exponential-backoff retry for
  transient I/O, with ``store.retries`` / ``store.gave_up`` telemetry
  and heartbeat-visible waits.
"""

from photon_ml_tpu.reliability.checkpoint import RunCheckpointer  # noqa: F401
from photon_ml_tpu.reliability.faults import Fault, FaultInjector  # noqa: F401
