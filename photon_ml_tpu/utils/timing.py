"""Device timing that survives async dispatch tunnels.

``jax.block_until_ready`` is the documented way to fence device work, but
on remote-tunneled backends (e.g. the axon TPU plugin in this image) the
client-side buffer can report ready while the device queue is still
draining — measured here as an 8192^3 matmul "completing" in 0.07 ms
(16,700 TFLOP/s on a v5e whose peak is ~200).  The only reliable fence is
a host fetch, which cannot complete before the producing program has run.

``device_fence`` fetches one scalar element of the last leaf (minimal
transfer).  ``measure`` times ``iters`` back-to-back dispatches and
fences once at the end: per-device queues execute programs in FIFO
order, so (total / iters) is the true per-call device time once the
queue depth exceeds the dispatch latency.  A measured ~5-6 ms fixed
dispatch overhead per call means single-call timings are meaningless for
sub-10ms kernels — always measure loops, or wrap the iteration in
``lax.scan`` (see ``measure_scanned``).
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import numpy as np


def device_fence(out: Any) -> None:
    """Block until ``out`` has actually been computed on device."""
    leaves = jax.tree_util.tree_leaves(out)
    if not leaves:
        return
    leaf = leaves[-1]
    if hasattr(leaf, "ravel") and getattr(leaf, "size", 1) > 0:
        np.asarray(jax.device_get(leaf.ravel()[-1:]))
    else:
        np.asarray(jax.device_get(leaf))


def measure(fn: Callable, *args, iters: int = 20, warmup: int = 1) -> float:
    """Median-free queue-drain timing: seconds per call.

    Dispatches ``iters`` calls back to back and fences once; the queue
    serializes execution, so dispatch overhead overlaps device work.
    """
    out = None
    for _ in range(max(warmup, 1)):
        out = fn(*args)
    device_fence(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    device_fence(out)
    return (time.perf_counter() - t0) / iters


def measure_scanned(fn: Callable, *args, length: int = 10,
                    iters: int = 3) -> float:
    """Seconds per call with the loop inside one jitted ``lax.scan``.

    Removes per-dispatch overhead entirely; ``fn``'s first argument is
    treated as the loop carry (its output must match its shape/dtype).
    """
    import jax.numpy as jnp  # noqa: F401  (kept local: utils stays light)

    def chain(carry, *rest):
        def body(c, _):
            return fn(c, *rest), None
        out, _ = jax.lax.scan(body, carry, None, length=length)
        return out

    # photon-lint: disable=jit-in-function (measurement harness, by design)
    chained = jax.jit(chain)
    return measure(chained, *args, iters=iters) / length
