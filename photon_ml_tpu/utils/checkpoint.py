"""Coordinate-descent checkpointing: save/restore training state.

Reference counterpart: the reference has NO mid-optimizer checkpointing —
its recovery points are whole saved models (``ModelOutputMode``,
warm-start re-load; SURVEY.md §5.4).  The rebuild adds the honest TPU
equivalent the survey calls for: a checkpoint of (per-coordinate
coefficients, finished CD iteration) after every outer iteration, so a
preempted run resumes at the last completed sweep instead of from
scratch.  TPU slices fail as a unit — checkpoint/restart IS the failure
-recovery story (no per-task lineage retry exists to lean on).

Format: one ``cd_iter_<k>.npz`` per completed iteration + a ``latest``
text pointer, all host-side numpy (pulled from device once per outer
iteration — negligible next to the solves).  Fixed-effect coefficients
are flat arrays; random-effect coefficients are per-bucket block lists.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np


def _flatten(coefs: dict) -> dict:
    """coordinate → Array | list[Array]  ⇒  flat npz-key dict."""
    arrs = {}
    for name, w in coefs.items():
        if isinstance(w, (list, tuple)):
            arrs[f"{name}__nblocks"] = np.asarray(len(w))
            for b, blk in enumerate(w):
                arrs[f"{name}__block_{b}"] = np.asarray(blk)
        else:
            arrs[f"{name}__flat"] = np.asarray(w)
    return arrs


def _unflatten(data) -> dict:
    coefs: dict = {}
    for key in data.files:
        name, kind = key.rsplit("__", 1)
        if kind == "flat":
            coefs[name] = jnp.asarray(data[key])
        elif kind == "nblocks":
            coefs[name] = [
                jnp.asarray(data[f"{name}__block_{b}"])
                for b in range(int(data[key]))
            ]
    return coefs


def save_checkpoint(ckpt_dir: str, iteration: int, coefs: dict,
                    scores: dict | None = None) -> str:
    """Persist state after completed CD iteration ``iteration`` (1-based).

    ``scores`` (coordinate → [n] array) captures the coordinate-descent
    score state: restoring it makes a resumed run's offsets *bitwise*
    equal to the uninterrupted run's (re-scoring from coefficients would
    rebuild the total as a fresh sum, while the live loop accumulates it
    incrementally — a float-reordering difference that optimization then
    amplifies)."""
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"cd_iter_{iteration}.npz")
    tmp = path + ".tmp"
    arrs = _flatten(coefs)
    for name, s in (scores or {}).items():
        arrs[f"{name}__score"] = np.asarray(s)
    with open(tmp, "wb") as f:
        np.savez(f, **arrs)
    os.replace(tmp, path)  # atomic: a crash never leaves a torn "latest"
    with open(os.path.join(ckpt_dir, "latest.tmp"), "w") as f:
        f.write(str(iteration))
    os.replace(os.path.join(ckpt_dir, "latest.tmp"),
               os.path.join(ckpt_dir, "latest"))
    return path


def load_latest_checkpoint(
    ckpt_dir: str,
) -> tuple[int, dict, dict] | None:
    """(completed_iteration, coefficients, scores) or None.

    ``scores`` is empty for checkpoints written before scores were
    saved (the caller re-scores from coefficients)."""
    latest = os.path.join(ckpt_dir, "latest")
    if not os.path.exists(latest):
        return None
    with open(latest) as f:
        iteration = int(f.read().strip())
    path = os.path.join(ckpt_dir, f"cd_iter_{iteration}.npz")
    with np.load(path) as data:
        scores = {
            key.rsplit("__", 1)[0]: jnp.asarray(data[key])
            for key in data.files if key.endswith("__score")
        }
        coefs = _unflatten(
            _NpzView({k: data[k] for k in data.files
                      if not k.endswith("__score")})
        )
        return iteration, coefs, scores


class _NpzView:
    """Minimal files/getitem adapter so _unflatten reads a dict."""

    def __init__(self, data: dict):
        self._data = data
        self.files = list(data)

    def __getitem__(self, key):
        return self._data[key]
