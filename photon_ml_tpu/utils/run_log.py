"""Run logging: structured JSONL event log + phase wall-clock timers.

Reference counterparts: ``PhotonLogger`` (a log file written to the
output dir in addition to log4j) and the ``Timed { }`` driver-phase
timer utility (photon-client/photon-api utils [expected paths, mount
unavailable — see SURVEY.md §5.1/§5.5]).

The rebuild upgrades free-text logs to structured JSONL — one event per
line with a monotonic timestamp — so convergence traces and phase
timings are machine-readable (the reference's observability gap).  The
same events also go to the stdlib logger for human eyes.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time

logger = logging.getLogger("photon_ml_tpu")


class RunLogger:
    """JSONL event sink; the reference's PhotonLogger role.

    Events: ``{"t": <seconds-since-start>, "event": <kind>, ...}``.
    A ``None`` path makes it a pure stdlib-logging sink (tests, library
    use); drivers point it at ``<output_dir>/run_log.jsonl``.
    """

    def __init__(self, path: str | None = None, mode: str = "w"):
        """``mode="w"`` (default) makes each run's log self-contained —
        rerunning into the same output dir must not interleave events
        from prior runs; pass ``"a"`` to accumulate deliberately."""
        self.path = path
        self._t0 = time.monotonic()
        self._f = None
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._f = open(path, mode)

    def event(self, kind: str, **fields) -> None:
        rec = {"t": round(time.monotonic() - self._t0, 6), "event": kind}
        rec.update(fields)
        if self._f is not None:
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
        logger.info("%s %s", kind, fields)

    @contextlib.contextmanager
    def timed(self, phase: str, profile_dir: str | None = None, **fields):
        """The reference's ``Timed { }``: log phase start/end + duration.

        ``profile_dir``: when set, the phase also runs under
        ``jax.profiler.trace`` — a TensorBoard/XProf device trace lands
        there (SURVEY §5.1: tracing is a first-class aux subsystem).
        """
        self.event("phase_start", phase=phase, **fields)
        start = time.monotonic()
        prof = contextlib.nullcontext()
        if profile_dir:
            import jax

            prof = jax.profiler.trace(profile_dir)
        try:
            with prof:
                yield
        finally:
            self.event(
                "phase_end", phase=phase,
                duration_s=round(time.monotonic() - start, 6),
                **({"profile_dir": profile_dir} if profile_dir else {}),
                **fields,
            )

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def read_run_log(path: str) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
