"""Run logging: structured JSONL event log + phase wall-clock timers.

Reference counterparts: ``PhotonLogger`` (a log file written to the
output dir in addition to log4j) and the ``Timed { }`` driver-phase
timer utility (photon-client/photon-api utils [expected paths, mount
unavailable — see SURVEY.md §5.1/§5.5]).

The rebuild upgrades free-text logs to structured JSONL — one event per
line with a monotonic timestamp — so convergence traces and phase
timings are machine-readable (the reference's observability gap).  The
same events also go to the stdlib logger for human eyes.

Since ISSUE 7 the logger is the telemetry tier's event channel too:
``event`` is thread-safe (heartbeats arrive from prefetch/sink
threads), ``timed`` phases double as telemetry spans when a session is
active, and the file handle has a real lifecycle — ``close()``,
context-manager support, and an ``atexit`` flush fallback so an
abandoned logger can no longer leak its handle (or its last buffered
events) on interpreter exit.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import logging
import os
import sys
import threading
import time
import uuid

logger = logging.getLogger("photon_ml_tpu")

# run_header schema version (ISSUE 8): bump when header fields change
# meaning; report/history consumers key their parsing on it and must
# tolerate ABSENCE entirely (pre-ISSUE-8 logs have no header).
RUN_LOG_SCHEMA = 1

# Cadence-flush default for the drivers (ISSUE 10): a live consumer
# (`telemetry watch`, crash forensics) sees events at most this stale,
# while hot instrumented paths stop paying one flush syscall per line.
DEFAULT_FLUSH_EVERY_S = 2.0

# Events a live consumer (or a post-mortem) must never find missing:
# flushed immediately regardless of the cadence.  ``progress`` is
# already cadence-throttled at the monitor, so flushing each one costs
# nothing extra and keeps `watch` within one snapshot cadence of truth.
_FLUSH_NOW = frozenset({
    "run_header", "alert", "thread_exception", "progress",
    "phase_start", "phase_end", "telemetry_summary", "monitor_summary",
    "status_server", "done",
})


def _runtime_info() -> dict:
    """Best-effort runtime facts for the header: jax version/platform
    only when jax is ALREADY imported (a header must never pull a
    backend into a host-only driver), configured-platform string over
    backend init for the same reason."""
    info = {
        "schema": RUN_LOG_SCHEMA,
        "run_id": uuid.uuid4().hex[:12],
        "argv": list(sys.argv),
        "pid": os.getpid(),
        "host_platform": sys.platform,
    }
    jax = sys.modules.get("jax")
    if jax is not None:
        info["jax"] = getattr(jax, "__version__", None)
        try:
            platforms = jax.config.jax_platforms
        except Exception:
            platforms = None
        if platforms:
            info["jax_platforms"] = platforms
    return info


class RunLogger:
    """JSONL event sink; the reference's PhotonLogger role.

    Events: ``{"t": <seconds-since-start>, "event": <kind>, ...}``.
    A ``None`` path makes it a pure stdlib-logging sink (tests, library
    use); drivers point it at ``<output_dir>/run_log.jsonl``.
    """

    def __init__(self, path: str | None = None, mode: str = "w",
                 run_info: dict | None = None,
                 header: bool | None = None,
                 flush_every_s: float | None = None):
        """``mode="w"`` (default) makes each run's log self-contained —
        rerunning into the same output dir must not interleave events
        from prior runs; pass ``"a"`` to accumulate deliberately.

        A schema-versioned ``run_header`` event (run id, argv, jax
        version, platform — plus caller facts via ``run_info``, e.g.
        the telemetry mode) is written as the FIRST JSONL line of every
        fresh file; append mode skips it by default (the original
        header stands).  ``header`` overrides that default: a RESUMED
        driver run appends WITH a header, so the stitched log carries
        one ``run_header`` per process segment and ``telemetry
        report`` can reconcile the segments separately (their clocks
        restart at each header).  ``report``/``history`` consume it and
        tolerate its absence in pre-existing logs.

        ``flush_every_s`` (ISSUE 10): None (default) flushes after
        EVERY event — maximal freshness for library/test use; a
        positive cadence batches flushes so a hot instrumented path
        pays one syscall per cadence window instead of per line, while
        ``_FLUSH_NOW`` event kinds (headers, alerts, progress
        snapshots, thread deaths, phase boundaries) still flush
        immediately — a live ``telemetry watch`` and a kill-forensic
        read both stay current.  Drivers pass
        ``DEFAULT_FLUSH_EVERY_S``."""
        self.path = path
        self._t0 = time.monotonic()
        self._f = None
        if flush_every_s is not None and flush_every_s < 0:
            raise ValueError(
                f"flush_every_s must be >= 0, got {flush_every_s!r}")
        self._flush_every_s = flush_every_s
        self._last_flush = time.monotonic()
        self.run_info = dict(run_info or {})
        # Events arrive from pipeline threads too (telemetry heartbeats,
        # span merges): one lock keeps lines whole and the handle state
        # coherent (photon-lint unlocked-shared-write contract).
        self._lock = threading.Lock()
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._f = open(path, mode)
            if mode == "a":
                # A killed predecessor can leave a TORN final line with
                # no newline; appending straight after it would fuse
                # this run's first event into the garbage.  Terminate
                # the tail so the stitch is line-clean (ISSUE 9).
                torn = False
                try:
                    with open(path, "rb") as tail:
                        tail.seek(0, os.SEEK_END)
                        if tail.tell() > 0:
                            tail.seek(-1, os.SEEK_END)
                            torn = tail.read(1) != b"\n"
                    if torn:
                        self._f.write("\n")
                except OSError:  # photon-lint: disable=swallowed-exception (tail probe is best-effort; worst case is one fused line, the pre-fix behavior)
                    pass
            # Flush fallback: a logger abandoned without close() (the
            # pre-ISSUE-7 driver bug) still lands its buffered tail on
            # interpreter exit.  Unregistered again in close().
            atexit.register(self.close)
            if header if header is not None else mode == "w":
                self.event("run_header", **_runtime_info(),
                           **self.run_info)

    def now(self) -> float:
        """Seconds on this logger's monotonic clock (the ``t`` field);
        telemetry spans stamp themselves on the same clock."""
        return time.monotonic() - self._t0

    def event(self, kind: str, **fields) -> None:
        rec = {"t": round(self.now(), 6), "event": kind}
        rec.update(fields)
        with self._lock:
            if self._f is not None:
                self._f.write(json.dumps(rec) + "\n")
                now_m = time.monotonic()
                if (not self._flush_every_s or kind in _FLUSH_NOW
                        or now_m - self._last_flush
                        >= self._flush_every_s):
                    self._f.flush()
                    self._last_flush = now_m
        logger.info("%s %s", kind, fields)

    def flush(self) -> None:
        """Force buffered events to disk (the cadence path flushes on
        its own; this is for callers handing the file to a reader)."""
        with self._lock:
            if self._f is not None:
                self._f.flush()
                self._last_flush = time.monotonic()

    @contextlib.contextmanager
    def timed(self, phase: str, profile_dir: str | None = None, **fields):
        """The reference's ``Timed { }``: log phase start/end + duration.

        ``profile_dir``: when set, the phase also runs under
        ``jax.profiler.trace`` — a TensorBoard/XProf device trace lands
        there (SURVEY §5.1: tracing is a first-class aux subsystem).

        When a telemetry session is active the phase is also a span
        (cat ``phase``), so driver phases appear on the trace timeline
        and in the report's reconciliation alongside the streaming
        tier's stage spans.
        """
        from photon_ml_tpu import telemetry
        from photon_ml_tpu.telemetry import monitor as _monitor

        self.event("phase_start", phase=phase, **fields)
        # The live monitor's /status "phase" field tracks the innermost
        # open driver phase (no-op when monitoring is off, ISSUE 10).
        _monitor.phase_begin(phase)
        start = time.monotonic()
        prof = contextlib.nullcontext()
        if profile_dir:
            import jax

            prof = jax.profiler.trace(profile_dir)
        try:
            with telemetry.span(phase, cat="phase"), prof:
                yield
        finally:
            _monitor.phase_end(phase)
            self.event(
                "phase_end", phase=phase,
                duration_s=round(time.monotonic() - start, 6),
                **({"profile_dir": profile_dir} if profile_dir else {}),
                **fields,
            )

    def close(self) -> None:
        """Flush and release the file handle.  Idempotent (also runs
        as the atexit fallback)."""
        with self._lock:
            f, self._f = self._f, None
        if f is not None:
            f.close()
            # An explicitly closed logger must not resurrect at exit
            # (atexit holds a ref to the bound method otherwise).
            with contextlib.suppress(Exception):
                atexit.unregister(self.close)

    def __enter__(self) -> "RunLogger":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False


def read_run_log(path: str) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]
