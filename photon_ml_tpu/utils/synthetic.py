"""Deterministic synthetic datasets standing in for the reference fixtures.

The reference's integration tests run on small Avro fixtures (a1a-style
binary classification, Yahoo-music-style user/song random effects —
SURVEY.md §4 tier 3).  This environment has no network, so equivalent
datasets are generated deterministically: same shapes, same statistical
character (sparse binary indicator features, power-law entity sizes),
fixed seeds.  They serve as the permanent parity fixtures and the
benchmark inputs.
"""

from __future__ import annotations

import numpy as np


def make_a1a_like(
    n: int = 3000,
    dim: int = 123,
    nnz_per_row: int = 14,
    seed: int = 7,
) -> tuple[list[tuple[np.ndarray, np.ndarray]], np.ndarray, np.ndarray]:
    """a1a-shaped binary classification: sparse 0/1 indicator features.

    a1a (Adult) has d=123 binary features, ~14 nnz/row.  Labels follow a
    sparse logistic ground truth with an achievable AUC in the high .80s,
    matching the class of threshold the reference's a1a fixtures gate on.

    Returns (rows, labels01, w_true).
    """
    rng = np.random.default_rng(seed)
    # Feature popularity is skewed (indicator features from categorical
    # one-hots): sample columns with a Zipf-ish distribution.
    popularity = 1.0 / np.arange(1, dim + 1) ** 0.7
    popularity /= popularity.sum()
    w_true = np.zeros(dim)
    active = rng.choice(dim, size=25, replace=False)
    w_true[active] = rng.normal(0, 1.6, size=25)

    rows = []
    margins = np.empty(n)
    for i in range(n):
        k = int(np.clip(rng.poisson(nnz_per_row), 3, dim))
        cols = np.sort(
            rng.choice(dim, size=k, replace=False, p=popularity)
        ).astype(np.int32)
        vals = np.ones(k, np.float32)
        rows.append((cols, vals))
        margins[i] = w_true[cols].sum()
    margins -= margins.mean()
    p = 1.0 / (1.0 + np.exp(-margins))
    labels = (rng.uniform(size=n) < p).astype(np.float32)
    return rows, labels, w_true


def make_movielens_like(
    n_users: int = 200,
    n_items: int = 100,
    n_obs: int = 8000,
    dim_global: int = 20,
    seed: int = 11,
) -> dict:
    """Mixed-effect data: global features + per-user and per-item effects.

    The GAME analog of the reference's Yahoo-music integration fixture:
    response = sigmoid(x·w_global + u_user + b_item-ish per-entity effects)
    with power-law entity frequencies (the skew that makes random-effect
    bucketing hard, SURVEY.md §7 "hard parts").

    Returns dict with x [n,dim_global], user_ids, item_ids, labels, and
    the ground-truth effects.
    """
    rng = np.random.default_rng(seed)
    w_global = rng.normal(0, 1.0, dim_global)
    # Per-entity coefficient vectors over a small per-entity feature space
    # (intercept-only effects here; richer RE features in game tests).
    u_eff = rng.normal(0, 1.2, n_users)
    i_eff = rng.normal(0, 0.8, n_items)

    user_pop = 1.0 / np.arange(1, n_users + 1) ** 1.1
    user_pop /= user_pop.sum()
    item_pop = 1.0 / np.arange(1, n_items + 1) ** 0.8
    item_pop /= item_pop.sum()

    users = rng.choice(n_users, size=n_obs, p=user_pop)
    items = rng.choice(n_items, size=n_obs, p=item_pop)
    x = rng.normal(0, 1, (n_obs, dim_global)).astype(np.float32)
    margins = x @ w_global + u_eff[users] + i_eff[items]
    p = 1.0 / (1.0 + np.exp(-margins))
    labels = (rng.uniform(size=n_obs) < p).astype(np.float32)
    return {
        "x": x,
        "user_ids": users.astype(np.int32),
        "item_ids": items.astype(np.int32),
        "labels": labels,
        "w_global": w_global,
        "user_effects": u_eff,
        "item_effects": i_eff,
    }
