"""Estimator/transformer API (reference ``GameEstimator`` /
``GameTransformer``, SURVEY.md §2.6 — expected paths, mount unavailable).
"""

from photon_ml_tpu.estimators.game_estimator import FitResult, GameEstimator
from photon_ml_tpu.estimators.game_transformer import GameTransformer
from photon_ml_tpu.estimators.streaming_scorer import StreamingGameScorer

__all__ = ["FitResult", "GameEstimator", "GameTransformer",
           "StreamingGameScorer"]
