"""GameEstimator: configs + data → trained, evaluated GAME models.

Reference counterpart: ``GameEstimator``
(photon-api ``com.linkedin.photon.ml.estimators.GameEstimator``
[expected path, mount unavailable — see SURVEY.md §2.6/§3.1]): build
datasets/coordinates from configuration, run coordinate descent once per
optimization configuration in the hyperparameter grid, evaluate each on
validation, return (model, evaluations, config) triples.

TPU translation notes:

- dataset/coordinate construction is the host ETL (entity grouping,
  intercept column, normalization stats, down-sampling), done ONCE and
  reused across the λ grid — only objectives change per grid point
  (the reference likewise persists datasets across the grid);
- per-iteration validation uses the trained-so-far model via
  ``GameTransformer`` on the validation set;
- normalization with shifts folds the margin correction into the
  intercept coefficient at export, so saved models score raw features
  directly (see ``_export_fixed``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import logging

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.config import (
    CoordinateConfig,
    CoordinateKind,
    OptimizerSettings,
    TrainingConfig,
)
from photon_ml_tpu.data.batch import make_dense_batch, make_sparse_batch
from photon_ml_tpu.data.normalization import (
    NormalizationContext,
    NormalizationType,
    compute_normalization,
)
from photon_ml_tpu.data.statistics import compute_statistics
from photon_ml_tpu.estimators.game_transformer import GameTransformer
from photon_ml_tpu.evaluation import evaluate, better_than
from photon_ml_tpu.game.coordinates import (
    FixedEffectCoordinate,
    build_random_effect_coordinate,
    build_random_effect_coordinate_sparse,
)
from photon_ml_tpu.game.coordinate_descent import run_coordinate_descent
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.game.sampling import binary_classification_down_sample
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import TaskType
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.prior import GaussianPrior
from photon_ml_tpu.ops.regularization import (
    RegularizationContext,
    RegularizationType,
    SweptRegularization,
)
from photon_ml_tpu.optim import OptimizationProblem, OptimizerConfig
from photon_ml_tpu.optim.variance import VarianceComputationType
from photon_ml_tpu.telemetry import monitor as _mon

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class FitResult:
    """(model, evaluations, grid point) — the reference's result triple."""

    model: GameModel
    evaluations: dict            # EvaluatorType → float (validation)
    reg_weights: dict            # coordinate name → λ used
    # Per-CD-iteration validation metrics (reference: CoordinateDescent
    # logs every evaluator each sweep); empty without validation data.
    validation_history: list = dataclasses.field(default_factory=list)


def _reg_context(settings: OptimizerSettings, weight: float, dim: int,
                 intercept_index: int | None) -> RegularizationContext:
    from photon_ml_tpu.ops.regularization import exclude_intercept_mask

    mask = exclude_intercept_mask(dim, intercept_index)
    if settings.regularization == RegularizationType.NONE or weight == 0.0:
        return RegularizationContext.none()
    if settings.regularization == RegularizationType.L2:
        return RegularizationContext.l2(weight, mask)
    if settings.regularization == RegularizationType.L1:
        return RegularizationContext.l1(weight, mask)
    return RegularizationContext.elastic_net(
        weight, settings.elastic_net_alpha, mask
    )


def _optimizer_config(settings: OptimizerSettings) -> OptimizerConfig:
    return OptimizerConfig(
        max_iters=settings.max_iters,
        tolerance=settings.tolerance,
        track_states=settings.track_states,
    )


class GameEstimator:
    """Build coordinates once; fit once per λ-grid point."""

    def __init__(self, config: TrainingConfig):
        config.validate()
        self.config = config
        self.task = config.task_type
        self.loss = self.task.loss
        self._mesh_cache = None
        self._entity_mesh_cache = None
        self._warm_model = None
        if config.warm_start_model_dir:
            from photon_ml_tpu.io.model_io import load_game_model

            self._warm_model, warm_task = load_game_model(
                config.warm_start_model_dir)
            if warm_task != self.task:
                raise ValueError(
                    f"warm-start model task {warm_task} != {self.task}")

    # -- dataset preparation (once) ----------------------------------------

    def _prepare(self, train: GameDataset):
        cfg = self.config
        prep = {}
        for coord_cfg in cfg.coordinates:
            if coord_cfg.kind == CoordinateKind.FIXED_EFFECT:
                prep[coord_cfg.name] = self._prepare_fixed(train, coord_cfg)
        return prep

    def _mesh(self):
        if self.config.n_devices is None:
            return None
        if self._mesh_cache is None:
            from photon_ml_tpu.parallel import data_parallel_mesh

            self._mesh_cache = data_parallel_mesh(self.config.n_devices)
        return self._mesh_cache

    def _entity_mesh(self):
        if self.config.n_devices is None:
            return None
        if self._entity_mesh_cache is None:
            from photon_ml_tpu.parallel.mesh import entity_mesh

            self._entity_mesh_cache = entity_mesh(self.config.n_devices)
        return self._entity_mesh_cache

    def _prepare_fixed(self, train: GameDataset, coord_cfg: CoordinateConfig):
        cfg = self.config
        feats = train.features[coord_cfg.feature_shard]
        labels = train.labels.astype(np.float32)
        weights = train.weight_array()
        mesh = self._mesh()

        intercept_index = None
        if isinstance(feats, np.ndarray):
            if cfg.chunk_rows is not None:
                raise ValueError(
                    "chunk_rows supports sparse feature shards only; "
                    f"fixed-effect shard '{coord_cfg.feature_shard}' is "
                    "a dense array (a resident DenseBatch would defeat "
                    "the beyond-HBM purpose of chunking)")
            x = np.asarray(feats, np.float32)
            if cfg.intercept:
                x = np.concatenate([x, np.ones((len(x), 1), np.float32)], 1)
                intercept_index = x.shape[1] - 1
            if mesh is not None:
                from photon_ml_tpu.parallel import padded_rows, shard_batch

                batch = make_dense_batch(
                    x, labels, weights=weights,
                    pad_to=padded_rows(len(x), mesh.devices.size),
                )
                batch = shard_batch(batch, mesh)
            else:
                batch = make_dense_batch(x, labels, weights=weights)
            dim = x.shape[1]
        else:  # sparse rows
            dim = train.feature_dim(coord_cfg.feature_shard)
            rows = feats
            if cfg.intercept:
                from photon_ml_tpu.data.sparse_rows import SparseRows

                if isinstance(rows, SparseRows):
                    rows = rows.with_constant_col(dim)
                else:
                    rows = [
                        (np.append(c, dim).astype(np.int32),
                         np.append(v, 1.0).astype(np.float32))
                        for c, v in rows
                    ]
                intercept_index = dim
                dim += 1
            if cfg.chunk_rows is not None:
                # Chunk-accumulated path (beyond-HBM residency; SURVEY
                # §1 L1): K congruent host chunk batches streamed per
                # objective evaluation.  Composes with the mesh
                # (chunks × shards).
                from photon_ml_tpu.data.chunked_batch import (
                    build_chunked_batch,
                )

                layout = cfg.chunk_layout
                if layout == "AUTO":
                    import jax

                    layout = ("GRR" if jax.default_backend() == "tpu"
                              else "ELL")
                from photon_ml_tpu.data.chunk_store import (
                    resolve_spill_dir,
                )

                chunked = build_chunked_batch(
                    rows, dim, labels, weights=weights,
                    chunk_rows=cfg.chunk_rows, layout=layout.lower(),
                    mesh=mesh,
                    cache_dir=cfg.plan_cache_dir,
                    # Env default ($PHOTON_ML_TPU_SPILL_DIR) applies at
                    # THIS layer only; the library builder stays
                    # explicit so resident baselines can't be flipped
                    # by ambient environment.
                    spill_dir=resolve_spill_dir(cfg.spill_dir),
                    host_max_resident=cfg.host_max_resident,
                )
                return {
                    "chunked": chunked, "batch": None,
                    "norm": NormalizationContext.identity(), "dim": dim,
                    "intercept_index": intercept_index,
                    "train_idx": None, "train_weights": None,
                    "mesh": mesh, "n_examples": train.n,
                }
            if mesh is not None:
                # Mesh path: per-shard layouts (each device indexes its
                # own rows; SURVEY §5.8's one-time "shuffle").  AUTO
                # picks the sharded GRR compiled plans on TPU — the fast
                # path IS the distributed path — and colmajor elsewhere.
                from photon_ml_tpu.parallel import shard_sparse_batch

                layout = cfg.sparse_layout
                if layout == "AUTO":
                    import jax

                    layout = ("GRR" if jax.default_backend() == "tpu"
                              else "COLMAJOR")
                batch = shard_sparse_batch(
                    rows, dim, labels, mesh, weights=weights,
                    layout=layout.lower(),
                    cache_dir=cfg.plan_cache_dir,
                )
            else:
                # Layout: the GRR compiled plan is the fast TPU path
                # (the intercept column lands on its dense MXU side);
                # plain ELL elsewhere (see data/grr.py).
                layout = cfg.sparse_layout
                if layout == "AUTO":
                    import jax

                    layout = ("GRR" if jax.default_backend() == "tpu"
                              else "ELL")
                # Device ELL is only consumed by normalization stats
                # and the down-sampled view; a GRR batch that needs
                # neither skips the 8-bytes/nnz HBM copy.
                keep_ell = (
                    cfg.normalization != NormalizationType.NONE
                    or coord_cfg.down_sampling_rate is not None
                )
                batch = make_sparse_batch(
                    rows, dim, labels, weights=weights,
                    grr=(layout == "GRR"),
                    col_major=(layout == "COLMAJOR"),
                    keep_ell=keep_ell,
                    cache_dir=cfg.plan_cache_dir,
                )

        norm = NormalizationContext.identity()
        if cfg.normalization != NormalizationType.NONE:
            stats = compute_statistics(batch)
            if (cfg.normalization == NormalizationType.STANDARDIZATION
                    and intercept_index is None):
                raise ValueError(
                    "STANDARDIZATION requires intercept=True (the margin "
                    "shift folds into the intercept at export)"
                )
            norm = compute_normalization(
                stats.mean, stats.std, stats.max_abs, cfg.normalization,
                intercept_index=intercept_index,
            )

        train_idx = train_weights = None
        if coord_cfg.down_sampling_rate is not None:
            idx, new_w = binary_classification_down_sample(
                labels, weights, coord_cfg.down_sampling_rate, seed=cfg.seed
            )
            train_idx = jnp.asarray(idx.astype(np.int32))
            train_weights = jnp.asarray(new_w)

        return {
            "batch": batch, "norm": norm, "dim": dim,
            "intercept_index": intercept_index,
            "train_idx": train_idx, "train_weights": train_weights,
            "mesh": mesh, "n_examples": train.n,
        }

    # -- warm-start import (saved raw-space model → training space) --------

    def _import_fixed(self, comp: FixedEffectModel, p: dict):
        """Invert ``_export_fixed``: raw-space means (+variances) →
        model-space (means, variances)."""
        w_raw = np.asarray(comp.coefficients.means, np.float64)
        dim, ii = p["dim"], p["intercept_index"]
        if len(w_raw) != dim:
            raise ValueError(
                f"warm-start fixed-effect dim {len(w_raw)} != {dim} "
                "(feature space changed; rebuild index maps)")
        norm = p["norm"]
        f = (np.asarray(norm.factors, np.float64)
             if norm.factors is not None else np.ones(dim))
        wm = w_raw / f
        if norm.shifts is not None and ii is not None:
            # Undo the margin-correction fold into the intercept; the
            # correction only involves non-intercept coords (shift=0 at
            # the intercept), all already final in wm.
            s = np.asarray(norm.shifts, np.float64)
            wm[ii] = w_raw[ii] + float(np.dot(s * f, wm))
        variances = None
        if comp.coefficients.variances is not None:
            # var scales as the square of the linear reparameterization
            # (intercept cross-terms under shifts ignored — documented).
            variances = np.asarray(comp.coefficients.variances,
                                   np.float64) / (f * f)
        return (jnp.asarray(wm.astype(np.float32)),
                None if variances is None
                else jnp.asarray(variances.astype(np.float32)))

    def _import_random(self, comp: RandomEffectModel, coord):
        """Map a saved RandomEffectModel onto a (possibly different)
        training-run grouping by entity id; unseen entities start at 0.

        Fully vectorized (SURVEY §7 entity-ETL scale): one sorted join
        of new vs saved entity ids, then per-(new bucket, old bucket)
        block gathers — the bucket grid is O(log² max-count), each cell
        one fancy-indexed copy."""
        w0s = [np.zeros(shape, np.float32)
               for shape in coord.coefficient_shapes]
        g = coord.grouping
        gs = comp.grouping
        if g.n_total_entities == 0 or gs.n_total_entities == 0:
            return [jnp.asarray(w) for w in w0s]

        # Sorted join on entity id (both sides are np.unique output =
        # sorted; saved models preserve that order through I/O).
        saved_pos = gs.join_ids(np.asarray(g.entity_ids))
        found = saved_pos >= 0
        pos_c = np.maximum(saved_pos, 0)
        old_bucket = np.asarray(gs.entity_bucket)[pos_c]
        old_slot = np.asarray(gs.entity_slot)[pos_c]
        new_bucket = np.asarray(g.entity_bucket)
        new_slot = np.asarray(g.entity_slot)

        old_blocks = [np.asarray(blk) for blk in comp.coefficient_blocks]
        for b in range(len(w0s)):
            for ob in range(len(old_blocks)):
                sel = found & (new_bucket == b) & (old_bucket == ob)
                if not sel.any():
                    continue
                ns, os_ = new_slot[sel], old_slot[sel]
                blk_old = old_blocks[ob][os_]           # [m, p_old]
                if coord.projection is None and comp.projection is None:
                    if blk_old.shape[1] != w0s[b].shape[1]:
                        continue  # width mismatch: entity starts at 0
                    w0s[b][ns] = blk_old
                elif coord.projection is None:
                    # Saved model projected, target dense: scatter each
                    # entity's local coefs to its global columns.
                    if comp.projection.global_dim != w0s[b].shape[1]:
                        continue
                    fids = comp.projection.feature_ids[ob][os_]
                    rr, cc = np.nonzero(fids >= 0)
                    w0s[b][ns[rr], fids[rr, cc]] = blk_old[rr, cc]
                elif comp.projection is None:
                    # Saved dense, target projected: gather the target's
                    # subspace columns out of the saved global rows.
                    fids = coord.projection.feature_ids[b][ns]  # [m, p]
                    valid = fids >= 0
                    valid &= fids < blk_old.shape[1]
                    rr, cc = np.nonzero(valid)
                    w0s[b][ns[rr], cc] = blk_old[rr, fids[rr, cc]]
                else:
                    # Both projected: sparse merge-join on (entity,
                    # global col) keys.
                    from photon_ml_tpu.game.dataset import sorted_key_join

                    G = np.int64(comp.projection.global_dim)
                    f_old = comp.projection.feature_ids[ob][os_]
                    ro, co = np.nonzero(f_old >= 0)
                    key_old = ro.astype(np.int64) * G + f_old[ro, co]
                    f_new = coord.projection.feature_ids[b][ns]
                    rn, cn = np.nonzero((f_new >= 0) & (f_new < G))
                    key_new = rn.astype(np.int64) * G + f_new[rn, cn]
                    w_at, hit = sorted_key_join(key_old, blk_old[ro, co],
                                                key_new)
                    w0s[b][ns[rn[hit]], cn[hit]] = w_at[hit]
        return [jnp.asarray(w) for w in w0s]

    def _warm_coefficients(self, coords: dict, prep: dict) -> dict:
        """Per-coordinate starting coefficients from the warm model."""
        out = {}
        if self._warm_model is None:
            return out
        by_name = {c.name: c for c in self.config.coordinates}
        for name, comp in self._warm_model.models.items():
            if name not in coords:
                continue
            if by_name[name].kind == CoordinateKind.FIXED_EFFECT:
                out[name], _ = self._import_fixed(comp, prep[name])
            else:
                out[name] = self._import_random(comp, coords[name])
        return out

    # -- coordinate construction (per grid point) --------------------------

    def _build_coordinates(self, train: GameDataset, prep: dict,
                           reg_weights: dict):
        cfg = self.config
        coords = {}
        for coord_cfg in cfg.coordinates:
            weight = reg_weights.get(coord_cfg.name,
                                     coord_cfg.optimizer.reg_weight)
            ocfg = _optimizer_config(coord_cfg.optimizer)
            if coord_cfg.kind == CoordinateKind.FIXED_EFFECT:
                p = prep[coord_cfg.name]
                prior = None
                if (cfg.use_warm_start_as_prior
                        and self._warm_model is not None
                        and coord_cfg.name in self._warm_model.models):
                    comp = self._warm_model.models[coord_cfg.name]
                    means, variances = self._import_fixed(comp, p)
                    if variances is not None:
                        prior = GaussianPrior.from_model(
                            means, variances, cfg.prior_weight)
                objective = GLMObjective(
                    loss=self.loss,
                    reg=_reg_context(coord_cfg.optimizer, weight, p["dim"],
                                     p["intercept_index"]),
                    norm=p["norm"],
                    prior=prior,
                )
                if p.get("chunked") is not None:
                    from photon_ml_tpu.game.coordinates import (
                        ChunkedFixedEffectCoordinate,
                    )

                    coords[coord_cfg.name] = ChunkedFixedEffectCoordinate(
                        name=coord_cfg.name,
                        chunked=p["chunked"],
                        objective=objective,
                        optimizer=coord_cfg.optimizer.optimizer,
                        config=ocfg,
                        max_resident=cfg.chunk_max_resident,
                        prefetch_depth=cfg.prefetch_depth,
                    )
                    continue
                distributed = None
                if p["mesh"] is not None:
                    from photon_ml_tpu.parallel import DistributedGLMObjective

                    distributed = DistributedGLMObjective(
                        objective=objective, mesh=p["mesh"])
                coords[coord_cfg.name] = FixedEffectCoordinate(
                    name=coord_cfg.name,
                    batch=p["batch"],
                    problem=OptimizationProblem(
                        objective=objective,
                        optimizer=coord_cfg.optimizer.optimizer,
                        config=ocfg,
                    ),
                    distributed=distributed,
                    train_idx=p["train_idx"],
                    train_weights=p["train_weights"],
                    n_examples=p["n_examples"],
                )
            else:
                feats = train.features[coord_cfg.feature_shard]
                objective = GLMObjective(
                    loss=self.loss,
                    reg=_reg_context(coord_cfg.optimizer, weight, 1, None),
                    norm=NormalizationContext.identity(),
                )
                e_mesh = self._entity_mesh()
                if cfg.re_chunk_entities is not None:
                    # Out-of-core streamed RE training (ISSUE 5): the
                    # builder handles dense and sparse shards; env
                    # default for spill_dir applies at THIS layer only
                    # (library builders stay explicit — same rule as
                    # the chunked fixed-effect path).
                    from photon_ml_tpu.data.chunk_store import (
                        resolve_spill_dir,
                    )
                    from photon_ml_tpu.game.coordinates import (
                        build_streamed_random_effect_coordinate,
                    )

                    spill = resolve_spill_dir(cfg.spill_dir)
                    if spill is None:
                        raise ValueError(
                            "re_chunk_entities requires spill_dir (or "
                            "$PHOTON_ML_TPU_SPILL_DIR)")
                    coords[coord_cfg.name] = (
                        build_streamed_random_effect_coordinate(
                            coord_cfg.entity_key, train,
                            coord_cfg.feature_shard, objective,
                            spill_dir=spill,
                            chunk_entities=cfg.re_chunk_entities,
                            config=ocfg,
                            optimizer=coord_cfg.optimizer.optimizer,
                            host_max_resident=cfg.host_max_resident,
                            prefetch_depth=cfg.prefetch_depth,
                            retirement=cfg.re_retirement,
                            mesh=e_mesh,
                        )
                    )
                elif isinstance(feats, np.ndarray):
                    coords[coord_cfg.name] = build_random_effect_coordinate(
                        coord_cfg.entity_key, train, coord_cfg.feature_shard,
                        objective, config=ocfg,
                        optimizer=coord_cfg.optimizer.optimizer,
                        mesh=e_mesh,
                    )
                else:
                    coords[coord_cfg.name] = (
                        build_random_effect_coordinate_sparse(
                            coord_cfg.entity_key, train,
                            coord_cfg.feature_shard, objective,
                            global_dim=train.feature_dim(
                                coord_cfg.feature_shard),
                            config=ocfg,
                            optimizer=coord_cfg.optimizer.optimizer,
                            mesh=e_mesh,
                        )
                    )
                # Coordinate was registered under entity_key by the
                # builder; expose it under the coordinate name.
                coords[coord_cfg.name].name = coord_cfg.name
        self._share_chunk_window(coords)
        return coords

    def _share_chunk_window(self, coords: dict) -> None:
        """One LRU residency budget across every store-backed
        coordinate (ISSUE 11 satellite): the legacy per-coordinate CD
        cycle streams the fixed effect's store and each streamed RE's
        store in turn, and per-store windows pinned
        (host_max_resident × stores) chunks — each coordinate's sweep
        thrashing the others' budget expectation.  Grouping makes
        ``host_max_resident`` the TOTAL decoded-chunk bound for the
        whole descent; the active coordinate's sweep naturally fills
        the window and the previous coordinate's stale chunks evict
        first."""
        self._chunk_window_group = None
        stores = []
        for coord in coords.values():
            chunked = getattr(coord, "chunked", None)
            if chunked is not None and getattr(chunked, "store",
                                               None) is not None:
                stores.append(chunked.store)
            store = getattr(coord, "store", None)
            if store is not None:
                stores.append(store)
        if len(stores) < 2:
            return
        from photon_ml_tpu.data.chunk_store import SharedChunkWindow

        group = SharedChunkWindow(self.config.host_max_resident)
        for store in stores:
            store.join_window_group(group)
        self._chunk_window_group = group

    # -- model export ------------------------------------------------------

    def _export_fixed(self, coord: FixedEffectCoordinate, w,
                      coord_cfg: CoordinateConfig,
                      variances=None) -> FixedEffectModel:
        """Export in RAW feature space: scale by normalization factors and
        fold the margin shift-correction into the intercept (its presence
        under shifts is validated in _prepare_fixed), so saved models
        score raw features with a plain dot product."""
        norm = coord.problem.objective.norm
        w_raw = np.asarray(norm.model_to_raw(w)).copy()
        if norm.shifts is not None:
            w_raw[-1] -= float(norm.margin_correction(w))
        var_raw = None
        if variances is not None:
            # Variances scale with the square of the reparameterization.
            f = (np.asarray(norm.factors)
                 if norm.factors is not None
                 else np.ones_like(w_raw))
            var_raw = jnp.asarray(np.asarray(variances) * f * f)
        return FixedEffectModel(
            coefficients=Coefficients(means=jnp.asarray(w_raw),
                                      variances=var_raw),
            feature_shard=coord_cfg.feature_shard,
            intercept=self.config.intercept,
        )

    def _model_snapshot(self, coords, coefficients: dict) -> GameModel:
        """Current-coefficients model, no variances — the cheap export
        used for per-iteration validation scoring."""
        models = {}
        by_name = {c.name: c for c in self.config.coordinates}
        for name, w in coefficients.items():
            coord_cfg = by_name[name]
            coord = coords[name]
            if coord_cfg.kind == CoordinateKind.FIXED_EFFECT:
                models[name] = self._export_fixed(coord, w, coord_cfg, None)
            else:
                models[name] = coord.as_model(w)
                models[name].feature_shard = coord_cfg.feature_shard
                models[name].entity_key = coord_cfg.entity_key
        return GameModel(models=models)

    def _to_game_model(self, coords, cd) -> GameModel:
        models = {}
        by_name = {c.name: c for c in self.config.coordinates}
        for name, w in cd.coefficients.items():
            coord_cfg = by_name[name]
            coord = coords[name]
            vtype = coord_cfg.optimizer.variance_type
            offsets = cd.total_scores - cd.scores[name]
            if coord_cfg.kind == CoordinateKind.FIXED_EFFECT:
                variances = None
                if vtype != VarianceComputationType.NONE:
                    variances = coord.compute_variances(w, offsets, vtype)
                models[name] = self._export_fixed(
                    coord, w, coord_cfg, variances)
            else:
                models[name] = coord.as_model(w)
                if vtype != VarianceComputationType.NONE:
                    # Per-entity variances are SIMPLE by design (a FULL
                    # inverse per entity is neither needed nor tractable).
                    models[name].variance_blocks = (
                        coord.compute_variance_blocks(w, offsets))
                models[name].feature_shard = coord_cfg.feature_shard
                models[name].entity_key = coord_cfg.entity_key
        return GameModel(models=models)

    # -- batched λ-sweep (one data stream for the whole grid) --------------

    def _swept_coordinate_name(self) -> str | None:
        """The single trainable fixed-effect coordinate eligible for
        batched λ-sweep training, or None.

        Eligibility: exactly one trainable (non-locked) coordinate in
        the update sequence, FIXED_EFFECT, LBFGS/OWL-QN (TRON per-point
        fits stay sequential), and no locked coordinate requesting
        variances (those need per-coordinate score bookkeeping the
        swept path doesn't carry).  Locked coordinates are fine
        otherwise — their scores fold into the (lane-shared) offsets.
        """
        cfg = self.config
        trainable = [n for n in dict.fromkeys(cfg.update_sequence)
                     if n not in cfg.locked_coordinates]
        if len(trainable) != 1:
            return None
        name = trainable[0]
        by_name = {c.name: c for c in cfg.coordinates}
        cc = by_name.get(name)
        if cc is None or cc.kind != CoordinateKind.FIXED_EFFECT:
            return None
        from photon_ml_tpu.optim.base import OptimizerType

        if cc.optimizer.optimizer == OptimizerType.TRON:
            return None
        for c in cfg.coordinates:
            if (c.name in cfg.locked_coordinates
                    and c.optimizer.variance_type
                    != VarianceComputationType.NONE):
                return None
        return name

    def _locked_offsets(self, coords, locked: dict, n: int):
        """Offsets the trainable coordinate sees = Σ locked scores
        (CD semantics with one trainable coordinate: total −
        own-scores, and own scores cancel)."""
        total = jnp.zeros((n,), jnp.float32)
        for ln, lw in locked.items():
            total = total + coords[ln].score(lw)
        return total

    def _lane_coordinate(self, coord, coord_cfg: CoordinateConfig,
                         lam: float):
        """Clone of a fixed-effect coordinate with one lane's λ
        installed — for per-lane variance computation (the Hessian
        includes λ₂)."""
        from photon_ml_tpu.game.coordinates import (
            ChunkedFixedEffectCoordinate,
        )

        reg1 = SweptRegularization.from_grid(
            coord_cfg.optimizer.regularization, [lam],
            coord_cfg.optimizer.elastic_net_alpha)
        if isinstance(coord, ChunkedFixedEffectCoordinate):
            base = coord.objective
            obj_l = base.replace(reg=base.reg.replace(
                l1_weight=reg1.l1_weights[0],
                l2_weight=reg1.l2_weights[0]))
            return ChunkedFixedEffectCoordinate(
                name=coord.name, chunked=coord.chunked, objective=obj_l,
                optimizer=coord.optimizer, config=coord.config,
                max_resident=coord.max_resident,
                prefetch_depth=coord.prefetch_depth)
        base = coord.problem.objective
        obj_l = base.replace(reg=base.reg.replace(
            l1_weight=reg1.l1_weights[0], l2_weight=reg1.l2_weights[0]))
        dist_l = (None if coord.distributed is None
                  else coord.distributed.replace(objective=obj_l))
        return dataclasses.replace(
            coord, problem=coord.problem.replace(objective=obj_l),
            distributed=dist_l)

    def _swept_lane_model(self, coords, name: str, w_j, locked: dict,
                          offsets, lam: float,
                          with_variances: bool = True) -> GameModel:
        """One lane's GameModel: the snapshot export (fixed effect at
        this λ plus the locked coordinates), with the trainable entry
        re-exported variance-bearing when requested (variances need
        the LANE's reg context — the Hessian includes λ₂)."""
        model = self._model_snapshot(coords, {**locked, name: w_j})
        by_name = {c.name: c for c in self.config.coordinates}
        cc = by_name[name]
        vtype = cc.optimizer.variance_type
        if with_variances and vtype != VarianceComputationType.NONE:
            variances = self._lane_coordinate(
                coords[name], cc, lam).compute_variances(
                    w_j, offsets, vtype)
            model.models[name] = self._export_fixed(
                coords[name], w_j, cc, variances)
        return model

    def _train_swept_lanes(self, coords, name: str, lams, offsets,
                          locked: dict, validation, run_logger,
                          warm_W=None, base_w0=None, checkpointer=None,
                          resume: bool = False, stage: str = "swept"):
        """Train λ lanes as ONE batched sweep; returns (FitResults in
        the order of ``lams``, W [L, dim] in that order).

        Lanes run λ-DESCENDING inside the solve (continuation order:
        strongly regularized lanes converge first and coast under the
        masked while_loop while weakly regularized stragglers keep
        refining); results are mapped back to the caller's order.

        With a ``checkpointer`` (ISSUE 9) the lane matrix, sweep index,
        and per-lane validation history snapshot to stage ``stage``
        after every sweep, the swept solver checkpoints mid-solve under
        a per-sweep scope, and ``resume`` restores — so a SIGKILL mid
        swept fit resumes at its exact (sweep, solver iteration).
        """
        import time as _time

        from photon_ml_tpu.game.coordinate_descent import (
            _revive_validation,
            _serialize_validation,
        )
        from photon_ml_tpu.reliability import checkpoint as _ckpt

        cfg = self.config
        by_name = {c.name: c for c in cfg.coordinates}
        cc = by_name[name]
        coord = coords[name]
        lams_arr = np.asarray(lams, np.float32)
        order = np.argsort(-lams_arr, kind="stable")
        inv = np.empty_like(order)
        inv[order] = np.arange(len(order))
        reg = SweptRegularization.from_grid(
            cc.optimizer.regularization, lams_arr[order],
            cc.optimizer.elastic_net_alpha)
        L = len(lams)
        if warm_W is not None:
            W = jnp.asarray(warm_W)[jnp.asarray(order)]
        elif base_w0 is not None:
            W = jnp.tile(jnp.asarray(base_w0)[None, :], (L, 1))
        else:
            W = None
        from photon_ml_tpu import telemetry

        t0 = _time.perf_counter()
        res = None
        res_summary: dict | None = None
        inv_idx = jnp.asarray(inv)
        # Per-sweep validation mirrors _fit_point's validator (the
        # reference scores validation data every CD iteration): one
        # snapshot evaluation per lane per sweep — the same L·n_iter
        # transforms the sequential grid pays.
        validate = (validation is not None and cfg.validate_per_iteration)
        lane_history: list[list] = [[] for _ in range(L)]
        start_sweep = 0
        if checkpointer is not None and resume:
            st = checkpointer.load_stage(stage)
            if (st is not None
                    and [float(x) for x in st["lams"]]
                    == [float(x) for x in lams]):
                start_sweep = int(st["sweep"])
                if st.get("W") is not None:
                    W = jnp.asarray(st["W"], jnp.float32)
                lane_history = [_revive_validation(h)
                                for h in st.get("lane_history") or []]
                while len(lane_history) < L:
                    lane_history.append([])
                res_summary = st.get("res_summary")
                logger.info("swept fit '%s': resumed at sweep %d/%d",
                            name, start_sweep, cfg.n_iterations)
        with _ckpt.session(checkpointer):
            for i in range(start_sweep, cfg.n_iterations):
                scope = (checkpointer.scope(f"{stage}_s{i + 1}")
                         if checkpointer is not None
                         else contextlib.nullcontext())
                with scope, telemetry.span("swept_train", cat="train",
                                           coordinate=name, lanes=L):
                    W, res = coord.train_swept(offsets, reg, warm_start=W)
                # Live swept-sweep progress (ISSUE 10): the swept grid
                # bypasses the CD loop, so it reports its own
                # sweep-level trajectory for watch/ETA.
                _mon.progress("swept", i + 1, cfg.n_iterations,
                              unit="sweeps", coordinate=name, lanes=L,
                              lanes_done=int(jnp.sum(res.converged)))
                if validate:
                    with telemetry.span("swept_validation", cat="train",
                                        coordinate=name, lanes=L):
                        W_now = W[inv_idx]
                        for j in range(L):
                            snap = self._swept_lane_model(
                                coords, name, W_now[j], locked, offsets,
                                float(lams[j]), with_variances=False)
                            lane_history[j].append(
                                self._evaluate(snap, validation))
                # Sweep-boundary lane snapshots honor the same
                # ``checkpoint_every_sweeps`` cadence as maybe_save_cd —
                # the [L, dim] lane matrix is the expensive part of the
                # payload, and the final sweep always saves.
                if checkpointer is not None and (
                        (i + 1) == cfg.n_iterations
                        or (i + 1) % checkpointer.every_sweeps == 0):
                    res_summary = {
                        "lanes_converged": int(jnp.sum(res.converged)),
                        "max_solver_iterations": int(
                            jnp.max(res.iterations))}
                    checkpointer.save_stage(stage, {
                        "lams": [float(x) for x in lams],
                        "sweep": i + 1,
                        "W": W,   # internal λ-descending lane order
                        "lane_history": [
                            _serialize_validation(h)
                            for h in lane_history],
                        "res_summary": res_summary,
                    })
        elapsed = _time.perf_counter() - t0
        logger.info("swept fit: %d λ-lanes of '%s' in %.2fs", L, name,
                    elapsed)
        if res is not None:
            res_summary = {
                "lanes_converged": int(jnp.sum(res.converged)),
                "max_solver_iterations": int(jnp.max(res.iterations))}
        if run_logger is not None:
            run_logger.event(
                "swept_fit", coordinate=name, lanes=L,
                duration_s=round(elapsed, 4), **(res_summary or {}),
            )
        W_out = W[inv_idx]
        results = []
        for j in range(L):
            # The caller's λ, not the float32 round-trip (reg_weights
            # in the FitResult must equal the grid/proposal values).
            lam = float(lams[j])
            model = self._swept_lane_model(coords, name, W_out[j],
                                           locked, offsets, lam)
            if lane_history[j]:
                # The last sweep's snapshot IS the final model
                # (variances don't affect scoring) — _fit_point rule.
                evals = dict(lane_history[j][-1])
            else:
                evals = (self._evaluate(model, validation)
                         if validation is not None else {})
            results.append(FitResult(
                model=model, evaluations=evals,
                reg_weights={c.name: (lam if c.name == name
                                      else c.optimizer.reg_weight)
                             for c in cfg.coordinates},
                validation_history=lane_history[j],
            ))
        return results, W_out

    def _swept_setup(self, train: GameDataset, prep: dict, name: str,
                     lam_build: float):
        """Shared swept-fit preamble: coordinates built once (at the
        largest λ, so the reg context carries the intercept mask), warm
        coefficients, locked-coordinate filter, lane-shared offsets.

        Returns (coords, locked, offsets, base_w0)."""
        cfg = self.config
        coords = self._build_coordinates(train, prep, {name: lam_build})
        warm = self._warm_coefficients(coords, prep)
        locked = {n: warm[n] for n in cfg.locked_coordinates if n in warm}
        missing = set(cfg.locked_coordinates) - set(locked)
        if missing:
            raise ValueError(
                f"locked coordinates {sorted(missing)} absent from "
                "the warm-start model")
        offsets = self._locked_offsets(coords, locked, train.n)
        return coords, locked, offsets, warm.get(name)

    def _fit_grid_swept(self, train: GameDataset, prep: dict, name: str,
                        grid_points: list[dict], validation,
                        run_logger) -> list[FitResult]:
        """The whole ``reg_weight_grid`` as ONE batched sweep: L
        coefficient lanes share every objective evaluation (data
        stream) instead of paying one full fit per grid point.
        Returns results in grid order (the ``fit`` contract), with
        per-sweep ``validation_history`` per lane when
        ``validate_per_iteration`` is on — the same record the
        per-point path produces."""
        lams = [gp[name] for gp in grid_points]
        coords, locked, offsets, base_w0 = self._swept_setup(
            train, prep, name, max(lams))
        logger.info("fit: swept λ grid over '%s' (%d lanes)", name,
                    len(lams))
        results, _ = self._train_swept_lanes(
            coords, name, lams, offsets, locked, validation, run_logger,
            base_w0=base_w0,
            checkpointer=self._checkpointer(self.config.checkpoint_dir,
                                            run_logger),
            resume=self.config.resume)
        return results

    # -- fit ---------------------------------------------------------------

    def _checkpointer(self, ckpt_dir: str | None, run_logger):
        """Config-cadenced ``reliability.checkpoint.RunCheckpointer``
        for ``ckpt_dir`` (None when checkpointing is off).

        Under an active fleet context the directory is sharded per
        host (``host_NNN/`` subdir): every host snapshots its own
        replicated solver state plus its private fleet reduce
        sequence, so a killed host resumes from its OWN manifest
        without restarting — or reading the state of — its peers."""
        if not ckpt_dir:
            return None
        from photon_ml_tpu.parallel import fleet
        from photon_ml_tpu.reliability.checkpoint import RunCheckpointer

        ckpt_dir = fleet.host_dir(ckpt_dir, fleet.active())
        cfg = self.config
        return RunCheckpointer(
            ckpt_dir, every_sweeps=cfg.checkpoint_every_sweeps,
            every_solver_iters=cfg.checkpoint_every_solver_iters,
            run_logger=run_logger, resume=cfg.resume)

    def _grid_points(self) -> list[dict]:
        grid = self.config.reg_weight_grid
        if not grid:
            return [{}]
        names = sorted(grid)
        return [dict(zip(names, vals))
                for vals in itertools.product(*(grid[n] for n in names))]

    def _evaluate(self, model: GameModel, validation: GameDataset) -> dict:
        transformer = GameTransformer(model=model, task=self.task)
        margins = jnp.asarray(transformer.transform(validation))
        labels = jnp.asarray(validation.labels.astype(np.float32))
        weights = jnp.asarray(validation.weight_array())
        out = {}
        for ev in self.config.evaluators:
            # RMSE/squared-loss evaluate mean-space, others margin-space
            # (reference per-evaluator score conventions).
            scores = margins
            if ev.value in ("RMSE", "SQUARED_LOSS"):
                scores = self.task.loss.mean(margins)
            out[ev] = float(evaluate(ev, scores, labels, weights))
        return out

    def _fit_point(self, train: GameDataset, prep: dict, reg_weights: dict,
                   validation: GameDataset | None, run_logger,
                   ckpt_tag: str | None = None,
                   checkpointing: bool = True) -> FitResult:
        """One full coordinate-descent fit at fixed λ per coordinate.

        ``checkpointing=False`` runs the point without checkpoint/
        resume machinery even when the config carries a checkpoint_dir
        — the non-swept tuned path, where per-trial fits sharing one
        directory would overwrite (and cross-resume) each other."""
        cfg = self.config
        coords = self._build_coordinates(train, prep, reg_weights)
        logger.info("fit: point %s", reg_weights or "(default)")

        warm = self._warm_coefficients(coords, prep)
        locked = {name: warm[name] for name in cfg.locked_coordinates
                  if name in warm}
        missing = set(cfg.locked_coordinates) - set(locked)
        if missing:
            raise ValueError(
                f"locked coordinates {sorted(missing)} absent from "
                "the warm-start model")
        initial = {n: w for n, w in warm.items() if n not in locked}

        ckpt_dir = cfg.checkpoint_dir if checkpointing else None
        if ckpt_dir and ckpt_tag:
            ckpt_dir = f"{ckpt_dir}/{ckpt_tag}"
        checkpointer = self._checkpointer(ckpt_dir, run_logger)
        validator = None
        if validation is not None and cfg.validate_per_iteration:
            # The reference's CoordinateDescent scores validation data
            # and logs every evaluator each sweep (SURVEY §2.3/§3.1):
            # snapshot the current coefficients into a (variance-free)
            # model and evaluate it.
            def validator(coefficients, _total_scores):
                snap = self._model_snapshot(coords, coefficients)
                return self._evaluate(snap, validation)

        fused = None
        if cfg.cd_fused:
            # Fused CD super-sweep (ISSUE 11): one streamed store pass
            # per cycle accumulates every coordinate's statistics.
            # Config.validate() already enforced the structural
            # requirements (chunk_rows, one fixed effect, smooth reg,
            # no locked coordinates, single device).
            from photon_ml_tpu.data.chunk_store import (
                SharedChunkWindow,
                resolve_spill_dir,
            )
            from photon_ml_tpu.game.fused_sweep import (
                build_fused_cycle_engine,
            )

            spill = resolve_spill_dir(cfg.spill_dir)
            group = getattr(self, "_chunk_window_group", None)
            if group is None and spill is not None:
                # The fused pass consumes FE chunk i AND sidecar chunk
                # i together every step; without a shared group each
                # spilled store pins its own host_max_resident window —
                # 2× the documented budget in the COMMON fused shape
                # (one spilled FE store, resident REs, so
                # _share_chunk_window saw < 2 stores).
                fe_store = next(
                    (c.chunked.store for c in coords.values()
                     if getattr(c, "chunked", None) is not None
                     and getattr(c.chunked, "store", None) is not None),
                    None)
                if fe_store is not None:
                    group = SharedChunkWindow(cfg.host_max_resident)
                    fe_store.join_window_group(group)
                    self._chunk_window_group = group
            fused = build_fused_cycle_engine(
                train, coords, cfg.update_sequence,
                re_shards={c.name: c.feature_shard
                           for c in cfg.coordinates},
                spill_dir=spill,
                host_max_resident=cfg.host_max_resident,
                prefetch_depth=cfg.prefetch_depth,
                retirement=cfg.re_retirement,
                window_group=group,
            )
        cd = run_coordinate_descent(
            coordinates=coords,
            update_sequence=cfg.update_sequence,
            n_iterations=cfg.n_iterations,
            validator=validator,
            locked_coordinates=locked,
            initial_coefficients=initial,
            checkpoint_dir=ckpt_dir,
            resume=cfg.resume and checkpointing,
            run_logger=run_logger,
            checkpointer=checkpointer,
            fused_engine=fused,
        )
        model = self._to_game_model(coords, cd)
        if cd.validation_history:
            # The last sweep's snapshot IS the final model (variances
            # don't affect scoring) — no second validation pass needed.
            evals = dict(cd.validation_history[-1])
        else:
            evals = (self._evaluate(model, validation)
                     if validation is not None else {})
        return FitResult(
            model=model, evaluations=evals,
            reg_weights={c.name: reg_weights.get(
                c.name, c.optimizer.reg_weight)
                for c in cfg.coordinates},
            validation_history=cd.validation_history,
        )

    def fit(self, train: GameDataset,
            validation: GameDataset | None = None,
            run_logger=None) -> list[FitResult]:
        """Train the λ grid; returns results in grid order.

        An eligible fixed-effect grid (see ``_swept_coordinate_name``)
        trains as ONE batched sweep — every grid point shares each
        objective evaluation's data stream instead of paying its own
        full fit; other shapes fit once per grid point."""
        # Programmatic callers (no driver) still get the warm compile
        # path from config; no-op when neither config nor env sets it.
        from photon_ml_tpu import telemetry
        from photon_ml_tpu.cache import enable_compilation_cache

        enable_compilation_cache(self.config.compilation_cache_dir)
        # Telemetry honors the config knob for programmatic callers too
        # (a driver-configured session takes precedence — maybe_session
        # is a no-op when one is already active).  The whole grid fit
        # is one top-level span so the report's reconciliation has a
        # wall-clock anchor on the main thread.
        # "estimator_fit", not "fit": the driver's timed fit phase is
        # already a span of that name, and a same-name nested span
        # double-counts in the report's stage table.
        with telemetry.maybe_session(
                self.config.telemetry,
                self.config.telemetry_dir or self.config.output_dir,
                run_logger=run_logger), \
                _mon.maybe_monitor(
                    self.config.monitor == "on", run_logger=run_logger,
                    status_port=self.config.status_port,
                    every_s=self.config.monitor_every_s), \
                telemetry.span("estimator_fit", cat="phase"):
            prep = self._prepare(train)
            # Device-memory data point right after dataset placement
            # (ISSUE 8): the residency the HBM scale math sizes is the
            # post-ETL, pre-solve footprint — phase boundaries alone
            # would fold it into the fit-span sample.
            telemetry.device_memory("datasets_placed")
            grid_points = self._grid_points()
            name = self._swept_coordinate_name()
            if (len(grid_points) > 1 and name is not None
                    and set(self.config.reg_weight_grid) == {name}
                    and not self.config.cd_fused):
                # cd_fused trains grid points as separate fused fits —
                # the swept lane machinery solves per-coordinate.
                # Checkpointing no longer forces the sequential path
                # (ISSUE 9): the swept fit snapshots its lane state per
                # sweep and its solver state per iteration.
                return self._fit_grid_swept(train, prep, name,
                                            grid_points, validation,
                                            run_logger)
            return [
                self._fit_point(
                    train, prep, reg_weights, validation, run_logger,
                    ckpt_tag=(f"grid_{gi}" if len(grid_points) > 1
                              else None),
                )
                for gi, reg_weights in enumerate(grid_points)
            ]

    def fit_tuned(self, train: GameDataset, validation: GameDataset,
                  run_logger=None) -> list[FitResult]:
        """Bayesian/random tuning of per-coordinate reg weights
        (reference HyperparameterTuner wrapping GameEstimator.fit,
        SURVEY §3.5).  Returns one FitResult per trial, in trial order."""
        from photon_ml_tpu import telemetry

        cfg = self.config
        tuning = cfg.tuning
        if tuning is None:
            raise ValueError("fit_tuned requires config.tuning")
        if not cfg.evaluators:
            raise ValueError("tuning needs at least one evaluator")
        ev = cfg.evaluators[0]
        with contextlib.ExitStack() as stack:
            stack.enter_context(telemetry.maybe_session(
                cfg.telemetry, cfg.telemetry_dir or cfg.output_dir,
                run_logger=run_logger))
            stack.enter_context(_mon.maybe_monitor(
                cfg.monitor == "on", run_logger=run_logger,
                status_port=cfg.status_port,
                every_s=cfg.monitor_every_s))
            stack.enter_context(telemetry.span("fit_tuned", cat="phase"))
            return self._fit_tuned_inner(train, validation, run_logger,
                                         ev, tuning)

    def _fit_tuned_inner(self, train, validation, run_logger, ev,
                         tuning) -> list[FitResult]:
        from photon_ml_tpu.hyperparameter import (
            HyperparameterTuner,
            ParamRange,
            ParamScale,
            SearchSpace,
            TunerMode,
        )

        cfg = self.config

        space = SearchSpace([
            ParamRange(name, r["low"], r["high"],
                       ParamScale(r.get("scale", "LOG")))
            for name, r in sorted(tuning.reg_weight_ranges.items())
        ])
        prep = self._prepare(train)
        tuner = HyperparameterTuner(
            space,
            mode=TunerMode(tuning.mode),
            larger_is_better=ev.larger_is_better,
            seed=tuning.seed,
        )

        swept_name = self._swept_coordinate_name()
        if (swept_name is not None
                and set(tuning.reg_weight_ranges) == {swept_name}):
            return self._fit_tuned_swept(train, prep, swept_name, tuner,
                                         validation, run_logger, ev)

        if cfg.checkpoint_dir:
            # Documented limit: tuner checkpointing rides the swept
            # batched evaluator (round-granular lane state); per-point
            # tuned fits run without checkpoints rather than dying.
            logger.warning(
                "checkpoint_dir is set but this tuning shape is not "
                "swept-eligible; running WITHOUT tuner checkpoints")

        def evaluate_fn(point: dict):
            result = self._fit_point(
                train, prep, dict(point), validation, run_logger,
                ckpt_tag=None, checkpointing=False)
            return result.evaluations[ev], result

        trials = tuner.run(evaluate_fn, tuning.n_trials,
                           run_logger=run_logger)
        return [t.payload for t in trials]

    def _fit_tuned_swept(self, train: GameDataset, prep: dict, name: str,
                         tuner, validation: GameDataset, run_logger,
                         ev) -> list[FitResult]:
        """Batched trial evaluation: each tuner round proposes a BATCH
        of λ points (``propose_batch`` — one GP fit per round) and the
        whole batch trains as one swept solve, so a round of q trials
        pays ~one fit's worth of data streams instead of q.

        Warm-start continuation across rounds: each new lane starts
        from the previous round's nearest-log-λ solution (lanes
        ordered λ-descending inside each solve)."""
        from photon_ml_tpu.game.coordinate_descent import (
            _revive_validation,
            _serialize_validation,
        )

        cfg = self.config
        tuning = cfg.tuning
        hi = float(tuning.reg_weight_ranges[name]["high"])
        coords, locked, offsets, base_w0 = self._swept_setup(
            train, prep, name, hi)
        prev: dict = {"lams": None, "W": None}
        ck = self._checkpointer(cfg.checkpoint_dir, run_logger)
        rounds: list = []
        restored: list = []
        if ck is not None and cfg.resume:
            # One stage file PER round (``tuner_hist_<r>``): each round
            # writes only its own lane matrix — a cumulative snapshot
            # would re-serialize every prior round's [L, d] matrix each
            # round (O(R²) checkpoint I/O over the search).
            while True:
                st = ck.load_stage(f"tuner_hist_{len(rounds)}")
                if st is None:
                    break
                rounds.append(st)
            # Restored tuner history (ISSUE 9): completed rounds feed
            # the search as observations, and their FitResults
            # materialize straight from the checkpointed lane matrix —
            # model export + saved metrics, NO re-training.
            for r in rounds:
                W_r = jnp.asarray(r["W"], jnp.float32)
                hists = r.get("histories") or []
                for j, lam in enumerate(r["lams"]):
                    lam = float(lam)
                    model = self._swept_lane_model(
                        coords, name, W_r[j], locked, offsets, lam)
                    evals = _revive_validation([r["evals"][j]])[0]
                    fr = FitResult(
                        model=model, evaluations=evals,
                        reg_weights={c.name: (lam if c.name == name
                                              else c.optimizer.reg_weight)
                                     for c in cfg.coordinates},
                        validation_history=_revive_validation(
                            hists[j] if j < len(hists) else []))
                    restored.append(({name: lam},
                                     float(r["values"][j]), fr))
                prev["lams"] = [float(x) for x in r["lams"]]
                prev["W"] = W_r
            if rounds:
                logger.info("tuned fit: restored %d trials from %d "
                            "checkpointed rounds", len(restored),
                            len(rounds))

        def evaluate_batch(configs: list[dict]):
            lams = [float(c[name]) for c in configs]
            warm_W = None
            if prev["W"] is not None:
                log_prev = np.log(np.maximum(
                    np.asarray(prev["lams"], np.float64), 1e-30))
                idx = [int(np.argmin(np.abs(
                    np.log(max(lam, 1e-30)) - log_prev)))
                    for lam in lams]
                warm_W = jnp.stack([prev["W"][i] for i in idx])
            results, W_out = self._train_swept_lanes(
                coords, name, lams, offsets, locked, validation,
                run_logger, warm_W=warm_W, base_w0=base_w0,
                checkpointer=ck, resume=cfg.resume,
                stage=f"tuner_round_{len(rounds)}")
            prev["lams"], prev["W"] = lams, W_out
            if ck is not None:
                rd = {
                    "lams": lams,
                    "values": [float(r.evaluations[ev])
                               for r in results],
                    "W": W_out,
                    "evals": _serialize_validation(
                        [r.evaluations for r in results]),
                    # Per-sweep validation trace per trial, so a
                    # restored round's FitResults keep the
                    # validation_history an uninterrupted run carries.
                    "histories": [_serialize_validation(
                        r.validation_history) for r in results],
                }
                rounds.append(rd)
                ck.save_stage(f"tuner_hist_{len(rounds) - 1}", rd)
            return [(r.evaluations[ev], r) for r in results]

        trials = tuner.run_batched(
            evaluate_batch, tuning.n_trials,
            batch_size=tuning.trial_batch, run_logger=run_logger,
            restored=restored)
        return [t.payload for t in trials]

    def best(self, results: list[FitResult]) -> FitResult:
        """Model selection by the first evaluator (reference rule)."""
        if not self.config.evaluators or not results[0].evaluations:
            return results[0]
        ev = self.config.evaluators[0]
        best = results[0]
        for r in results[1:]:
            if bool(better_than(ev, r.evaluations[ev], best.evaluations[ev])):
                best = r
        return best
