"""GameTransformer: batch scoring of (new) data with a GameModel.

Reference counterpart: ``GameTransformer``
(photon-api ``com.linkedin.photon.ml.transformers.GameTransformer``
[expected path, mount unavailable — see SURVEY.md §2.6/§3.2]).

The reference scores per coordinate — fixed effect by broadcasting
coefficients over the data, random effects by joining data with the
per-entity coefficient RDD — and sums ``CoordinateDataScores``.  Here:

- fixed effect: one matmul (dense shard) or ELL gather-dot (sparse),
- random effect: host-side entity-id → trained-entity-index resolution
  (the "join"), then a device gather of coefficient rows + dot.
  Entities unseen at training time score 0, the reference's semantics.

The summed scores are raw margins (``ModelDataScores``); callers apply
the task's mean function for probability-space outputs.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import TaskType

Array = jax.Array


def _score_fixed(model: FixedEffectModel, dataset: GameDataset) -> np.ndarray:
    feats = dataset.features[model.feature_shard]
    w_np = np.asarray(model.coefficients.means)
    if isinstance(feats, np.ndarray):
        x = np.asarray(feats, np.float32)
        if model.intercept:
            x = np.concatenate([x, np.ones((len(x), 1), np.float32)], 1)
        return np.asarray(jnp.asarray(x) @ jnp.asarray(w_np))
    # Sparse rows: gather-dot per example; intercept is the last coef.
    base = w_np[-1] if model.intercept else 0.0
    return np.asarray(
        [float(v @ w_np[c]) + base for c, v in feats], np.float32
    )


def _score_random(model: RandomEffectModel, entity_ids: np.ndarray,
                  dataset: GameDataset) -> np.ndarray:
    n = dataset.n
    index = model.grouping.entity_index()

    if model.projection is None:
        feats = dataset.features[model.feature_shard]
        x = np.asarray(feats, np.float32)
        w_all = np.asarray(model.all_coefficients())   # [E, d_re]
        # The "join": id → trained row, unseen → extra zero row.
        uniq = {int(e): i for i, e in enumerate(model.grouping.entity_ids)}
        idx = np.asarray([uniq.get(int(e), -1) for e in entity_ids])
        w_pad = np.vstack([w_all, np.zeros((1, w_all.shape[1]), w_all.dtype)])
        gathered = w_pad[idx]                           # -1 → zero row
        return np.einsum("nd,nd->n", x, gathered).astype(np.float32)

    # Projected model: score in each entity's local subspace.
    feats = dataset.features[model.feature_shard]
    scores = np.zeros(n, np.float32)
    cache: dict = {}
    for i in range(n):
        e = int(entity_ids[i])
        if e not in cache:
            cache[e] = model.global_coefficients_for(e)
        w_g = cache[e]
        if w_g is None:
            continue
        c, v = feats[i]
        scores[i] = float(v @ w_g[c])
    return scores


@dataclasses.dataclass
class GameTransformer:
    """Score a GameDataset with a GameModel (margins per example)."""

    model: GameModel
    task: TaskType

    def transform(self, dataset: GameDataset) -> np.ndarray:
        """Summed raw scores [n] (+ dataset offsets, reference semantics)."""
        total = dataset.offset_array().astype(np.float64).copy()
        for name, comp in self.model.models.items():
            if isinstance(comp, FixedEffectModel):
                total += _score_fixed(comp, dataset)
            elif isinstance(comp, RandomEffectModel):
                ids = dataset.entity_ids[comp.entity_key or name]
                total += _score_random(comp, ids, dataset)
            else:
                raise TypeError(f"unknown component model {type(comp)}")
        return total.astype(np.float32)

    def transform_mean(self, dataset: GameDataset) -> np.ndarray:
        """Mean-space predictions (sigmoid/identity/exp of margins)."""
        margins = self.transform(dataset)
        return np.asarray(self.task.loss.mean(jnp.asarray(margins)))
