"""GameTransformer: batch scoring of (new) data with a GameModel.

Reference counterpart: ``GameTransformer``
(photon-api ``com.linkedin.photon.ml.transformers.GameTransformer``
[expected path, mount unavailable — see SURVEY.md §2.6/§3.2]).

The reference scores per coordinate — fixed effect by broadcasting
coefficients over the data, random effects by joining data with the
per-entity coefficient RDD — and sums ``CoordinateDataScores``.  Here:

- fixed effect: one matmul (dense shard) or ELL gather-dot (sparse),
- random effect: host-side entity-id → trained-entity-index resolution
  (the "join"), then a device gather of coefficient rows + dot.
  Entities unseen at training time score 0, the reference's semantics.

The summed scores are raw margins (``ModelDataScores``); callers apply
the task's mean function for probability-space outputs.

``transform`` walks the dataset once PER COORDINATE with host float64
accumulation — right for validation-sized data between CD sweeps.  The
serving-scale path is ``transform_streamed`` /
``estimators.streaming_scorer``: one pass in fixed-shape chunks where a
single fused device program scores every coordinate at once (ISSUE 4).
The per-coordinate helpers here are shared by both paths.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import TaskType

Array = jax.Array


# Below this many rows the host numpy pass beats device dispatch +
# transfer; above it, scoring streams chunks through the accelerator
# (round-4 verdict: training rode the device, scoring 10⁸ rows must not
# stay on host float64).  Applies to the fixed-effect sparse path AND
# (ISSUE 4 satellite) the non-projected random-effect gather-dot.
_DEVICE_SCORE_MIN_ROWS = 200_000
_DEVICE_SCORE_CHUNK = 2_000_000


@functools.lru_cache(maxsize=None)
def _jit_scorer(fn):
    """Memoized jit wrapper: a per-call ``jax.jit(gather_rowsum)``
    gave every ``_device_score_sparse`` invocation a fresh executable
    cache, re-tracing and recompiling the identical program once per
    scoring call (photon-lint jit-in-function; the PR-2 recompile
    hazard, found at lint introduction).  Keyed on the function object
    so the production path reuses ONE compiled wrapper while a
    monkeypatched spy (tests) transparently gets its own."""
    return jax.jit(fn)


def _device_score_sparse(rows, w_np: np.ndarray) -> np.ndarray:
    """Chunked device X·w over SparseRows: equal-shape ELL chunks (the
    tail is padded, so ONE compile serves every chunk), with at most
    two chunks in flight — chunk i's output is consumed before chunk
    i+2 dispatches, bounding device residency to two chunk buffers
    (unbounded dispatch-ahead would queue the whole dataset's ELL on
    device, defeating the chunking).

    The chunk grid is sized to min(n, _DEVICE_SCORE_CHUNK) rounded up
    to an 8192-row tile (advisor finding: padding every input to the
    fixed 2M grid made a 250k-row input pay ~8× wasted
    gather/rowsum/transfer); one compile still serves every chunk of a
    given input."""
    from photon_ml_tpu.ops import kernels

    n = len(rows)
    k = max(rows.max_nnz, 1)
    grid = -(-min(n, _DEVICE_SCORE_CHUNK) // 8192) * 8192
    w_dev = jnp.asarray(w_np, jnp.float32)
    score = _jit_scorer(kernels.gather_rowsum)
    outs = []
    pending: list = []
    for lo in range(0, n, grid):
        hi = min(lo + grid, n)
        cols, vals = rows[lo:hi].to_ell(row_capacity=k,
                                        pad_to=grid)
        pending.append(
            (score(w_dev, jnp.asarray(vals), jnp.asarray(cols)), hi - lo))
        if len(pending) >= 2:
            out, m = pending.pop(0)
            outs.append(np.asarray(out)[:m])
    for out, m in pending:
        outs.append(np.asarray(out)[:m])
    return np.concatenate(outs) if outs else np.zeros(0, np.float32)


@jax.jit
def _re_gather_dot(W_pad: Array, x: Array, idx: Array) -> Array:
    """``out[i] = x[i] · W_pad[idx[i]]`` — the random-effect
    coefficient-row gather-dot (the scoring-side "join" contraction;
    ``idx`` points unseen entities at the zero padding row)."""
    return jnp.sum(x * W_pad[idx], axis=-1)


def _device_score_re(feats, w_pad: np.ndarray,
                     idx: np.ndarray) -> np.ndarray:
    """Chunked device gather+dot for the non-projected random effect
    (ISSUE 4 satellite: the host ``np.einsum`` did this regardless of
    size).  Same two-in-flight chunk discipline as
    ``_device_score_sparse``; ``feats`` is a dense [n, d_re] array or
    ``SparseRows`` (densified per chunk — RE shards are narrow)."""
    from photon_ml_tpu.data.sparse_rows import SparseRows

    n = len(idx)
    d_re = w_pad.shape[1]
    grid = -(-min(n, _DEVICE_SCORE_CHUNK) // 8192) * 8192
    W_dev = jnp.asarray(w_pad, jnp.float32)
    pad_row = w_pad.shape[0] - 1
    outs = []
    pending: list = []
    for lo in range(0, n, grid):
        hi = min(lo + grid, n)
        if isinstance(feats, SparseRows):
            x = feats[lo:hi].to_dense(d_re)
        else:
            x = np.asarray(feats[lo:hi], np.float32)
        if hi - lo < grid:
            x = np.pad(x, ((0, grid - (hi - lo)), (0, 0)))
        ix = np.full(grid, pad_row, np.int32)
        ix[: hi - lo] = np.where(idx[lo:hi] < 0, pad_row,
                                 idx[lo:hi]).astype(np.int32)
        pending.append(
            (_re_gather_dot(W_dev, jnp.asarray(x), jnp.asarray(ix)),
             hi - lo))
        if len(pending) >= 2:
            out, m = pending.pop(0)
            outs.append(np.asarray(out)[:m])
    for out, m in pending:
        outs.append(np.asarray(out)[:m])
    return (np.concatenate(outs) if outs
            else np.zeros(0, np.float32))


def _score_fixed(model: FixedEffectModel, dataset: GameDataset) -> np.ndarray:
    feats = dataset.features[model.feature_shard]
    w_np = np.asarray(model.coefficients.means)
    if isinstance(feats, np.ndarray):
        x = np.asarray(feats, np.float32)
        if model.intercept:
            x = np.concatenate([x, np.ones((len(x), 1), np.float32)], 1)
        return np.asarray(jnp.asarray(x) @ jnp.asarray(w_np))
    # Sparse rows: intercept is the last coefficient.  (GameDataset
    # normalizes legacy list rows to SparseRows at construction, so
    # this is the only sparse path.)  Large inputs stream through the
    # accelerator; small ones stay on the host numpy pass.
    base = w_np[-1] if model.intercept else 0.0
    from photon_ml_tpu.data.sparse_rows import SparseRows

    rows = feats if isinstance(feats, SparseRows) else \
        SparseRows.from_rows(feats)
    if (len(rows) >= _DEVICE_SCORE_MIN_ROWS
            and jax.default_backend() != "cpu"):
        return (_device_score_sparse(rows, w_np).astype(np.float64)
                + np.float32(base))
    return rows.dot_dense(w_np.astype(np.float64)) + np.float32(base)


def _projected_score_table(
    model: RandomEffectModel) -> tuple[np.ndarray, np.ndarray]:
    """Projected model → sorted ``(entity_row·G + global_col) → value``
    map: the model side of the scoring merge-join, computed ONCE and
    reused per chunk (the streaming scorer joins against it chunk by
    chunk; ``transform`` in one shot)."""
    G = np.int64(model.projection.global_dim)
    keys_parts, vals_parts = [], []
    ent_row_of = model.grouping.entity_row_map()
    for b, blk in enumerate(model.coefficient_blocks):
        fids = model.projection.feature_ids[b]
        blk = np.asarray(blk)
        rr, cc = np.nonzero(fids >= 0)
        if not len(rr):
            continue
        erow = ent_row_of[b, rr]
        keys_parts.append(erow * G + fids[rr, cc])
        vals_parts.append(blk[rr, cc].astype(np.float64))
    if not keys_parts:
        return np.zeros(0, np.int64), np.zeros(0, np.float64)
    keys = np.concatenate(keys_parts)
    vals = np.concatenate(vals_parts)
    order = np.argsort(keys)
    return keys[order], vals[order]


def _score_projected_rows(model: RandomEffectModel, table, idx, rows
                          ) -> np.ndarray:
    """Projected-model scores for one row range: merge-join of the
    rows' (entity row, global col) keys against the pre-sorted model
    table — all vectorized (no per-example Python).  ``idx`` is the
    rows' global entity index (−1 unseen), ``table`` from
    ``_projected_score_table``."""
    from photon_ml_tpu.game.dataset import sorted_key_join

    ks, vs = table
    n = len(rows)
    if ks.size == 0:
        return np.zeros(n, np.float32)
    G = np.int64(model.projection.global_dim)
    # One key per stored entry whose example's entity trained AND whose
    # column is inside the trained global space — out-of-space ids
    # would alias into the next entity's key range.
    row_of = rows.row_of()
    erow_nnz = idx[row_of]
    dsel = (erow_nnz >= 0) & (rows.cols.astype(np.int64) < G)
    key_d = erow_nnz[dsel] * G + rows.cols[dsel].astype(np.int64)
    w_at, hit = sorted_key_join(ks, vs, key_d, presorted=True)
    contrib = np.zeros(rows.nnz, np.float64)
    contrib[dsel] = np.where(hit, w_at, 0.0) * rows.vals[dsel]
    cs = np.zeros(rows.nnz + 1, np.float64)
    np.cumsum(contrib, out=cs[1:])
    return (cs[rows.indptr[1:]] - cs[rows.indptr[:-1]]).astype(np.float32)


def _score_random(model: RandomEffectModel, entity_ids: np.ndarray,
                  dataset: GameDataset) -> np.ndarray:
    from photon_ml_tpu.data.sparse_rows import SparseRows

    n = dataset.n
    idx = model.grouping.join_ids(entity_ids)

    if model.projection is None:
        feats = dataset.features[model.feature_shard]
        w_all = np.asarray(model.all_coefficients())   # [E, d_re]
        w_pad = np.vstack([w_all, np.zeros((1, w_all.shape[1]), w_all.dtype)])
        if (n >= _DEVICE_SCORE_MIN_ROWS
                and jax.default_backend() != "cpu"):
            # Large inputs ride the accelerator (gather+dot chunks) —
            # the sparse fixed-effect discipline, applied to the RE
            # coefficient-row gather (ISSUE 4 satellite).
            return _device_score_re(feats, w_pad, idx)
        x = np.asarray(feats, np.float32)
        gathered = w_pad[idx]                           # -1 → zero row
        return np.einsum("nd,nd->n", x, gathered).astype(np.float32)

    # Projected model: score in each entity's local subspace via a
    # sorted merge-join of (entity row, global col) keys — data side
    # from the example features, model side from each entity's
    # subspace.
    feats = dataset.features[model.feature_shard]
    rows = SparseRows.from_rows(feats)
    table = _projected_score_table(model)
    return _score_projected_rows(model, table, idx, rows)


@dataclasses.dataclass
class GameTransformer:
    """Score a GameDataset with a GameModel (margins per example)."""

    model: GameModel
    task: TaskType

    def transform(self, dataset: GameDataset) -> np.ndarray:
        """Summed raw scores [n] (+ dataset offsets, reference semantics)."""
        from photon_ml_tpu import telemetry

        total = dataset.offset_array().astype(np.float64).copy()
        with telemetry.span("transform", cat="score", n=int(dataset.n)):
            for name, comp in self.model.models.items():
                # One span per coordinate pass: the resident path walks
                # the dataset once PER COORDINATE — the report shows
                # which coordinate's pass dominates.
                with telemetry.span("score_coordinate", cat="score",
                                    coordinate=name):
                    if isinstance(comp, FixedEffectModel):
                        total += _score_fixed(comp, dataset)
                    elif isinstance(comp, RandomEffectModel):
                        ids = dataset.entity_ids[comp.entity_key or name]
                        total += _score_random(comp, ids, dataset)
                    else:
                        raise TypeError(
                            f"unknown component model {type(comp)}")
        return total.astype(np.float32)

    def transform_streamed(self, dataset: GameDataset,
                           score_chunk_rows: int = 1 << 20,
                           spill_dir: str | None = None,
                           host_max_resident: int = 2,
                           prefetch_depth: int = 2) -> np.ndarray:
        """Margins via the one-pass fused chunk pipeline
        (``estimators.streaming_scorer``) — identical to ``transform``
        up to float-summation order, with memory bounded by the chunk
        window instead of per-coordinate full passes."""
        from photon_ml_tpu.estimators.streaming_scorer import (
            StreamingGameScorer,
        )

        scorer = StreamingGameScorer(
            model=self.model, task=self.task,
            chunk_rows=score_chunk_rows, spill_dir=spill_dir,
            host_max_resident=host_max_resident,
            prefetch_depth=prefetch_depth)
        return scorer.score(dataset, keep_margins=True)["margins"]

    def transform_mean(self, dataset: GameDataset) -> np.ndarray:
        """Mean-space predictions (sigmoid/identity/exp of margins)."""
        margins = self.transform(dataset)
        return np.asarray(self.task.loss.mean(jnp.asarray(margins)))
