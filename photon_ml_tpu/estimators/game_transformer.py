"""GameTransformer: batch scoring of (new) data with a GameModel.

Reference counterpart: ``GameTransformer``
(photon-api ``com.linkedin.photon.ml.transformers.GameTransformer``
[expected path, mount unavailable — see SURVEY.md §2.6/§3.2]).

The reference scores per coordinate — fixed effect by broadcasting
coefficients over the data, random effects by joining data with the
per-entity coefficient RDD — and sums ``CoordinateDataScores``.  Here:

- fixed effect: one matmul (dense shard) or ELL gather-dot (sparse),
- random effect: host-side entity-id → trained-entity-index resolution
  (the "join"), then a device gather of coefficient rows + dot.
  Entities unseen at training time score 0, the reference's semantics.

The summed scores are raw margins (``ModelDataScores``); callers apply
the task's mean function for probability-space outputs.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import TaskType

Array = jax.Array


# Below this many rows the host numpy pass beats device dispatch +
# transfer; above it, sparse scoring streams ELL chunks through the
# accelerator (round-4 verdict: training rode the device, scoring 10⁸
# rows must not stay on host float64).
_DEVICE_SCORE_MIN_ROWS = 200_000
_DEVICE_SCORE_CHUNK = 2_000_000


def _device_score_sparse(rows, w_np: np.ndarray) -> np.ndarray:
    """Chunked device X·w over SparseRows: equal-shape ELL chunks (the
    tail is padded, so ONE compile serves every chunk), with at most
    two chunks in flight — chunk i's output is consumed before chunk
    i+2 dispatches, bounding device residency to two chunk buffers
    (unbounded dispatch-ahead would queue the whole dataset's ELL on
    device, defeating the chunking).

    The chunk grid is sized to min(n, _DEVICE_SCORE_CHUNK) rounded up
    to an 8192-row tile (advisor finding: padding every input to the
    fixed 2M grid made a 250k-row input pay ~8× wasted
    gather/rowsum/transfer); one compile still serves every chunk of a
    given input."""
    from photon_ml_tpu.ops.kernels import gather_rowsum

    n = len(rows)
    k = max(rows.max_nnz, 1)
    grid = -(-min(n, _DEVICE_SCORE_CHUNK) // 8192) * 8192
    w_dev = jnp.asarray(w_np, jnp.float32)
    score = jax.jit(gather_rowsum)
    outs = []
    pending: list = []
    for lo in range(0, n, grid):
        hi = min(lo + grid, n)
        cols, vals = rows[lo:hi].to_ell(row_capacity=k,
                                        pad_to=grid)
        pending.append(
            (score(w_dev, jnp.asarray(vals), jnp.asarray(cols)), hi - lo))
        if len(pending) >= 2:
            out, m = pending.pop(0)
            outs.append(np.asarray(out)[:m])
    for out, m in pending:
        outs.append(np.asarray(out)[:m])
    return np.concatenate(outs) if outs else np.zeros(0, np.float32)


def _score_fixed(model: FixedEffectModel, dataset: GameDataset) -> np.ndarray:
    feats = dataset.features[model.feature_shard]
    w_np = np.asarray(model.coefficients.means)
    if isinstance(feats, np.ndarray):
        x = np.asarray(feats, np.float32)
        if model.intercept:
            x = np.concatenate([x, np.ones((len(x), 1), np.float32)], 1)
        return np.asarray(jnp.asarray(x) @ jnp.asarray(w_np))
    # Sparse rows: intercept is the last coefficient.  (GameDataset
    # normalizes legacy list rows to SparseRows at construction, so
    # this is the only sparse path.)  Large inputs stream through the
    # accelerator; small ones stay on the host numpy pass.
    base = w_np[-1] if model.intercept else 0.0
    from photon_ml_tpu.data.sparse_rows import SparseRows

    rows = feats if isinstance(feats, SparseRows) else \
        SparseRows.from_rows(feats)
    if (len(rows) >= _DEVICE_SCORE_MIN_ROWS
            and jax.default_backend() != "cpu"):
        return (_device_score_sparse(rows, w_np).astype(np.float64)
                + np.float32(base))
    return rows.dot_dense(w_np.astype(np.float64)) + np.float32(base)


def _score_random(model: RandomEffectModel, entity_ids: np.ndarray,
                  dataset: GameDataset) -> np.ndarray:
    from photon_ml_tpu.data.sparse_rows import SparseRows

    n = dataset.n
    idx = model.grouping.join_ids(entity_ids)

    if model.projection is None:
        feats = dataset.features[model.feature_shard]
        x = np.asarray(feats, np.float32)
        w_all = np.asarray(model.all_coefficients())   # [E, d_re]
        w_pad = np.vstack([w_all, np.zeros((1, w_all.shape[1]), w_all.dtype)])
        gathered = w_pad[idx]                           # -1 → zero row
        return np.einsum("nd,nd->n", x, gathered).astype(np.float32)

    # Projected model: score in each entity's local subspace via a
    # sorted merge-join of (entity row, global col) keys — data side
    # from the example features, model side from each entity's
    # subspace — all vectorized (no per-example Python).
    feats = dataset.features[model.feature_shard]
    rows = SparseRows.from_rows(feats)
    g = model.grouping
    G = np.int64(model.projection.global_dim)

    # Model side: (entity row, global col) → coefficient value.
    keys_parts, vals_parts = [], []
    ent_row_of = g.entity_row_map()
    for b, blk in enumerate(model.coefficient_blocks):
        fids = model.projection.feature_ids[b]
        blk = np.asarray(blk)
        rr, cc = np.nonzero(fids >= 0)
        if not len(rr):
            continue
        erow = ent_row_of[b, rr]
        keys_parts.append(erow * G + fids[rr, cc])
        vals_parts.append(blk[rr, cc].astype(np.float64))
    if not keys_parts:
        return np.zeros(n, np.float32)
    key_m = np.concatenate(keys_parts)
    val_m = np.concatenate(vals_parts)

    # Data side: one key per stored entry whose example's entity
    # trained AND whose column is inside the trained global space —
    # out-of-space ids would alias into the next entity's key range.
    from photon_ml_tpu.game.dataset import sorted_key_join

    row_of = rows.row_of()
    erow_nnz = idx[row_of]
    dsel = (erow_nnz >= 0) & (rows.cols.astype(np.int64) < G)
    key_d = erow_nnz[dsel] * G + rows.cols[dsel].astype(np.int64)
    w_at, hit = sorted_key_join(key_m, val_m, key_d)
    contrib = np.zeros(rows.nnz, np.float64)
    contrib[dsel] = np.where(hit, w_at, 0.0) * rows.vals[dsel]
    cs = np.zeros(rows.nnz + 1, np.float64)
    np.cumsum(contrib, out=cs[1:])
    return (cs[rows.indptr[1:]] - cs[rows.indptr[:-1]]).astype(np.float32)


@dataclasses.dataclass
class GameTransformer:
    """Score a GameDataset with a GameModel (margins per example)."""

    model: GameModel
    task: TaskType

    def transform(self, dataset: GameDataset) -> np.ndarray:
        """Summed raw scores [n] (+ dataset offsets, reference semantics)."""
        total = dataset.offset_array().astype(np.float64).copy()
        for name, comp in self.model.models.items():
            if isinstance(comp, FixedEffectModel):
                total += _score_fixed(comp, dataset)
            elif isinstance(comp, RandomEffectModel):
                ids = dataset.entity_ids[comp.entity_key or name]
                total += _score_random(comp, ids, dataset)
            else:
                raise TypeError(f"unknown component model {type(comp)}")
        return total.astype(np.float32)

    def transform_mean(self, dataset: GameDataset) -> np.ndarray:
        """Mean-space predictions (sigmoid/identity/exp of margins)."""
        margins = self.transform(dataset)
        return np.asarray(self.task.loss.mean(jnp.asarray(margins)))
