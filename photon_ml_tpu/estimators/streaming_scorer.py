"""Streaming fused inference: one-pass multi-coordinate scoring (ISSUE 4).

Reference counterpart: the reference scores with one Spark pass per
coordinate and a union of ``CoordinateDataScores`` RDDs — but Spark
streams partitions, so no executor ever holds the dataset.  The
round-4..8 rebuild gave *training* that shape (congruent chunk
programs, disk→host→device prefetch, bounded host window); this module
gives the same architecture to the serving half:

- **One pass, fixed-shape chunks**: the dataset is walked ONCE in
  ``chunk_rows``-row chunks (tail padded — one compile serves every
  chunk) instead of once per coordinate.
- **One fused device program per chunk** computes the fixed-effect ELL
  gather-dot AND every random effect's coefficient-row gather-dot,
  sums them into margins, and applies the task mean function — so
  mean-space predictions never round-trip a full ``[n]`` array through
  the device (ISSUE 4 satellite; the old driver uploaded the whole
  margins array just to sigmoid it).
- **Projected random effects** are inherently host-side (per-entity
  subspace merge-join); their per-chunk scores are folded into the
  chunk's ``base`` plane (offsets + host scores) before device
  dispatch, so the device program stays one fused sum.
- **Overlapped I/O**: chunks optionally spill through the round-8
  ``data.chunk_store`` (atomic content-keyed ``.npz``, memory-mapped
  loads, LRU ``host_max_resident`` window — spilled chunks double as a
  persistent warm-scoring artifact) and are fed by the round-8
  ``optim.streaming.ChunkPrefetcher`` thread: disk read → host staging
  → async ``device_put`` of chunks i+1..i+depth hide under chunk i's
  compute, with the same lag-2 dispatch backpressure so in-flight
  device buffers stay bounded at two chunks.
- **Streaming downstream**: a writer thread drains finished chunks
  into the output sinks (``io.score_sink``: incremental ``.npz``,
  block-per-chunk Avro) while ``evaluation.streaming`` accumulators
  fold the metrics — neither output nor evaluation ever holds the full
  dataset.

``GameTransformer.transform`` remains the per-coordinate resident path
(validation-sized data); this pipeline produces margins identical to it
up to float-summation order (device f32 chunk sums vs host f64 full
passes — tested to float tolerance on every coordinate mix).
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import queue
import threading
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.reliability import faults as _faults
from photon_ml_tpu.telemetry import monitor as _mon
from photon_ml_tpu.data.sparse_rows import SparseRows
from photon_ml_tpu.game.dataset import GameDataset
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import TaskType

logger = logging.getLogger(__name__)

Array = jax.Array

# On-disk score-chunk format version (rides in the store key).
SCORE_CHUNK_VERSION = 1

# How many scored chunks may be in flight (dispatched, D2H copying)
# before the oldest is drained — two matches the device double-buffer
# everywhere else in the codebase.
_INFLIGHT = 2


@dataclasses.dataclass(frozen=True)
class _CoordSpec:
    """Static description of one coordinate's device-side scoring —
    the per-chunk program is specialized on the tuple of these."""

    name: str
    kind: str          # "fixed_sparse" | "fixed_dense" | "re"


@partial(jax.jit, static_argnums=(0, 1))
def _run_chunk(specs, mean_fn, tables, chunk):
    """THE fused per-chunk device program: every coordinate's
    contraction summed into margins + the task mean function, one
    dispatch per chunk.  Jitted at module level with the (hashable)
    spec tuple and mean function static, so every scorer instance for
    the same model STRUCTURE shares one compile — repeated scoring
    passes (bench arms, driver re-runs in-process) never re-trace."""
    from photon_ml_tpu.ops.kernels import gather_rowsum

    m = chunk["base"]
    for s in specs:
        if s.kind == "fixed_sparse":
            m = m + gather_rowsum(
                tables[s.name], chunk[s.name + ".vals"],
                chunk[s.name + ".cols"]) + tables[s.name + ".base"]
        elif s.kind == "fixed_dense":
            m = m + chunk[s.name + ".x"] @ tables[s.name] \
                + tables[s.name + ".base"]
        else:   # re: coefficient-row gather-dot
            m = m + jnp.sum(
                chunk[s.name + ".x"]
                * tables[s.name][chunk[s.name + ".idx"]],
                axis=-1)
    return m, mean_fn(m)


class _SinkWriter:
    """Background writer thread: drains finished (host) chunks into the
    output sinks while the device scores later chunks.  Items are
    written in queue order (the main loop drains chunks in sweep order,
    so sinks see rows in order); errors surface at ``close``."""

    _SENTINEL = object()

    def __init__(self, sinks):
        self._sinks = list(sinks)
        self._q: queue.Queue = queue.Queue(maxsize=4)
        # _error crosses threads (written by the writer, read by the
        # producer mid-stream in put()), so it lives under a lock —
        # the photon-lint unlocked-shared-write contract.
        self._lock = threading.Lock()
        self._error: BaseException | None = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="photon-score-writer")
        self._thread.start()

    def _failed(self) -> "BaseException | None":
        with self._lock:
            return self._error

    def _next_item(self):
        """Queue pop; with telemetry active, polls with liveness
        heartbeats so a starved (or hung-upstream) writer thread is
        visible in the run log."""
        t = telemetry.active()
        if t is None:
            # photon-lint: disable=eternal-wait (close() always enqueues the sentinel, and put() runs on the producer that also calls close(); the get is bounded by shutdown)
            return self._q.get()
        start = time.perf_counter()
        beat = start
        while True:
            try:
                return self._q.get(timeout=0.05)
            except queue.Empty:
                now = time.perf_counter()
                if now - beat >= t.heartbeat_s:
                    t.heartbeat("sink-writer", state="queue_empty",
                                waiting_s=round(now - start, 3))
                    beat = now

    def _run(self) -> None:
        while True:
            item = self._next_item()
            if item is self._SENTINEL:
                return
            if self._failed() is not None:
                continue       # drain without writing after a failure
            try:
                lo, hi, margins, preds, labels, ids = item
                t0 = time.perf_counter()
                with telemetry.span("sink_write", cat="sink",
                                    lo=lo, hi=hi):
                    _faults.fire("sink.write", lo=lo, hi=hi)
                    for s in self._sinks:
                        s.write(lo, hi, margins, preds, labels, ids=ids)
                telemetry.observe("sink.write_s",
                                  time.perf_counter() - t0)
            except BaseException as e:
                # Death event first (hung-run forensics), then the
                # locked error hand-off the producer reads in put().
                telemetry.thread_exception("sink-writer", e)
                with self._lock:
                    self._error = e
                # A failed writer must never leave a torn container on
                # disk, no matter what the producer does next (ISSUE 9
                # satellite): abort every sink HERE, at the chunk
                # boundary the failure landed on.  abort() is
                # idempotent, so the producer's own cleanup racing this
                # is harmless.
                for s in self._sinks:
                    try:
                        s.abort()
                    except BaseException:  # photon-lint: disable=swallowed-exception (cleanup of an already-failed sink; the primary error is already recorded above)
                        pass

    def put(self, lo, hi, margins, preds, labels, ids) -> None:
        err = self._failed()
        if err is not None:
            raise err
        telemetry.gauge("sink.queue_depth", self._q.qsize())
        self._q.put((lo, hi, margins, preds, labels, ids))

    def close(self) -> None:
        self._q.put(self._SENTINEL)
        # Bounded drain (photon-lint eternal-wait): a sink wedged in a
        # hung filesystem write must surface as an actionable error,
        # not pin close() forever.
        self._thread.join(timeout=600.0)
        if self._thread.is_alive():
            raise RuntimeError(
                "score sink writer did not drain within 600s (sink "
                "write wedged); output containers may be incomplete")
        err = self._failed()
        if err is not None:
            raise err


def _fingerprint_arrays(parts, extra: str = "") -> str:
    """blake2b content key over a sequence of arrays (+ a config tag).
    Hashes through the buffer protocol — no ``tobytes`` copy, so the
    transient RSS cost is zero for already-contiguous arrays (the
    bounded-window pipeline must not double-buffer its own inputs)."""
    h = hashlib.blake2b(digest_size=16)
    for a in parts:
        a = np.ascontiguousarray(np.asarray(a))
        h.update(str((a.shape, a.dtype.str)).encode())
        h.update(memoryview(a).cast("B"))
    h.update(extra.encode())
    return h.hexdigest()


class StreamingGameScorer:
    """One-pass fused scoring of a ``GameDataset`` with a ``GameModel``.

    ``chunk_rows`` fixes the chunk grid (tail padded).  ``spill_dir``
    (None = chunks are built on the fly each pass, never all resident)
    activates the disk tier: prepared score chunks spill to
    content-keyed ``.npz`` files at plan time — built ONE AT A TIME, so
    the ELL densification never materializes more than a window of
    chunks — and stream back memory-mapped through an LRU
    ``host_max_resident`` window.  ``prefetch_depth`` > 0 runs the
    background disk→host→device prefetch thread either way (without a
    store it overlaps chunk BUILD with device compute).
    """

    def __init__(self, model: GameModel, task: TaskType,
                 chunk_rows: int = 1 << 20,
                 spill_dir: str | None = None,
                 host_max_resident: int = 2,
                 prefetch_depth: int = 2):
        if chunk_rows <= 0:
            raise ValueError("chunk_rows must be positive")
        self.model = model
        self.task = task
        self.chunk_rows = int(chunk_rows)
        self.spill_dir = spill_dir
        self.host_max_resident = int(host_max_resident)
        self.prefetch_depth = int(prefetch_depth)
        # Plan memo for repeated score() calls over the SAME dataset
        # object (bench arms, in-process re-scoring): the plan embeds
        # device tables and — with a spill store — a full content hash
        # of every chunk input, which would otherwise be re-derived per
        # pass.  Identity-keyed (strong ref); callers mutating a
        # dataset in place must use a fresh scorer (or dataset) — the
        # same contract as the training objective's device chunk cache.
        self._plan_memo: tuple | None = None
        self._key_memo: tuple | None = None

    # -- plan ---------------------------------------------------------------

    def _plan(self, dataset: GameDataset):
        """Classify coordinates, resolve entity joins, build device
        tables, and return (specs, tables, build_chunk, key_parts)."""
        from photon_ml_tpu.estimators.game_transformer import (
            _projected_score_table,
            _score_projected_rows,
        )

        n = dataset.n
        R = self.chunk_rows
        specs: list[_CoordSpec] = []
        tables: dict = {}
        builders: dict = {}    # name -> per-chunk host-array builder
        host_parts: list = []  # (model, table, idx, rows) projected REs
        key_parts: list = [dataset.offset_array()]
        key_cfg: list = [f"v{SCORE_CHUNK_VERSION}", f"R{R}"]

        for name, comp in self.model.models.items():
            if isinstance(comp, FixedEffectModel):
                feats = dataset.features[comp.feature_shard]
                w_np = np.asarray(comp.coefficients.means, np.float32)
                if isinstance(feats, np.ndarray):
                    x_all = np.asarray(feats, np.float32)
                    specs.append(_CoordSpec(name, "fixed_dense"))
                    tables[name] = jnp.asarray(
                        w_np[:-1] if comp.intercept else w_np)
                    tables[name + ".base"] = jnp.float32(
                        w_np[-1] if comp.intercept else 0.0)

                    def build_dense(lo, hi, x_all=x_all):
                        x = x_all[lo:hi]
                        if hi - lo < R:
                            x = np.pad(x, ((0, R - (hi - lo)), (0, 0)))
                        return {".x": np.ascontiguousarray(x)}

                    builders[name] = build_dense
                    key_parts.append(x_all)
                    key_cfg.append(f"{name}:dense:{comp.intercept}")
                else:
                    rows = feats if isinstance(feats, SparseRows) else \
                        SparseRows.from_rows(feats)
                    k = max(rows.max_nnz, 1)
                    specs.append(_CoordSpec(name, "fixed_sparse"))
                    tables[name] = jnp.asarray(w_np)
                    tables[name + ".base"] = jnp.float32(
                        w_np[-1] if comp.intercept else 0.0)

                    def build_sparse(lo, hi, rows=rows, k=k):
                        cols, vals = rows[lo:hi].to_ell(
                            row_capacity=k, pad_to=R)
                        return {".cols": cols, ".vals": vals}

                    builders[name] = build_sparse
                    key_parts.extend([rows.indptr, rows.cols, rows.vals])
                    key_cfg.append(f"{name}:sparse:k{k}:{comp.intercept}")
            elif isinstance(comp, RandomEffectModel):
                ids = dataset.entity_ids[comp.entity_key or name]
                idx = comp.grouping.join_ids(ids)
                feats = dataset.features[comp.feature_shard]
                if comp.projection is not None:
                    # Host-side subspace merge-join, chunk by chunk —
                    # folded into the base plane below.
                    rows = feats if isinstance(feats, SparseRows) else \
                        SparseRows.from_rows(feats)
                    table = _projected_score_table(comp)
                    host_parts.append((comp, table, idx, rows))
                    key_parts.extend([rows.indptr, rows.cols, rows.vals,
                                      idx, table[0], table[1]])
                    key_cfg.append(f"{name}:proj")
                    continue
                w_all = np.asarray(comp.all_coefficients(), np.float32)
                E, d_re = w_all.shape
                w_pad = np.vstack([w_all, np.zeros((1, d_re), np.float32)])
                specs.append(_CoordSpec(name, "re"))
                tables[name] = jnp.asarray(w_pad)
                idx32 = np.where(idx < 0, E, idx).astype(np.int32)

                def build_re(lo, hi, feats=feats, idx32=idx32, E=E,
                             d_re=d_re):
                    if isinstance(feats, SparseRows):
                        x = feats[lo:hi].to_dense(d_re)
                    else:
                        x = np.asarray(feats[lo:hi], np.float32)
                    if hi - lo < R:
                        x = np.pad(x, ((0, R - (hi - lo)), (0, 0)))
                    ix = np.full(R, E, np.int32)
                    ix[: hi - lo] = idx32[lo:hi]
                    return {".x": np.ascontiguousarray(x), ".idx": ix}

                builders[name] = build_re
                if isinstance(feats, SparseRows):
                    key_parts.extend([feats.indptr, feats.cols,
                                      feats.vals])
                else:
                    key_parts.append(np.asarray(feats, np.float32))
                key_parts.append(idx32)
                key_cfg.append(f"{name}:re:d{d_re}")
            else:
                raise TypeError(f"unknown component model {type(comp)}")

        offsets = dataset.offset_array()

        def build_chunk(i: int) -> dict:
            lo = i * R
            hi = min(lo + R, n)
            base = np.zeros(R, np.float32)
            base[: hi - lo] = offsets[lo:hi]
            for comp, table, idx, rows in host_parts:
                base[: hi - lo] += _score_projected_rows(
                    comp, table, idx[lo:hi], rows[lo:hi])
            chunk = {"base": base}
            for name, build in builders.items():
                for suffix, arr in build(lo, hi).items():
                    chunk[name + suffix] = arr
            return chunk

        return tuple(specs), tables, build_chunk, (key_parts, key_cfg)

    def _make_program(self, specs):
        mean = self.task.loss.mean

        def run(tables, chunk):
            return _run_chunk(specs, mean, tables, chunk)

        return run

    def _store_key(self, key_parts) -> str:
        """Content key for the spill store, memoized alongside the plan
        (identity on the plan's key_parts): repeated score() calls over
        the same dataset must not re-hash the full content per pass."""
        if self._key_memo is None or self._key_memo[0] is not key_parts:
            parts, cfg = key_parts
            self._key_memo = (
                key_parts,
                "score-" + _fingerprint_arrays(parts, "|".join(cfg)))
        return self._key_memo[1]

    def _make_store(self, n_chunks: int, key_parts, build_chunk):
        from photon_ml_tpu.data.chunk_store import (
            ChunkStore,
            decode_array_chunk,
            encode_array_chunk,
            release_free_heap,
        )

        key = self._store_key(key_parts)
        store = ChunkStore(
            self.spill_dir, key, n_chunks,
            host_max_resident=self.host_max_resident,
            rebuild=build_chunk,
            codec=(encode_array_chunk, decode_array_chunk))
        missing = [i for i in range(n_chunks) if not store.has(i)]
        for i in missing:        # one chunk in flight: bounded ETL RSS
            store.put(i, build_chunk(i))
        if missing:
            release_free_heap()
        logger.info(
            "score chunks: %d spilled to %s (%d built, %d reused; "
            "host window %d)", n_chunks, self.spill_dir, len(missing),
            n_chunks - len(missing), store.host_max_resident)
        return store

    # -- the pass -----------------------------------------------------------

    def score(self, dataset: GameDataset, sinks=(), evaluators=(),
              keep_margins: bool = False) -> dict:
        """One fused pass.  ``sinks``: ``io.score_sink`` writers
        (drained by a background thread).  ``evaluators``:
        ``evaluation.streaming`` adapters (updated in chunk order on
        the main thread).  ``keep_margins`` additionally returns full
        ``margins``/``predictions`` arrays (parity tests / small runs —
        defeats the bounded-memory point at scale)."""
        from photon_ml_tpu.optim.streaming import ChunkPrefetcher

        n = dataset.n
        R = self.chunk_rows
        n_chunks = max(1, -(-n // R))
        if (self._plan_memo is not None
                and self._plan_memo[0] is dataset):
            specs, tables, build_chunk, key_parts = self._plan_memo[1]
        else:
            planned = self._plan(dataset)
            # The dataset object itself anchors the memo (an id() key
            # could be reused by a new dataset after GC); the plan's
            # builders close over its arrays anyway.
            self._plan_memo = (dataset, planned)
            specs, tables, build_chunk, key_parts = planned
        run = self._make_program(specs)

        from photon_ml_tpu.data.chunk_store import probe_spill_dir

        store = None
        # Unwritable spill dir degrades to build-on-the-fly chunks with
        # one warning (ISSUE 9): the disk tier is an optimization here,
        # never a correctness dependency.
        if probe_spill_dir(self.spill_dir) is not None:
            store = self._make_store(n_chunks, key_parts, build_chunk)
            load = store.get
        else:
            load = build_chunk

        labels = dataset.labels
        # Only evaluators read weights; without them the [n] ones array
        # weight_array() synthesizes would be dead resident memory.
        weights = dataset.weight_array() if evaluators else None
        entity_cols = dataset.entity_ids

        margins_out = np.empty(n, np.float32) if keep_margins else None
        preds_out = np.empty(n, np.float32) if keep_margins else None
        writer = _SinkWriter(sinks) if sinks else None
        evaluators = list(evaluators)

        def drain(item) -> None:
            i, m_dev, p_dev = item
            lo = i * R
            hi = min(lo + R, n)
            t0 = time.perf_counter()
            with telemetry.span("chunk_drain", cat="score", chunk=i):
                # Planned D2H harvest spelled explicitly (device_get) so
                # the chunk loop stays clean under
                # guards.no_implicit_transfers.
                m = jax.device_get(m_dev)[: hi - lo]
                p = jax.device_get(p_dev)[: hi - lo]
                lab = labels[lo:hi]
                for ev in evaluators:
                    ev.update(m, p, lab, weights[lo:hi])
                if writer is not None:
                    writer.put(lo, hi, m, p, lab,
                               {k: v[lo:hi]
                                for k, v in entity_cols.items()})
                if keep_margins:
                    margins_out[lo:hi] = m
                    preds_out[lo:hi] = p
            telemetry.observe("score.chunk_drain_s",
                              time.perf_counter() - t0)

        def placed_chunks():
            """Device chunks in order, prefetched (build/disk-read +
            async transfer under compute) when depth > 0."""
            if self.prefetch_depth > 0:
                pf = ChunkPrefetcher(load, jax.device_put,
                                     self.prefetch_depth, store=store)
                pf.start(range(n_chunks))
                try:
                    for i in range(n_chunks):
                        yield pf.next(i)
                finally:
                    pf.close()
            else:
                for i in range(n_chunks):
                    yield jax.device_put(load(i))

        # perf_counter, not time.time: the difference below is DURATION
        # arithmetic (the photon-lint naked-clock rule — wall clock
        # steps under NTP adjustment).
        t0 = time.perf_counter()
        pending: list = []
        try:
            with telemetry.span("score_pass", cat="score",
                                chunks=n_chunks):
                telemetry.count("score.passes")
                for i, buf in enumerate(placed_chunks()):
                    with telemetry.span("chunk_compute", cat="device"):
                        if pending:
                            # Lag-2 dispatch backpressure (the round-8
                            # rule): the previous chunk's margins are
                            # fenced before this chunk dispatches, so
                            # the async queue holds ~two chunks'
                            # buffers, not all K.  D2H copies of
                            # drained chunks keep overlapping
                            # regardless.
                            jax.block_until_ready(pending[-1][1])
                        m, p = run(tables, buf)
                    for out in (m, p):
                        try:
                            out.copy_to_host_async()
                        except AttributeError:  # photon-lint: disable=swallowed-exception (backends without async D2H; drain copies synchronously)
                            pass
                    pending.append((i, m, p))
                    # Live scoring progress in ROWS (ISSUE 10): the
                    # monitor's rolling rate is then rows/s directly.
                    _mon.progress("score", min((i + 1) * R, n), n,
                                  unit="rows")
                    if len(pending) > _INFLIGHT:
                        drain(pending.pop(0))
                for item in pending:
                    drain(item)
                if writer is not None:
                    writer.close()
                    writer = None
                for s in sinks:
                    s.close()
        except BaseException:
            if writer is not None:
                try:
                    writer.close()
                except BaseException:  # photon-lint: disable=swallowed-exception (error-path cleanup; the original pass failure re-raises below)
                    pass
            for s in sinks:
                try:
                    s.abort()
                except BaseException:  # photon-lint: disable=swallowed-exception (error-path cleanup; the original pass failure re-raises below)
                    pass
            raise
        wall_s = time.perf_counter() - t0

        result = {
            "n": int(n),
            "n_chunks": int(n_chunks),
            "chunk_rows": int(R),
            "wall_s": wall_s,
            "rows_per_sec": (n / wall_s) if wall_s > 0 else None,
            "evaluation": {ev.type.value: ev.result()
                           for ev in evaluators},
        }
        if store is not None:
            result["store"] = {
                "loads": store.loads, "hits": store.hits,
                "spills": store.spills,
                "peak_resident": store.peak_resident,
            }
        if keep_margins:
            result["margins"] = margins_out
            result["predictions"] = preds_out
        return result
