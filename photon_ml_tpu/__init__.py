"""photon_ml_tpu — a TPU-native framework with the capabilities of Photon ML.

A ground-up JAX/XLA/Pallas re-design (NOT a port) of the reference
hubayirp/photon-ml (a fork of linkedin/photon-ml): GLMs (logistic, linear,
Poisson, smoothed-hinge SVM) and GAME generalized additive mixed-effect
models, trained by L-BFGS / OWL-QN / TRON, scaled by data parallelism
(shard_map + psum over ICI) and entity sharding (vmapped per-entity solves)
instead of Spark RDDs, broadcast, and treeAggregate.

See SURVEY.md at the repo root for the layer map this package mirrors.
"""

__version__ = "0.1.0"
