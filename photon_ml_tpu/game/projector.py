"""Per-entity linear subspace projection for sparse random-effect shards.

Reference counterpart: ``LinearSubspaceProjector`` / ``ProjectorType``
(photon-api ``com.linkedin.photon.ml.projector`` [expected paths, mount
unavailable — see SURVEY.md §2.4]).

Purpose (same as the reference): a random-effect feature shard may be
wide (10⁴⁺ features), but each entity only ever sees a few dozen of
them — so each entity's local problem is solved in the subspace of
features it actually observed, making per-entity coefficient vectors
tiny and vmapped solves dense.

TPU translation: projection happens ONCE, in the host ETL.  For each
entity, the distinct global feature ids it saw become its subspace
(``feature_ids [E, p]``, padded); its examples' sparse entries are
remapped to local column indices and densified into [cap, p] blocks.
Device-side training never sees the global width.  ``project_back``
scatters learned local coefficients into global-width rows for model
export/scoring against new data.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from photon_ml_tpu.game.dataset import EntityGrouping


@dataclasses.dataclass
class SubspaceProjection:
    """Per-bucket per-entity subspaces for one random-effect shard.

    ``feature_ids[b]`` is [E_b, p_b] int32: global feature id of each
    local column (−1 padding).  ``local_dim[b]`` = p_b.
    """

    feature_ids: list[np.ndarray]
    global_dim: int

    def local_dim(self, bucket: int) -> int:
        return self.feature_ids[bucket].shape[1]

    def project_back(self, bucket: int, w_local: np.ndarray) -> list[
            tuple[np.ndarray, np.ndarray]]:
        """[E_b, p_b] local coefficients → per-entity sparse global rows
        (col_ids, values) — the reference's model-export direction."""
        fids = self.feature_ids[bucket]
        out = []
        for e in range(fids.shape[0]):
            valid = fids[e] >= 0
            out.append((fids[e][valid], np.asarray(w_local[e])[valid]))
        return out


def build_subspace_projection(
    grouping: EntityGrouping,
    rows: list[tuple[np.ndarray, np.ndarray]],
    global_dim: int,
) -> tuple[SubspaceProjection, list[np.ndarray]]:
    """Build per-entity subspaces + projected dense feature blocks.

    Args:
      grouping: entity grouping of the n examples.
      rows: per-example sparse (col_ids, values) in the GLOBAL space.
      global_dim: width of the global space.

    Returns:
      (projection, x_blocks) where ``x_blocks[b]`` is a dense
      [E_b, cap_b, p_b] array of projected features.
    """
    from photon_ml_tpu.data.sparse_rows import SparseRows

    rows = SparseRows.from_rows(rows)
    n_buckets = len(grouping.capacities)
    E = grouping.n_total_entities

    # Global entity index per example (stored by group_by_entity; rebuilt
    # from (bucket, slot) for groupings that predate the field).
    ex_entity = grouping.example_entity
    if ex_entity is None:
        ent_of = grouping.entity_row_map()
        ex_entity = ent_of[grouping.example_bucket, grouping.example_row]

    # Distinct (entity, global feature) pairs, sorted — each entity's
    # subspace is its run of distinct features; the run offset is the
    # feature's LOCAL column.  All vectorized (SURVEY §7 ETL scale).
    row_of = rows.row_of()
    ent_nnz = np.asarray(ex_entity)[row_of]
    order = np.lexsort((rows.cols, ent_nnz))
    e_s = ent_nnz[order]
    c_s = rows.cols[order].astype(np.int64)
    nnz = len(e_s)
    if nnz:
        new_g = np.empty(nnz, bool)
        new_g[0] = True
        np.logical_or(e_s[1:] != e_s[:-1], c_s[1:] != c_s[:-1],
                      out=new_g[1:])
        gid_s = np.cumsum(new_g) - 1
        starts = np.flatnonzero(new_g)
        ge = e_s[starts]                    # entity of each distinct feat
        gc = c_s[starts]                    # global col of each
    else:
        gid_s = np.zeros(0, np.int64)
        ge = np.zeros(0, np.int64)
        gc = np.zeros(0, np.int64)
    ent_feat_count = np.bincount(ge, minlength=E)
    ent_feat_start = np.zeros(E, np.int64)
    np.cumsum(ent_feat_count[:-1], out=ent_feat_start[1:])
    loc_of_group = np.arange(len(ge), dtype=np.int64) - ent_feat_start[ge]
    # Local column of every stored entry, in original nnz order.
    loc = np.empty(nnz, np.int64)
    loc[order] = loc_of_group[gid_s]

    feature_ids = []
    x_blocks = []
    ent_bucket = np.asarray(grouping.entity_bucket)
    ent_slot = np.asarray(grouping.entity_slot)
    for b in range(n_buckets):
        ne = grouping.n_entities[b]
        members = ent_bucket == b
        p = int(ent_feat_count[members].max()) if members.any() else 1
        p = max(p, 1)
        fids = np.full((ne, p), -1, np.int32)
        gsel = ent_bucket[ge] == b
        fids[ent_slot[ge[gsel]], loc_of_group[gsel]] = gc[gsel]
        feature_ids.append(fids)

        cap = grouping.capacities[b]
        xb = np.zeros((ne, cap, p), np.float32)
        nsel = ent_bucket[ent_nnz] == b
        ex = row_of[nsel]
        xb[grouping.example_row[ex], grouping.example_col[ex],
           loc[nsel]] = rows.vals[nsel]
        x_blocks.append(xb)

    return SubspaceProjection(feature_ids=feature_ids,
                              global_dim=global_dim), x_blocks
