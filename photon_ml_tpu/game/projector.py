"""Per-entity linear subspace projection for sparse random-effect shards.

Reference counterpart: ``LinearSubspaceProjector`` / ``ProjectorType``
(photon-api ``com.linkedin.photon.ml.projector`` [expected paths, mount
unavailable — see SURVEY.md §2.4]).

Purpose (same as the reference): a random-effect feature shard may be
wide (10⁴⁺ features), but each entity only ever sees a few dozen of
them — so each entity's local problem is solved in the subspace of
features it actually observed, making per-entity coefficient vectors
tiny and vmapped solves dense.

TPU translation: projection happens ONCE, in the host ETL.  For each
entity, the distinct global feature ids it saw become its subspace
(``feature_ids [E, p]``, padded); its examples' sparse entries are
remapped to local column indices and densified into [cap, p] blocks.
Device-side training never sees the global width.  ``project_back``
scatters learned local coefficients into global-width rows for model
export/scoring against new data.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from photon_ml_tpu.game.dataset import EntityGrouping


@dataclasses.dataclass
class SubspaceProjection:
    """Per-bucket per-entity subspaces for one random-effect shard.

    ``feature_ids[b]`` is [E_b, p_b] int32: global feature id of each
    local column (−1 padding).  ``local_dim[b]`` = p_b.
    """

    feature_ids: list[np.ndarray]
    global_dim: int

    def local_dim(self, bucket: int) -> int:
        return self.feature_ids[bucket].shape[1]

    def project_back(self, bucket: int, w_local: np.ndarray) -> list[
            tuple[np.ndarray, np.ndarray]]:
        """[E_b, p_b] local coefficients → per-entity sparse global rows
        (col_ids, values) — the reference's model-export direction."""
        fids = self.feature_ids[bucket]
        out = []
        for e in range(fids.shape[0]):
            valid = fids[e] >= 0
            out.append((fids[e][valid], np.asarray(w_local[e])[valid]))
        return out


def build_subspace_projection(
    grouping: EntityGrouping,
    rows: list[tuple[np.ndarray, np.ndarray]],
    global_dim: int,
) -> tuple[SubspaceProjection, list[np.ndarray]]:
    """Build per-entity subspaces + projected dense feature blocks.

    Args:
      grouping: entity grouping of the n examples.
      rows: per-example sparse (col_ids, values) in the GLOBAL space.
      global_dim: width of the global space.

    Returns:
      (projection, x_blocks) where ``x_blocks[b]`` is a dense
      [E_b, cap_b, p_b] array of projected features.
    """
    n_buckets = len(grouping.capacities)
    # Distinct features per entity.
    entity_feats: list[np.ndarray] = []
    for e in range(grouping.n_total_entities):
        entity_feats.append(np.empty(0, np.int64))
    feats_accum: dict[int, set] = {}
    uniq_pos = {int(v): i for i, v in enumerate(grouping.entity_ids)}

    # Map each example to its global entity index via (bucket, row).
    slot_to_entity = {}
    for e in range(grouping.n_total_entities):
        slot_to_entity[(int(grouping.entity_bucket[e]),
                        int(grouping.entity_slot[e]))] = e

    ex_entity = np.empty(grouping.n_examples, np.int64)
    for i in range(grouping.n_examples):
        ex_entity[i] = slot_to_entity[(int(grouping.example_bucket[i]),
                                       int(grouping.example_row[i]))]

    for i, (c, _) in enumerate(rows):
        s = feats_accum.setdefault(int(ex_entity[i]), set())
        s.update(int(x) for x in c)

    for e, s in feats_accum.items():
        entity_feats[e] = np.asarray(sorted(s), np.int64)

    # Per-bucket local width = max distinct features among its entities.
    feature_ids = []
    x_blocks = []
    for b in range(n_buckets):
        members = np.where(grouping.entity_bucket == b)[0]
        p = max((len(entity_feats[e]) for e in members), default=1)
        p = max(p, 1)
        fids = np.full((len(members), p), -1, np.int32)
        local_index: list[dict] = []
        for s_i, e in enumerate(members):
            f = entity_feats[e]
            fids[s_i, : len(f)] = f
            local_index.append({int(g): j for j, g in enumerate(f)})
        feature_ids.append(fids)

        cap = grouping.capacities[b]
        xb = np.zeros((len(members), cap, p), np.float32)
        sel = np.where(grouping.example_bucket == b)[0]
        for i in sel:
            r = grouping.example_row[i]
            col = grouping.example_col[i]
            li = local_index[r]
            c, v = rows[i]
            for g, val in zip(c, v):
                xb[r, col, li[int(g)]] = val
        x_blocks.append(xb)

    return SubspaceProjection(feature_ids=feature_ids,
                              global_dim=global_dim), x_blocks
