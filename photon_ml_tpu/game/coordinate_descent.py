"""Coordinate descent: the GAME outer loop.

Reference counterpart: ``CoordinateDescent``
(photon-api ``com.linkedin.photon.ml.algorithm.CoordinateDescent``
[expected path, mount unavailable — see SURVEY.md §2.3/§3.1]).

Semantics mirror the reference exactly:

    for iteration 1..N:
      for coordinate in update_sequence:
        offsets   = total_scores − coordinate_scores[coordinate]
        model     = coordinate.train(offsets, warm start = prior coefs)
        scores    = coordinate.score(model)
        total     = total − old_scores + new_scores
      (validation metrics once per iteration)

The loop itself is host-level Python — like the reference's driver loop
— but every ``train``/``score`` inside it is a single jitted device
program, so per-coordinate work is one dispatch, and scores/offsets
live on device for the whole descent (no host round-trips between
coordinates).
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.reliability import checkpoint as _ckpt
from photon_ml_tpu.telemetry import convergence as _conv
from photon_ml_tpu.telemetry import monitor as _mon
from photon_ml_tpu.game.coordinates import Coordinate

logger = logging.getLogger(__name__)


def _serialize_history(history: list) -> list:
    """Per-iteration diagnostics → checkpoint-tree form (raw
    OptimizationResult diagnostics reduce through ``_diag_fields``;
    already-serialized entries — a resumed run's restored prefix —
    pass through)."""
    out = []
    for iter_diag in history:
        out.append({name: (diag if isinstance(diag, dict)
                           else _diag_fields(diag))
                    for name, diag in iter_diag.items()})
    return out


def _serialize_validation(entries: list) -> list:
    out = []
    for e in entries:
        if isinstance(e, dict):
            out.append({str(getattr(k, "value", k)): float(v)
                        for k, v in e.items()})
        else:
            out.append(float(e))
    return out


def _revive_validation(entries: list) -> list:
    """Inverse of ``_serialize_validation``: dict keys come back as
    ``EvaluatorType`` where they parse (downstream model selection
    indexes evaluations by the enum), else stay strings."""
    from photon_ml_tpu.evaluation.evaluators import EvaluatorType

    out = []
    for e in entries or []:
        if isinstance(e, dict):
            revived = {}
            for k, v in e.items():
                try:
                    revived[EvaluatorType(k)] = v
                except ValueError:
                    revived[k] = v
            out.append(revived)
        else:
            out.append(e)
    return out


@jax.jit
def _re_diag_reduce(diag):
    """Batched-RE convergence aggregation as ONE device program: the
    per-bucket Python loop of ``jnp.sum``/``jnp.max`` calls performed
    one blocking host sync per bucket per stat (ISSUE 5 satellite);
    this folds every bucket's reduction into a single dispatch whose
    result is fetched with one bulk device→host copy per sweep."""
    conv = sum(jnp.sum(r.converged.astype(jnp.int32)) for r in diag)
    iters = jnp.max(jnp.stack([jnp.max(r.iterations) for r in diag]))
    return conv, iters


def _diag_fields(diag) -> dict:
    """Scalar convergence fields from a coordinate's train diagnostics
    (an ``OptimizationResult`` for fixed effects; a per-bucket list of
    batched results for random effects; a plain dict — already host
    scalars — for the streamed random-effect coordinate)."""
    if isinstance(diag, dict):
        return dict(diag)
    if hasattr(diag, "value") and jnp.ndim(diag.value) == 0:
        out = {
            "value": float(diag.value),
            "grad_norm": float(diag.grad_norm),
            "solver_iterations": int(diag.iterations),
            "converged": bool(diag.converged),
        }
        tracker = getattr(diag, "tracker", None)
        if tracker is not None and int(tracker.count) > 0:
            # Per-solver-iteration convergence trace (reference
            # OptimizationStatesTracker; slot 0 = initial point).
            # Bulk device→host copies, not one sync per element.
            c = int(tracker.count)
            out["states"] = {
                "values": np.round(
                    np.asarray(tracker.values[:c], np.float64), 8).tolist(),
                "grad_norms": np.round(
                    np.asarray(tracker.grad_norms[:c], np.float64),
                    8).tolist(),
            }
        return out
    if isinstance(diag, (list, tuple)) and diag and hasattr(diag[0], "value"):
        # Batched per-entity results: one jitted reduction, one bulk
        # device→host copy (not one sync per bucket per stat).
        n = sum(int(r.value.shape[0]) for r in diag)
        conv, iters = jax.device_get(_re_diag_reduce(list(diag)))
        return {"entities": n, "entities_converged": int(conv),
                "max_solver_iterations": int(iters)}
    return {}


def _call_validator(validator, coefs, total):
    """Call a per-sweep validator, accepting both the current two-arg
    ``(coefficients, total_scores)`` signature and the pre-round-4
    one-arg ``(total_scores)`` form (advisor finding: the signature
    changed with no shim, so an external caller's old validator would
    TypeError mid-training).  Arity is inspected up front — catching
    TypeError around the call would mask genuine TypeErrors raised
    *inside* the validator.  The rule is TOTAL positional count
    (advisor finding: counting only REQUIRED positionals misclassified
    a current-API ``(coefficients, total_scores=None)`` validator as
    legacy and silently bound its coefficients to the scores slot):
    a callable with two or more positional parameters is new-style
    regardless of defaults; only an exactly-one-positional callable is
    the legacy ``(total_scores)`` form."""
    import inspect

    try:
        params = list(inspect.signature(validator).parameters.values())
    except (TypeError, ValueError):  # builtins / C callables: assume new
        return validator(coefs, total)
    positional = [
        p for p in params
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    var_pos = any(p.kind is p.VAR_POSITIONAL for p in params)
    if len(positional) == 1 and not var_pos:
        return validator(total)
    return validator(coefs, total)


def _record_validation(validator, coefs, total, it, validation_history,
                       run_logger) -> None:
    """One per-sweep validation: evaluate, append to the history, log
    (shared by the per-coordinate and fused loops — one place for the
    metric-to-fields conversion)."""
    with telemetry.span("cd_validation", cat="cd", iteration=it + 1):
        metric = _call_validator(validator, coefs, total)
    validation_history.append(metric)
    if isinstance(metric, dict):
        fields = {str(getattr(k, "value", k)): float(v)
                  for k, v in metric.items()}
    else:
        fields = {"metric": float(metric)}
    logger.info("CD iter %d validation %s", it + 1, fields)
    if run_logger is not None:
        run_logger.event("cd_validation", iteration=it + 1, **fields)


@dataclasses.dataclass
class CoordinateDescentResult:
    """Trained coefficients per coordinate + per-iteration history."""

    coefficients: dict          # name → coordinate-specific coefficients
    scores: dict                # name → final per-example scores [n]
    total_scores: jnp.ndarray   # [n]
    history: list               # per iteration: {coordinate: scalar
                                # diagnostic fields (plain dict — the
                                # checkpoint-serializable form, uniform
                                # across fresh and resumed runs)}
    validation_history: list    # per iteration: metric value (if validator)


def run_coordinate_descent(
    coordinates: dict[str, Coordinate],
    update_sequence: list[str],
    n_iterations: int,
    validator=None,
    locked_coordinates: dict | None = None,
    initial_coefficients: dict | None = None,
    checkpoint_dir: str | None = None,
    resume: bool = False,
    run_logger=None,
    checkpointer=None,
    fused_engine=None,
) -> CoordinateDescentResult:
    """Run GAME coordinate descent.

    Args:
      coordinates: name → Coordinate (trainable units).
      update_sequence: coordinate update order (reference
        ``updateSequence`` param).
      n_iterations: full sweeps over the sequence (reference
        ``coordinateDescentIterations``).
      validator: optional callable ``(coefficients: dict, total_scores)
        → float | dict`` run once per full sweep (the reference's
        per-iteration validation: CoordinateDescent scores the
        validation set and logs every evaluator each iteration, SURVEY
        §2.3/§3.1).  ``coefficients`` are the current per-coordinate
        values (for scoring held-out data); ``total_scores`` the
        current train-set score sum (for cheap train-side metrics).
        A dict return (evaluator → value) is recorded as-is in
        ``validation_history`` and the run log.
      locked_coordinates: name → pre-trained coefficients for partial
        retraining (reference ``partialRetrainLockedCoordinates``):
        locked coordinates contribute scores but are never retrained.
      initial_coefficients: name → starting coefficients (warm start
        from a previous model, reference ``modelInputDir`` semantics):
        the coordinate starts scored at these values instead of zero.
      checkpoint_dir: if set, snapshot run state after every completed
        sweep via ``reliability.checkpoint.RunCheckpointer`` (format is
        a superset of the legacy ``utils.checkpoint`` files).
      resume: resume from the most advanced checkpoint in
        ``checkpoint_dir`` (overrides ``initial_coefficients`` for
        checkpointed names; restores mid-sweep position and streamed-RE
        retirement state when present).
      run_logger: optional ``photon_ml_tpu.utils.run_log.RunLogger`` for
        structured per-iteration events.
      checkpointer: pre-configured ``RunCheckpointer`` (cadence knobs
        from ``TrainingConfig``); built from ``checkpoint_dir`` with
        defaults when omitted.  While the loop runs it is also the
        ACTIVE checkpoint session, so the streaming solvers snapshot
        mid-solve state under the loop's (iteration, coordinate) scope.
      fused_engine: optional ``game.fused_sweep.FusedCycleEngine``
        (ISSUE 11): each CD iteration becomes ONE fused streamed pass
        that accumulates every coordinate's statistics, followed by the
        Jacobi solves — ~1 store pass per cycle instead of C ×
        solver-iterations.  All coordinate updates within a cycle are
        computed against cycle-START offsets (Jacobi staleness — the
        ``validator``'s ``total_scores`` are therefore the cycle-start
        scores).  Locked coordinates are not supported on this path.
    """
    if fused_engine is not None and locked_coordinates:
        raise ValueError("fused CD does not support locked coordinates")
    locked_coordinates = locked_coordinates or {}
    initial_coefficients = dict(initial_coefficients or {})
    for name in update_sequence:
        if name not in coordinates and name not in locked_coordinates:
            raise ValueError(f"coordinate '{name}' has no trainable unit "
                             "and is not locked")

    if checkpointer is None and checkpoint_dir:
        checkpointer = _ckpt.RunCheckpointer(checkpoint_dir,
                                             run_logger=run_logger,
                                             resume=resume)
    start_iteration = 0
    start_pos = 0
    ckpt_scores: dict = {}
    restored_extra: dict = {}
    fused_state: dict | None = None
    if resume:
        if checkpointer is None:
            raise ValueError("resume=True requires checkpoint_dir")
        loaded = checkpointer.load_latest_cd()
        if loaded is not None:
            start_iteration = loaded["iteration"]
            start_pos = loaded["coord_pos"]
            initial_coefficients.update(loaded["coefs"])
            restored_extra = loaded["extra"]
            # Fleet resume: restore the reduce counter recorded at this
            # checkpoint boundary so the host replays its reduce
            # sequence (cache-answered) back to the live barrier.
            from photon_ml_tpu.optim.streaming import _restore_fleet_seq

            _restore_fleet_seq(restored_extra.get("fleet_seq"))
            # Fused-cycle engine state rides re_state under a reserved
            # key (ISSUE 11); it is restored by the fused branch below
            # and the per-coordinate loop skips it (no such coordinate).
            fused_state = (loaded["re_state"] or {}).get("__cd_fused__")
            if fused_state is not None and fused_engine is None:
                # A fused checkpoint pairs post-Jacobi-step coefficients
                # with cycle-START score planes (the fused loop never
                # reads the scores back — it composes margins from
                # coefficients).  The per-coordinate loop DOES read
                # them as a consistent pair, so adopting this snapshot
                # would train every coordinate against offsets one
                # Jacobi step stale.  Refuse rather than drift.
                raise ValueError(
                    "checkpoint was written by a fused run (cd_fused); "
                    "resume with cd_fused=true or start a fresh "
                    "checkpoint_dir")
            if fused_state is None and fused_engine is not None:
                # Symmetric refusal: a legacy checkpoint's iteration
                # count budgets FULL inner solves — adopting it as a
                # fused start (start_iteration of n_iterations damped
                # Jacobi cycles, mid-sweep position dropped, engine
                # state fresh) would "complete" severely
                # under-converged with no error.
                raise ValueError(
                    "checkpoint was written by a per-coordinate run; "
                    "resume with cd_fused=false or start a fresh "
                    "checkpoint_dir")
            if fused_engine is None:
                # Device placement of the restored score planes is the
                # per-coordinate path's business only — the fused loop
                # recomputes scores from coefficients and would drop
                # these [n] planes unread (wasted H2D at scale).
                ckpt_scores = {k: jnp.asarray(v)
                               for k, v in loaded["scores"].items()}
            # Streamed-RE runtime state (retirement masks, solved
            # offsets, resident coefficient blocks): the coordinate's
            # canonical blocks become the warm start, so its own
            # warm-start identity check sees ITS arrays and keeps the
            # restored retirement bookkeeping intact.
            for name, st in (loaded["re_state"] or {}).items():
                coord = coordinates.get(name)
                if coord is not None and hasattr(coord,
                                                 "restore_runtime_state"):
                    blocks, cached_scores = coord.restore_runtime_state(st)
                    initial_coefficients[name] = blocks
                    if name not in ckpt_scores:
                        ckpt_scores[name] = cached_scores
            if run_logger is not None:
                run_logger.event("cd_resume", iteration=start_iteration,
                                 coord_pos=start_pos)

    if fused_engine is not None:
        # Fused super-sweep (ISSUE 11): every iteration is ONE streamed
        # pass + Jacobi solves; no per-coordinate score planes are
        # carried as training state, so the per-coordinate preamble
        # below (which would stream a scoring pass per warm start) is
        # bypassed entirely.
        return _run_fused_cycles(
            fused_engine, coordinates, update_sequence, n_iterations,
            validator, initial_coefficients, checkpointer, run_logger,
            start_iteration, restored_extra, fused_state)

    coefs: dict = {}
    scores: dict = {}

    # Locked coordinates score once, up front, and never move.
    for name, locked_coefs in locked_coordinates.items():
        coefs[name] = locked_coefs
        scores[name] = coordinates[name].score(locked_coefs)

    # Trainable coordinates start at their warm-start coefficients
    # (scored in) or contribute zero until first trained.
    for name in update_sequence:
        if name in locked_coordinates:
            continue
        if name in ckpt_scores and name in initial_coefficients:
            # Restored score state: bitwise-identical to what the
            # uninterrupted loop carried at this point.
            coefs[name] = initial_coefficients[name]
            scores[name] = ckpt_scores[name]
        elif name in initial_coefficients:
            coefs[name] = initial_coefficients[name]
            scores[name] = coordinates[name].score(coefs[name])
        else:
            s = coordinates[name].score(
                coordinates[name].initial_coefficients())
            scores[name] = jnp.zeros_like(s)

    if "__cd_total__" in ckpt_scores:
        total = ckpt_scores["__cd_total__"]
    else:
        total = None
        for s in scores.values():
            total = s if total is None else total + s

    history = _serialize_history(restored_extra.get("history") or [])
    validation_history = _revive_validation(
        restored_extra.get("validation_history"))
    # Per-coordinate objective trajectory across sweeps (ISSUE 8): the
    # delta between consecutive sweeps' final objective values is the
    # CD-level convergence signal the reference logs per iteration.
    prev_values: dict = dict(restored_extra.get("prev_values") or {})

    def _re_states() -> dict:
        return {name: coord.runtime_state()
                for name, coord in coordinates.items()
                if hasattr(coord, "runtime_state")
                and name not in locked_coordinates}

    def _extra() -> dict:
        from photon_ml_tpu.optim.streaming import _fleet_seq

        return {"history": _serialize_history(history),
                "validation_history": _serialize_validation(
                    validation_history),
                "prev_values": dict(prev_values),
                "fleet_seq": _fleet_seq()}

    # A mid-sweep resume re-enters a PARTIAL sweep: the coordinates it
    # skips already trained before the kill, and their diagnostics ride
    # in the partial snapshot — seed them back so the resumed sweep's
    # history entry matches the uninterrupted run's record.
    partial_diag = dict(restored_extra.get("partial_iter_diag") or {})

    ckpt_session = (_ckpt.session(checkpointer) if checkpointer is not None
                    else contextlib.nullcontext())
    with ckpt_session:
        for it in range(start_iteration, n_iterations):
            total, iter_diag = _run_sweep(
                coordinates, update_sequence, locked_coordinates, coefs,
                scores, it, start_iteration, start_pos, checkpointer,
                run_logger, prev_values, total, _extra, _re_states,
                n_iterations,
                seed_diag=(partial_diag if it == start_iteration
                           else None))
            # Completed CD cycle: the denominator of the report's
            # passes-per-cycle metric (ISSUE 11 — sweep odometer ÷
            # cycles is how the C → ~1 fused drop is measured).
            telemetry.count("cd.cycles")
            # Normalized to the serialized (plain-dict) diagnostic form
            # so ``CoordinateDescentResult.history`` is uniformly typed
            # whether or not the run was resumed (the restored prefix
            # arrives serialized from the checkpoint).
            history.append(_serialize_history([iter_diag])[0])
            if validator is not None:
                _record_validation(validator, coefs, total, it,
                                   validation_history, run_logger)
            if checkpointer is not None:
                checkpointer.maybe_save_cd(
                    it + 1, coefs,
                    scores={**scores, "__cd_total__": total},
                    re_state=_re_states(), extra=_extra(),
                    final=(it + 1 == n_iterations))

    return CoordinateDescentResult(
        coefficients=coefs,
        scores=scores,
        total_scores=total,
        history=history,
        validation_history=validation_history,
    )


def _run_fused_cycles(engine, coordinates, update_sequence,
                      n_iterations, validator, initial_coefficients,
                      checkpointer, run_logger, start_iteration,
                      restored_extra, fused_state):
    """The fused-CD loop (ISSUE 11): one streamed super-sweep per
    iteration, harvested statistics solved once per cycle, offsets
    updated once per cycle (Jacobi).  Checkpoints land at cycle
    boundaries — the engine's retirement/step-scale state rides
    ``re_state["__cd_fused__"]`` so a resumed run steps identically."""
    engine.restore_runtime_state(fused_state)
    trainable = [n for n in dict.fromkeys(update_sequence)]
    coefs: dict = {}
    for name in trainable:
        if name in initial_coefficients:
            coefs[name] = initial_coefficients[name]
        else:
            coefs[name] = coordinates[name].initial_coefficients()

    history = _serialize_history(restored_extra.get("history") or [])
    validation_history = _revive_validation(
        restored_extra.get("validation_history"))

    def _extra() -> dict:
        from photon_ml_tpu.optim.streaming import _fleet_seq

        return {"history": _serialize_history(history),
                "validation_history": _serialize_validation(
                    validation_history),
                "fleet_seq": _fleet_seq()}

    scores: dict = {}
    total = None
    ckpt_session = (_ckpt.session(checkpointer) if checkpointer is not None
                    else contextlib.nullcontext())
    with ckpt_session:
        for it in range(start_iteration, n_iterations):
            t0 = time.perf_counter()
            with telemetry.span("cd_fused_cycle", cat="cd",
                                iteration=it + 1):
                coefs, scores, total, iter_diag = engine.run_cycle(coefs)
            elapsed = time.perf_counter() - t0
            telemetry.count("cd.cycles")
            telemetry.count("cd.coordinate_updates", len(trainable))
            history.append(_serialize_history([iter_diag])[0])
            # Cycle-level progress (the fused analog of the legacy
            # loop's per-coordinate updates; per-CHUNK progress comes
            # from the engine's train.cd_fused stage).
            _mon.progress("cd", it + 1, n_iterations, unit="cycles",
                          iteration=it + 1)
            fe_diag = iter_diag.get(engine.fe_name, {})
            logger.info(
                "CD fused cycle %d in %.2fs (value %s, alpha %s)",
                it + 1, elapsed, fe_diag.get("value"),
                fe_diag.get("alpha"))
            if run_logger is not None:
                retired = sum(d.get("entities_retired", 0)
                              for d in iter_diag.values()
                              if isinstance(d, dict))
                run_logger.event(
                    "cd_fused_cycle", iteration=it + 1,
                    duration_s=round(elapsed, 4),
                    value=fe_diag.get("value"),
                    grad_norm=fe_diag.get("grad_norm"),
                    alpha=fe_diag.get("alpha"),
                    entities_retired=retired)
            if validator is not None:
                # ``total`` holds the CYCLE-START scores (Jacobi
                # staleness — documented in run_coordinate_descent);
                # snapshot scoring of held-out data uses the fresh
                # coefficients either way.
                _record_validation(validator, coefs, total, it,
                                   validation_history, run_logger)
            if checkpointer is not None:
                checkpointer.maybe_save_cd(
                    it + 1, coefs,
                    scores={**scores, "__cd_total__": total},
                    re_state={"__cd_fused__": engine.runtime_state()},
                    extra=_extra(),
                    final=(it + 1 == n_iterations))

    # One final pass brings the score planes to the FINAL coefficients
    # (each cycle's planes are at its start) — counted as an auxiliary
    # sweep, so passes/cycle stays (N+1)/N ≈ 1.
    scores, total = engine.score_pass(coefs)
    return CoordinateDescentResult(
        coefficients=coefs,
        scores=scores,
        total_scores=total,
        history=history,
        validation_history=validation_history,
    )


def _run_sweep(coordinates, update_sequence, locked_coordinates, coefs,
               scores, it, start_iteration, start_pos, checkpointer,
               run_logger, prev_values, total, extra_fn, re_states_fn,
               n_iterations, seed_diag=None):
    """One CD sweep over the update sequence (split out so the resume
    position logic stays readable).  Mutates ``coefs``/``scores``/
    ``prev_values`` in place; returns (total, iteration diagnostics).
    ``extra_fn``/``re_states_fn`` supply the parent loop's history and
    streamed-RE state snapshots for mid-sweep partial checkpoints (one
    collection rule for partial AND boundary snapshots); ``seed_diag``
    pre-fills the skipped coordinates' diagnostics when re-entering a
    partial sweep after a resume."""
    iter_diag = dict(seed_diag or {})
    for pos, name in enumerate(update_sequence):
        if name in locked_coordinates:
            continue
        if it == start_iteration and pos < start_pos:
            # Mid-sweep resume: this coordinate already trained in the
            # interrupted sweep — its coefficients/scores came back
            # with the partial snapshot.
            continue
        coord = coordinates[name]
        t0 = time.perf_counter()
        scope = (checkpointer.scope(f"it{it + 1}", name)
                 if checkpointer is not None
                 else contextlib.nullcontext())
        # Per-coordinate stage span (ISSUE 7): one CD sweep's
        # train+score for this coordinate is one block on the
        # timeline, the unit the report's stage table attributes
        # time to.
        with scope, telemetry.span("cd_coordinate", cat="cd",
                                   coordinate=name, iteration=it + 1):
            offsets = total - scores[name]
            # The warm-start buffer is rebound to the result right
            # below, so let XLA write the new coefficients into the
            # old buffer (donation; SURVEY §5.2).  NOTE: on the
            # first sweep this consumes the caller's
            # initial_coefficients / checkpoint-restored arrays —
            # any later read of those buffers would hit a
            # deleted-buffer error; nothing in this loop re-reads
            # them (coefs[name] is rebound below).
            w, diag = coord.train(offsets, coefs.get(name),
                                  donate_warm_start=True)
            new_scores = coord.score(w)
        # ``offsets`` already holds total − old scores; reusing it
        # saves one [n]-vector op per coordinate per sweep (and
        # matches the reference's residual algebra exactly).
        total = offsets + new_scores
        scores[name] = new_scores
        coefs[name] = w
        iter_diag[name] = diag
        elapsed = time.perf_counter() - t0
        # Retirement hook (streamed random effects, ISSUE 5): the
        # coordinate stashed this sweep's converged-entity
        # candidates during train; committing them HERE — after the
        # scores are folded into the totals — freezes their
        # coefficients so the next sweep re-packs only the active
        # entities into chunks.  Part of the Coordinate contract:
        # the base returns None (no retirement protocol).
        newly_retired = coord.retire_converged()
        if newly_retired:
            telemetry.count("cd.entities_retired", newly_retired)
        # Live CD progress (ISSUE 10): coordinate updates completed
        # against the whole descent's plan — the top-level ETA the
        # watch view leads with.
        _mon.progress("cd", it * len(update_sequence) + pos + 1,
                      n_iterations * len(update_sequence),
                      unit="updates", iteration=it + 1,
                      coordinate=name)
        extra = ({} if newly_retired is None
                 else {"entities_newly_retired": newly_retired})
        telemetry.count("cd.coordinate_updates")
        # Objective delta vs this coordinate's previous sweep, and
        # a convergence trace for resident solves (streaming
        # coordinates emit their own — traces_convergence).
        if hasattr(diag, "value") and jnp.ndim(diag.value) == 0:
            value = float(diag.value)
            if name in prev_values:
                delta = prev_values[name] - value
                extra["value_delta"] = round(delta, 8)
                telemetry.observe("cd.objective_delta", delta)
            prev_values[name] = value
            if not getattr(coord, "traces_convergence", False):
                _conv.solve_trace("resident", name, diag)
        logger.info(
            "CD iter %d coordinate %s trained in %.2fs",
            it + 1, name, elapsed,
        )
        if run_logger is not None:
            run_logger.event(
                "cd_coordinate", iteration=it + 1, coordinate=name,
                duration_s=round(elapsed, 4), **_diag_fields(diag),
                **extra,
            )
        if checkpointer is not None and checkpointer.mid_sweep_enabled:
            # Mid-sweep position snapshot (ISSUE 9): ``pos + 1``
            # update-sequence entries of sweep ``it + 1`` are done, so
            # a kill during the NEXT coordinate's solve resumes here
            # (plus whatever mid-solve state that solve checkpointed).
            checkpointer.save_cd_partial(
                it, pos + 1, coefs,
                scores={**scores, "__cd_total__": total},
                re_state=re_states_fn(),
                extra={**extra_fn(),
                       "partial_iter_diag":
                           _serialize_history([iter_diag])[0]})
    return total, iter_diag
