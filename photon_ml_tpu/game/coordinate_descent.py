"""Coordinate descent: the GAME outer loop.

Reference counterpart: ``CoordinateDescent``
(photon-api ``com.linkedin.photon.ml.algorithm.CoordinateDescent``
[expected path, mount unavailable — see SURVEY.md §2.3/§3.1]).

Semantics mirror the reference exactly:

    for iteration 1..N:
      for coordinate in update_sequence:
        offsets   = total_scores − coordinate_scores[coordinate]
        model     = coordinate.train(offsets, warm start = prior coefs)
        scores    = coordinate.score(model)
        total     = total − old_scores + new_scores
      (validation metrics once per iteration)

The loop itself is host-level Python — like the reference's driver loop
— but every ``train``/``score`` inside it is a single jitted device
program, so per-coordinate work is one dispatch, and scores/offsets
live on device for the whole descent (no host round-trips between
coordinates).
"""

from __future__ import annotations

import dataclasses
import logging
import time

import jax.numpy as jnp

from photon_ml_tpu.game.coordinates import Coordinate

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class CoordinateDescentResult:
    """Trained coefficients per coordinate + per-iteration history."""

    coefficients: dict          # name → coordinate-specific coefficients
    scores: dict                # name → final per-example scores [n]
    total_scores: jnp.ndarray   # [n]
    history: list               # per iteration: {coordinate: diagnostics}
    validation_history: list    # per iteration: metric value (if validator)


def run_coordinate_descent(
    coordinates: dict[str, Coordinate],
    update_sequence: list[str],
    n_iterations: int,
    validator=None,
    locked_coordinates: dict | None = None,
) -> CoordinateDescentResult:
    """Run GAME coordinate descent.

    Args:
      coordinates: name → Coordinate (trainable units).
      update_sequence: coordinate update order (reference
        ``updateSequence`` param).
      n_iterations: full sweeps over the sequence (reference
        ``coordinateDescentIterations``).
      validator: optional callable ``(total_scores) → float`` run once
        per iteration (the reference's per-iteration validation).
      locked_coordinates: name → pre-trained coefficients for partial
        retraining (reference ``partialRetrainLockedCoordinates``):
        locked coordinates contribute scores but are never retrained.
    """
    locked_coordinates = locked_coordinates or {}
    for name in update_sequence:
        if name not in coordinates and name not in locked_coordinates:
            raise ValueError(f"coordinate '{name}' has no trainable unit "
                             "and is not locked")

    coefs: dict = {}
    scores: dict = {}
    n = None

    # Locked coordinates score once, up front, and never move.
    for name, locked_coefs in locked_coordinates.items():
        coefs[name] = locked_coefs
        scores[name] = coordinates[name].score(locked_coefs)

    # Initialize trainable scores at zero.
    for name in update_sequence:
        if name in locked_coordinates:
            continue
        s = coordinates[name].score(coordinates[name].initial_coefficients())
        scores[name] = jnp.zeros_like(s)
        n = s.shape[0]

    total = None
    for s in scores.values():
        total = s if total is None else total + s

    history, validation_history = [], []
    for it in range(n_iterations):
        iter_diag = {}
        for name in update_sequence:
            if name in locked_coordinates:
                continue
            coord = coordinates[name]
            t0 = time.perf_counter()
            offsets = total - scores[name]
            w, diag = coord.train(offsets, coefs.get(name))
            new_scores = coord.score(w)
            total = total - scores[name] + new_scores
            scores[name] = new_scores
            coefs[name] = w
            iter_diag[name] = diag
            logger.info(
                "CD iter %d coordinate %s trained in %.2fs",
                it + 1, name, time.perf_counter() - t0,
            )
        history.append(iter_diag)
        if validator is not None:
            metric = validator(total)
            validation_history.append(metric)
            logger.info("CD iter %d validation metric %.6f", it + 1,
                        float(metric))

    return CoordinateDescentResult(
        coefficients=coefs,
        scores=scores,
        total_scores=total,
        history=history,
        validation_history=validation_history,
    )
