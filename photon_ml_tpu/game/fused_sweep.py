"""Fused CD super-sweep: ONE streamed store pass per coordinate-descent
cycle (ISSUE 11 tentpole).

The per-coordinate CD loop pays one full data stream per objective
evaluation per coordinate — C coordinates × (solver iterations + line
search) store passes per cycle.  PR 4 already proved at inference that
one streamed pass can feed a single fused device program covering the
fixed effect and every random effect; this module gives TRAINING the
same shape:

- **Cycle-aligned chunks**: the fixed-effect chunk grid (the round-8
  ``data.chunked_batch`` store) is the master grid; a *sidecar* chunk
  per example chunk co-locates every random effect's per-row entity
  index and (projected) feature planes (``data.chunk_store``
  ``FUSED_CHUNK_CODEC``, content-keyed spill), so one prefetched chunk
  pair feeds all coordinates.
- **One fused per-chunk device program** (mirroring the streaming
  scorer's ``_CoordSpec`` fusion, but emitting statistics instead of
  margins): margins are composed from the CURRENT coefficients inside
  the program (fixed-effect contraction + every RE's coefficient-row
  gather-dot), and from the shared per-example loss derivatives it
  accumulates the fixed effect's (value, gradient, Hessian-diagonal)
  partials AND every random effect's segment-summed per-entity
  statistics (gradient [E, p] and Gauss–Newton Gram [E, p, p]).
  Retirement masks gate which entities' Gram statistics are even
  accumulated.
- **Once-per-cycle Jacobi update**: after the pass, the fixed effect
  takes one diagonally preconditioned Newton step and every ACTIVE
  entity one exact regularized Newton solve of its p×p system — all
  against CYCLE-START offsets ("Parallel training of linear models
  without compromising convergence", PAPERS.md, is the staleness
  convergence reference).  A cycle therefore costs ~1 store pass
  instead of C × solver-iterations; per-cycle progress is a damped
  Newton step rather than a full inner solve, so fused fits run more
  (cheap) cycles — both paths converge to the same block-stationary
  point (tested to documented tolerance).
- **Safeguard**: the joint objective value comes out of the same pass;
  if a cycle's value rose, the global step scale halves (and recovers
  geometrically on progress) — the Jacobi analog of a line search that
  costs zero extra passes.

Offsets/score planes: the fused program composes margins from
coefficients directly, so NO per-coordinate score planes are training
state — per-coordinate scores still come out of each pass (one [n]
plane per coordinate, the same D2H the scorer pays) for validation,
retirement bookkeeping, and the CD result contract.
"""

from __future__ import annotations

import dataclasses
import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import convergence as _conv
from photon_ml_tpu.telemetry import monitor as _mon
from photon_ml_tpu.ops.objective import GLMObjective, _elementwise_square_batch

logger = logging.getLogger(__name__)

Array = jax.Array

# Ridge added to every Newton system: keeps the FE diagonal and the
# per-entity Gram solvable at zero curvature (masked-out entities,
# projected padding columns) without moving any real solution.
_RIDGE = 1e-6
_MIN_ALPHA = 1.0 / 64.0


# ---------------------------------------------------------------------------
# THE fused per-chunk device program.  Jitted at module level (the loss
# is the only static argument) so every engine instance for the same
# task shares one compile, and every chunk of a run replays it — zero
# new compiles across fused cycles after warmup (guard-pinned).
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnums=(0,))
def _fused_chunk(loss, w_fe, re_tabs, re_actives, batch, re_xs, re_idxs):
    """One chunk's fused statistics.

    Args:
      loss: static ``PointwiseLoss``.
      w_fe: [d] fixed-effect coefficients.
      re_tabs: tuple of [E_r + 1, p_r] flattened coefficient tables
        (last row = padding/unseen dump row, all-zero).
      re_actives: tuple of [E_r + 1] float gates — 1.0 accumulates the
        entity's statistics, 0.0 (retired / dump row) skips them.
      batch: the fixed-effect ``SparseBatch`` chunk (host offsets are
        IGNORED: margins are composed from coefficients here).
      re_xs: tuple of [R, p_r] per-row (projected) feature planes.
      re_idxs: tuple of [R] int32 flattened entity indices (padding
        rows point at the dump row E_r).

    Returns (value, fe_grad [d], fe_hess_diag [d], re_grads, re_grams,
    fe_scores [R], re_scores tuple of [R]) — data-side partials only;
    regularization/prior are example-independent and added once by the
    solves.
    """
    fe_scores = batch.x_dot(w_fe)
    off = jnp.zeros_like(fe_scores)
    re_scores = []
    for x, tab, idx in zip(re_xs, re_tabs, re_idxs):
        s = jnp.sum(x * tab[idx], axis=-1)
        re_scores.append(s)
        off = off + s
    m = fe_scores + off
    wl = batch.weights * batch.mask
    f = jnp.sum(wl * loss.loss(m, batch.labels))
    dl = wl * loss.d1(m, batch.labels)
    d2 = wl * loss.d2(m, batch.labels)
    fe_g = batch.xt_dot(dl)
    fe_h = _elementwise_square_batch(batch).xt_dot(d2)
    re_gs, re_Gs = [], []
    for x, tab, idx, act in zip(re_xs, re_tabs, re_idxs, re_actives):
        gate = act[idx]
        gd1 = dl * gate
        gd2 = d2 * gate
        E1, p = tab.shape
        g = jnp.zeros((E1, p), jnp.float32).at[idx].add(gd1[:, None] * x)
        G = jnp.zeros((E1, p, p), jnp.float32).at[idx].add(
            gd2[:, None, None] * x[:, :, None] * x[:, None, :])
        re_gs.append(g)
        re_Gs.append(G)
    return (f, fe_g, fe_h, tuple(re_gs), tuple(re_Gs), fe_scores,
            tuple(re_scores))


@jax.jit
def _acc_add(acc, out):
    """Tree-add of the accumulated statistics (value/grad/hess/RE
    stats) — one dispatch per chunk, like the chunked objective's
    combine."""
    return jax.tree.map(lambda a, b: a + b, acc, out)


@jax.jit
def _fe_step(obj: GLMObjective, w: Array, g: Array, h: Array, alpha):
    """Diagonally preconditioned Newton step on the fixed effect from
    the gathered (grad, Hessian-diagonal) partials; regularization and
    prior are added HERE, outside the chunk loop (the chunked
    objective's rule)."""
    g = g + obj.reg.l2_gradient(w)
    h = h + obj.reg.l2_hessian_diagonal(w)
    if obj.prior is not None:
        g = g + obj.prior.gradient(w)
        h = h + obj.prior.hessian_diagonal()
    step = g / jnp.maximum(h, _RIDGE)
    w_new = w - alpha * step
    return w_new, jnp.max(jnp.abs(alpha * step)), jnp.linalg.norm(g)


@jax.jit
def _re_step(tab: Array, g: Array, G: Array, active: Array, lam,
             alpha):
    """Per-entity regularized Newton solve from the segment-summed
    statistics: Δ_e = (G_e + (λ+δ)I)⁻¹ (g_e + λ w_e), applied to
    ACTIVE entities only.  Padding columns (projected buckets narrower
    than the table) have zero x, zero w, zero g → Δ = 0 exactly.

    Returns (new table [E+1, p], per-entity UNDAMPED |Δ|_∞ [E+1]): the
    movement plane is the full Newton step's norm, not the α-damped
    step actually applied — retirement compares it against the solver
    tolerance, and gating on the damped step would loosen the
    effective threshold to tolerance/α (up to 64× at ``_MIN_ALPHA``),
    freezing entities whose own residual is still large."""
    E1, p = tab.shape
    eye = jnp.eye(p, dtype=tab.dtype)
    g_tot = g + lam * tab
    A = G + (lam + _RIDGE) * eye[None]
    delta = jnp.linalg.solve(A, g_tot[..., None])[..., 0]
    dw = alpha * delta
    gate = active[:, None] > 0.0
    tab_new = jnp.where(gate, tab - dw, tab)
    # The dump row stays pinned at zero (unseen/padding rows gather it).
    tab_new = tab_new.at[-1].set(0.0)
    move = jnp.where(active > 0.0, jnp.max(jnp.abs(delta), axis=-1), 0.0)
    return tab_new, move


@dataclasses.dataclass
class _FusedRE:
    """One random effect's fused-cycle bookkeeping."""

    name: str
    coord: "object"                # the estimator-facing coordinate
    lam: float                     # smooth L2 weight of its objective
    tolerance: float               # retirement / movement threshold
    widths: list[int]              # per-bucket p_b
    p_max: int
    n_entities: list[int]
    boff: np.ndarray               # [buckets] flat entity-index bases
    E_total: int
    # Per-entity example-run maps (flat entity order): example ids
    # sorted by (entity, position) + run starts — the vectorized
    # per-entity reductions (retirement drift) run off these.
    ex_sorted: np.ndarray          # [n_r] example ids
    ent_starts: np.ndarray         # [E_total + 1]
    # Retirement state (the PR 5 semantics, engine-resident):
    active: np.ndarray = None      # [E_total] bool
    solved_off: np.ndarray = None  # [n] offsets at each entity's last solve
    prev_off: np.ndarray = None    # [n] previous cycle's offsets

    def entity_max(self, per_example: np.ndarray) -> np.ndarray:
        """[E_total] per-entity max of a per-example plane (one
        vectorized reduceat; entities with no examples get 0)."""
        out = np.zeros(self.E_total, np.float32)
        counts = np.diff(self.ent_starts)
        nz = counts > 0
        if self.ex_sorted.size:
            v = per_example[self.ex_sorted]
            red = np.maximum.reduceat(v, self.ent_starts[:-1][nz])
            out[nz] = red
        return out


class FusedCycleEngine:
    """One-pass-per-cycle fused coordinate descent over a chunked
    fixed effect + any number of random effects (see module docstring).

    Coefficients cross the boundary in the COORDINATE formats the rest
    of the stack speaks — [d] for the fixed effect, per-bucket
    [E_b, p_b] block lists for random effects — and are flattened to
    device tables internally, so model export, validation scoring, and
    checkpoints are unchanged.
    """

    def __init__(self, fe_name: str, fe_coord, res: list[_FusedRE],
                 n_examples: int, prefetch_depth: int = 2,
                 retirement: bool = True, sidecar_store=None,
                 sidecar_resident: list | None = None):
        self.fe_name = fe_name
        self.fe_coord = fe_coord
        self.chunked = fe_coord.chunked
        self.objective = fe_coord.objective
        self.loss = fe_coord.objective.loss
        self.res = res
        self.n = int(n_examples)
        self.prefetch_depth = int(prefetch_depth)
        self.retirement = bool(retirement)
        self._sidecar_store = sidecar_store
        self._sidecar_resident = sidecar_resident
        self.alpha = 1.0
        self.prev_value: float | None = None
        self.cycles = 0
        self.last_scores: dict | None = None
        self.last_total = None
        # Device-table cache keyed BY IDENTITY of the block list this
        # engine itself returned last cycle (the streamed-RE
        # `_is_last_train_output` rule): fused runs take many cheap
        # cycles, and re-flattening an unchanged [E, p] table host-side
        # + H2D every cycle is pure waste.  Any caller-substituted
        # blocks (warm start, resume) miss the cache and re-flatten.
        self._tab_cache: dict = {}

    # -- coefficient format conversions -------------------------------------

    def _flatten(self, r: _FusedRE, blocks) -> Array:
        tab = np.zeros((r.E_total + 1, r.p_max), np.float32)
        for b, blk in enumerate(blocks):
            lo = int(r.boff[b])
            tab[lo:lo + r.n_entities[b], : r.widths[b]] = np.asarray(blk)
        return jnp.asarray(tab)

    def _tab_for(self, r: _FusedRE, blocks) -> Array:
        cached = self._tab_cache.get(r.name)
        if cached is not None and cached[0] is blocks:
            return cached[1]
        return self._flatten(r, blocks)

    def _unflatten(self, r: _FusedRE, tab: Array) -> list[Array]:
        tab = np.asarray(tab)
        out = []
        for b in range(len(r.n_entities)):
            lo = int(r.boff[b])
            out.append(jnp.asarray(
                tab[lo:lo + r.n_entities[b], : r.widths[b]].copy()))
        return out

    # -- chunk feed ----------------------------------------------------------

    def _sidecar(self, i: int) -> dict:
        if self._sidecar_store is not None:
            return self._sidecar_store.get(i)
        if self._sidecar_resident is None:     # fixed-effect-only fit
            return {}
        return self._sidecar_resident[i]

    def _stream(self):
        """(i, device (batch, sidecar)) pairs in this host's schedule
        order, through the round-8 prefetch pipeline when the FE chunks
        are store-backed.  Fleet sentinels (``EMPTY_CHUNK`` — ragged
        shards padded to the fleet-common step count) yield
        ``(id, None)`` and stream nothing."""
        from photon_ml_tpu.optim.streaming import prefetch_stream

        sched = self.chunked.chunk_schedule
        real = [i for i in sched if i >= 0]
        load = lambda i: (self.chunked.chunk(i), self._sidecar(i))
        inner = prefetch_stream(load, jax.device_put, real,
                                self.prefetch_depth,
                                store=self.chunked.store)
        try:
            for i in sched:
                yield (i, None) if i < 0 else next(inner)
        finally:
            inner.close()   # quiesce the prefetcher on early exit too

    # -- the pass ------------------------------------------------------------

    def _zero_stats(self):
        """The sentinel chunk's statistics partial — exact zeros in the
        5-tuple shape ``_fused_chunk`` accumulates, so a ragged-shard
        host contributes nothing to the fleet reduction while still
        taking every chunk barrier."""
        d = self.chunked.dim
        return (jnp.zeros((), jnp.float32),
                jnp.zeros((d,), jnp.float32),
                jnp.zeros((d,), jnp.float32),
                tuple(jnp.zeros((r.E_total + 1, r.p_max), jnp.float32)
                      for r in self.res),
                tuple(jnp.zeros((r.E_total + 1, r.p_max, r.p_max),
                                jnp.float32) for r in self.res))

    def _pass(self, w_fe: Array, tabs: list[Array],
              actives: list[Array]):
        """One streamed pass: accumulated statistics + per-coordinate
        score planes at the INPUT coefficients.  Backpressure: chunk
        i−1's accumulate fences before chunk i dispatches (the round-8
        rule), and per-example planes D2H-copy asynchronously under
        later chunks' compute.

        Fleet runs reduce the 5-tuple statistics across hosts at EVERY
        schedule step (the chunk barrier — each host contributed a
        different chunk, or zeros past its ragged shard) and the score
        planes ONCE at the end, so all hosts return identical global
        statistics and full [n] planes: the Jacobi solves and the
        retirement bookkeeping above stay fleet-oblivious and
        replicated."""
        from photon_ml_tpu.parallel import fleet as _fleet

        K = self.chunked.n_chunks
        names = [r.name for r in self.res]
        fred = _fleet.reducer()
        acc = None
        per_ex: list = []       # (chunk id, (fe_plane, re_planes))
        steps = len(self.chunked.chunk_schedule)
        sidecar_store = self._sidecar_store
        if sidecar_store is not None:
            sidecar_store.begin_read()
        try:
            with telemetry.span("fused_cycle_pass", cat="solver",
                                chunks=K):
                telemetry.count("solver.sweeps")
                for si, (i, placed) in enumerate(self._stream()):
                    if i < 0:
                        stats = self._zero_stats()
                        if fred is not None:
                            stats = fred.reduce(stats)
                        acc = (stats if acc is None
                               else _acc_add(acc, stats))
                        _mon.progress("train.cd_fused", si + 1, steps,
                                      unit="chunks",
                                      cycle=self.cycles + 1)
                        continue
                    batch, sc = placed
                    re_xs = tuple(sc[n + ".x"] for n in names)
                    re_idxs = tuple(sc[n + ".idx"] for n in names)
                    with telemetry.span("chunk_compute", cat="device"):
                        if acc is not None:
                            jax.block_until_ready(acc[0])
                        out = _fused_chunk(
                            self.loss, w_fe, tuple(tabs),
                            tuple(actives), batch, re_xs, re_idxs)
                    stats, planes = out[:5], out[5:]
                    for pl in (planes[0], *planes[1]):
                        try:
                            pl.copy_to_host_async()
                        except AttributeError:  # photon-lint: disable=swallowed-exception (backends without async D2H; device_get below copies synchronously)
                            pass
                    per_ex.append((i, planes))
                    if fred is not None:
                        stats = fred.reduce(stats)
                        telemetry.count("fleet.chunks_streamed")
                    acc = stats if acc is None else _acc_add(acc, stats)
                    # Live fused-cycle progress (ISSUE 11 satellite):
                    # chunks done/total drives watch/ETA exactly like
                    # every other instrumented loop.
                    _mon.progress("train.cd_fused", si + 1, steps,
                                  unit="chunks", cycle=self.cycles + 1)
        finally:
            if sidecar_store is not None:
                sidecar_store.end_read()
        fe_scores = np.zeros(self.n, np.float32)
        re_scores = [np.zeros(self.n, np.float32) for _ in self.res]
        for i, (fe_pl, re_pls) in per_ex:
            lo, hi = self.chunked.chunk_slice(i)
            fe_scores[lo:hi] = jax.device_get(fe_pl)[: hi - lo]
            for j, pl in enumerate(re_pls):
                re_scores[j][lo:hi] = jax.device_get(pl)[: hi - lo]
        if fred is not None:
            # One barrier for ALL score planes: examples are disjoint
            # across hosts, so the sum is the concatenation.
            fe_scores, re_scores = fred.reduce((fe_scores, re_scores))
            fe_scores = np.asarray(fe_scores)
            re_scores = [np.asarray(s) for s in re_scores]
        return acc, fe_scores, re_scores

    # -- value bookkeeping ---------------------------------------------------

    def _total_value(self, data_value: float, w_fe: Array,
                     tabs: list[Array]) -> float:
        """Joint objective (data + smooth reg + prior) at the
        coefficients the pass evaluated — the Jacobi safeguard's
        scalar."""
        obj = self.objective
        v = float(data_value) + float(obj.reg.l2_value(w_fe))
        if obj.prior is not None:
            v += float(obj.prior.value(w_fe))
        for r, tab in zip(self.res, tabs):
            v += 0.5 * r.lam * float(jnp.sum(tab * tab))
        return v

    # -- one cycle -----------------------------------------------------------

    def run_cycle(self, coefs: dict):
        """One fused CD cycle: one streamed pass at the given
        coefficients, then the Jacobi solves.  Returns
        (new coefficients dict, scores dict AT THE INPUT coefficients,
        total scores, per-coordinate diagnostics dict)."""
        w_fe = jnp.asarray(coefs[self.fe_name], jnp.float32)
        tabs = [self._tab_for(r, coefs[r.name]) for r in self.res]
        actives = [
            jnp.asarray(np.concatenate(
                [r.active.astype(np.float32),
                 np.zeros(1, np.float32)]))     # dump row stays gated
            for r in self.res
        ]
        telemetry.count("solver.fused_cycle_sweeps")
        acc, fe_scores, re_scores = self._pass(w_fe, tabs, actives)
        f_data, fe_g, fe_h, re_gs, re_Gs = acc
        value = self._total_value(f_data, w_fe, tabs)

        # Jacobi safeguard: a cycle whose value ROSE means the previous
        # step overshot — halve the global step scale before applying
        # this cycle's; recover geometrically on progress (zero extra
        # passes either way).
        if self.prev_value is not None:
            if value > self.prev_value + 1e-12 * (1.0
                                                  + abs(self.prev_value)):
                self.alpha = max(self.alpha * 0.5, _MIN_ALPHA)
            else:
                self.alpha = min(1.0, self.alpha * 1.25)
        self.prev_value = value

        total = fe_scores.copy()
        for s in re_scores:
            total += s

        # Wake retired entities whose offsets drifted past tolerance
        # since their last solve (their statistics were gated off this
        # cycle, so they re-enter NEXT cycle — retirement can never
        # move the final model beyond tolerance).
        diag: dict = {}
        new_coefs = dict(coefs)
        w_fe_new, fe_step, fe_gnorm = _fe_step(
            self.objective, w_fe, fe_g, fe_h, self.alpha)
        new_coefs[self.fe_name] = w_fe_new
        diag[self.fe_name] = {
            "value": round(value, 8),
            "grad_norm": round(float(fe_gnorm), 8),
            "step_inf_norm": round(float(fe_step), 8),
            "alpha": round(self.alpha, 6),
            "fused": True,
        }
        for j, r in enumerate(self.res):
            off_r = total - re_scores[j]
            # Only the entities whose statistics were ACCUMULATED this
            # cycle may solve: the pass gated on the cycle-START active
            # mask, so a woken entity re-enters accumulation (and
            # solving) next cycle.
            solved_mask = r.active.copy()
            woken = 0
            if self.retirement and r.solved_off is not None:
                retired = ~r.active
                if retired.any():
                    drift = r.entity_max(np.abs(off_r - r.solved_off))
                    woke = retired & (drift >= r.tolerance)
                    woken = int(woke.sum())
                    r.active |= woke
            if r.solved_off is None:
                r.solved_off = off_r.copy()
            tab_new, move = _re_step(tabs[j], re_gs[j], re_Gs[j],
                                     actives[j], r.lam, self.alpha)
            move = np.asarray(move)[:-1]
            new_blocks = self._unflatten(r, tab_new)
            new_coefs[r.name] = new_blocks
            # Next cycle's _tab_for resolves these very blocks back to
            # the device table without a host rebuild + H2D.
            self._tab_cache[r.name] = (new_blocks, tab_new)
            # Solved entities' offset baseline moves to THIS cycle's
            # offsets (their statistics were computed against them).
            if solved_mask.any() and r.ex_sorted.size:
                per_ex_solved = solved_mask[
                    np.repeat(np.arange(r.E_total),
                              np.diff(r.ent_starts))]
                ex = r.ex_sorted[per_ex_solved]
                r.solved_off[ex] = off_r[ex]
            # Retire: solved, step below tolerance, offsets quiet since
            # the previous cycle (the PR 5 dual criterion).
            newly = 0
            if self.retirement:
                quiet = np.ones(r.E_total, bool)
                if r.prev_off is not None:
                    quiet = (r.entity_max(np.abs(off_r - r.prev_off))
                             < r.tolerance)
                retire = solved_mask & (move < r.tolerance) & quiet
                newly = int(retire.sum())
                r.active &= ~retire
                if newly:
                    _conv.re_retirement(r.name, newly,
                                        int((~r.active).sum()))
            r.prev_off = off_r.copy()
            diag[r.name] = {
                "entities": r.E_total,
                "entities_solved": int(solved_mask.sum()),
                "entities_retired": int((~r.active).sum()),
                "entities_newly_retired": newly,
                "entities_woken": woken,
                "fused": True,
            }
        self.cycles += 1
        telemetry.count("solver.iterations")
        _conv.iteration("fused_cd", self.fe_name, self.cycles, value,
                        float(fe_gnorm))
        scores = {self.fe_name: jnp.asarray(fe_scores)}
        for j, r in enumerate(self.res):
            scores[r.name] = jnp.asarray(re_scores[j])
        self.last_scores = scores
        self.last_total = jnp.asarray(total)
        return new_coefs, scores, jnp.asarray(total), diag

    def score_pass(self, coefs: dict):
        """Scores at the GIVEN coefficients via one more fused pass
        (statistics discarded) — the once-per-fit final pass that
        brings the result's score planes to the final coefficients.
        Counted as an auxiliary sweep, so the sweep-odometer identity
        holds."""
        w_fe = jnp.asarray(coefs[self.fe_name], jnp.float32)
        tabs = [self._tab_for(r, coefs[r.name]) for r in self.res]
        zeros = [jnp.zeros(r.E_total + 1, jnp.float32) for r in self.res]
        telemetry.count("solver.aux_sweeps")
        _, fe_scores, re_scores = self._pass(w_fe, tabs, zeros)
        scores = {self.fe_name: jnp.asarray(fe_scores)}
        total = fe_scores.copy()
        for j, r in enumerate(self.res):
            scores[r.name] = jnp.asarray(re_scores[j])
            total += re_scores[j]
        return scores, jnp.asarray(total)

    # -- checkpoint state (ISSUE 9 granularities) ---------------------------

    def _identity_fingerprint(self) -> str:
        """Config-identity hash of everything the snapshot's semantics
        depend on (the PR 9 solver-snapshot rule): regularization
        weights, tolerances, entity/chunk geometry.  A resume after a
        config edit must REJECT the stale retirement masks / offset
        baselines / step-scale rather than adopt state computed under
        different λs — retired-under-old-λ entities would stay frozen
        (wake only watches offsets) and the stale prev_value would
        spuriously damp alpha."""
        import hashlib

        ident = (
            self.fe_name,
            float(np.asarray(self.objective.reg.l2_weight)),
            [(r.name, float(r.lam), float(r.tolerance), int(r.E_total),
              int(r.p_max)) for r in self.res],
            int(self.chunked.n_chunks), int(self.chunked.chunk_rows),
            int(self.chunked.dim),
            # Retirement mode is snapshot semantics too: a mask frozen
            # under retirement=True adopted by a retirement=False run
            # would gate those entities off FOREVER (no wake branch).
            bool(self.retirement),
        )
        return hashlib.blake2b(repr(ident).encode(),
                               digest_size=16).hexdigest()

    def runtime_state(self) -> dict:
        """Everything the fused loop carries BETWEEN cycles beyond the
        coefficients: retirement masks, offset baselines, and the
        Jacobi step-scale — so a resumed run steps exactly as the
        uninterrupted run would have."""
        from photon_ml_tpu.optim.streaming import _fleet_seq

        return {
            "fingerprint": self._identity_fingerprint(),
            "alpha": float(self.alpha),
            "prev_value": (None if self.prev_value is None
                           else float(self.prev_value)),
            "cycles": int(self.cycles),
            # Fleet reduce counter at this cycle boundary: a killed
            # host restores it and replays its reduce sequence through
            # the coordinator's result cache (see parallel.fleet).
            "fleet_seq": _fleet_seq(),
            "re": {r.name: {
                "active": np.asarray(r.active),
                "solved_off": (None if r.solved_off is None
                               else np.asarray(r.solved_off)),
                "prev_off": (None if r.prev_off is None
                             else np.asarray(r.prev_off)),
            } for r in self.res},
        }

    def restore_runtime_state(self, state: dict | None) -> None:
        if not state:
            return
        snap = state.get("fingerprint")
        if snap is not None:
            snap = str(np.asarray(snap).item()) \
                if not isinstance(snap, str) else snap
            cur = self._identity_fingerprint()
            if snap != cur:
                raise ValueError(
                    "fused checkpoint was written under a different "
                    "configuration (regularization / tolerance / chunk "
                    "geometry changed); start a fresh checkpoint_dir")
        from photon_ml_tpu.optim.streaming import _restore_fleet_seq

        self.alpha = float(state.get("alpha", 1.0))
        pv = state.get("prev_value")
        self.prev_value = None if pv is None else float(pv)
        self.cycles = int(state.get("cycles", 0))
        _restore_fleet_seq(state.get("fleet_seq"))
        for r in self.res:
            st = (state.get("re") or {}).get(r.name)
            if st is None:
                continue
            r.active = np.asarray(st["active"], bool).copy()
            so = st.get("solved_off")
            r.solved_off = (None if so is None
                            else np.asarray(so, np.float32).copy())
            po = st.get("prev_off")
            r.prev_off = (None if po is None
                          else np.asarray(po, np.float32).copy())


# ---------------------------------------------------------------------------
# Engine construction: coordinates (already built by the estimator) →
# sidecar chunks on the fixed-effect chunk grid + per-RE bookkeeping.
# ---------------------------------------------------------------------------


def _flat_entity_runs(grouping, boff: np.ndarray):
    """(ex_sorted, ent_starts) over the FLAT entity order (bucket base
    + slot): example ids sorted by (flat entity, within-entity
    position) and the [E+1] run starts — the per-entity reduction maps
    the retirement bookkeeping uses."""
    E = grouping.n_total_entities
    flat = boff[grouping.example_bucket] + grouping.example_row
    order = np.lexsort((grouping.example_col, flat))
    ex_sorted = order.astype(np.int64)
    counts = np.bincount(flat[order], minlength=E)
    starts = np.zeros(E + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    return ex_sorted, starts


def _per_example_features(train, coord):
    """Per-example (x [n, p_max], flat entity idx [n]) for one random
    effect — dense shards directly, sparse shards through the
    (deterministic) subspace projection, per-bucket widths padded to
    the coordinate's max width."""
    grouping = coord.grouping
    n = grouping.n_examples
    n_ents = list(grouping.n_entities)
    boff = np.zeros(len(n_ents), np.int64)
    if len(n_ents) > 1:
        boff[1:] = np.cumsum(n_ents)[:-1]
    flat_idx = (boff[grouping.example_bucket]
                + grouping.example_row).astype(np.int32)
    # The estimator stamps feature_shard on the coordinate; direct
    # callers fall back to probing the dataset's shards by kind.
    shard = getattr(coord, "feature_shard", None)
    if shard is None or shard not in train.features:
        shard = _find_shard(train, coord,
                            sparse=coord.projection is not None)
    if coord.projection is None:
        x = np.asarray(train.features[shard], np.float32)
        widths = [x.shape[1]] * len(n_ents)
        x_ex = x
    else:
        from photon_ml_tpu.data.sparse_rows import SparseRows
        from photon_ml_tpu.game.projector import build_subspace_projection

        rows = train.features[shard]
        if not isinstance(rows, SparseRows):
            rows = SparseRows.from_rows(rows)
        _, x_blocks = build_subspace_projection(
            grouping, rows, coord.projection.global_dim)
        widths = [xb.shape[-1] for xb in x_blocks]
        p_max = max(widths) if widths else 1
        x_ex = np.zeros((n, p_max), np.float32)
        for b in range(len(n_ents)):
            sel = np.flatnonzero(grouping.example_bucket == b)
            x_ex[sel, : widths[b]] = np.asarray(x_blocks[b])[
                grouping.example_row[sel], grouping.example_col[sel]]
    p_max = max(widths) if widths else 1
    if x_ex.shape[1] < p_max:
        x_ex = np.pad(x_ex, ((0, 0), (0, p_max - x_ex.shape[1])))
    return (np.ascontiguousarray(x_ex, dtype=np.float32), flat_idx,
            widths, boff, n_ents)


def _find_shard(train, coord, sparse: bool = False) -> str:
    """Feature shard the coordinate was built from.  Coordinates built
    by the estimator don't carry their shard name; match by the
    grouping's example count + dense/sparse kind.  Ambiguity is an
    ERROR, not a guess: with a sparse fixed-effect shard AND a sparse
    RE shard in the same dataset (every chunked workload), returning
    the first sparse match could silently train the random effect on
    the fixed effect's features — direct callers must pass
    ``re_shards`` instead."""
    n = coord.grouping.n_examples
    candidates = []
    for name, feats in train.features.items():
        is_dense = isinstance(feats, np.ndarray)
        if is_dense == sparse:
            continue
        if not hasattr(feats, "__len__") or len(feats) != n:
            continue
        candidates.append(name)
    if len(candidates) == 1:
        return candidates[0]
    if not candidates:
        raise ValueError("could not resolve the random effect's feature "
                         "shard from the dataset")
    raise ValueError(
        f"ambiguous feature shard for random effect "
        f"'{getattr(coord, 'name', '?')}': {sorted(candidates)} all "
        f"match; pass re_shards= to build_fused_cycle_engine")


def build_fused_cycle_engine(
    train,
    coords: dict,
    update_sequence: list[str],
    re_shards: dict[str, str] | None = None,
    spill_dir: str | None = None,
    host_max_resident: int = 2,
    prefetch_depth: int = 2,
    retirement: bool = True,
    window_group=None,
) -> FusedCycleEngine:
    """Build the fused engine over already-built coordinates.

    ``coords`` must contain exactly one ``ChunkedFixedEffectCoordinate``
    in the update sequence (its chunk grid is the master grid) plus any
    number of random-effect coordinates.  ``re_shards`` maps RE
    coordinate name → feature shard name (the estimator knows; direct
    callers may omit it and let the shard be probed).  With
    ``spill_dir`` the sidecar chunks spill through the chunk store
    (content-keyed — warm across runs); otherwise they stay resident.
    """
    from photon_ml_tpu.game.coordinates import ChunkedFixedEffectCoordinate

    fe_name = None
    re_names = []
    for name in dict.fromkeys(update_sequence):
        coord = coords[name]
        if isinstance(coord, ChunkedFixedEffectCoordinate):
            if fe_name is not None:
                raise ValueError(
                    "cd_fused supports exactly one chunked fixed-effect "
                    "coordinate")
            fe_name = name
        else:
            if not hasattr(coord, "grouping"):
                raise ValueError(
                    f"cd_fused: coordinate '{name}' is neither a "
                    "chunked fixed effect nor a random effect")
            re_names.append(name)
    if fe_name is None:
        raise ValueError("cd_fused requires a chunked fixed-effect "
                         "coordinate (chunk_rows)")
    fe_coord = coords[fe_name]
    chunked = fe_coord.chunked
    K, R = chunked.n_chunks, chunked.chunk_rows
    n = chunked.n

    res: list[_FusedRE] = []
    side_planes: dict[str, tuple[np.ndarray, np.ndarray]] = {}
    for name in re_names:
        coord = coords[name]
        if coord.grouping.n_examples != n:
            raise ValueError(
                f"cd_fused: random effect '{name}' covers "
                f"{coord.grouping.n_examples} examples, the fixed "
                f"effect {n} — one chunk grid must fit both")
        if (re_shards or {}).get(name):
            coord.feature_shard = re_shards[name]
        x_ex, flat_idx, widths, boff, n_ents = _per_example_features(
            train, coord)
        E_total = int(sum(n_ents))
        ex_sorted, ent_starts = _flat_entity_runs(coord.grouping, boff)
        lam = float(np.asarray(
            coord.problem.objective.reg.l2_weight)) if hasattr(
                coord.problem.objective.reg, "l2_weight") else 0.0
        tol = float(coord.problem.config.tolerance)
        res.append(_FusedRE(
            name=name, coord=coord, lam=lam, tolerance=tol,
            widths=[int(w) for w in widths],
            p_max=max(widths) if widths else 1,
            n_entities=[int(e) for e in n_ents],
            boff=boff, E_total=E_total,
            ex_sorted=ex_sorted, ent_starts=ent_starts,
            active=np.ones(E_total, bool),
        ))
        side_planes[name] = (x_ex, flat_idx)

    e_totals = {r.name: r.E_total for r in res}

    def _planes() -> dict:
        """The per-example feature planes, re-materialized from the
        dataset + coordinates on demand: once every sidecar chunk is
        spilled, the planes are DROPPED (a projected sparse RE's dense
        [n, p_max] plane is the whole point of spilling — keeping it
        closed over by the store's rebuild hook would pin it for the
        engine's lifetime and void the window bound); a corrupt/missing
        chunk rebuild pays one deterministic re-projection instead."""
        if not side_planes:
            for r in res:
                x_ex, flat_idx, *_ = _per_example_features(train, r.coord)
                side_planes[r.name] = (x_ex, flat_idx)
        return side_planes

    def build_sidecar(i: int) -> dict:
        lo = i * R
        hi = min(lo + R, n)
        out: dict = {}
        for name, (x_ex, flat_idx) in _planes().items():
            E_total = e_totals[name]
            x = x_ex[lo:hi]
            if hi - lo < R:
                x = np.pad(x, ((0, R - (hi - lo)), (0, 0)))
            idx = np.full(R, E_total, np.int32)
            idx[: hi - lo] = flat_idx[lo:hi]
            out[name + ".x"] = np.ascontiguousarray(x)
            out[name + ".idx"] = idx
        return out

    # Fleet mode: sidecars (like the FE chunks) are built and spilled
    # only for this host's shard, under its per-host spill subdir.
    from photon_ml_tpu.parallel import fleet as _fleet

    fctx = _fleet.active()
    owned = chunked.owned_chunk_ids

    sidecar_store = None
    sidecar_resident = None
    if res and spill_dir is not None:
        from photon_ml_tpu.data.chunk_store import (
            FUSED_CHUNK_CODEC,
            ChunkStore,
            array_content_key,
            probe_spill_dir,
            release_free_heap,
        )

        spill_dir = _fleet.host_dir(spill_dir, fctx)
        if probe_spill_dir(spill_dir) is not None:
            key_arrays = []
            for name in sorted(side_planes):
                key_arrays.extend(side_planes[name])
            key = array_content_key(key_arrays, {
                "kind": "fused-sidecar", "chunk_rows": int(R),
                "n_chunks": int(K),
                "res": sorted(side_planes),
            })
            sidecar_store = ChunkStore(
                spill_dir, key, K,
                host_max_resident=host_max_resident,
                rebuild=build_sidecar, codec=FUSED_CHUNK_CODEC,
                window_group=window_group)
            missing = [i for i in owned if not sidecar_store.has(i)]
            for i in missing:
                sidecar_store.put(i, build_sidecar(i))
            # Spilled: drop the materialized planes (see ``_planes``) —
            # the store's LRU window is now the only sidecar residency.
            side_planes.clear()
            if missing:
                release_free_heap()
            logger.info(
                "fused sidecar: %d chunks (%d built, %d reused) "
                "spilled to %s", len(owned), len(missing),
                len(owned) - len(missing), spill_dir)
    if res and sidecar_store is None:
        owned_set = set(owned)
        sidecar_resident = [build_sidecar(i) if i in owned_set else None
                            for i in range(K)]

    engine = FusedCycleEngine(
        fe_name=fe_name, fe_coord=fe_coord, res=res, n_examples=n,
        prefetch_depth=prefetch_depth, retirement=retirement,
        sidecar_store=sidecar_store, sidecar_resident=sidecar_resident)
    logger.info(
        "fused CD engine: fixed effect '%s' (%d chunks × %d rows) + "
        "%d random effect(s) %s — one store pass per cycle", fe_name,
        K, R, len(res), [r.name for r in res])
    return engine
