"""GAME: generalized additive mixed effects — coordinates + descent.

Reference: photon-api ``com.linkedin.photon.ml.algorithm`` / ``...data``
(SURVEY.md §2.3/§2.4 — expected paths, mount unavailable).
"""

from photon_ml_tpu.game.coordinate_descent import (
    CoordinateDescentResult,
    run_coordinate_descent,
)
from photon_ml_tpu.game.coordinates import (
    Coordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
    StreamedRandomEffectCoordinate,
    build_random_effect_coordinate,
    build_random_effect_coordinate_sparse,
    build_streamed_random_effect_coordinate,
)
from photon_ml_tpu.game.projector import (
    SubspaceProjection,
    build_subspace_projection,
)
from photon_ml_tpu.game.sampling import (
    binary_classification_down_sample,
    default_down_sample,
)
from photon_ml_tpu.game.dataset import (
    EntityGrouping,
    GameDataset,
    gather_from_blocks,
    group_by_entity,
    scatter_to_blocks,
)

__all__ = [
    "CoordinateDescentResult",
    "run_coordinate_descent",
    "Coordinate",
    "FixedEffectCoordinate",
    "RandomEffectCoordinate",
    "StreamedRandomEffectCoordinate",
    "build_random_effect_coordinate",
    "build_random_effect_coordinate_sparse",
    "build_streamed_random_effect_coordinate",
    "SubspaceProjection",
    "build_subspace_projection",
    "binary_classification_down_sample",
    "default_down_sample",
    "EntityGrouping",
    "GameDataset",
    "gather_from_blocks",
    "group_by_entity",
    "scatter_to_blocks",
]
