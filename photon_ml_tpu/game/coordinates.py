"""GAME coordinates: the per-coordinate train/score units.

Reference counterparts: ``Coordinate``, ``FixedEffectCoordinate``,
``RandomEffectCoordinate`` (photon-api
``com.linkedin.photon.ml.algorithm`` [expected paths, mount unavailable —
see SURVEY.md §2.3]).

The reference contract carries over exactly — ``train(offsets, warm
start) → model`` and ``score(model) → per-example scores`` — but the
execution model flips:

- ``FixedEffectCoordinate``: the reference runs
  ``DistributedOptimizationProblem`` (broadcast + treeAggregate per
  L-BFGS iteration).  Here the SAME ``OptimizationProblem`` runs over
  either a local batch or a mesh-sharded batch wrapped in
  ``DistributedGLMObjective`` — one jitted solve either way.
- ``RandomEffectCoordinate``: the reference's
  ``RDD[(REId, LocalDataset)].mapValues(solve per entity)`` — thousands
  of sequential JVM L-BFGS loops per partition — becomes ONE
  ``vmap``ped solve per size bucket: every entity in a bucket optimizes
  simultaneously on the VPU/MXU, each converging by its own criterion
  (masked while_loop).  Entity blocks are built once by the host ETL
  (``EntityGrouping``); per-CD-iteration offsets move between example
  space and block space by static-index gather/scatter on device.

- ``StreamedRandomEffectCoordinate`` (round 10): the same vmapped
  per-bucket solve driven chunk-by-chunk through the out-of-core chunk
  store + prefetch pipeline, with converged-entity retirement between
  CD sweeps — entity count bounded by DISK and the host window, not by
  residency (see the class docstring).

Scores are raw dot products x·w (no offset, no link), summable across
coordinates — the reference's ``CoordinateDataScores`` convention.
"""

from __future__ import annotations

import dataclasses
import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.telemetry import convergence as _conv
from photon_ml_tpu.telemetry import device as _device
from photon_ml_tpu.telemetry import monitor as _mon
from photon_ml_tpu.data.batch import Batch, DenseBatch
from photon_ml_tpu.game.dataset import (
    EntityGrouping,
    GameDataset,
    group_by_entity,
)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim import OptimizationProblem, OptimizerConfig
from photon_ml_tpu.optim.lbfgs import lbfgs_solve
from photon_ml_tpu.optim.tron import tron_solve
from photon_ml_tpu.parallel.distributed_objective import DistributedGLMObjective

logger = logging.getLogger(__name__)

Array = jax.Array


# ---------------------------------------------------------------------------
# Module-level jitted solves.  Everything data-like (batch, objective with
# its traced reg/norm arrays, offsets, warm starts) is a TRACED argument;
# only the optimizer type and config are static.  Two consequences, both
# verdict findings from round 2:
#   * grid/tuning points that differ only in reg weight λ hit the SAME
#     compiled executable (λ lives in RegularizationContext leaves);
#   * the batch is never closed over as a jit constant — a constant batch
#     would be baked into the HLO and shipped through the compiler, which
#     at production sizes means gigabytes through the compile path.
# ---------------------------------------------------------------------------


def _pad_offsets(offsets: Array, n_padded: int) -> Array:
    """Example-space offsets [n] → batch row space [n_padded] (padding
    rows are masked, so zeros are exact)."""
    if offsets.shape[0] == n_padded:
        return offsets
    return jnp.pad(offsets, (0, n_padded - offsets.shape[0]))


def _apply_training_view(batch, offsets: Array, train_idx, train_weights):
    """Offsets installed; optionally the down-sampled row view."""
    offsets = _pad_offsets(offsets, batch.n_padded)
    if train_idx is None:
        return batch.replace(offsets=offsets)
    from photon_ml_tpu.data.batch import SparseBatch

    base = batch
    if isinstance(base, SparseBatch) and (
        base.colmajor is not None or base.grr is not None
    ):
        # The transposed-ELL / GRR plans index *all* rows; subsetting
        # their layout arrays by example ids would silently corrupt
        # X^T r.  Drop them — the subsetted batch falls back to the ELL
        # paths (down-sampled solves are smaller anyway).
        base = base.replace(colmajor=None, grr=None)
    sub = jax.tree.map(lambda a: a[train_idx], base)
    return sub.replace(offsets=offsets[train_idx], weights=train_weights)


def _jit_solve(fn, donate_argnums):
    """(plain, warm-start-donating) jit pair for a solve entry.

    Donation (SURVEY §5.2 rebuild guidance): the warm-start
    coefficients are the one solve input shaped like a solve output, so
    XLA can write the new coefficients into the old buffer — for
    random effects that is the full [E_b, cap, p]-adjacent coefficient
    blocks, the dominant recurring allocation of a CD sweep.
    Coordinate descent rebinds ``coefs[name]`` to the result
    immediately after each call, so the donated buffer is dead there;
    direct ``train()`` callers (tests, notebooks) may reuse their
    arrays, so the plain variant stays the default — donation is
    opt-in via ``donate_warm_start``.
    """
    return (
        # photon-lint: disable=jit-in-function (module-import-time factory)
        jax.jit(fn, static_argnums=(0, 1, 2)),
        # photon-lint: disable=jit-in-function (module-import-time factory)
        jax.jit(fn, static_argnums=(0, 1, 2),
                donate_argnums=donate_argnums))


def _fixed_train_local_impl(optimizer, config, has_l1, objective, batch,
                            offsets, train_idx, train_weights, w0):
    problem = OptimizationProblem(
        objective=objective, optimizer=optimizer, config=config
    )
    view = _apply_training_view(batch, offsets, train_idx, train_weights)
    return problem.run(view, w0, has_l1=has_l1)


_fixed_train_local, _fixed_train_local_donating = _jit_solve(
    _fixed_train_local_impl, donate_argnums=(8,))  # w0


def _fixed_train_distributed_impl(optimizer, config, has_l1, dist_obj, batch,
                                  offsets, train_idx, train_weights, w0):
    from photon_ml_tpu.optim.base import OptimizerType

    view = _apply_training_view(batch, offsets, train_idx, train_weights)
    vg = lambda w: dist_obj.value_and_gradient(w, view)
    if optimizer == OptimizerType.TRON:
        if has_l1:
            raise ValueError(
                "TRON requires a smooth objective; use LBFGS (OWL-QN) "
                "for L1/elastic-net problems"
            )
        hvp = lambda w, v: dist_obj.hessian_vector(w, v, view)
        return tron_solve(vg, hvp, w0, config)
    problem = OptimizationProblem(
        objective=dist_obj.objective, optimizer=optimizer, config=config
    )
    l1 = problem._l1_vector(w0.shape[-1]) if has_l1 else None
    return lbfgs_solve(vg, w0, config, l1_weight=l1)


_fixed_train_distributed, _fixed_train_distributed_donating = _jit_solve(
    _fixed_train_distributed_impl, donate_argnums=(8,))  # w0


def _lane_vg(objective, view):
    """Per-lane smooth objective for the swept solvers: the lane's L2
    weight rides as the lane context (a traced [L] leaf row), so one
    compiled program covers any λ grid."""
    def vg(w, l2):
        obj = objective.replace(reg=objective.reg.replace(l2_weight=l2))
        return obj.value_and_gradient(w, view)
    return vg


@partial(jax.jit, static_argnums=(0, 1))
def _fixed_train_swept(config, use_map, objective, batch, offsets,
                       train_idx, train_weights, W0, l2s, l1v):
    """Batched λ-sweep fixed-effect solve: W0 [L, d] lanes against ONE
    shared training view — the whole regularization grid in a single
    masked-lane program (``optim.lbfgs.lbfgs_solve_swept``).
    ``use_map`` (static) lane-loops via ``lax.map`` when the batch
    carries a GRR plan (the Pallas kernel has no batching rule)."""
    from photon_ml_tpu.optim.lbfgs import lbfgs_solve_swept

    view = _apply_training_view(batch, offsets, train_idx, train_weights)
    return lbfgs_solve_swept(_lane_vg(objective, view), W0, l2s, config,
                             l1_weights=l1v, use_map=use_map)


@partial(jax.jit, static_argnums=(0,))
def _fixed_train_swept_distributed(config, dist_obj, batch, offsets,
                                   train_idx, train_weights, W0, l2s, l1v):
    """Mesh variant of the swept solve: lanes lax.map-loop around the
    shard_mapped objective (no batching rule through shard_map); the
    sharded batch stays resident across every lane."""
    from photon_ml_tpu.optim.lbfgs import lbfgs_solve_swept

    view = _apply_training_view(batch, offsets, train_idx, train_weights)

    def vg(w, l2):
        obj = dist_obj.objective
        o = dist_obj.replace(objective=obj.replace(
            reg=obj.reg.replace(l2_weight=l2)))
        return o.value_and_gradient(w, view)

    return lbfgs_solve_swept(vg, W0, l2s, config, l1_weights=l1v,
                             use_map=True)


@jax.jit
def _score_batch(batch, w: Array) -> Array:
    return batch.x_dot(w)


@jax.jit
def _score_batch_distributed(dist_obj, batch, w: Array) -> Array:
    """Sharded scoring: per-shard layouts (GRR plan / colmajor) index
    only their device's rows, so X·w must run under shard_map.  Module
    -level jit so per-CD-iteration scoring hits the compile cache."""
    return dist_obj.x_dot(w, batch)


def _re_block_batch(blocks, b: int, offsets: Array) -> DenseBatch:
    """Bucket b's entity blocks as one vmappable DenseBatch, with
    per-example offsets scattered into block space."""
    (x_blocks, label_blocks, weight_blocks, mask_blocks,
     ex_idx, row_idx, col_idx) = blocks
    off_blk = jnp.zeros_like(label_blocks[b]).at[
        row_idx[b], col_idx[b]
    ].set(offsets[ex_idx[b]])
    return DenseBatch(
        x=x_blocks[b], labels=label_blocks[b], weights=weight_blocks[b],
        offsets=off_blk, mask=mask_blocks[b],
    )


def _re_train_impl(optimizer, config, has_l1, objective, blocks,
                   offsets: Array, w0s: list[Array]):
    problem = OptimizationProblem(
        objective=objective, optimizer=optimizer, config=config
    )
    run = partial(problem.run, has_l1=has_l1)
    return [
        jax.vmap(run)(_re_block_batch(blocks, b, offsets), w0s[b])
        for b in range(len(blocks[0]))
    ]


_re_train, _re_train_donating = _jit_solve(
    _re_train_impl, donate_argnums=(6,))  # w0s blocks


# -- streamed-RE per-chunk programs (ISSUE 5) -------------------------------
# One compiled program per (bucket shape, optimizer config): every entity
# chunk of a bucket is congruent [C, cap_b, p_b], so the vmapped masked
# while_loop solve replays one executable chunk after chunk, exactly as
# the fixed-effect streaming tier replays its per-chunk objective.


def _re_chunk_train_impl(optimizer, config, has_l1, objective, x, labels,
                         weights, mask, offsets, w0):
    problem = OptimizationProblem(
        objective=objective, optimizer=optimizer, config=config
    )
    batch = DenseBatch(x=x, labels=labels, weights=weights,
                       offsets=offsets, mask=mask)
    res = jax.vmap(partial(problem.run, has_l1=has_l1))(batch, w0)
    # Scores and per-entity movement come out of the SAME dispatch: the
    # chunk is already in device memory, so the CD sweep never pays a
    # second scoring pass over the store.
    scores = jnp.einsum("ecp,ep->ec", x, res.w)
    dw = jnp.max(jnp.abs(res.w - w0), axis=-1)
    return res.w, scores, dw, res.converged, res.iterations


_re_chunk_train = jax.jit(_re_chunk_train_impl, static_argnums=(0, 1, 2))


@jax.jit
def _re_chunk_score(x, w):
    return jnp.einsum("ecp,ep->ec", x, w)


@jax.jit
def _re_chunk_vars(objective, x, labels, weights, mask, offsets, w):
    from photon_ml_tpu.optim.variance import simple_variances

    batch = DenseBatch(x=x, labels=labels, weights=weights,
                       offsets=offsets, mask=mask)
    return jax.vmap(
        lambda w_, b_: simple_variances(objective, w_, b_)
    )(w, batch)


def _entity_example_runs(ex_sorted_b: np.ndarray, starts_b: np.ndarray,
                         ents: np.ndarray):
    """Vectorized (example ids, chunk rows, within-entity cols) for the
    entities ``ents`` (global bucket slots) — the index maps that move
    per-example offsets into block space and block scores back out.
    ``ex_sorted_b`` orders the bucket's examples by (entity slot,
    within-entity position), so each entity is one contiguous run and
    any packed chunk's map is O(examples) numpy arithmetic."""
    counts = (starts_b[ents + 1] - starts_b[ents]).astype(np.int64)
    total = int(counts.sum())
    rows = np.repeat(np.arange(len(ents), dtype=np.int64), counts)
    cum = np.cumsum(counts) - counts
    cols = np.arange(total, dtype=np.int64) - np.repeat(cum, counts)
    idx = np.repeat(starts_b[ents], counts) + cols
    return ex_sorted_b[idx], rows, cols


def _example_runs(grouping: EntityGrouping):
    """Per-bucket (ex_sorted, ent_starts) run maps (see
    ``_entity_example_runs``)."""
    ex_sorted, ent_starts = [], []
    for b, ne in enumerate(grouping.n_entities):
        sel = np.flatnonzero(grouping.example_bucket == b)
        order = np.lexsort((grouping.example_col[sel],
                            grouping.example_row[sel]))
        sel = sel[order].astype(np.int64)
        ex_sorted.append(sel)
        counts = np.bincount(grouping.example_row[sel], minlength=ne)
        starts = np.zeros(ne + 1, np.int64)
        np.cumsum(counts, out=starts[1:])
        ent_starts.append(starts)
    return ex_sorted, ent_starts


@partial(jax.jit, static_argnums=0)
def _re_score(n_examples: int, x_blocks, ex_idx, row_idx, col_idx,
              coefficient_blocks) -> Array:
    scores = jnp.zeros((n_examples,), jnp.float32)
    for b, w_b in enumerate(coefficient_blocks):
        blk_scores = jnp.einsum("ecp,ep->ec", x_blocks[b], w_b)
        scores = scores.at[ex_idx[b]].set(
            blk_scores[row_idx[b], col_idx[b]]
        )
    return scores


@jax.jit
def _re_variances(objective, blocks, coefficient_blocks, offsets: Array):
    from photon_ml_tpu.optim.variance import simple_variances

    return [
        jax.vmap(
            lambda w, bb: simple_variances(objective, w, bb)
        )(w_b, _re_block_batch(blocks, b, offsets))
        for b, w_b in enumerate(coefficient_blocks)
    ]


class Coordinate:
    """train/score contract (reference ``Coordinate`` abstraction)."""

    name: str

    # True when this coordinate's solver emits its own convergence
    # telemetry (the host-driven streaming solvers / streamed REs);
    # the CD loop then skips its resident-result trace so one solve
    # never lands twice in the log (ISSUE 8).
    traces_convergence = False

    def initial_coefficients(self):
        raise NotImplementedError

    def train(self, offsets: Array, warm_start):
        """offsets [n] (residual scores from other coordinates) → (model
        coefficients, optimizer diagnostics)."""
        raise NotImplementedError

    def score(self, coefficients) -> Array:
        """coefficients → per-example scores [n]."""
        raise NotImplementedError

    def retire_converged(self) -> int | None:
        """Commit this sweep's converged-entity retirement candidates
        (the coordinate-descent between-sweeps hook).  Base contract:
        no retirement protocol — returns None; the streamed
        random-effect coordinate overrides with the number of newly
        frozen entities."""
        return None


@dataclasses.dataclass(eq=False)
class FixedEffectCoordinate(Coordinate):
    """Global solve over the full batch (reference
    ``FixedEffectCoordinate`` + ``DistributedOptimizationProblem``)."""

    name: str
    batch: Batch                      # full batch (scoring); local or sharded
    problem: OptimizationProblem
    distributed: DistributedGLMObjective | None = None  # set if sharded
    # Down-sampled training view (reference DownSampler, SURVEY §2.4):
    # train on batch rows ``train_idx`` with ``train_weights``; score all.
    train_idx: Array | None = None
    train_weights: Array | None = None
    # Real example count when the batch rows were padded (mesh sharding
    # pads n to a multiple of the device count); scores are sliced back
    # to example space so they stay summable with other coordinates'.
    n_examples: int | None = None

    def initial_coefficients(self) -> Array:
        return jnp.zeros((self.batch.dim,), jnp.float32)

    def _training_batch(self, offsets: Array) -> Batch:
        return _apply_training_view(self.batch, offsets, self.train_idx,
                                    self.train_weights)

    def train(self, offsets: Array, warm_start: Array | None = None,
              donate_warm_start: bool = False):
        w0 = self.initial_coefficients() if warm_start is None else warm_start
        has_l1 = self.problem.has_l1()
        if self.distributed is None:
            fn = (_fixed_train_local_donating if donate_warm_start
                  else _fixed_train_local)
            res = fn(
                self.problem.optimizer, self.problem.config, has_l1,
                self.problem.objective, self.batch, offsets,
                self.train_idx, self.train_weights, w0,
            )
        else:
            fn = (_fixed_train_distributed_donating if donate_warm_start
                  else _fixed_train_distributed)
            res = fn(
                self.problem.optimizer, self.problem.config, has_l1,
                self.distributed, self.batch, offsets,
                self.train_idx, self.train_weights, w0,
            )
        return res.w, res

    def train_swept(self, offsets: Array, reg, warm_start=None):
        """Train the whole λ grid as ONE batched solve: L stacked
        coefficient lanes share every objective evaluation against the
        same training view (one data stream amortized across the grid).

        Args:
          offsets: [n] shared residual scores (the λ sweep varies only
            regularization, so all lanes see the same offsets).
          reg: ``ops.regularization.SweptRegularization`` — per-lane
            (l1, l2) weight splits, one lane per grid point.
          warm_start: optional [L, dim] stacked starting points
            (continuation across tuning rounds).

        Returns (W [L, dim], batched OptimizationResult).
        """
        from photon_ml_tpu.data.batch import SparseBatch
        from photon_ml_tpu.optim.base import OptimizerType

        if self.problem.optimizer == OptimizerType.TRON:
            raise ValueError(
                "train_swept supports LBFGS/OWL-QN lanes only (the λ "
                "sweep is the L-BFGS grid workload; fit TRON "
                "coordinates per grid point)")
        L = reg.n_lanes
        dim = self.batch.dim
        W0 = (jnp.zeros((L, dim), jnp.float32) if warm_start is None
              else jnp.asarray(warm_start, jnp.float32))
        l1v = (reg.l1_vectors(dim, self.problem.objective.reg.reg_mask)
               if reg.has_l1() else None)
        if self.distributed is not None:
            res = _fixed_train_swept_distributed(
                self.problem.config, self.distributed, self.batch,
                offsets, self.train_idx, self.train_weights, W0,
                reg.l2_weights, l1v,
            )
        else:
            # GRR-plan batches lane-loop (lax.map): the Mosaic kernel
            # has no batching rule; the plan stays HBM-resident across
            # lanes either way.
            use_map = (isinstance(self.batch, SparseBatch)
                       and self.batch.grr is not None)
            res = _fixed_train_swept(
                self.problem.config, use_map, self.problem.objective,
                self.batch, offsets, self.train_idx, self.train_weights,
                W0, reg.l2_weights, l1v,
            )
        return res.w, res

    def score(self, coefficients: Array) -> Array:
        if self.distributed is not None:
            scores = _score_batch_distributed(
                self.distributed, self.batch, coefficients)
        else:
            scores = _score_batch(self.batch, coefficients)
        if (self.n_examples is not None
                and self.n_examples != self.batch.n_padded):
            scores = scores[: self.n_examples]
        return scores

    def as_model(self, coefficients: Array) -> FixedEffectModel:
        return FixedEffectModel(
            coefficients=Coefficients(means=coefficients),
            feature_shard=self.name,
        )

    def compute_variances(self, coefficients: Array, offsets: Array,
                          variance_type) -> Array | None:
        """Coefficient variances at the optimum over the training view
        (reference VarianceComputationType pipeline, SURVEY §2.1).

        Under mesh sharding the distributed objective must aggregate
        the Hessian quantities (its colmajor row indices are
        shard-local, and the diagonal is a cross-shard sum)."""
        from photon_ml_tpu.optim.variance import compute_variances

        obj = self.distributed or self.problem.objective
        return compute_variances(
            obj, coefficients, self._training_batch(offsets), variance_type,
        )


@dataclasses.dataclass(eq=False)
class ChunkedFixedEffectCoordinate(Coordinate):
    """Fixed effect trained by chunk-accumulated streaming — the
    beyond-HBM-residency class (reference: Spark streams splits through
    executors, SURVEY §1 L1/§5.8; see ``data.chunked_batch``).

    Same ``train``/``score`` contract as ``FixedEffectCoordinate``; the
    solve is the host-driven ``optim.streaming.streaming_lbfgs_solve``
    over a ``ChunkedGLMObjective`` (per-chunk device programs, exact
    chunk-accumulated objective), or ``streaming_tron_solve`` when the
    optimizer is TRON (ISSUE 17: chunk-accumulated Hessian-vector
    passes feed the Steihaug-CG inner loop, Jacobi-preconditioned from
    the Hessian-diagonal pass).  When the chunked batch is disk-spilled
    (``spill_dir`` — the out-of-core tier), every training AND
    ``_per_example`` scoring sweep runs the async disk→host→device
    prefetch pipeline, ``prefetch_depth`` chunks ahead.  Down-sampling
    views are not supported on this path (documented config error);
    TRON λ-sweeps stay per-grid-point (``train_swept`` is the L-BFGS
    lane workload, as on the resident path)."""

    name: str
    chunked: "object"                 # data.chunked_batch.ChunkedBatch
    objective: GLMObjective           # reg/prior included (added once)
    optimizer: "object"               # OptimizerType
    config: OptimizerConfig
    max_resident: int = 1
    prefetch_depth: int = 2

    traces_convergence = True         # the streaming solvers emit live

    def __post_init__(self):
        from photon_ml_tpu.optim.streaming import ChunkedGLMObjective

        self._obj = ChunkedGLMObjective(
            self.objective, self.chunked, max_resident=self.max_resident,
            prefetch_depth=self.prefetch_depth)

    @property
    def problem(self) -> OptimizationProblem:
        """Estimator-facing surface parity with FixedEffectCoordinate
        (model export reads ``coord.problem.objective.norm``)."""
        return OptimizationProblem(
            objective=self.objective, optimizer=self.optimizer,
            config=self.config)

    def initial_coefficients(self) -> Array:
        return jnp.zeros((self.chunked.dim,), jnp.float32)

    def _coerce_offsets(self, offsets) -> np.ndarray:
        """Offsets → exactly ``chunked.n`` entries.  Over-long arrays
        are accepted ONLY when the length matches the known padding
        grid (the chunk grid, which already folds in the mesh's device
        rounding) — anything else is a caller bug that silent
        truncation would turn into wrong training data (advisor
        finding); under-long arrays fail in ``set_offsets``."""
        off = np.asarray(offsets, np.float32)
        n = self.chunked.n
        if off.shape[0] == n:
            return off
        grid = self.chunked.n_chunks * self.chunked.chunk_rows
        if off.shape[0] == grid:
            return off[:n]
        if off.shape[0] > n:
            raise ValueError(
                f"offsets length {off.shape[0]} exceeds n {n} and does "
                f"not match the chunk padding grid {grid}")
        return off

    def train(self, offsets: Array, warm_start: Array | None = None,
              donate_warm_start: bool = False):
        from photon_ml_tpu.optim.base import OptimizerType
        from photon_ml_tpu.optim.streaming import (
            streaming_lbfgs_solve,
            streaming_tron_solve,
        )

        self.chunked.set_offsets(self._coerce_offsets(offsets))
        self._obj.invalidate()
        w0 = (self.initial_coefficients() if warm_start is None
              else warm_start)
        problem = self.problem
        l1 = (problem._l1_vector(self.chunked.dim) if problem.has_l1()
              else None)
        if self.optimizer == OptimizerType.TRON:
            if l1 is not None:
                raise ValueError(
                    "TRON supports smooth objectives only (no L1) — "
                    "as on the resident path")
            res = streaming_tron_solve(
                self._obj.value_and_gradient, self._obj.hvp_pass, w0,
                self.config, hessian_diag=self._obj.hessian_diagonal,
                label=self.name)
        else:
            res = streaming_lbfgs_solve(
                self._obj.value_and_gradient, w0, self.config,
                l1_weight=l1, value_fn=self._obj.value, label=self.name)
        return res.w, res

    def train_swept(self, offsets: Array, reg, warm_start=None):
        """Batched λ-sweep on the chunked path: ONE double-buffered
        chunk sweep per objective evaluation feeds all L lanes
        (``ChunkedGLMObjective.value_and_gradient_swept``) — the grid's
        data passes per solver iteration drop from L to ~1.

        Same contract as ``FixedEffectCoordinate.train_swept``.
        """
        from photon_ml_tpu.optim.base import OptimizerType
        from photon_ml_tpu.optim.streaming import (
            streaming_lbfgs_solve_swept,
        )

        if self.optimizer == OptimizerType.TRON:
            raise ValueError(
                "train_swept supports LBFGS/OWL-QN lanes only (the λ "
                "sweep is the L-BFGS grid workload; fit TRON "
                "coordinates per grid point)")
        self.chunked.set_offsets(self._coerce_offsets(offsets))
        self._obj.invalidate()
        L = reg.n_lanes
        W0 = (jnp.zeros((L, self.chunked.dim), jnp.float32)
              if warm_start is None
              else jnp.asarray(warm_start, jnp.float32))
        l1v = (reg.l1_vectors(self.chunked.dim,
                              self.objective.reg.reg_mask)
               if reg.has_l1() else None)
        res = streaming_lbfgs_solve_swept(
            lambda W: self._obj.value_and_gradient_swept(W, reg),
            lambda W: self._obj.value_swept(W, reg),
            W0, self.config, l1_weights=l1v, label=self.name,
        )
        return res.w, res

    def score(self, coefficients: Array) -> Array:
        """Raw X·w per example — offset-free, the same
        ``CoordinateDataScores`` convention as the resident path."""
        return jnp.asarray(self._obj.x_dot(coefficients))

    def as_model(self, coefficients: Array) -> FixedEffectModel:
        return FixedEffectModel(
            coefficients=Coefficients(means=coefficients),
            feature_shard=self.name,
        )

    def compute_variances(self, coefficients: Array, offsets: Array,
                          variance_type) -> Array | None:
        from photon_ml_tpu.optim.variance import VarianceComputationType

        if variance_type == VarianceComputationType.NONE:
            return None
        if variance_type == VarianceComputationType.FULL:
            raise ValueError(
                "FULL variances materialize a [d, d] Hessian — not "
                "supported on the chunked path; use SIMPLE")
        self.chunked.set_offsets(self._coerce_offsets(offsets))
        self._obj.invalidate()
        diag = self._obj.hessian_diagonal(coefficients)
        return 1.0 / jnp.maximum(diag, 1e-12)


@dataclasses.dataclass(eq=False)
class RandomEffectCoordinate(Coordinate):
    """Entity-sharded solves, one vmapped batch per size bucket
    (reference ``RandomEffectCoordinate``)."""

    name: str
    grouping: EntityGrouping
    # Per-bucket device arrays (built by ``build_random_effect_coordinate``):
    # widths may differ per bucket when a subspace projection is applied.
    x_blocks: list[Array]        # [E_b, cap_b, p_b]
    label_blocks: list[Array]    # [E_b, cap_b]
    weight_blocks: list[Array]   # [E_b, cap_b]
    mask_blocks: list[Array]     # [E_b, cap_b]
    # Static per-bucket example-index maps (example space ↔ block space):
    ex_idx: list[Array]          # [n_b] example positions in this bucket
    row_idx: list[Array]         # [n_b] entity slot
    col_idx: list[Array]         # [n_b] within-entity position
    n_examples: int
    problem: OptimizationProblem
    # Set when features were subspace-projected (sparse global shard):
    projection: "SubspaceProjection | None" = None

    def initial_coefficients(self) -> list[Array]:
        return [
            jnp.zeros((blk.shape[0], blk.shape[-1]), jnp.float32)
            for blk in self.x_blocks
        ]

    def _blocks(self):
        return (self.x_blocks, self.label_blocks, self.weight_blocks,
                self.mask_blocks, self.ex_idx, self.row_idx, self.col_idx)

    def train(self, offsets: Array, warm_start=None,
              donate_warm_start: bool = False):
        w0s = self.initial_coefficients() if warm_start is None else warm_start
        fn = _re_train_donating if donate_warm_start else _re_train
        results = fn(
            self.problem.optimizer, self.problem.config,
            self.problem.has_l1(), self.problem.objective,
            self._blocks(), offsets, w0s,
        )
        return [r.w for r in results], results

    def score(self, coefficient_blocks: list[Array]) -> Array:
        """Block-space scoring: x·w per entity block, gathered back to
        example order (works for projected and unprojected widths)."""
        return _re_score(self.n_examples, self.x_blocks, self.ex_idx,
                         self.row_idx, self.col_idx, coefficient_blocks)

    def as_model(self, coefficient_blocks: list[Array]) -> RandomEffectModel:
        return RandomEffectModel(
            coefficient_blocks=coefficient_blocks,
            grouping=self.grouping,
            feature_shard=self.name,
            projection=self.projection,
        )

    def compute_variance_blocks(
        self, coefficient_blocks: list[Array], offsets: Array
    ) -> list[Array]:
        """SIMPLE per-entity variances (1/diag H), vmapped per bucket —
        the per-entity arm of the reference's variance pipeline."""
        return _re_variances(self.problem.objective, self._blocks(),
                             coefficient_blocks, offsets)

    @property
    def coefficient_shapes(self) -> list[tuple[int, int]]:
        """(entities, width) per bucket — the shape contract shared
        with the streamed coordinate (warm-start import sizes its
        zero blocks from this, not from resident x_blocks)."""
        return [(blk.shape[0], blk.shape[-1]) for blk in self.x_blocks]


@dataclasses.dataclass(eq=False)
class StreamedRandomEffectCoordinate(Coordinate):
    """Out-of-core random-effect training: streamed entity-bucket
    solves with converged-entity retirement (ISSUE 5 tentpole).

    The resident ``RandomEffectCoordinate`` holds every bucket's
    ``[E_b, cap_b, p_b]`` entity blocks in device/host memory for the
    whole descent — the last subsystem still capped at the resident
    class.  Here each bucket's entities are split into fixed-shape
    *entity chunks* (``chunk_entities`` per chunk, last chunk padded
    with zero-mask entities), spilled through ``data.chunk_store``
    (entity-block codec; content-keyed, mmap-loaded, LRU
    ``host_max_resident`` window, lineage rebuild, warm across runs)
    and driven chunk-by-chunk through the round-8 prefetch pipeline
    (``optim.streaming.prefetch_stream``: disk read → host staging →
    async device_put under the previous chunk's solve).  Only the
    coefficient blocks ``[E_b, p_b]``, the per-example run maps, and
    the score plane stay resident, so host/HBM footprint is bounded by
    the window instead of E.

    **Converged-entity retirement**: between CD sweeps
    (``retire_converged``, called by the coordinate-descent loop),
    entities whose coefficients AND offsets moved less than the solver
    tolerance are retired into a frozen set — their scores stay folded
    into the totals (x and w are unchanged, so the cached scores are
    exact) while subsequent sweeps re-pack only the ACTIVE entities
    into chunks.  Per-sweep solve work shrinks as the descent
    converges, and one hard entity no longer keeps thousands of
    converged lanes spinning through the masked while_loop.  Retired
    entities wake up if their offsets later drift by more than the
    tolerance, so retirement can never move the final model beyond
    solver tolerance.
    """

    traces_convergence = True        # re_convergence events per sweep

    name: str
    grouping: EntityGrouping
    problem: OptimizationProblem
    store: "object"                  # data.chunk_store.ChunkStore
    # Entities per chunk, PER BUCKET: the requested ``re_chunk_entities``
    # balanced across each bucket's chunk count and capped by the
    # bucket's entity count (a global chunk size would pad a small
    # bucket's chunks with dead solve lanes — at cap 1024 that is real
    # FLOPs and real bytes), then rounded up to the mesh grid.
    chunk_ents: list[int]
    widths: list[int]                # p_b per bucket
    ex_sorted: list[np.ndarray]      # per bucket [n_b] example ids
    ent_starts: list[np.ndarray]     # per bucket [E_b + 1] run starts
    chunk_base: list[int]            # global chunk-id base per bucket
    n_source_chunks: list[int]       # chunks per bucket
    n_examples: int
    mesh: "object | None" = None
    prefetch_depth: int = 2
    retirement: bool = True
    # Coefficient/offset movement threshold for retirement; None =
    # the solver tolerance (the ISSUE contract).
    retire_tolerance: float | None = None
    projection: "SubspaceProjection | None" = None

    def __post_init__(self):
        if self.retire_tolerance is None:
            self.retire_tolerance = float(self.problem.config.tolerance)
        ne = self.grouping.n_entities
        self._w_host = [np.zeros((e, p), np.float32)
                        for e, p in zip(ne, self.widths)]
        self._active = [np.ones(e, bool) for e in ne]
        self._pending = [np.zeros(e, bool) for e in ne]
        self._scores_host = np.zeros(self.n_examples, np.float32)
        self._solved_offsets: np.ndarray | None = None
        self._prev_offsets: np.ndarray | None = None
        # The blocks the last train() returned, held BY REFERENCE (an
        # id()-only key could match a recycled address after GC and
        # serve stale cached scores / skip warm-start adoption).
        self._last_w_blocks: list | None = None
        self._cached_scores: Array | None = None
        self.last_diag: dict = {}

    def _is_last_train_output(self, blocks) -> bool:
        return (self._last_w_blocks is not None
                and len(blocks) == len(self._last_w_blocks)
                and all(a is b for a, b in zip(blocks,
                                               self._last_w_blocks)))

    # -- shape/contract surface -------------------------------------------

    @property
    def coefficient_shapes(self) -> list[tuple[int, int]]:
        return [(w.shape[0], w.shape[1]) for w in self._w_host]

    def initial_coefficients(self) -> list[Array]:
        return [jnp.zeros((e, p), jnp.float32)
                for e, p in zip(self.grouping.n_entities, self.widths)]

    @property
    def entities_retired(self) -> int:
        return int(sum((~a).sum() for a in self._active))

    # -- index/run helpers --------------------------------------------------

    def _entity_max(self, b: int, per_example: np.ndarray) -> np.ndarray:
        """Per-entity max of a per-example quantity over bucket b's
        runs ([E_b]; one vectorized reduceat, no Python per entity)."""
        v = per_example[self.ex_sorted[b]]
        return np.maximum.reduceat(v, self.ent_starts[b][:-1])

    @property
    def chunk_entities(self) -> int:
        """Largest per-bucket chunk size (display/diagnostics)."""
        return max(self.chunk_ents) if self.chunk_ents else 0

    def _specs(self) -> list[tuple[int, np.ndarray]]:
        """Packed chunk plan for this sweep: active entities of each
        bucket, ascending slot order, ``chunk_ents[b]`` per chunk —
        ascending slots keep source-chunk access sequential, so the
        LRU window streams forward exactly like a fixed-effect sweep."""
        specs = []
        for b, act in enumerate(self._active):
            C = self.chunk_ents[b]
            sel = np.flatnonzero(act)
            for lo in range(0, len(sel), C):
                specs.append((b, sel[lo:lo + C]))
        return specs

    def _assemble(self, spec, offsets: np.ndarray, with_w0: bool = True,
                  x_only: bool = False):
        """Load stage (runs on the prefetch thread): pull the source
        chunk(s) from the store window, gather the active entities'
        rows into one fixed-shape packed chunk, scatter the CURRENT
        offsets into block space, and gather the warm-start lanes from
        the resident coefficients.  A full, untouched source chunk
        passes its (possibly memmap) arrays straight through — the
        all-active steady state costs no host copy.  ``x_only`` skips
        the scalar planes and the offsets scatter for consumers that
        read nothing but ``x`` (the foreign-blocks scoring pass)."""
        b, ents = spec
        C = self.chunk_ents[b]
        cap = self.grouping.capacities[b]
        p = self.widths[b]
        base = self.chunk_base[b]
        src = ents // C
        full = (len(ents) == C and src[0] == src[-1]
                and int(ents[0]) == int(src[0]) * C
                and int(ents[-1]) == int(src[0]) * C + C - 1)
        if full:
            ch = self.store.get(base + int(src[0]))
            x = ch["x"]
            if not x_only:
                lab, wt, mk = ch["labels"], ch["weights"], ch["mask"]
        else:
            x = np.zeros((C, cap, p), np.float32)
            if not x_only:
                lab = np.zeros((C, cap), np.float32)
                wt = np.zeros((C, cap), np.float32)
                mk = np.zeros((C, cap), np.float32)
            for s in np.unique(src):          # ascending: LRU-friendly
                m = src == s
                ch = self.store.get(base + int(s))
                rows_local = (ents[m] - int(s) * C).astype(np.int64)
                dst = np.flatnonzero(m)
                x[dst] = ch["x"][rows_local]
                if not x_only:
                    lab[dst] = ch["labels"][rows_local]
                    wt[dst] = ch["weights"][rows_local]
                    mk[dst] = ch["mask"][rows_local]
        ex, rows, cols = _entity_example_runs(
            self.ex_sorted[b], self.ent_starts[b], ents)
        if x_only:
            arrays = {"x": x}
        else:
            off = np.zeros((C, cap), np.float32)
            off[rows, cols] = offsets[ex]
            arrays = {"x": x, "labels": lab, "weights": wt, "mask": mk,
                      "offsets": off}
        if with_w0:
            w0 = np.zeros((C, p), np.float32)
            w0[: len(ents)] = self._w_host[b][ents]
            arrays["w0"] = w0
        return {"arrays": arrays, "b": b, "ents": ents, "ex": ex,
                "rows": rows, "cols": cols}

    def _place(self, item):
        """Device placement stage: async device_put (entity-sharded on
        the mesh); the host index maps ride alongside for the
        consumer's score scatter."""
        from photon_ml_tpu.parallel.mesh import place_entity_chunk

        dev = place_entity_chunk(item["arrays"], self.mesh)
        return (dev, item["b"], item["ents"], item["ex"], item["rows"],
                item["cols"])

    def _stream(self, specs, offsets: np.ndarray, with_w0: bool = True,
                x_only: bool = False):
        from photon_ml_tpu.optim.streaming import prefetch_stream

        load = lambda j: self._assemble(specs[j], offsets, with_w0,
                                        x_only)
        return prefetch_stream(load, self._place, range(len(specs)),
                               self.prefetch_depth, store=self.store)

    # -- train ---------------------------------------------------------------

    def _adopt_warm_start(self, warm_start) -> None:
        """External warm-start coefficients (saved model import,
        checkpoint resume): overwrite the resident blocks and reset the
        retirement state — the movement bookkeeping the retirement
        decision rests on is no longer about these coefficients."""
        for b, w in enumerate(warm_start):
            wb = np.asarray(w, np.float32)
            if wb.shape != self._w_host[b].shape:
                raise ValueError(
                    f"warm-start bucket {b} shape {wb.shape} != "
                    f"{self._w_host[b].shape}")
            self._w_host[b] = wb.copy()
        for b in range(len(self._active)):
            self._active[b][:] = True
            self._pending[b][:] = False
        self._solved_offsets = None
        self._prev_offsets = None

    def train(self, offsets: Array, warm_start=None,
              donate_warm_start: bool = False):
        """One streamed sweep over the ACTIVE entities.  Scores come
        out of the same per-chunk dispatch as the solve (no second
        store pass); ``donate_warm_start`` is accepted for contract
        parity and ignored (training state is host-resident)."""
        del donate_warm_start
        off = np.asarray(offsets, np.float32)
        if off.shape[0] != self.n_examples:
            raise ValueError(f"offsets length {off.shape[0]} != "
                             f"n {self.n_examples}")
        if warm_start is not None and not self._is_last_train_output(
                list(warm_start)):
            self._adopt_warm_start(warm_start)
        rtol = self.retire_tolerance
        woken = 0
        if self._solved_offsets is None:
            self._solved_offsets = off.copy()
        elif self.retirement and self.entities_retired:
            # Wake retired entities whose offsets drifted past the
            # tolerance since their last solve — retirement must never
            # move the final model beyond solver tolerance.  (Skipped
            # while nothing is retired: the drift scan is O(n) per
            # bucket.)
            drift = np.abs(off - self._solved_offsets)
            for b in range(len(self._active)):
                woke = ((~self._active[b])
                        & (self._entity_max(b, drift) >= rtol))
                woken += int(woke.sum())
                self._active[b] |= woke

        specs = self._specs()
        retired_now = self.entities_retired
        ne = self.grouping.n_entities
        solved = [np.zeros(e, bool) for e in ne]
        conv = [np.zeros(e, bool) for e in ne]
        dw = [np.zeros(e, np.float32) for e in ne]
        max_iters = 0

        def harvest(out, b, ents, ex, rows, cols):
            nonlocal max_iters
            k = len(ents)
            w_np = np.asarray(out[0])[:k]
            scores_np = np.asarray(out[1])
            self._w_host[b][ents] = w_np
            self._scores_host[ex] = scores_np[rows, cols]
            dw[b][ents] = np.asarray(out[2])[:k]
            solved[b][ents] = True
            conv[b][ents] = np.asarray(out[3])[:k]
            if k:
                max_iters = max(max_iters,
                                int(np.asarray(out[4])[:k].max()))
            self._solved_offsets[ex] = off[ex]

        opt = self.problem
        has_l1 = opt.has_l1()
        pending = None
        # Stage span (ISSUE 7): one streamed RE sweep — the unit the
        # overlap-efficiency derivation divides consumer wait by.
        with telemetry.span("re_sweep", cat="solver",
                            coordinate=self.name, chunks=len(specs)):
            for ci, (_, item) in enumerate(self._stream(specs, off)):
                dev, b, ents, ex, rows, cols = item
                with telemetry.span("chunk_compute", cat="device",
                                    bucket=b):
                    out = _re_chunk_train(
                        opt.optimizer, opt.config, has_l1, opt.objective,
                        dev["x"], dev["labels"], dev["weights"],
                        dev["mask"], dev["offsets"], dev["w0"],
                    )
                    # Device cost of bucket b's chunk-train program
                    # (once per session per bucket shape; the program
                    # just dispatched, so the relower is cache-warm).
                    _device.maybe_capture(
                        f"re_chunk_train.b{b}", _re_chunk_train,
                        (opt.optimizer, opt.config, has_l1,
                         opt.objective, dev["x"], dev["labels"],
                         dev["weights"], dev["mask"], dev["offsets"],
                         dev["w0"]), span="chunk_compute")
                    if pending is not None:
                        # Lag-1 harvest IS the dispatch backpressure:
                        # fetching chunk j-1's blocks fences its solve
                        # while chunk j computes and chunks j+1..
                        # prefetch — at most two chunks' device buffers
                        # are ever in flight.
                        harvest(*pending)
                pending = (out, b, ents, ex, rows, cols)
                # Live entity-chunk progress (ISSUE 10): within-sweep
                # ETA from the observed chunk rate; no-op when off.
                _mon.progress(f"re.{self.name}", ci + 1, len(specs),
                              unit="chunks")
            if pending is not None:
                harvest(*pending)
        telemetry.count("re.sweeps")
        telemetry.count("re.chunks_streamed", len(specs))

        # Retirement candidates: solved, lane-converged, coefficients
        # AND offsets both moved < tolerance this sweep.  Committed by
        # the CD loop's retire_converged() hook, so direct train()
        # callers (parity tests, notebooks) see pure streaming.
        if self.retirement and self._prev_offsets is not None:
            drift_prev = np.abs(off - self._prev_offsets)
            for b in range(len(self._pending)):
                doff = self._entity_max(b, drift_prev)
                self._pending[b] = (solved[b] & conv[b]
                                    & (dw[b] < rtol) & (doff < rtol))
        self._prev_offsets = off.copy()

        # The sweep churned one staging chunk's arrays per packed chunk;
        # glibc retains much of that as arena slack, which would read as
        # permanent RSS — the exact number an out-of-core path exists to
        # bound.  Once per sweep, return it (no-op off Linux).
        from photon_ml_tpu.data.chunk_store import release_free_heap

        release_free_heap()
        blocks_out = [jnp.asarray(w) for w in self._w_host]
        self._last_w_blocks = list(blocks_out)
        self._cached_scores = jnp.asarray(self._scores_host)
        n_solved = int(sum(m.sum() for m in solved))
        telemetry.count("re.entities_solved", n_solved)
        diag = {
            "entities": int(sum(ne)),
            "entities_solved": n_solved,
            "entities_converged": int(sum((m & c).sum()
                                          for m, c in zip(solved, conv))),
            "entities_retired": retired_now,
            "entities_woken": woken,
            "max_solver_iterations": max_iters,
            "chunks_streamed": len(specs),
        }
        self.last_diag = diag
        # Per-sweep retirement/convergence dynamics event (ISSUE 8) —
        # the trajectory the retirement machinery is judged on, not
        # just end-state parity.
        _conv.re_sweep(self.name, diag)
        return blocks_out, diag

    # -- checkpoint/resume (ISSUE 9) -----------------------------------------

    def runtime_state(self) -> dict:
        """Checkpoint tree of everything the retirement machinery
        carries BETWEEN sweeps: resident coefficient blocks,
        active/pending masks, the score plane, and the offset baselines
        the wake/retire decisions compare against.  Captured by the CD
        loop's checkpointer so a resumed run retires/wakes exactly as
        the uninterrupted run would have."""
        return {
            "w_host": [np.asarray(w) for w in self._w_host],
            "active": [np.asarray(a) for a in self._active],
            "pending": [np.asarray(p) for p in self._pending],
            "scores_host": np.asarray(self._scores_host),
            "solved_offsets": (None if self._solved_offsets is None
                               else np.asarray(self._solved_offsets)),
            "prev_offsets": (None if self._prev_offsets is None
                             else np.asarray(self._prev_offsets)),
        }

    def restore_runtime_state(self, state: dict):
        """Inverse of ``runtime_state``.  Returns (canonical
        coefficient blocks, cached score plane): the CD loop installs
        the RETURNED blocks as the warm start, so ``train``'s identity
        check recognizes them and keeps the restored retirement
        bookkeeping instead of resetting it (``_adopt_warm_start``
        exists for FOREIGN warm starts, and a checkpoint is not
        foreign)."""
        for b, w in enumerate(state["w_host"]):
            wb = np.asarray(w, np.float32)
            if wb.shape != self._w_host[b].shape:
                raise ValueError(
                    f"checkpoint bucket {b} shape {wb.shape} != "
                    f"{self._w_host[b].shape} (grouping changed; a "
                    "checkpoint only resumes its own dataset/config)")
            self._w_host[b] = wb.copy()
            self._active[b] = np.asarray(state["active"][b], bool).copy()
            self._pending[b] = np.asarray(state["pending"][b],
                                          bool).copy()
        self._scores_host = np.asarray(state["scores_host"],
                                       np.float32).copy()
        self._solved_offsets = (
            None if state.get("solved_offsets") is None
            else np.asarray(state["solved_offsets"], np.float32).copy())
        self._prev_offsets = (
            None if state.get("prev_offsets") is None
            else np.asarray(state["prev_offsets"], np.float32).copy())
        blocks = [jnp.asarray(w) for w in self._w_host]
        self._last_w_blocks = list(blocks)
        self._cached_scores = jnp.asarray(self._scores_host)
        return blocks, self._cached_scores

    def retire_converged(self) -> int:
        """Commit this sweep's retirement candidates (the coordinate-
        descent hook, called between sweeps).  Returns the number of
        newly retired entities; a no-op (0) with retirement off."""
        if not self.retirement:
            return 0
        newly = 0
        for b in range(len(self._active)):
            pend = self._pending[b] & self._active[b]
            newly += int(pend.sum())
            self._active[b] &= ~pend
            self._pending[b][:] = False
        if newly:
            # Commit-time event: re_sweep samples retirement as of
            # sweep START, so the last sweep's commit lands here.
            _conv.re_retirement(self.name, newly, self.entities_retired)
        return newly

    # -- score / export / variances -----------------------------------------

    def score(self, coefficient_blocks: list[Array]) -> Array:
        """Raw x·w per example.  The blocks the last ``train`` returned
        hit the cached plane (scores were computed inside the solve
        dispatch); zero blocks short-circuit (the CD shape probe);
        anything else streams one scoring pass over the store."""
        if (self._cached_scores is not None
                and self._is_last_train_output(list(coefficient_blocks))):
            return self._cached_scores
        if all(not bool(jnp.any(bk != 0)) for bk in coefficient_blocks):
            return jnp.zeros((self.n_examples,), jnp.float32)
        blocks = [np.asarray(bk, np.float32) for bk in coefficient_blocks]
        scores = np.zeros(self.n_examples, np.float32)
        zeros = np.zeros(0, np.float32)   # unused: x_only skips offsets
        for j, item in self._stream(self._full_specs(), zeros,
                                    with_w0=False, x_only=True):
            dev, b, ents, ex, rows, cols = item
            w_chunk = np.zeros((self.chunk_ents[b], self.widths[b]),
                               np.float32)
            w_chunk[: len(ents)] = blocks[b][ents]
            blk = np.asarray(_re_chunk_score(dev["x"],
                                             jnp.asarray(w_chunk)))
            scores[ex] = blk[rows, cols]
        return jnp.asarray(scores)

    def _full_specs(self) -> list[tuple[int, np.ndarray]]:
        specs = []
        for b, e in enumerate(self.grouping.n_entities):
            C = self.chunk_ents[b]
            for s in range(self.n_source_chunks[b]):
                lo = s * C
                specs.append((b, np.arange(lo, min(lo + C, e),
                                           dtype=np.int64)))
        return specs

    def as_model(self, coefficient_blocks: list[Array]) -> RandomEffectModel:
        return RandomEffectModel(
            coefficient_blocks=coefficient_blocks,
            grouping=self.grouping,
            feature_shard=self.name,
            projection=self.projection,
        )

    def compute_variance_blocks(
        self, coefficient_blocks: list[Array], offsets: Array
    ) -> list[Array]:
        """SIMPLE per-entity variances, streamed chunk-by-chunk (one
        more full pass over the store — variances are a once-per-fit
        export, not sweep state)."""
        off = np.asarray(offsets, np.float32)
        blocks = [np.asarray(bk, np.float32) for bk in coefficient_blocks]
        out = [np.zeros((e, p), np.float32)
               for e, p in zip(self.grouping.n_entities, self.widths)]
        for j, item in self._stream(self._full_specs(), off,
                                    with_w0=False):
            dev, b, ents, ex, rows, cols = item
            w_chunk = np.zeros((self.chunk_ents[b], self.widths[b]),
                               np.float32)
            w_chunk[: len(ents)] = blocks[b][ents]
            v = np.asarray(_re_chunk_vars(
                self.problem.objective, dev["x"], dev["labels"],
                dev["weights"], dev["mask"], dev["offsets"],
                jnp.asarray(w_chunk)))
            out[b][ents] = v[: len(ents)]
        return [jnp.asarray(v) for v in out]


def _shard_re_blocks(coord_kwargs: dict, mesh) -> dict:
    """Entity-shard a coordinate's bucket blocks on the mesh
    (reference parallelism strategy #2 — per-entity solves are
    communication-free, so the leading entity axis shards cleanly)."""
    if mesh is None:
        return coord_kwargs
    from photon_ml_tpu.parallel.mesh import shard_entity_blocks

    for key in ("x_blocks", "label_blocks", "weight_blocks", "mask_blocks"):
        coord_kwargs[key] = shard_entity_blocks(coord_kwargs[key], mesh)
    return coord_kwargs


def build_random_effect_coordinate(
    name: str,
    dataset: GameDataset,
    feature_shard: str,
    objective: GLMObjective,
    config: OptimizerConfig | None = None,
    optimizer=None,
    bucket_base: int = 4,
    mesh=None,
) -> RandomEffectCoordinate:
    """Host ETL → device blocks: the reference's partition-and-group
    pipeline (``RandomEffectDataset.apply``) as one deterministic pass."""
    from photon_ml_tpu.optim.base import OptimizerType

    x = np.asarray(dataset.features[feature_shard], np.float32)
    entity_ids = dataset.entity_ids[name]
    grouping = group_by_entity(entity_ids, bucket_base=bucket_base)

    labels = dataset.labels.astype(np.float32)
    weights = dataset.weight_array()

    lab_blocks, wt_blocks, mask_blocks = _scalar_blocks(
        grouping, labels, weights
    )
    ex_idx, row_idx, col_idx = _index_maps(grouping)

    x_blocks = []
    for b, (cap, ne) in enumerate(zip(grouping.capacities,
                                      grouping.n_entities)):
        sel = np.where(grouping.example_bucket == b)[0]
        xb = np.zeros((ne, cap, x.shape[1]), np.float32)
        xb[grouping.example_row[sel], grouping.example_col[sel]] = x[sel]
        x_blocks.append(jnp.asarray(xb))

    blocks = _shard_re_blocks(
        dict(x_blocks=x_blocks, label_blocks=lab_blocks,
             weight_blocks=wt_blocks, mask_blocks=mask_blocks),
        mesh,
    )
    x_blocks = blocks["x_blocks"]
    lab_blocks = blocks["label_blocks"]
    wt_blocks = blocks["weight_blocks"]
    mask_blocks = blocks["mask_blocks"]

    problem = OptimizationProblem(
        objective=objective,
        optimizer=optimizer or OptimizerType.LBFGS,
        config=config or OptimizerConfig(),
    )
    _log_occupancy(name, grouping)
    return RandomEffectCoordinate(
        name=name,
        grouping=grouping,
        x_blocks=x_blocks,
        label_blocks=lab_blocks,
        weight_blocks=wt_blocks,
        mask_blocks=mask_blocks,
        ex_idx=ex_idx,
        row_idx=row_idx,
        col_idx=col_idx,
        n_examples=len(labels),
        problem=problem,
    )


def _log_occupancy(name: str, grouping) -> None:
    """One line of bucket occupancy / padding-waste stats per RE
    coordinate build (ISSUE 5 satellite): a ``bucket_base`` regression
    multiplies every block array silently — make it visible."""
    from photon_ml_tpu.game.dataset import bucket_occupancy

    occ = bucket_occupancy(grouping)
    per_bucket = ", ".join(
        f"cap={b['capacity']}:E={b['entities']}:fill={b['fill_fraction']}"
        for b in occ["buckets"])
    logger.info(
        "RE coordinate '%s': %d entities / %d examples in %d buckets "
        "[%s]; padded-slot ratio %.4f (%d of %d slots)",
        name, occ["entities"], occ["examples"], len(occ["buckets"]),
        per_bucket, occ["padded_slot_ratio"], occ["padded_slots"],
        occ["total_slots"])


def _scalar_blocks(grouping, labels, weights):
    """labels/weights/mask → per-bucket [E_b, cap_b] blocks."""
    lab_blocks, wt_blocks, mask_blocks = [], [], []
    for b, (cap, ne) in enumerate(zip(grouping.capacities,
                                      grouping.n_entities)):
        sel = np.where(grouping.example_bucket == b)[0]
        rows = grouping.example_row[sel]
        cols = grouping.example_col[sel]
        lb = np.zeros((ne, cap), np.float32)
        wb = np.zeros((ne, cap), np.float32)
        mb = np.zeros((ne, cap), np.float32)
        lb[rows, cols] = labels[sel]
        wb[rows, cols] = weights[sel]
        mb[rows, cols] = 1.0
        lab_blocks.append(jnp.asarray(lb))
        wt_blocks.append(jnp.asarray(wb))
        mask_blocks.append(jnp.asarray(mb))
    return lab_blocks, wt_blocks, mask_blocks


def _index_maps(grouping):
    ex_idx, row_idx, col_idx = [], [], []
    for b in range(len(grouping.capacities)):
        sel = np.where(grouping.example_bucket == b)[0]
        ex_idx.append(jnp.asarray(sel.astype(np.int32)))
        row_idx.append(jnp.asarray(grouping.example_row[sel].astype(np.int32)))
        col_idx.append(jnp.asarray(grouping.example_col[sel].astype(np.int32)))
    return ex_idx, row_idx, col_idx


def build_random_effect_coordinate_sparse(
    name: str,
    dataset: GameDataset,
    feature_shard: str,
    objective: GLMObjective,
    global_dim: int,
    config: OptimizerConfig | None = None,
    optimizer=None,
    bucket_base: int = 4,
    mesh=None,
) -> RandomEffectCoordinate:
    """Sparse-shard variant: features arrive as per-example (col_ids,
    values) rows in a wide global space; each entity's problem is solved
    in its observed-feature subspace (reference
    ``LinearSubspaceProjector`` path, SURVEY §2.4)."""
    from photon_ml_tpu.game.projector import build_subspace_projection
    from photon_ml_tpu.optim.base import OptimizerType

    rows = dataset.features[feature_shard]
    entity_ids = dataset.entity_ids[name]
    grouping = group_by_entity(entity_ids, bucket_base=bucket_base)

    projection, x_blocks_np = build_subspace_projection(
        grouping, rows, global_dim
    )
    labels = dataset.labels.astype(np.float32)
    weights = dataset.weight_array()
    lab_blocks, wt_blocks, mask_blocks = _scalar_blocks(
        grouping, labels, weights
    )
    ex_idx, row_idx, col_idx = _index_maps(grouping)

    problem = OptimizationProblem(
        objective=objective,
        optimizer=optimizer or OptimizerType.LBFGS,
        config=config or OptimizerConfig(),
    )
    blocks = _shard_re_blocks(
        dict(x_blocks=[jnp.asarray(xb) for xb in x_blocks_np],
             label_blocks=lab_blocks, weight_blocks=wt_blocks,
             mask_blocks=mask_blocks),
        mesh,
    )
    lab_blocks = blocks["label_blocks"]
    wt_blocks = blocks["weight_blocks"]
    mask_blocks = blocks["mask_blocks"]
    _log_occupancy(name, grouping)
    return RandomEffectCoordinate(
        name=name,
        grouping=grouping,
        x_blocks=blocks["x_blocks"],
        label_blocks=lab_blocks,
        weight_blocks=wt_blocks,
        mask_blocks=mask_blocks,
        ex_idx=ex_idx,
        row_idx=row_idx,
        col_idx=col_idx,
        n_examples=len(labels),
        problem=problem,
        projection=projection,
    )


def build_streamed_random_effect_coordinate(
    name: str,
    dataset: GameDataset,
    feature_shard: str,
    objective: GLMObjective,
    spill_dir: str,
    chunk_entities: int,
    config: OptimizerConfig | None = None,
    optimizer=None,
    bucket_base: int = 4,
    host_max_resident: int = 2,
    prefetch_depth: int = 2,
    retirement: bool = True,
    mesh=None,
) -> StreamedRandomEffectCoordinate:
    """Out-of-core variant of the RE coordinate builders: entity
    blocks are built ONE CHUNK AT A TIME and spilled straight to the
    chunk store (content-keyed; an existing file for the same data +
    config is reused, so a second run's build is pure stat calls), so
    peak host RSS during ETL is bounded by the chunk, not by E.

    Dense feature shards assemble each chunk directly from the
    per-example feature rows; sparse shards go through the subspace
    projection (``game.projector``) first — the projection build is
    inherently global (per-entity column sets), so its blocks are
    materialized once, spilled, and freed, with lineage rebuild
    re-running the (deterministic) projection on demand.

    ``chunk_entities`` is rounded up to the mesh grid when ``mesh`` is
    given: every packed chunk then entity-shards evenly
    (``parallel.mesh.place_entity_chunk``).
    """
    from photon_ml_tpu.data.chunk_store import (
        ENTITY_CHUNK_CODEC,
        ChunkStore,
        array_content_key,
        release_free_heap,
    )
    from photon_ml_tpu.data.sparse_rows import SparseRows
    from photon_ml_tpu.optim.base import OptimizerType

    if chunk_entities <= 0:
        raise ValueError("chunk_entities must be positive")
    if not spill_dir:
        raise ValueError(
            "streamed random-effect training requires spill_dir (the "
            "chunk store is the architecture, not an option)")
    feats = dataset.features[feature_shard]
    entity_ids = np.asarray(dataset.entity_ids[name])
    grouping = group_by_entity(entity_ids, bucket_base=bucket_base)
    labels = dataset.labels.astype(np.float32)
    weights = dataset.weight_array()
    n_dev = 1 if mesh is None else mesh.devices.size
    # Per-bucket chunk size: the requested budget, balanced across the
    # bucket's chunk count and capped by its entity count — a GLOBAL
    # chunk size would pad a small bucket's one chunk with dead solve
    # lanes (at cap 1024 × p that is real FLOPs and real transfer) —
    # then rounded up to the mesh grid.
    chunk_ents = []
    for e in grouping.n_entities:
        k_b = max(1, -(-e // max(1, int(chunk_entities))))
        cb = -(-e // k_b)
        chunk_ents.append(-(-cb // n_dev) * n_dev)
    ex_sorted, ent_starts = _example_runs(grouping)

    sparse = not isinstance(feats, np.ndarray)
    projection = None
    if sparse:
        from photon_ml_tpu.game.projector import build_subspace_projection

        if not isinstance(feats, SparseRows):
            feats = SparseRows.from_rows(feats)
        global_dim = dataset.feature_dim(feature_shard)
        projection, x_blocks_np = build_subspace_projection(
            grouping, feats, global_dim)
        widths = [xb.shape[-1] for xb in x_blocks_np]
        # Blocks are freed after the spill below; lineage rebuild
        # re-runs the (deterministic) projection on demand.
        src_holder = {"blocks": x_blocks_np}

        def chunk_x(b, lo, hi):
            if src_holder["blocks"] is None:
                src_holder["blocks"] = build_subspace_projection(
                    grouping, feats, global_dim)[1]
            return src_holder["blocks"][b][lo:hi]

        key_arrays = [np.asarray(feats.indptr), np.asarray(feats.cols),
                      np.asarray(feats.vals, np.float32), labels,
                      weights, entity_ids]
    else:
        x = np.asarray(feats, np.float32)
        widths = [x.shape[1]] * len(grouping.capacities)
        src_holder = None
        chunk_x = None
        key_arrays = [x, labels, weights, entity_ids]

    n_source_chunks = [-(-e // cb)
                       for e, cb in zip(grouping.n_entities, chunk_ents)]
    chunk_base = list(np.concatenate(
        [[0], np.cumsum(n_source_chunks)[:-1]]).astype(int)) \
        if n_source_chunks else []
    total_chunks = int(sum(n_source_chunks))

    def locate(gid: int) -> tuple[int, int]:
        for b in range(len(chunk_base) - 1, -1, -1):
            if gid >= chunk_base[b]:
                return b, gid - chunk_base[b]
        raise IndexError(gid)

    def build_chunk(b: int, s: int) -> dict:
        cap = grouping.capacities[b]
        p = widths[b]
        C = chunk_ents[b]
        lo = s * C
        hi = min(lo + C, grouping.n_entities[b])
        ents = np.arange(lo, hi, dtype=np.int64)
        ex, rows, cols = _entity_example_runs(
            ex_sorted[b], ent_starts[b], ents)
        lb = np.zeros((C, cap), np.float32)
        wt = np.zeros((C, cap), np.float32)
        mk = np.zeros((C, cap), np.float32)
        lb[rows, cols] = labels[ex]
        wt[rows, cols] = weights[ex]
        mk[rows, cols] = 1.0
        xc = np.zeros((C, cap, p), np.float32)
        if sparse:
            xc[: hi - lo] = chunk_x(b, lo, hi)
        else:
            xc[rows, cols] = x[ex]
        return {"x": xc, "labels": lb, "weights": wt, "mask": mk}

    def rebuild(gid: int) -> dict:
        b, s = locate(gid)
        return build_chunk(b, s)

    key = array_content_key(key_arrays, {
        "kind": "re-sparse" if sparse else "re-dense",
        "chunk_ents": [int(cb) for cb in chunk_ents],
        "bucket_base": int(bucket_base),
        "widths": [int(p) for p in widths],
    })
    store = ChunkStore(spill_dir, key, total_chunks,
                       host_max_resident=host_max_resident,
                       rebuild=rebuild, codec=ENTITY_CHUNK_CODEC)
    missing = [gid for gid in range(total_chunks) if not store.has(gid)]
    for gid in missing:
        b, s = locate(gid)
        # Default admission (the first window's worth stays resident):
        # the first sweep visits chunks in exactly this order, so it
        # starts warm.
        store.put(gid, build_chunk(b, s))
    if sparse:
        src_holder["blocks"] = None   # spilled; lineage rebuilds
    if missing:
        release_free_heap()   # build churn must not read as steady RSS

    problem = OptimizationProblem(
        objective=objective,
        optimizer=optimizer or OptimizerType.LBFGS,
        config=config or OptimizerConfig(),
    )
    _log_occupancy(name, grouping)
    logger.info(
        "streamed RE coordinate '%s': %d entity chunks (per-bucket "
        "sizes %s; %d built, %d reused; host window %d) spilled to %s",
        name, total_chunks, chunk_ents, len(missing),
        total_chunks - len(missing), store.host_max_resident, spill_dir)
    return StreamedRandomEffectCoordinate(
        name=name,
        grouping=grouping,
        problem=problem,
        store=store,
        chunk_ents=[int(cb) for cb in chunk_ents],
        widths=[int(p) for p in widths],
        ex_sorted=ex_sorted,
        ent_starts=ent_starts,
        chunk_base=[int(cb) for cb in chunk_base],
        n_source_chunks=[int(ks) for ks in n_source_chunks],
        n_examples=len(labels),
        mesh=mesh,
        prefetch_depth=prefetch_depth,
        retirement=retirement,
        projection=projection,
    )
