"""GAME coordinates: the per-coordinate train/score units.

Reference counterparts: ``Coordinate``, ``FixedEffectCoordinate``,
``RandomEffectCoordinate`` (photon-api
``com.linkedin.photon.ml.algorithm`` [expected paths, mount unavailable —
see SURVEY.md §2.3]).

The reference contract carries over exactly — ``train(offsets, warm
start) → model`` and ``score(model) → per-example scores`` — but the
execution model flips:

- ``FixedEffectCoordinate``: the reference runs
  ``DistributedOptimizationProblem`` (broadcast + treeAggregate per
  L-BFGS iteration).  Here the SAME ``OptimizationProblem`` runs over
  either a local batch or a mesh-sharded batch wrapped in
  ``DistributedGLMObjective`` — one jitted solve either way.
- ``RandomEffectCoordinate``: the reference's
  ``RDD[(REId, LocalDataset)].mapValues(solve per entity)`` — thousands
  of sequential JVM L-BFGS loops per partition — becomes ONE
  ``vmap``ped solve per size bucket: every entity in a bucket optimizes
  simultaneously on the VPU/MXU, each converging by its own criterion
  (masked while_loop).  Entity blocks are built once by the host ETL
  (``EntityGrouping``); per-CD-iteration offsets move between example
  space and block space by static-index gather/scatter on device.

Scores are raw dot products x·w (no offset, no link), summable across
coordinates — the reference's ``CoordinateDataScores`` convention.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import Batch, DenseBatch
from photon_ml_tpu.game.dataset import (
    EntityGrouping,
    GameDataset,
    group_by_entity,
)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim import OptimizationProblem, OptimizerConfig
from photon_ml_tpu.optim.lbfgs import lbfgs_solve
from photon_ml_tpu.optim.tron import tron_solve
from photon_ml_tpu.parallel.distributed_objective import DistributedGLMObjective

Array = jax.Array


class Coordinate:
    """train/score contract (reference ``Coordinate`` abstraction)."""

    name: str

    def initial_coefficients(self):
        raise NotImplementedError

    def train(self, offsets: Array, warm_start):
        """offsets [n] (residual scores from other coordinates) → (model
        coefficients, optimizer diagnostics)."""
        raise NotImplementedError

    def score(self, coefficients) -> Array:
        """coefficients → per-example scores [n]."""
        raise NotImplementedError


@dataclasses.dataclass(eq=False)
class FixedEffectCoordinate(Coordinate):
    """Global solve over the full batch (reference
    ``FixedEffectCoordinate`` + ``DistributedOptimizationProblem``)."""

    name: str
    batch: Batch                      # full batch (scoring); local or sharded
    problem: OptimizationProblem
    distributed: DistributedGLMObjective | None = None  # set if sharded
    # Down-sampled training view (reference DownSampler, SURVEY §2.4):
    # train on batch rows ``train_idx`` with ``train_weights``; score all.
    train_idx: Array | None = None
    train_weights: Array | None = None

    def initial_coefficients(self) -> Array:
        return jnp.zeros((self.batch.dim,), jnp.float32)

    def _training_batch(self, offsets: Array) -> Batch:
        if self.train_idx is None:
            return self.batch.replace(offsets=offsets)
        base = self.batch
        from photon_ml_tpu.data.batch import SparseBatch

        if isinstance(base, SparseBatch) and (
            base.colmajor is not None or base.grr is not None
        ):
            # The transposed-ELL / GRR plans index *all* rows;
            # subsetting their layout arrays by example ids would
            # silently corrupt X^T r.  Drop them — the subsetted batch
            # falls back to the ELL paths (down-sampled solves are
            # smaller anyway).
            base = base.replace(colmajor=None, grr=None)
        sub = jax.tree.map(lambda a: a[self.train_idx], base)
        return sub.replace(offsets=offsets[self.train_idx],
                           weights=self.train_weights)

    @partial(jax.jit, static_argnums=0)
    def _train_jit(self, offsets: Array, w0: Array):
        batch = self._training_batch(offsets)
        if self.distributed is None:
            return self.problem.run(batch, w0)
        # Same solver over the psum-reduced objective.
        obj = self.distributed
        vg = lambda w: obj.value_and_gradient(w, batch)
        from photon_ml_tpu.optim.base import OptimizerType

        if self.problem.optimizer == OptimizerType.TRON:
            hvp = lambda w, v: obj.hessian_vector(w, v, batch)
            return tron_solve(vg, hvp, w0, self.problem.config)
        return lbfgs_solve(
            vg, w0, self.problem.config,
            l1_weight=self.problem._l1_vector(w0.shape[-1]),
        )

    def train(self, offsets: Array, warm_start: Array | None = None):
        w0 = self.initial_coefficients() if warm_start is None else warm_start
        res = self._train_jit(offsets, w0)
        return res.w, res

    @partial(jax.jit, static_argnums=0)
    def score(self, coefficients: Array) -> Array:
        return self.batch.x_dot(coefficients)

    def as_model(self, coefficients: Array) -> FixedEffectModel:
        return FixedEffectModel(
            coefficients=Coefficients(means=coefficients),
            feature_shard=self.name,
        )

    def compute_variances(self, coefficients: Array, offsets: Array,
                          variance_type) -> Array | None:
        """Coefficient variances at the optimum over the training view
        (reference VarianceComputationType pipeline, SURVEY §2.1)."""
        from photon_ml_tpu.optim.variance import compute_variances

        return compute_variances(
            self.problem.objective, coefficients,
            self._training_batch(offsets), variance_type,
        )


@dataclasses.dataclass(eq=False)
class RandomEffectCoordinate(Coordinate):
    """Entity-sharded solves, one vmapped batch per size bucket
    (reference ``RandomEffectCoordinate``)."""

    name: str
    grouping: EntityGrouping
    # Per-bucket device arrays (built by ``build_random_effect_coordinate``):
    # widths may differ per bucket when a subspace projection is applied.
    x_blocks: list[Array]        # [E_b, cap_b, p_b]
    label_blocks: list[Array]    # [E_b, cap_b]
    weight_blocks: list[Array]   # [E_b, cap_b]
    mask_blocks: list[Array]     # [E_b, cap_b]
    # Static per-bucket example-index maps (example space ↔ block space):
    ex_idx: list[Array]          # [n_b] example positions in this bucket
    row_idx: list[Array]         # [n_b] entity slot
    col_idx: list[Array]         # [n_b] within-entity position
    n_examples: int
    problem: OptimizationProblem
    # Set when features were subspace-projected (sparse global shard):
    projection: "SubspaceProjection | None" = None

    def initial_coefficients(self) -> list[Array]:
        return [
            jnp.zeros((blk.shape[0], blk.shape[-1]), jnp.float32)
            for blk in self.x_blocks
        ]

    @partial(jax.jit, static_argnums=0)
    def _train_jit(self, offsets: Array, w0s: list[Array]):
        outs = []
        for b in range(len(self.x_blocks)):
            off_blk = jnp.zeros_like(self.label_blocks[b]).at[
                self.row_idx[b], self.col_idx[b]
            ].set(offsets[self.ex_idx[b]])
            batch_b = DenseBatch(
                x=self.x_blocks[b],
                labels=self.label_blocks[b],
                weights=self.weight_blocks[b],
                offsets=off_blk,
                mask=self.mask_blocks[b],
            )
            res = jax.vmap(self.problem.run)(batch_b, w0s[b])
            outs.append(res)
        return outs

    def train(self, offsets: Array, warm_start=None):
        w0s = self.initial_coefficients() if warm_start is None else warm_start
        results = self._train_jit(offsets, w0s)
        return [r.w for r in results], results

    @partial(jax.jit, static_argnums=0)
    def score(self, coefficient_blocks: list[Array]) -> Array:
        """Block-space scoring: x·w per entity block, gathered back to
        example order (works for projected and unprojected widths)."""
        scores = jnp.zeros((self.n_examples,), jnp.float32)
        for b, w_b in enumerate(coefficient_blocks):
            blk_scores = jnp.einsum("ecp,ep->ec", self.x_blocks[b], w_b)
            scores = scores.at[self.ex_idx[b]].set(
                blk_scores[self.row_idx[b], self.col_idx[b]]
            )
        return scores

    def as_model(self, coefficient_blocks: list[Array]) -> RandomEffectModel:
        return RandomEffectModel(
            coefficient_blocks=coefficient_blocks,
            grouping=self.grouping,
            feature_shard=self.name,
            projection=self.projection,
        )

    @partial(jax.jit, static_argnums=0)
    def compute_variance_blocks(
        self, coefficient_blocks: list[Array], offsets: Array
    ) -> list[Array]:
        """SIMPLE per-entity variances (1/diag H), vmapped per bucket —
        the per-entity arm of the reference's variance pipeline."""
        from photon_ml_tpu.optim.variance import simple_variances

        out = []
        for b, w_b in enumerate(coefficient_blocks):
            off_blk = jnp.zeros_like(self.label_blocks[b]).at[
                self.row_idx[b], self.col_idx[b]
            ].set(offsets[self.ex_idx[b]])
            batch_b = DenseBatch(
                x=self.x_blocks[b],
                labels=self.label_blocks[b],
                weights=self.weight_blocks[b],
                offsets=off_blk,
                mask=self.mask_blocks[b],
            )
            out.append(jax.vmap(
                lambda w, bb: simple_variances(
                    self.problem.objective, w, bb)
            )(w_b, batch_b))
        return out


def build_random_effect_coordinate(
    name: str,
    dataset: GameDataset,
    feature_shard: str,
    objective: GLMObjective,
    config: OptimizerConfig | None = None,
    optimizer=None,
    bucket_base: int = 4,
) -> RandomEffectCoordinate:
    """Host ETL → device blocks: the reference's partition-and-group
    pipeline (``RandomEffectDataset.apply``) as one deterministic pass."""
    from photon_ml_tpu.optim.base import OptimizerType

    x = np.asarray(dataset.features[feature_shard], np.float32)
    entity_ids = dataset.entity_ids[name]
    grouping = group_by_entity(entity_ids, bucket_base=bucket_base)

    labels = dataset.labels.astype(np.float32)
    weights = dataset.weight_array()

    lab_blocks, wt_blocks, mask_blocks = _scalar_blocks(
        grouping, labels, weights
    )
    ex_idx, row_idx, col_idx = _index_maps(grouping)

    x_blocks = []
    for b, (cap, ne) in enumerate(zip(grouping.capacities,
                                      grouping.n_entities)):
        sel = np.where(grouping.example_bucket == b)[0]
        xb = np.zeros((ne, cap, x.shape[1]), np.float32)
        xb[grouping.example_row[sel], grouping.example_col[sel]] = x[sel]
        x_blocks.append(jnp.asarray(xb))

    problem = OptimizationProblem(
        objective=objective,
        optimizer=optimizer or OptimizerType.LBFGS,
        config=config or OptimizerConfig(),
    )
    return RandomEffectCoordinate(
        name=name,
        grouping=grouping,
        x_blocks=x_blocks,
        label_blocks=lab_blocks,
        weight_blocks=wt_blocks,
        mask_blocks=mask_blocks,
        ex_idx=ex_idx,
        row_idx=row_idx,
        col_idx=col_idx,
        n_examples=len(labels),
        problem=problem,
    )


def _scalar_blocks(grouping, labels, weights):
    """labels/weights/mask → per-bucket [E_b, cap_b] blocks."""
    lab_blocks, wt_blocks, mask_blocks = [], [], []
    for b, (cap, ne) in enumerate(zip(grouping.capacities,
                                      grouping.n_entities)):
        sel = np.where(grouping.example_bucket == b)[0]
        rows = grouping.example_row[sel]
        cols = grouping.example_col[sel]
        lb = np.zeros((ne, cap), np.float32)
        wb = np.zeros((ne, cap), np.float32)
        mb = np.zeros((ne, cap), np.float32)
        lb[rows, cols] = labels[sel]
        wb[rows, cols] = weights[sel]
        mb[rows, cols] = 1.0
        lab_blocks.append(jnp.asarray(lb))
        wt_blocks.append(jnp.asarray(wb))
        mask_blocks.append(jnp.asarray(mb))
    return lab_blocks, wt_blocks, mask_blocks


def _index_maps(grouping):
    ex_idx, row_idx, col_idx = [], [], []
    for b in range(len(grouping.capacities)):
        sel = np.where(grouping.example_bucket == b)[0]
        ex_idx.append(jnp.asarray(sel.astype(np.int32)))
        row_idx.append(jnp.asarray(grouping.example_row[sel].astype(np.int32)))
        col_idx.append(jnp.asarray(grouping.example_col[sel].astype(np.int32)))
    return ex_idx, row_idx, col_idx


def build_random_effect_coordinate_sparse(
    name: str,
    dataset: GameDataset,
    feature_shard: str,
    objective: GLMObjective,
    global_dim: int,
    config: OptimizerConfig | None = None,
    optimizer=None,
    bucket_base: int = 4,
) -> RandomEffectCoordinate:
    """Sparse-shard variant: features arrive as per-example (col_ids,
    values) rows in a wide global space; each entity's problem is solved
    in its observed-feature subspace (reference
    ``LinearSubspaceProjector`` path, SURVEY §2.4)."""
    from photon_ml_tpu.game.projector import build_subspace_projection
    from photon_ml_tpu.optim.base import OptimizerType

    rows = dataset.features[feature_shard]
    entity_ids = dataset.entity_ids[name]
    grouping = group_by_entity(entity_ids, bucket_base=bucket_base)

    projection, x_blocks_np = build_subspace_projection(
        grouping, rows, global_dim
    )
    labels = dataset.labels.astype(np.float32)
    weights = dataset.weight_array()
    lab_blocks, wt_blocks, mask_blocks = _scalar_blocks(
        grouping, labels, weights
    )
    ex_idx, row_idx, col_idx = _index_maps(grouping)

    problem = OptimizationProblem(
        objective=objective,
        optimizer=optimizer or OptimizerType.LBFGS,
        config=config or OptimizerConfig(),
    )
    return RandomEffectCoordinate(
        name=name,
        grouping=grouping,
        x_blocks=[jnp.asarray(xb) for xb in x_blocks_np],
        label_blocks=lab_blocks,
        weight_blocks=wt_blocks,
        mask_blocks=mask_blocks,
        ex_idx=ex_idx,
        row_idx=row_idx,
        col_idx=col_idx,
        n_examples=len(labels),
        problem=problem,
        projection=projection,
    )
