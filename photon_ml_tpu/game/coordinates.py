"""GAME coordinates: the per-coordinate train/score units.

Reference counterparts: ``Coordinate``, ``FixedEffectCoordinate``,
``RandomEffectCoordinate`` (photon-api
``com.linkedin.photon.ml.algorithm`` [expected paths, mount unavailable —
see SURVEY.md §2.3]).

The reference contract carries over exactly — ``train(offsets, warm
start) → model`` and ``score(model) → per-example scores`` — but the
execution model flips:

- ``FixedEffectCoordinate``: the reference runs
  ``DistributedOptimizationProblem`` (broadcast + treeAggregate per
  L-BFGS iteration).  Here the SAME ``OptimizationProblem`` runs over
  either a local batch or a mesh-sharded batch wrapped in
  ``DistributedGLMObjective`` — one jitted solve either way.
- ``RandomEffectCoordinate``: the reference's
  ``RDD[(REId, LocalDataset)].mapValues(solve per entity)`` — thousands
  of sequential JVM L-BFGS loops per partition — becomes ONE
  ``vmap``ped solve per size bucket: every entity in a bucket optimizes
  simultaneously on the VPU/MXU, each converging by its own criterion
  (masked while_loop).  Entity blocks are built once by the host ETL
  (``EntityGrouping``); per-CD-iteration offsets move between example
  space and block space by static-index gather/scatter on device.

Scores are raw dot products x·w (no offset, no link), summable across
coordinates — the reference's ``CoordinateDataScores`` convention.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import Batch, DenseBatch
from photon_ml_tpu.game.dataset import (
    EntityGrouping,
    GameDataset,
    group_by_entity,
)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim import OptimizationProblem, OptimizerConfig
from photon_ml_tpu.optim.lbfgs import lbfgs_solve
from photon_ml_tpu.optim.tron import tron_solve
from photon_ml_tpu.parallel.distributed_objective import DistributedGLMObjective

Array = jax.Array


# ---------------------------------------------------------------------------
# Module-level jitted solves.  Everything data-like (batch, objective with
# its traced reg/norm arrays, offsets, warm starts) is a TRACED argument;
# only the optimizer type and config are static.  Two consequences, both
# verdict findings from round 2:
#   * grid/tuning points that differ only in reg weight λ hit the SAME
#     compiled executable (λ lives in RegularizationContext leaves);
#   * the batch is never closed over as a jit constant — a constant batch
#     would be baked into the HLO and shipped through the compiler, which
#     at production sizes means gigabytes through the compile path.
# ---------------------------------------------------------------------------


def _pad_offsets(offsets: Array, n_padded: int) -> Array:
    """Example-space offsets [n] → batch row space [n_padded] (padding
    rows are masked, so zeros are exact)."""
    if offsets.shape[0] == n_padded:
        return offsets
    return jnp.pad(offsets, (0, n_padded - offsets.shape[0]))


def _apply_training_view(batch, offsets: Array, train_idx, train_weights):
    """Offsets installed; optionally the down-sampled row view."""
    offsets = _pad_offsets(offsets, batch.n_padded)
    if train_idx is None:
        return batch.replace(offsets=offsets)
    from photon_ml_tpu.data.batch import SparseBatch

    base = batch
    if isinstance(base, SparseBatch) and (
        base.colmajor is not None or base.grr is not None
    ):
        # The transposed-ELL / GRR plans index *all* rows; subsetting
        # their layout arrays by example ids would silently corrupt
        # X^T r.  Drop them — the subsetted batch falls back to the ELL
        # paths (down-sampled solves are smaller anyway).
        base = base.replace(colmajor=None, grr=None)
    sub = jax.tree.map(lambda a: a[train_idx], base)
    return sub.replace(offsets=offsets[train_idx], weights=train_weights)


def _jit_solve(fn, donate_argnums):
    """(plain, warm-start-donating) jit pair for a solve entry.

    Donation (SURVEY §5.2 rebuild guidance): the warm-start
    coefficients are the one solve input shaped like a solve output, so
    XLA can write the new coefficients into the old buffer — for
    random effects that is the full [E_b, cap, p]-adjacent coefficient
    blocks, the dominant recurring allocation of a CD sweep.
    Coordinate descent rebinds ``coefs[name]`` to the result
    immediately after each call, so the donated buffer is dead there;
    direct ``train()`` callers (tests, notebooks) may reuse their
    arrays, so the plain variant stays the default — donation is
    opt-in via ``donate_warm_start``.
    """
    return (jax.jit(fn, static_argnums=(0, 1, 2)),
            jax.jit(fn, static_argnums=(0, 1, 2),
                    donate_argnums=donate_argnums))


def _fixed_train_local_impl(optimizer, config, has_l1, objective, batch,
                            offsets, train_idx, train_weights, w0):
    problem = OptimizationProblem(
        objective=objective, optimizer=optimizer, config=config
    )
    view = _apply_training_view(batch, offsets, train_idx, train_weights)
    return problem.run(view, w0, has_l1=has_l1)


_fixed_train_local, _fixed_train_local_donating = _jit_solve(
    _fixed_train_local_impl, donate_argnums=(8,))  # w0


def _fixed_train_distributed_impl(optimizer, config, has_l1, dist_obj, batch,
                                  offsets, train_idx, train_weights, w0):
    from photon_ml_tpu.optim.base import OptimizerType

    view = _apply_training_view(batch, offsets, train_idx, train_weights)
    vg = lambda w: dist_obj.value_and_gradient(w, view)
    if optimizer == OptimizerType.TRON:
        if has_l1:
            raise ValueError(
                "TRON requires a smooth objective; use LBFGS (OWL-QN) "
                "for L1/elastic-net problems"
            )
        hvp = lambda w, v: dist_obj.hessian_vector(w, v, view)
        return tron_solve(vg, hvp, w0, config)
    problem = OptimizationProblem(
        objective=dist_obj.objective, optimizer=optimizer, config=config
    )
    l1 = problem._l1_vector(w0.shape[-1]) if has_l1 else None
    return lbfgs_solve(vg, w0, config, l1_weight=l1)


_fixed_train_distributed, _fixed_train_distributed_donating = _jit_solve(
    _fixed_train_distributed_impl, donate_argnums=(8,))  # w0


def _lane_vg(objective, view):
    """Per-lane smooth objective for the swept solvers: the lane's L2
    weight rides as the lane context (a traced [L] leaf row), so one
    compiled program covers any λ grid."""
    def vg(w, l2):
        obj = objective.replace(reg=objective.reg.replace(l2_weight=l2))
        return obj.value_and_gradient(w, view)
    return vg


@partial(jax.jit, static_argnums=(0, 1))
def _fixed_train_swept(config, use_map, objective, batch, offsets,
                       train_idx, train_weights, W0, l2s, l1v):
    """Batched λ-sweep fixed-effect solve: W0 [L, d] lanes against ONE
    shared training view — the whole regularization grid in a single
    masked-lane program (``optim.lbfgs.lbfgs_solve_swept``).
    ``use_map`` (static) lane-loops via ``lax.map`` when the batch
    carries a GRR plan (the Pallas kernel has no batching rule)."""
    from photon_ml_tpu.optim.lbfgs import lbfgs_solve_swept

    view = _apply_training_view(batch, offsets, train_idx, train_weights)
    return lbfgs_solve_swept(_lane_vg(objective, view), W0, l2s, config,
                             l1_weights=l1v, use_map=use_map)


@partial(jax.jit, static_argnums=(0,))
def _fixed_train_swept_distributed(config, dist_obj, batch, offsets,
                                   train_idx, train_weights, W0, l2s, l1v):
    """Mesh variant of the swept solve: lanes lax.map-loop around the
    shard_mapped objective (no batching rule through shard_map); the
    sharded batch stays resident across every lane."""
    from photon_ml_tpu.optim.lbfgs import lbfgs_solve_swept

    view = _apply_training_view(batch, offsets, train_idx, train_weights)

    def vg(w, l2):
        obj = dist_obj.objective
        o = dist_obj.replace(objective=obj.replace(
            reg=obj.reg.replace(l2_weight=l2)))
        return o.value_and_gradient(w, view)

    return lbfgs_solve_swept(vg, W0, l2s, config, l1_weights=l1v,
                             use_map=True)


@jax.jit
def _score_batch(batch, w: Array) -> Array:
    return batch.x_dot(w)


@jax.jit
def _score_batch_distributed(dist_obj, batch, w: Array) -> Array:
    """Sharded scoring: per-shard layouts (GRR plan / colmajor) index
    only their device's rows, so X·w must run under shard_map.  Module
    -level jit so per-CD-iteration scoring hits the compile cache."""
    return dist_obj.x_dot(w, batch)


def _re_block_batch(blocks, b: int, offsets: Array) -> DenseBatch:
    """Bucket b's entity blocks as one vmappable DenseBatch, with
    per-example offsets scattered into block space."""
    (x_blocks, label_blocks, weight_blocks, mask_blocks,
     ex_idx, row_idx, col_idx) = blocks
    off_blk = jnp.zeros_like(label_blocks[b]).at[
        row_idx[b], col_idx[b]
    ].set(offsets[ex_idx[b]])
    return DenseBatch(
        x=x_blocks[b], labels=label_blocks[b], weights=weight_blocks[b],
        offsets=off_blk, mask=mask_blocks[b],
    )


def _re_train_impl(optimizer, config, has_l1, objective, blocks,
                   offsets: Array, w0s: list[Array]):
    problem = OptimizationProblem(
        objective=objective, optimizer=optimizer, config=config
    )
    run = partial(problem.run, has_l1=has_l1)
    return [
        jax.vmap(run)(_re_block_batch(blocks, b, offsets), w0s[b])
        for b in range(len(blocks[0]))
    ]


_re_train, _re_train_donating = _jit_solve(
    _re_train_impl, donate_argnums=(6,))  # w0s blocks


@partial(jax.jit, static_argnums=0)
def _re_score(n_examples: int, x_blocks, ex_idx, row_idx, col_idx,
              coefficient_blocks) -> Array:
    scores = jnp.zeros((n_examples,), jnp.float32)
    for b, w_b in enumerate(coefficient_blocks):
        blk_scores = jnp.einsum("ecp,ep->ec", x_blocks[b], w_b)
        scores = scores.at[ex_idx[b]].set(
            blk_scores[row_idx[b], col_idx[b]]
        )
    return scores


@jax.jit
def _re_variances(objective, blocks, coefficient_blocks, offsets: Array):
    from photon_ml_tpu.optim.variance import simple_variances

    return [
        jax.vmap(
            lambda w, bb: simple_variances(objective, w, bb)
        )(w_b, _re_block_batch(blocks, b, offsets))
        for b, w_b in enumerate(coefficient_blocks)
    ]


class Coordinate:
    """train/score contract (reference ``Coordinate`` abstraction)."""

    name: str

    def initial_coefficients(self):
        raise NotImplementedError

    def train(self, offsets: Array, warm_start):
        """offsets [n] (residual scores from other coordinates) → (model
        coefficients, optimizer diagnostics)."""
        raise NotImplementedError

    def score(self, coefficients) -> Array:
        """coefficients → per-example scores [n]."""
        raise NotImplementedError


@dataclasses.dataclass(eq=False)
class FixedEffectCoordinate(Coordinate):
    """Global solve over the full batch (reference
    ``FixedEffectCoordinate`` + ``DistributedOptimizationProblem``)."""

    name: str
    batch: Batch                      # full batch (scoring); local or sharded
    problem: OptimizationProblem
    distributed: DistributedGLMObjective | None = None  # set if sharded
    # Down-sampled training view (reference DownSampler, SURVEY §2.4):
    # train on batch rows ``train_idx`` with ``train_weights``; score all.
    train_idx: Array | None = None
    train_weights: Array | None = None
    # Real example count when the batch rows were padded (mesh sharding
    # pads n to a multiple of the device count); scores are sliced back
    # to example space so they stay summable with other coordinates'.
    n_examples: int | None = None

    def initial_coefficients(self) -> Array:
        return jnp.zeros((self.batch.dim,), jnp.float32)

    def _training_batch(self, offsets: Array) -> Batch:
        return _apply_training_view(self.batch, offsets, self.train_idx,
                                    self.train_weights)

    def train(self, offsets: Array, warm_start: Array | None = None,
              donate_warm_start: bool = False):
        w0 = self.initial_coefficients() if warm_start is None else warm_start
        has_l1 = self.problem.has_l1()
        if self.distributed is None:
            fn = (_fixed_train_local_donating if donate_warm_start
                  else _fixed_train_local)
            res = fn(
                self.problem.optimizer, self.problem.config, has_l1,
                self.problem.objective, self.batch, offsets,
                self.train_idx, self.train_weights, w0,
            )
        else:
            fn = (_fixed_train_distributed_donating if donate_warm_start
                  else _fixed_train_distributed)
            res = fn(
                self.problem.optimizer, self.problem.config, has_l1,
                self.distributed, self.batch, offsets,
                self.train_idx, self.train_weights, w0,
            )
        return res.w, res

    def train_swept(self, offsets: Array, reg, warm_start=None):
        """Train the whole λ grid as ONE batched solve: L stacked
        coefficient lanes share every objective evaluation against the
        same training view (one data stream amortized across the grid).

        Args:
          offsets: [n] shared residual scores (the λ sweep varies only
            regularization, so all lanes see the same offsets).
          reg: ``ops.regularization.SweptRegularization`` — per-lane
            (l1, l2) weight splits, one lane per grid point.
          warm_start: optional [L, dim] stacked starting points
            (continuation across tuning rounds).

        Returns (W [L, dim], batched OptimizationResult).
        """
        from photon_ml_tpu.data.batch import SparseBatch
        from photon_ml_tpu.optim.base import OptimizerType

        if self.problem.optimizer == OptimizerType.TRON:
            raise ValueError(
                "train_swept supports LBFGS/OWL-QN lanes only (the λ "
                "sweep is the L-BFGS grid workload; fit TRON "
                "coordinates per grid point)")
        L = reg.n_lanes
        dim = self.batch.dim
        W0 = (jnp.zeros((L, dim), jnp.float32) if warm_start is None
              else jnp.asarray(warm_start, jnp.float32))
        l1v = (reg.l1_vectors(dim, self.problem.objective.reg.reg_mask)
               if reg.has_l1() else None)
        if self.distributed is not None:
            res = _fixed_train_swept_distributed(
                self.problem.config, self.distributed, self.batch,
                offsets, self.train_idx, self.train_weights, W0,
                reg.l2_weights, l1v,
            )
        else:
            # GRR-plan batches lane-loop (lax.map): the Mosaic kernel
            # has no batching rule; the plan stays HBM-resident across
            # lanes either way.
            use_map = (isinstance(self.batch, SparseBatch)
                       and self.batch.grr is not None)
            res = _fixed_train_swept(
                self.problem.config, use_map, self.problem.objective,
                self.batch, offsets, self.train_idx, self.train_weights,
                W0, reg.l2_weights, l1v,
            )
        return res.w, res

    def score(self, coefficients: Array) -> Array:
        if self.distributed is not None:
            scores = _score_batch_distributed(
                self.distributed, self.batch, coefficients)
        else:
            scores = _score_batch(self.batch, coefficients)
        if (self.n_examples is not None
                and self.n_examples != self.batch.n_padded):
            scores = scores[: self.n_examples]
        return scores

    def as_model(self, coefficients: Array) -> FixedEffectModel:
        return FixedEffectModel(
            coefficients=Coefficients(means=coefficients),
            feature_shard=self.name,
        )

    def compute_variances(self, coefficients: Array, offsets: Array,
                          variance_type) -> Array | None:
        """Coefficient variances at the optimum over the training view
        (reference VarianceComputationType pipeline, SURVEY §2.1).

        Under mesh sharding the distributed objective must aggregate
        the Hessian quantities (its colmajor row indices are
        shard-local, and the diagonal is a cross-shard sum)."""
        from photon_ml_tpu.optim.variance import compute_variances

        obj = self.distributed or self.problem.objective
        return compute_variances(
            obj, coefficients, self._training_batch(offsets), variance_type,
        )


@dataclasses.dataclass(eq=False)
class ChunkedFixedEffectCoordinate(Coordinate):
    """Fixed effect trained by chunk-accumulated streaming — the
    beyond-HBM-residency class (reference: Spark streams splits through
    executors, SURVEY §1 L1/§5.8; see ``data.chunked_batch``).

    Same ``train``/``score`` contract as ``FixedEffectCoordinate``; the
    solve is the host-driven ``optim.streaming.streaming_lbfgs_solve``
    over a ``ChunkedGLMObjective`` (per-chunk device programs, exact
    chunk-accumulated objective).  When the chunked batch is
    disk-spilled (``spill_dir`` — the out-of-core tier), every training
    AND ``_per_example`` scoring sweep runs the async disk→host→device
    prefetch pipeline, ``prefetch_depth`` chunks ahead.  Down-sampling
    views and TRON are not supported on this path (documented config
    error)."""

    name: str
    chunked: "object"                 # data.chunked_batch.ChunkedBatch
    objective: GLMObjective           # reg/prior included (added once)
    optimizer: "object"               # OptimizerType
    config: OptimizerConfig
    max_resident: int = 1
    prefetch_depth: int = 2

    def __post_init__(self):
        from photon_ml_tpu.optim.base import OptimizerType
        from photon_ml_tpu.optim.streaming import ChunkedGLMObjective

        if self.optimizer == OptimizerType.TRON:
            raise ValueError(
                "chunked training supports LBFGS/OWL-QN only (TRON's "
                "inner CG would stream the dataset once per CG step)")
        self._obj = ChunkedGLMObjective(
            self.objective, self.chunked, max_resident=self.max_resident,
            prefetch_depth=self.prefetch_depth)

    @property
    def problem(self) -> OptimizationProblem:
        """Estimator-facing surface parity with FixedEffectCoordinate
        (model export reads ``coord.problem.objective.norm``)."""
        return OptimizationProblem(
            objective=self.objective, optimizer=self.optimizer,
            config=self.config)

    def initial_coefficients(self) -> Array:
        return jnp.zeros((self.chunked.dim,), jnp.float32)

    def _coerce_offsets(self, offsets) -> np.ndarray:
        """Offsets → exactly ``chunked.n`` entries.  Over-long arrays
        are accepted ONLY when the length matches the known padding
        grid (the chunk grid, which already folds in the mesh's device
        rounding) — anything else is a caller bug that silent
        truncation would turn into wrong training data (advisor
        finding); under-long arrays fail in ``set_offsets``."""
        off = np.asarray(offsets, np.float32)
        n = self.chunked.n
        if off.shape[0] == n:
            return off
        grid = self.chunked.n_chunks * self.chunked.chunk_rows
        if off.shape[0] == grid:
            return off[:n]
        if off.shape[0] > n:
            raise ValueError(
                f"offsets length {off.shape[0]} exceeds n {n} and does "
                f"not match the chunk padding grid {grid}")
        return off

    def train(self, offsets: Array, warm_start: Array | None = None,
              donate_warm_start: bool = False):
        from photon_ml_tpu.optim.streaming import streaming_lbfgs_solve

        self.chunked.set_offsets(self._coerce_offsets(offsets))
        self._obj.invalidate()
        w0 = (self.initial_coefficients() if warm_start is None
              else warm_start)
        problem = self.problem
        l1 = (problem._l1_vector(self.chunked.dim) if problem.has_l1()
              else None)
        res = streaming_lbfgs_solve(
            self._obj.value_and_gradient, w0, self.config, l1_weight=l1,
            value_fn=self._obj.value)
        return res.w, res

    def train_swept(self, offsets: Array, reg, warm_start=None):
        """Batched λ-sweep on the chunked path: ONE double-buffered
        chunk sweep per objective evaluation feeds all L lanes
        (``ChunkedGLMObjective.value_and_gradient_swept``) — the grid's
        data passes per solver iteration drop from L to ~1.

        Same contract as ``FixedEffectCoordinate.train_swept``.
        """
        from photon_ml_tpu.optim.streaming import (
            streaming_lbfgs_solve_swept,
        )

        self.chunked.set_offsets(self._coerce_offsets(offsets))
        self._obj.invalidate()
        L = reg.n_lanes
        W0 = (jnp.zeros((L, self.chunked.dim), jnp.float32)
              if warm_start is None
              else jnp.asarray(warm_start, jnp.float32))
        l1v = (reg.l1_vectors(self.chunked.dim,
                              self.objective.reg.reg_mask)
               if reg.has_l1() else None)
        res = streaming_lbfgs_solve_swept(
            lambda W: self._obj.value_and_gradient_swept(W, reg),
            lambda W: self._obj.value_swept(W, reg),
            W0, self.config, l1_weights=l1v,
        )
        return res.w, res

    def score(self, coefficients: Array) -> Array:
        """Raw X·w per example — offset-free, the same
        ``CoordinateDataScores`` convention as the resident path."""
        return jnp.asarray(self._obj.x_dot(coefficients))

    def as_model(self, coefficients: Array) -> FixedEffectModel:
        return FixedEffectModel(
            coefficients=Coefficients(means=coefficients),
            feature_shard=self.name,
        )

    def compute_variances(self, coefficients: Array, offsets: Array,
                          variance_type) -> Array | None:
        from photon_ml_tpu.optim.variance import VarianceComputationType

        if variance_type == VarianceComputationType.NONE:
            return None
        if variance_type == VarianceComputationType.FULL:
            raise ValueError(
                "FULL variances materialize a [d, d] Hessian — not "
                "supported on the chunked path; use SIMPLE")
        self.chunked.set_offsets(self._coerce_offsets(offsets))
        self._obj.invalidate()
        diag = self._obj.hessian_diagonal(coefficients)
        return 1.0 / jnp.maximum(diag, 1e-12)


@dataclasses.dataclass(eq=False)
class RandomEffectCoordinate(Coordinate):
    """Entity-sharded solves, one vmapped batch per size bucket
    (reference ``RandomEffectCoordinate``)."""

    name: str
    grouping: EntityGrouping
    # Per-bucket device arrays (built by ``build_random_effect_coordinate``):
    # widths may differ per bucket when a subspace projection is applied.
    x_blocks: list[Array]        # [E_b, cap_b, p_b]
    label_blocks: list[Array]    # [E_b, cap_b]
    weight_blocks: list[Array]   # [E_b, cap_b]
    mask_blocks: list[Array]     # [E_b, cap_b]
    # Static per-bucket example-index maps (example space ↔ block space):
    ex_idx: list[Array]          # [n_b] example positions in this bucket
    row_idx: list[Array]         # [n_b] entity slot
    col_idx: list[Array]         # [n_b] within-entity position
    n_examples: int
    problem: OptimizationProblem
    # Set when features were subspace-projected (sparse global shard):
    projection: "SubspaceProjection | None" = None

    def initial_coefficients(self) -> list[Array]:
        return [
            jnp.zeros((blk.shape[0], blk.shape[-1]), jnp.float32)
            for blk in self.x_blocks
        ]

    def _blocks(self):
        return (self.x_blocks, self.label_blocks, self.weight_blocks,
                self.mask_blocks, self.ex_idx, self.row_idx, self.col_idx)

    def train(self, offsets: Array, warm_start=None,
              donate_warm_start: bool = False):
        w0s = self.initial_coefficients() if warm_start is None else warm_start
        fn = _re_train_donating if donate_warm_start else _re_train
        results = fn(
            self.problem.optimizer, self.problem.config,
            self.problem.has_l1(), self.problem.objective,
            self._blocks(), offsets, w0s,
        )
        return [r.w for r in results], results

    def score(self, coefficient_blocks: list[Array]) -> Array:
        """Block-space scoring: x·w per entity block, gathered back to
        example order (works for projected and unprojected widths)."""
        return _re_score(self.n_examples, self.x_blocks, self.ex_idx,
                         self.row_idx, self.col_idx, coefficient_blocks)

    def as_model(self, coefficient_blocks: list[Array]) -> RandomEffectModel:
        return RandomEffectModel(
            coefficient_blocks=coefficient_blocks,
            grouping=self.grouping,
            feature_shard=self.name,
            projection=self.projection,
        )

    def compute_variance_blocks(
        self, coefficient_blocks: list[Array], offsets: Array
    ) -> list[Array]:
        """SIMPLE per-entity variances (1/diag H), vmapped per bucket —
        the per-entity arm of the reference's variance pipeline."""
        return _re_variances(self.problem.objective, self._blocks(),
                             coefficient_blocks, offsets)


def _shard_re_blocks(coord_kwargs: dict, mesh) -> dict:
    """Entity-shard a coordinate's bucket blocks on the mesh
    (reference parallelism strategy #2 — per-entity solves are
    communication-free, so the leading entity axis shards cleanly)."""
    if mesh is None:
        return coord_kwargs
    from photon_ml_tpu.parallel.mesh import shard_entity_blocks

    for key in ("x_blocks", "label_blocks", "weight_blocks", "mask_blocks"):
        coord_kwargs[key] = shard_entity_blocks(coord_kwargs[key], mesh)
    return coord_kwargs


def build_random_effect_coordinate(
    name: str,
    dataset: GameDataset,
    feature_shard: str,
    objective: GLMObjective,
    config: OptimizerConfig | None = None,
    optimizer=None,
    bucket_base: int = 4,
    mesh=None,
) -> RandomEffectCoordinate:
    """Host ETL → device blocks: the reference's partition-and-group
    pipeline (``RandomEffectDataset.apply``) as one deterministic pass."""
    from photon_ml_tpu.optim.base import OptimizerType

    x = np.asarray(dataset.features[feature_shard], np.float32)
    entity_ids = dataset.entity_ids[name]
    grouping = group_by_entity(entity_ids, bucket_base=bucket_base)

    labels = dataset.labels.astype(np.float32)
    weights = dataset.weight_array()

    lab_blocks, wt_blocks, mask_blocks = _scalar_blocks(
        grouping, labels, weights
    )
    ex_idx, row_idx, col_idx = _index_maps(grouping)

    x_blocks = []
    for b, (cap, ne) in enumerate(zip(grouping.capacities,
                                      grouping.n_entities)):
        sel = np.where(grouping.example_bucket == b)[0]
        xb = np.zeros((ne, cap, x.shape[1]), np.float32)
        xb[grouping.example_row[sel], grouping.example_col[sel]] = x[sel]
        x_blocks.append(jnp.asarray(xb))

    blocks = _shard_re_blocks(
        dict(x_blocks=x_blocks, label_blocks=lab_blocks,
             weight_blocks=wt_blocks, mask_blocks=mask_blocks),
        mesh,
    )
    x_blocks = blocks["x_blocks"]
    lab_blocks = blocks["label_blocks"]
    wt_blocks = blocks["weight_blocks"]
    mask_blocks = blocks["mask_blocks"]

    problem = OptimizationProblem(
        objective=objective,
        optimizer=optimizer or OptimizerType.LBFGS,
        config=config or OptimizerConfig(),
    )
    return RandomEffectCoordinate(
        name=name,
        grouping=grouping,
        x_blocks=x_blocks,
        label_blocks=lab_blocks,
        weight_blocks=wt_blocks,
        mask_blocks=mask_blocks,
        ex_idx=ex_idx,
        row_idx=row_idx,
        col_idx=col_idx,
        n_examples=len(labels),
        problem=problem,
    )


def _scalar_blocks(grouping, labels, weights):
    """labels/weights/mask → per-bucket [E_b, cap_b] blocks."""
    lab_blocks, wt_blocks, mask_blocks = [], [], []
    for b, (cap, ne) in enumerate(zip(grouping.capacities,
                                      grouping.n_entities)):
        sel = np.where(grouping.example_bucket == b)[0]
        rows = grouping.example_row[sel]
        cols = grouping.example_col[sel]
        lb = np.zeros((ne, cap), np.float32)
        wb = np.zeros((ne, cap), np.float32)
        mb = np.zeros((ne, cap), np.float32)
        lb[rows, cols] = labels[sel]
        wb[rows, cols] = weights[sel]
        mb[rows, cols] = 1.0
        lab_blocks.append(jnp.asarray(lb))
        wt_blocks.append(jnp.asarray(wb))
        mask_blocks.append(jnp.asarray(mb))
    return lab_blocks, wt_blocks, mask_blocks


def _index_maps(grouping):
    ex_idx, row_idx, col_idx = [], [], []
    for b in range(len(grouping.capacities)):
        sel = np.where(grouping.example_bucket == b)[0]
        ex_idx.append(jnp.asarray(sel.astype(np.int32)))
        row_idx.append(jnp.asarray(grouping.example_row[sel].astype(np.int32)))
        col_idx.append(jnp.asarray(grouping.example_col[sel].astype(np.int32)))
    return ex_idx, row_idx, col_idx


def build_random_effect_coordinate_sparse(
    name: str,
    dataset: GameDataset,
    feature_shard: str,
    objective: GLMObjective,
    global_dim: int,
    config: OptimizerConfig | None = None,
    optimizer=None,
    bucket_base: int = 4,
    mesh=None,
) -> RandomEffectCoordinate:
    """Sparse-shard variant: features arrive as per-example (col_ids,
    values) rows in a wide global space; each entity's problem is solved
    in its observed-feature subspace (reference
    ``LinearSubspaceProjector`` path, SURVEY §2.4)."""
    from photon_ml_tpu.game.projector import build_subspace_projection
    from photon_ml_tpu.optim.base import OptimizerType

    rows = dataset.features[feature_shard]
    entity_ids = dataset.entity_ids[name]
    grouping = group_by_entity(entity_ids, bucket_base=bucket_base)

    projection, x_blocks_np = build_subspace_projection(
        grouping, rows, global_dim
    )
    labels = dataset.labels.astype(np.float32)
    weights = dataset.weight_array()
    lab_blocks, wt_blocks, mask_blocks = _scalar_blocks(
        grouping, labels, weights
    )
    ex_idx, row_idx, col_idx = _index_maps(grouping)

    problem = OptimizationProblem(
        objective=objective,
        optimizer=optimizer or OptimizerType.LBFGS,
        config=config or OptimizerConfig(),
    )
    blocks = _shard_re_blocks(
        dict(x_blocks=[jnp.asarray(xb) for xb in x_blocks_np],
             label_blocks=lab_blocks, weight_blocks=wt_blocks,
             mask_blocks=mask_blocks),
        mesh,
    )
    lab_blocks = blocks["label_blocks"]
    wt_blocks = blocks["weight_blocks"]
    mask_blocks = blocks["mask_blocks"]
    return RandomEffectCoordinate(
        name=name,
        grouping=grouping,
        x_blocks=blocks["x_blocks"],
        label_blocks=lab_blocks,
        weight_blocks=wt_blocks,
        mask_blocks=mask_blocks,
        ex_idx=ex_idx,
        row_idx=row_idx,
        col_idx=col_idx,
        n_examples=len(labels),
        problem=problem,
        projection=projection,
    )
