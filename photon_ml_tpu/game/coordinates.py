"""GAME coordinates: the per-coordinate train/score units.

Reference counterparts: ``Coordinate``, ``FixedEffectCoordinate``,
``RandomEffectCoordinate`` (photon-api
``com.linkedin.photon.ml.algorithm`` [expected paths, mount unavailable —
see SURVEY.md §2.3]).

The reference contract carries over exactly — ``train(offsets, warm
start) → model`` and ``score(model) → per-example scores`` — but the
execution model flips:

- ``FixedEffectCoordinate``: the reference runs
  ``DistributedOptimizationProblem`` (broadcast + treeAggregate per
  L-BFGS iteration).  Here the SAME ``OptimizationProblem`` runs over
  either a local batch or a mesh-sharded batch wrapped in
  ``DistributedGLMObjective`` — one jitted solve either way.
- ``RandomEffectCoordinate``: the reference's
  ``RDD[(REId, LocalDataset)].mapValues(solve per entity)`` — thousands
  of sequential JVM L-BFGS loops per partition — becomes ONE
  ``vmap``ped solve per size bucket: every entity in a bucket optimizes
  simultaneously on the VPU/MXU, each converging by its own criterion
  (masked while_loop).  Entity blocks are built once by the host ETL
  (``EntityGrouping``); per-CD-iteration offsets move between example
  space and block space by static-index gather/scatter on device.

Scores are raw dot products x·w (no offset, no link), summable across
coordinates — the reference's ``CoordinateDataScores`` convention.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import Batch, DenseBatch
from photon_ml_tpu.game.dataset import (
    EntityGrouping,
    GameDataset,
    group_by_entity,
)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim import OptimizationProblem, OptimizerConfig
from photon_ml_tpu.optim.lbfgs import lbfgs_solve
from photon_ml_tpu.optim.tron import tron_solve
from photon_ml_tpu.parallel.distributed_objective import DistributedGLMObjective

Array = jax.Array


class Coordinate:
    """train/score contract (reference ``Coordinate`` abstraction)."""

    name: str

    def initial_coefficients(self):
        raise NotImplementedError

    def train(self, offsets: Array, warm_start):
        """offsets [n] (residual scores from other coordinates) → (model
        coefficients, optimizer diagnostics)."""
        raise NotImplementedError

    def score(self, coefficients) -> Array:
        """coefficients → per-example scores [n]."""
        raise NotImplementedError


@dataclasses.dataclass(eq=False)
class FixedEffectCoordinate(Coordinate):
    """Global solve over the full batch (reference
    ``FixedEffectCoordinate`` + ``DistributedOptimizationProblem``)."""

    name: str
    batch: Batch                      # local or mesh-sharded
    problem: OptimizationProblem
    distributed: DistributedGLMObjective | None = None  # set if sharded

    def initial_coefficients(self) -> Array:
        return jnp.zeros((self.batch.dim,), jnp.float32)

    @partial(jax.jit, static_argnums=0)
    def _train_jit(self, offsets: Array, w0: Array):
        batch = self.batch.replace(offsets=offsets)
        if self.distributed is None:
            return self.problem.run(batch, w0)
        # Same solver over the psum-reduced objective.
        obj = self.distributed
        vg = lambda w: obj.value_and_gradient(w, batch)
        from photon_ml_tpu.optim.base import OptimizerType

        if self.problem.optimizer == OptimizerType.TRON:
            hvp = lambda w, v: obj.hessian_vector(w, v, batch)
            return tron_solve(vg, hvp, w0, self.problem.config)
        return lbfgs_solve(
            vg, w0, self.problem.config,
            l1_weight=self.problem._l1_vector(w0.shape[-1]),
        )

    def train(self, offsets: Array, warm_start: Array | None = None):
        w0 = self.initial_coefficients() if warm_start is None else warm_start
        res = self._train_jit(offsets, w0)
        return res.w, res

    @partial(jax.jit, static_argnums=0)
    def score(self, coefficients: Array) -> Array:
        return self.batch.x_dot(coefficients)

    def as_model(self, coefficients: Array) -> FixedEffectModel:
        return FixedEffectModel(
            coefficients=Coefficients(means=coefficients),
            feature_shard=self.name,
        )


@dataclasses.dataclass(eq=False)
class RandomEffectCoordinate(Coordinate):
    """Entity-sharded solves, one vmapped batch per size bucket
    (reference ``RandomEffectCoordinate``)."""

    name: str
    grouping: EntityGrouping
    # Per-bucket device arrays (built by ``build_random_effect_coordinate``):
    x_blocks: list[Array]        # [E_b, cap_b, d_re]
    label_blocks: list[Array]    # [E_b, cap_b]
    weight_blocks: list[Array]   # [E_b, cap_b]
    mask_blocks: list[Array]     # [E_b, cap_b]
    # Static per-bucket example-index maps (example space ↔ block space):
    ex_idx: list[Array]          # [n_b] example positions in this bucket
    row_idx: list[Array]         # [n_b] entity slot
    col_idx: list[Array]         # [n_b] within-entity position
    # Per-example gather map for scoring:
    x_re: Array                  # [n, d_re] per-example RE features
    example_entity: Array        # [n] global entity index per example
    bucket_global_idx: list[Array]  # per bucket: [E_b] global entity idx
    problem: OptimizationProblem

    @property
    def dim(self) -> int:
        return self.x_blocks[0].shape[-1]

    def initial_coefficients(self) -> list[Array]:
        return [
            jnp.zeros((blk.shape[0], self.dim), jnp.float32)
            for blk in self.x_blocks
        ]

    @partial(jax.jit, static_argnums=0)
    def _train_jit(self, offsets: Array, w0s: list[Array]):
        outs = []
        for b in range(len(self.x_blocks)):
            off_blk = jnp.zeros_like(self.label_blocks[b]).at[
                self.row_idx[b], self.col_idx[b]
            ].set(offsets[self.ex_idx[b]])
            batch_b = DenseBatch(
                x=self.x_blocks[b],
                labels=self.label_blocks[b],
                weights=self.weight_blocks[b],
                offsets=off_blk,
                mask=self.mask_blocks[b],
            )
            res = jax.vmap(self.problem.run)(batch_b, w0s[b])
            outs.append(res)
        return outs

    def train(self, offsets: Array, warm_start=None):
        w0s = self.initial_coefficients() if warm_start is None else warm_start
        results = self._train_jit(offsets, w0s)
        return [r.w for r in results], results

    @partial(jax.jit, static_argnums=0)
    def score(self, coefficient_blocks: list[Array]) -> Array:
        w_all = jnp.zeros((self.grouping.n_total_entities, self.dim),
                          jnp.float32)
        for b, blk in enumerate(coefficient_blocks):
            w_all = w_all.at[self.bucket_global_idx[b]].set(blk)
        w_per_example = w_all[self.example_entity]          # [n, d_re]
        return jnp.sum(self.x_re * w_per_example, axis=-1)  # [n]

    def as_model(self, coefficient_blocks: list[Array]) -> RandomEffectModel:
        return RandomEffectModel(
            coefficient_blocks=coefficient_blocks,
            grouping=self.grouping,
            feature_shard=self.name,
        )


def build_random_effect_coordinate(
    name: str,
    dataset: GameDataset,
    feature_shard: str,
    objective: GLMObjective,
    config: OptimizerConfig | None = None,
    optimizer=None,
    bucket_base: int = 4,
) -> RandomEffectCoordinate:
    """Host ETL → device blocks: the reference's partition-and-group
    pipeline (``RandomEffectDataset.apply``) as one deterministic pass."""
    from photon_ml_tpu.optim.base import OptimizerType

    x = np.asarray(dataset.features[feature_shard], np.float32)
    entity_ids = dataset.entity_ids[name]
    grouping = group_by_entity(entity_ids, bucket_base=bucket_base)

    labels = dataset.labels.astype(np.float32)
    weights = dataset.weight_array()

    x_blocks, lab_blocks, wt_blocks, mask_blocks = [], [], [], []
    ex_idx, row_idx, col_idx, bucket_gidx = [], [], [], []
    for b, (cap, ne) in enumerate(zip(grouping.capacities,
                                      grouping.n_entities)):
        sel = np.where(grouping.example_bucket == b)[0]
        rows = grouping.example_row[sel]
        cols = grouping.example_col[sel]
        xb = np.zeros((ne, cap, x.shape[1]), np.float32)
        lb = np.zeros((ne, cap), np.float32)
        wb = np.zeros((ne, cap), np.float32)
        mb = np.zeros((ne, cap), np.float32)
        xb[rows, cols] = x[sel]
        lb[rows, cols] = labels[sel]
        wb[rows, cols] = weights[sel]
        mb[rows, cols] = 1.0
        x_blocks.append(jnp.asarray(xb))
        lab_blocks.append(jnp.asarray(lb))
        wt_blocks.append(jnp.asarray(wb))
        mask_blocks.append(jnp.asarray(mb))
        ex_idx.append(jnp.asarray(sel.astype(np.int32)))
        row_idx.append(jnp.asarray(rows.astype(np.int32)))
        col_idx.append(jnp.asarray(cols.astype(np.int32)))
        bucket_gidx.append(jnp.asarray(
            np.where(grouping.entity_bucket == b)[0].astype(np.int32)
        ))

    # Global entity index per example (unique-id order).
    uniq_pos = {int(e): i for i, e in enumerate(grouping.entity_ids)}
    example_entity = np.asarray(
        [uniq_pos[int(e)] for e in entity_ids], np.int32
    )

    problem = OptimizationProblem(
        objective=objective,
        optimizer=optimizer or OptimizerType.LBFGS,
        config=config or OptimizerConfig(),
    )
    return RandomEffectCoordinate(
        name=name,
        grouping=grouping,
        x_blocks=x_blocks,
        label_blocks=lab_blocks,
        weight_blocks=wt_blocks,
        mask_blocks=mask_blocks,
        ex_idx=ex_idx,
        row_idx=row_idx,
        col_idx=col_idx,
        x_re=jnp.asarray(x),
        example_entity=jnp.asarray(example_entity),
        bucket_global_idx=bucket_gidx,
        problem=problem,
    )
