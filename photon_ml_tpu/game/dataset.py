"""GAME datasets: feature-sharded examples + entity-grouped blocks.

Reference counterparts: ``GameDatum``, ``FixedEffectDataset``,
``RandomEffectDataset``, ``LocalDataset``,
``RandomEffectDatasetPartitioner`` (photon-api
``com.linkedin.photon.ml.data`` [expected paths, mount unavailable — see
SURVEY.md §2.4]).

Design translation (SURVEY §7 stage 6):

- The reference's ``RDD[GameDatum]`` becomes a host-side ``GameDataset``:
  per-shard feature arrays + per-coordinate entity ids, all indexed by
  example position (the ``UniqueSampleId`` is literally the array index).
- The reference's shuffle (``partitionBy(RandomEffectDatasetPartitioner)``
  + ``groupBy(REId)``) becomes a ONE-TIME host ETL
  (``group_by_entity``): a stable sort by entity id yielding a
  permutation + per-example (block_row, block_col) coordinates into
  padded per-entity blocks.  After this, training-time regrouping is
  pure static-shape gather/scatter on device — no per-step shuffle.
- Power-law entity skew (the rebuild's hardest static-shape problem) is
  handled by **size-bucketing**: entities are binned by example count
  into capacity buckets (powers-of-bucket_base), one padded block array
  per bucket, so padding waste is bounded by bucket_base× instead of
  max-entity×.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class EntityGrouping:
    """Host-side grouping of n examples into per-entity padded blocks.

    Entities are ordered by example count (descending) and assigned to
    capacity buckets.  Bucket b holds ``n_entities[b]`` entities with
    capacity ``capacities[b]`` examples each; entity slots within a
    bucket are dense.  Per-example coordinates map example i to
    ``(bucket[i], row[i], col[i])`` — row is the entity's slot in its
    bucket, col the example's position within the entity's block.
    """

    n_examples: int
    # Per-entity (global entity order: unique ids sorted):
    entity_ids: np.ndarray      # [E] original ids (as passed in)
    entity_counts: np.ndarray   # [E] examples per entity
    entity_bucket: np.ndarray   # [E] bucket index per entity
    entity_slot: np.ndarray     # [E] slot within its bucket
    # Per-bucket:
    capacities: list[int]       # examples capacity per entity block
    n_entities: list[int]       # entities per bucket
    # Per-example:
    example_bucket: np.ndarray  # [n]
    example_row: np.ndarray     # [n] entity slot in bucket
    example_col: np.ndarray     # [n] position within entity block
    # [n] global entity index (into entity_ids) per example; None on
    # groupings reloaded from saved models (example maps aren't stored).
    example_entity: np.ndarray | None = None

    @property
    def n_total_entities(self) -> int:
        return len(self.entity_ids)

    def entity_index(self) -> dict:
        """original entity id → (bucket, slot)."""
        return {
            int(e): (int(b), int(s))
            for e, b, s in zip(self.entity_ids, self.entity_bucket,
                               self.entity_slot)
        }

    def join_ids(self, query_ids: np.ndarray) -> np.ndarray:
        """id → global entity index (into ``entity_ids``), −1 for
        unseen — the reference's RDD join as one vectorized
        searchsorted.  ``entity_ids`` must be strictly ascending
        (np.unique output; preserved by model I/O) — checked here
        because a grouping deserialized by any other path would
        otherwise misjoin silently (advisor finding)."""
        ids = np.asarray(self.entity_ids)
        if ids.size > 1 and not bool((np.diff(ids) > 0).all()):
            raise ValueError(
                "EntityGrouping.entity_ids must be strictly ascending "
                "and unique for join_ids (np.unique order)")
        return sorted_id_join(ids, query_ids)

    def entity_row_map(self) -> np.ndarray:
        """Dense (bucket, slot) → global entity index map
        [n_buckets, max_entities_per_bucket], −1 for empty slots."""
        n_buckets = len(self.capacities)
        max_ne = max(self.n_entities) if self.n_entities else 1
        out = np.full((n_buckets, max(max_ne, 1)), -1, np.int64)
        out[self.entity_bucket, self.entity_slot] = np.arange(
            self.n_total_entities)
        return out


def sorted_key_join(
    keys: np.ndarray, vals: np.ndarray, query_keys: np.ndarray,
    presorted: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Value of each query key under the (unique-keyed) ``keys → vals``
    map: returns ``(values, hit)`` where ``hit[i]`` is False (and the
    value meaningless) for absent keys.  ``keys`` need not be
    pre-sorted unless ``presorted`` is set — the streaming scorer joins
    many chunks against ONE pre-sorted model table and must not pay an
    argsort per chunk.  The merge-join primitive behind projected-model
    scoring and warm-start import (packed ``entity·G + col`` int64
    keys)."""
    nq = len(query_keys)
    if len(keys) == 0:
        return np.zeros(nq, vals.dtype if len(vals) else np.float64), \
            np.zeros(nq, bool)
    if presorted:
        ks, vs = keys, vals
    else:
        order = np.argsort(keys)
        ks, vs = keys[order], vals[order]
    p = np.minimum(np.searchsorted(ks, query_keys), len(ks) - 1)
    return vs[p], ks[p] == query_keys


def sorted_id_join(sorted_ids: np.ndarray,
                   query_ids: np.ndarray) -> np.ndarray:
    """Each query id's position in ``sorted_ids`` (ascending, unique),
    −1 where absent.  Shared by scoring, warm-start import, and
    projection — one implementation of the join idiom."""
    if len(sorted_ids) == 0:
        return np.full(len(query_ids), -1, np.int64)
    ids = np.asarray(query_ids, sorted_ids.dtype)
    pos = np.searchsorted(sorted_ids, ids)
    pos_c = np.minimum(pos, len(sorted_ids) - 1)
    return np.where(sorted_ids[pos_c] == ids, pos_c, -1)


def group_by_entity(
    entity_ids: np.ndarray,
    bucket_base: int = 4,
    min_capacity: int = 4,
) -> EntityGrouping:
    """Group example indices by entity with size-bucketed capacities.

    Bucket capacities are min_capacity·bucket_base^j, so within-bucket
    padding waste is < bucket_base×.  Deterministic given inputs.
    """
    entity_ids = np.asarray(entity_ids)
    n = len(entity_ids)
    uniq, inverse, counts = np.unique(
        entity_ids, return_inverse=True, return_counts=True
    )
    E = len(uniq)

    # Capacity per entity: smallest bucket capacity ≥ count.
    caps_needed = np.maximum(counts, 1)
    bucket_of_entity = np.zeros(E, np.int64)
    cap = min_capacity
    cap_list = [min_capacity]
    while cap < caps_needed.max():
        cap *= bucket_base
        cap_list.append(cap)
    cap_arr = np.asarray(cap_list)
    bucket_of_entity = np.searchsorted(cap_arr, caps_needed, side="left")

    # Keep only non-empty buckets, re-indexed densely.  (Everything
    # below is vectorized: E can be millions — see SURVEY §7 "entity-
    # grouping ETL at KDD2012 scale".)
    used = np.unique(bucket_of_entity)
    bucket_of_entity = np.searchsorted(used, bucket_of_entity)
    capacities = [int(cap_arr[b]) for b in used]

    # Slot of each entity within its bucket (stable order by entity id):
    # sort entities by bucket; slot = rank within the bucket's run.
    n_buckets = len(used)
    order_e = np.argsort(bucket_of_entity, kind="stable")
    sorted_b = bucket_of_entity[order_e]
    bucket_starts = np.searchsorted(sorted_b, np.arange(n_buckets))
    slot_of_entity = np.empty(E, np.int64)
    slot_of_entity[order_e] = (
        np.arange(E, dtype=np.int64) - bucket_starts[sorted_b]
    )
    n_entities = np.bincount(bucket_of_entity,
                             minlength=n_buckets).tolist()

    # Per-example coordinates: position within its entity via stable
    # sort (stable ⇒ original example order within each entity, the
    # reference's deterministic grouping).
    order = np.argsort(inverse, kind="stable")
    entity_starts = np.zeros(E, np.int64)
    np.cumsum(counts[:-1], out=entity_starts[1:])
    col = np.empty(n, np.int64)
    col[order] = (
        np.arange(n, dtype=np.int64) - entity_starts[inverse[order]]
    )

    ex_entity = inverse
    return EntityGrouping(
        n_examples=n,
        entity_ids=uniq,
        entity_counts=counts,
        entity_bucket=bucket_of_entity,
        entity_slot=slot_of_entity,
        capacities=capacities,
        n_entities=n_entities,
        example_bucket=bucket_of_entity[ex_entity],
        example_row=slot_of_entity[ex_entity],
        example_col=col,
        example_entity=ex_entity,
    )


def bucket_occupancy(grouping: EntityGrouping) -> dict:
    """Per-bucket occupancy / padding-waste stats for one grouping.

    The size-bucketing scheme bounds padding waste by ``bucket_base``×
    by construction, but the ACTUAL waste depends on the entity-count
    distribution — a regression in ``bucket_base`` (or a pathological
    id distribution) silently multiplies every block array and every
    vmapped solve lane.  Coordinate builders log this once per build so
    the number is visible instead of silent (ISSUE 5 satellite).

    Returns ``{"entities", "examples", "padded_slots", "total_slots",
    "padded_slot_ratio", "buckets": [{"capacity", "entities",
    "examples", "fill_fraction"}, ...]}``.
    """
    counts = np.asarray(grouping.entity_counts, np.int64)
    bucket = np.asarray(grouping.entity_bucket)
    n_buckets = len(grouping.capacities)
    ex_per_bucket = np.bincount(bucket, weights=counts,
                                minlength=n_buckets).astype(np.int64)
    buckets = []
    total_slots = 0
    for b, (cap, ne) in enumerate(zip(grouping.capacities,
                                      grouping.n_entities)):
        slots = int(cap) * int(ne)
        total_slots += slots
        buckets.append({
            "capacity": int(cap),
            "entities": int(ne),
            "examples": int(ex_per_bucket[b]),
            "fill_fraction": (round(float(ex_per_bucket[b]) / slots, 4)
                              if slots else 0.0),
        })
    n = int(grouping.n_examples)
    return {
        "entities": int(grouping.n_total_entities),
        "examples": n,
        "total_slots": total_slots,
        "padded_slots": total_slots - n,
        "padded_slot_ratio": (round((total_slots - n) / total_slots, 4)
                              if total_slots else 0.0),
        "buckets": buckets,
    }


def scatter_to_blocks(
    grouping: EntityGrouping, values: np.ndarray, fill: float = 0.0
) -> list[np.ndarray]:
    """Per-example values [n, ...] → per-bucket blocks [E_b, cap_b, ...]."""
    out = []
    trailing = values.shape[1:]
    for b, (cap, ne) in enumerate(
        zip(grouping.capacities, grouping.n_entities)
    ):
        blk = np.full((ne, cap) + trailing, fill, values.dtype)
        sel = grouping.example_bucket == b
        blk[grouping.example_row[sel], grouping.example_col[sel]] = values[sel]
        out.append(blk)
    return out


def gather_from_blocks(
    grouping: EntityGrouping, blocks: list[np.ndarray]
) -> np.ndarray:
    """Inverse of ``scatter_to_blocks`` (real example slots only)."""
    trailing = blocks[0].shape[2:]
    out = np.zeros((grouping.n_examples,) + trailing, blocks[0].dtype)
    for b, blk in enumerate(blocks):
        sel = grouping.example_bucket == b
        out[sel] = blk[grouping.example_row[sel], grouping.example_col[sel]]
    return out


@dataclasses.dataclass
class GameDataset:
    """Host-side GAME data: per-shard features + per-coordinate entity ids.

    The reference's ``GameDatum`` fields map to parallel arrays indexed
    by example position: ``labels/weights/offsets`` [n], feature shards
    (dense [n, d_shard] here; sparse shards enter via
    ``make_sparse_batch`` on the fixed-effect path), and
    ``entity_ids[coordinate]`` [n] integer ids (the reference's REId
    tags, pre-indexed by the feature/id maps).
    """

    labels: np.ndarray
    features: dict  # shard name → [n, d] float array (or sparse rows list)
    entity_ids: dict  # random-effect coordinate name → [n] int array
    weights: np.ndarray | None = None
    offsets: np.ndarray | None = None
    # Widths of sparse shards (dense shards infer from the array).
    feature_dims: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        # Normalize legacy ``list[(col_ids, values)]`` shards to
        # ``SparseRows`` ONCE at construction: every downstream consumer
        # (batch build, grouping, projection, transformer scoring) then
        # takes the vectorized flat-array path — no per-example Python
        # on any production loop (SURVEY §7 scale doctrine).
        from photon_ml_tpu.data.sparse_rows import SparseRows

        # Copy before normalizing: the caller may retain (or share) the
        # dict it passed in, and replacing its values in place would be
        # a surprising side effect (advisor finding).
        self.features = dict(self.features)
        for s, f in self.features.items():
            if not isinstance(f, (np.ndarray, SparseRows)):
                self.features[s] = SparseRows.from_rows(f)

    @property
    def n(self) -> int:
        return len(self.labels)

    def feature_dim(self, shard: str) -> int:
        feats = self.features[shard]
        if isinstance(feats, np.ndarray):
            return feats.shape[1]
        if shard in self.feature_dims:
            return int(self.feature_dims[shard])
        from photon_ml_tpu.data.sparse_rows import SparseRows

        if isinstance(feats, SparseRows):
            return feats.max_col + 1
        return int(max((int(c.max()) for c, _ in feats if len(c)),
                       default=-1)) + 1

    def weight_array(self) -> np.ndarray:
        return (np.ones(self.n, np.float32) if self.weights is None
                else self.weights.astype(np.float32))

    def take(self, idx) -> "GameDataset":
        """Row subset (train/validation splits in the drivers).

        A ``slice`` — or an index array that is a contiguous ascending
        range, the shape every train/valid split produces — subsets by
        numpy basic slicing, i.e. zero-copy VIEWS of every array
        (SURVEY §7 scale class: splitting a 10⁸-example dataset must
        not triple host RSS).  Arbitrary index arrays still copy.
        """
        from photon_ml_tpu.data.sparse_rows import SparseRows

        if not isinstance(idx, slice):
            idx = np.asarray(idx)
            if idx.dtype == bool:
                idx = np.flatnonzero(idx)
            idx = idx.astype(np.int64)
            if idx.size and idx[0] >= 0 and bool(
                (np.diff(idx) == 1).all() if idx.size > 1 else True
            ):
                idx = slice(int(idx[0]), int(idx[-1]) + 1)

        def sub(feats):
            if isinstance(feats, (np.ndarray, SparseRows)):
                return feats[idx]
            if isinstance(idx, slice):
                return feats[idx]
            return [feats[int(i)] for i in idx]

        return GameDataset(
            labels=self.labels[idx],
            features={s: sub(f) for s, f in self.features.items()},
            entity_ids={k: v[idx] for k, v in self.entity_ids.items()},
            weights=None if self.weights is None else self.weights[idx],
            offsets=None if self.offsets is None else self.offsets[idx],
            feature_dims=dict(self.feature_dims),
        )

    def offset_array(self) -> np.ndarray:
        return (np.zeros(self.n, np.float32) if self.offsets is None
                else self.offsets.astype(np.float32))
