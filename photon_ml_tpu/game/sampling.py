"""Down-sampling for fixed-effect training data.

Reference counterparts: ``DownSampler``, ``DefaultDownSampler``,
``BinaryClassificationDownSampler`` (photon-api
``com.linkedin.photon.ml.sampling`` [expected paths, mount unavailable —
see SURVEY.md §2.4]).

Semantics mirror the reference:

- ``BinaryClassificationDownSampler``: keep ALL positives, keep each
  negative with probability ``rate``, multiply kept negatives' weights
  by ``1/rate`` so the objective stays unbiased.
- ``DefaultDownSampler`` (non-binary tasks): keep each example with
  probability ``rate``, reweight by ``1/rate``.

Host-side (numpy): down-sampling decides WHICH examples form the
fixed-effect batch, so it runs once in the ETL before device upload —
the reference likewise samples RDDs before optimization, not inside it.
"""

from __future__ import annotations

import numpy as np


def binary_classification_down_sample(
    labels: np.ndarray,
    weights: np.ndarray,
    rate: float,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Keep-indices + adjusted weights for negative down-sampling.

    Returns (indices, new_weights_for_those_indices).
    """
    if not 0.0 < rate <= 1.0:
        raise ValueError(f"down-sampling rate must be in (0, 1], got {rate}")
    rng = np.random.default_rng(seed)
    labels = np.asarray(labels)
    weights = np.asarray(weights, np.float64)
    is_pos = labels > 0.5
    keep = is_pos | (rng.uniform(size=len(labels)) < rate)
    idx = np.where(keep)[0]
    new_w = weights[idx].copy()
    new_w[~is_pos[idx]] /= rate
    return idx, new_w.astype(np.float32)


def default_down_sample(
    n: int,
    weights: np.ndarray,
    rate: float,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Uniform down-sampling with 1/rate reweighting."""
    if not 0.0 < rate <= 1.0:
        raise ValueError(f"down-sampling rate must be in (0, 1], got {rate}")
    rng = np.random.default_rng(seed)
    keep = rng.uniform(size=n) < rate
    idx = np.where(keep)[0]
    new_w = (np.asarray(weights, np.float64)[idx] / rate).astype(np.float32)
    return idx, new_w
