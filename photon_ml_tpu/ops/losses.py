"""Pointwise GLM loss functions.

TPU-native re-design of the reference's pointwise loss hierarchy
(reference: photon-lib ``com.linkedin.photon.ml.function.glm`` —
``PointwiseLossFunction``, ``LogisticLossFunction``, ``SquaredLossFunction``,
``PoissonLossFunction``, ``SmoothedHingeLossFunction`` [expected paths,
mount unavailable — see SURVEY.md provenance banner]).

Each loss is a pure, stateless namespace of jittable/vmappable functions of
the *margin* ``z = x·w + offset`` and the label ``y``:

- ``loss(z, y)``   — per-example loss value
- ``d1(z, y)``     — ∂loss/∂z   (feeds the gradient:  X^T (w ⊙ d1))
- ``d2(z, y)``     — ∂²loss/∂z² (feeds the HVP:       X^T (w ⊙ d2 ⊙ Xv))
- ``mean(z)``      — the GLM mean function linking margin to prediction
  (sigmoid / identity / exp), used at scoring time.

All math is elementwise on arrays, so XLA fuses it straight into the
surrounding matmul/segment-sum — there is no per-example Python loop
anywhere (contrast with the reference's per-example Scala fold inside
``ValueAndGradientAggregator``).

Numerical notes: the logistic loss uses the log1p(exp(-|z|)) stable form;
Poisson clamps exp to avoid overflow in float32.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """A GLM pointwise loss: value + first/second margin-derivatives + link.

    Instances are hashable static pytree-leaves-free dataclasses, so they can
    be closed over by jitted functions or passed as static args.
    """

    name: str
    loss: Callable[[Array, Array], Array]
    d1: Callable[[Array, Array], Array]
    d2: Callable[[Array, Array], Array]
    mean: Callable[[Array], Array]
    # Convexity flag: every reference loss is convex; kept for validators.
    convex: bool = True

    def __hash__(self) -> int:  # static-arg friendliness under jit
        return hash(self.name)

    def __eq__(self, other) -> bool:
        return isinstance(other, PointwiseLoss) and other.name == self.name


# ---------------------------------------------------------------------------
# Logistic loss.  Labels follow the reference convention y ∈ {0, 1}
# (photon-ml's binary classification reads 0/1 labels from Avro).
# loss(z, y) = log(1 + e^z) − y·z   (cross-entropy on the margin)
# d1 = σ(z) − y ;  d2 = σ(z)(1 − σ(z))
# ---------------------------------------------------------------------------

def _logistic_loss(z: Array, y: Array) -> Array:
    # log(1+e^z) = max(z,0) + log1p(exp(-|z|))  (stable for large |z|)
    return jnp.maximum(z, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(z))) - y * z


def _logistic_d1(z: Array, y: Array) -> Array:
    return jax.nn.sigmoid(z) - y


def _logistic_d2(z: Array, y: Array) -> Array:
    s = jax.nn.sigmoid(z)
    return s * (1.0 - s)


LOGISTIC = PointwiseLoss(
    name="logistic",
    loss=_logistic_loss,
    d1=_logistic_d1,
    d2=_logistic_d2,
    mean=jax.nn.sigmoid,
)


# ---------------------------------------------------------------------------
# Squared loss (linear regression):  loss = ½ (z − y)²
# ---------------------------------------------------------------------------

def _squared_loss(z: Array, y: Array) -> Array:
    d = z - y
    return 0.5 * d * d


SQUARED = PointwiseLoss(
    name="squared",
    loss=_squared_loss,
    d1=lambda z, y: z - y,
    d2=lambda z, y: jnp.ones_like(z),
    mean=lambda z: z,
)


# ---------------------------------------------------------------------------
# Poisson loss (negative log-likelihood up to a constant):
#   loss = ẽ(z) − y·z ;  d1 = ẽ'(z) − y ;  d2 = ẽ''(z)
# where ẽ is exp softened beyond z=MAX_EXP_ARG by a quadratic (Huber-style)
# extension, so loss/d1/d2 remain exact mutual derivatives everywhere (a
# plain clamp makes value and gradient inconsistent past the clamp, which
# can stall Wolfe line searches).  ẽ matches exp in value and first two
# derivatives at the switch point, stays finite in float32, and keeps
# curvature positive so trust-region steps pull back toward the optimum.
# ---------------------------------------------------------------------------

_MAX_EXP_ARG = 30.0


def _soft_exp(z: Array) -> Array:
    """ẽ(z): exp for z ≤ M, e^M·(1 + t + t²/2), t = z − M, beyond."""
    t = z - _MAX_EXP_ARG
    cap = jnp.exp(jnp.asarray(_MAX_EXP_ARG, z.dtype))
    return jnp.where(
        z <= _MAX_EXP_ARG,
        jnp.exp(jnp.minimum(z, _MAX_EXP_ARG)),
        cap * (1.0 + t + 0.5 * t * t),
    )


def _soft_exp_d1(z: Array) -> Array:
    t = z - _MAX_EXP_ARG
    cap = jnp.exp(jnp.asarray(_MAX_EXP_ARG, z.dtype))
    return jnp.where(
        z <= _MAX_EXP_ARG,
        jnp.exp(jnp.minimum(z, _MAX_EXP_ARG)),
        cap * (1.0 + t),
    )


def _soft_exp_d2(z: Array) -> Array:
    cap = jnp.exp(jnp.asarray(_MAX_EXP_ARG, z.dtype))
    return jnp.where(
        z <= _MAX_EXP_ARG, jnp.exp(jnp.minimum(z, _MAX_EXP_ARG)), cap
    )


POISSON = PointwiseLoss(
    name="poisson",
    loss=lambda z, y: _soft_exp(z) - y * z,
    d1=lambda z, y: _soft_exp_d1(z) - y,
    d2=lambda z, y: _soft_exp_d2(z),
    mean=_soft_exp,
)


# ---------------------------------------------------------------------------
# Smoothed hinge loss (linear SVM surrogate).  Reference semantics
# (SmoothedHingeLossFunction): labels y ∈ {0,1} are mapped to s ∈ {−1,+1};
# with t = s·z:
#   t ≥ 1      → 0
#   t ≤ 0      → ½ − t
#   0 < t < 1  → ½ (1 − t)²
# Piecewise-smooth; d2 is its almost-everywhere second derivative (the
# reference likewise feeds TRON a Gauss-Newton-style d2).
# ---------------------------------------------------------------------------

def _hinge_t(z: Array, y: Array) -> Array:
    s = 2.0 * y - 1.0
    return s * z


def _smoothed_hinge_loss(z: Array, y: Array) -> Array:
    t = _hinge_t(z, y)
    return jnp.where(
        t >= 1.0,
        0.0,
        jnp.where(t <= 0.0, 0.5 - t, 0.5 * (1.0 - t) * (1.0 - t)),
    )


def _smoothed_hinge_d1(z: Array, y: Array) -> Array:
    s = 2.0 * y - 1.0
    t = s * z
    dt = jnp.where(t >= 1.0, 0.0, jnp.where(t <= 0.0, -1.0, t - 1.0))
    return s * dt


def _smoothed_hinge_d2(z: Array, y: Array) -> Array:
    t = _hinge_t(z, y)
    return jnp.where((t > 0.0) & (t < 1.0), 1.0, 0.0)


SMOOTHED_HINGE = PointwiseLoss(
    name="smoothed_hinge",
    loss=_smoothed_hinge_loss,
    d1=_smoothed_hinge_d1,
    d2=_smoothed_hinge_d2,
    # Scores for SVM are raw margins; "mean" is identity (no probabilistic link).
    mean=lambda z: z,
)


_BY_NAME = {
    l.name: l for l in (LOGISTIC, SQUARED, POISSON, SMOOTHED_HINGE)
}
# Reference task-type aliases (TaskType enum).
_BY_NAME.update(
    {
        "logistic_regression": LOGISTIC,
        "linear_regression": SQUARED,
        "poisson_regression": POISSON,
        "smoothed_hinge_loss_linear_svm": SMOOTHED_HINGE,
    }
)


def get_loss(name: str) -> PointwiseLoss:
    """Look up a loss by name or reference TaskType alias."""
    key = name.lower()
    if key not in _BY_NAME:
        raise ValueError(
            f"Unknown loss '{name}'. Available: {sorted(_BY_NAME)}"
        )
    return _BY_NAME[key]
