"""The sparse hot-op: ``rowsum(vals · table[ids])`` — XLA and Pallas paths.

Both directions of the sparse GLM hot loop are instances of one
gather-contract primitive over a padded-ELL tile:

- margins:   ``m[i] = Σ_k values[i,k] · w[col_ids[i,k]]``     (table = w)
- gradient:  ``p[v] = Σ_k tvals[v,k] · r[trows[v,k]]``         (table = r,
  over the transposed layout — see ``data.colmajor``)

Reference counterpart: the per-example fold inside
``ValueAndGradientAggregator`` (photon-lib
``com.linkedin.photon.ml.function.glm`` [expected path, mount unavailable
— SURVEY.md §2.2]).  The reference's hot loop is scalar JVM code over
Breeze sparse vectors; here it is one vectorized gather+multiply+reduce,
and on TPU a Pallas kernel that keeps the gather table resident in VMEM
and streams ELL tiles HBM→VMEM, so each nonzero costs ~8 bytes of HBM
traffic and no scatter ever happens (design rationale in
``data/colmajor.py``).

Dispatch:
- TPU backend + aligned shapes + table fits VMEM → Pallas kernel.
- anything else (CPU tests, virtual meshes, odd shapes) → pure-XLA
  ``jnp.sum(vals * table[ids], -1)``, which XLA fuses well everywhere
  except the TPU gather (the thing the kernel exists to fix).
- ``PHOTON_ML_TPU_PALLAS=0|1`` forces the choice (0 is the escape hatch
  if a jax/libtpu regression breaks the kernel; 1 + interpret mode is
  how CPU tests exercise the kernel body).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

Array = jax.Array

# Tables larger than this stay on the XLA path: the kernel holds the full
# gather table in VMEM (~16 MB/core on v5e) alongside double-buffered ELL
# tiles.  8 MB ≈ a 2M-row f32 table — covers w up to d=2M and residuals
# up to n=2M per device shard; beyond that, shard the batch.
_MAX_TABLE_BYTES = 8 * 1024 * 1024


def _want_pallas() -> bool:
    env = os.environ.get("PHOTON_ML_TPU_PALLAS")
    if env == "0":
        return False
    if env == "1":
        return True
    return jax.default_backend() == "tpu"


def _xla_gather_rowsum(table: Array, vals: Array, ids: Array) -> Array:
    return jnp.sum(vals * table[ids], axis=-1)


def _row_tile(capacity: int, n_rows: int) -> int:
    """Rows per grid step: target ~64k elements per (vals, ids) tile so
    two tiles double-buffer comfortably under the VMEM budget, clamped
    to the row count (tiny batches = one grid step)."""
    t = max(8, (65536 // max(capacity, 1)) // 8 * 8)
    return min(t, max(8, n_rows // 8 * 8))


def _pallas_gather_rowsum(table: Array, vals: Array, ids: Array,
                          interpret: bool = False) -> Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, k = vals.shape
    tile = _row_tile(k, n)
    grid = n // tile

    def kernel(table_ref, vals_ref, ids_ref, out_ref):
        gathered = table_ref[ids_ref[:]]          # [tile, k] VMEM gather
        out_ref[:] = jnp.sum(vals_ref[:] * gathered, axis=-1)

    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),            # full table
            pl.BlockSpec((tile, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile,), lambda i: (i,),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n,), vals.dtype),
        interpret=interpret,
    )(table, vals, ids)


def gather_rowsum(table: Array, vals: Array, ids: Array) -> Array:
    """``out[i] = Σ_k vals[i,k] · table[ids[i,k]]`` with TPU dispatch.

    Args:
      table: [L] float — the gather table (w for margins, r for Xᵀr).
      vals:  [n, k] float — ELL values (padding slots are 0).
      ids:   [n, k] int32 — ELL indices into ``table`` (padding → 0).
    """
    n, k = vals.shape
    if (
        _want_pallas()
        and table.ndim == 1
        and table.size * table.dtype.itemsize <= _MAX_TABLE_BYTES
        and n % _row_tile(k, n) == 0
    ):
        return _pallas_gather_rowsum(table, vals, ids)
    return _xla_gather_rowsum(table, vals, ids)
