"""The sparse hot-op: ``rowsum(vals · table[ids])`` — the XLA formulation.

Both directions of the sparse GLM hot loop are instances of one
gather-contract primitive over a padded-ELL tile:

- margins:   ``m[i] = Σ_k values[i,k] · w[col_ids[i,k]]``     (table = w)
- gradient:  ``p[v] = Σ_k tvals[v,k] · r[trows[v,k]]``         (table = r,
  over the transposed layout — see ``data.colmajor``)

Reference counterpart: the per-example fold inside
``ValueAndGradientAggregator`` (photon-lib
``com.linkedin.photon.ml.function.glm`` [expected path, mount unavailable
— SURVEY.md §2.2]).

``gather_rowsum`` is the pure-XLA formulation.  XLA lowers the gather to
a *scalar* loop on TPU (measured ~1 GB/s effective bandwidth on v5e —
~800× off the HBM roofline), so this path is only acceptable for small
batches, CPU tests, and fallbacks.  The production TPU path is the GRR
(gather-route-reduce) blocked layout in ``ops.grr`` + ``ops.grr_kernel``,
which ``SparseBatch`` dispatches to when the batch was built with it;
there the same contraction runs as Mosaic lane-gathers and crossbar
routes at near memory bandwidth.

``_pallas_gather_rowsum`` below is a naive whole-table-in-VMEM kernel
kept ONLY for interpret-mode tests of the gather-contract semantics: its
``table_ref[ids]`` body cannot be lowered by Mosaic on real TPUs
(verified on v5e: "Cannot do int indexing on TPU").  Nothing dispatches
to it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def _xla_gather_rowsum(table: Array, vals: Array, ids: Array) -> Array:
    return jnp.sum(vals * table[ids], axis=-1)


def gather_rowsum(table: Array, vals: Array, ids: Array) -> Array:
    """``out[i] = Σ_k vals[i,k] · table[ids[i,k]]``.

    Args:
      table: [L] float — the gather table (w for margins, r for Xᵀr).
      vals:  [n, k] float — ELL values (padding slots are 0).
      ids:   [n, k] int32 — ELL indices into ``table`` (padding → 0).
    """
    return _xla_gather_rowsum(table, vals, ids)


def vrow_pad(v: int, multiple: int | None = None) -> int:
    """Padded virtual-row count for the transposed-ELL build (multiple
    of 8 — the f32 sublane count — unless an explicit multiple is
    given).  The single source of truth shared by the numpy and native
    colmajor builders (their outputs must stay byte-identical)."""
    v = max(int(v), 1)
    if multiple is None:
        multiple = 8
    return max(-(-v // multiple) * multiple, 8)


def _pallas_gather_rowsum(table: Array, vals: Array, ids: Array,
                          interpret: bool = False) -> Array:
    """Interpret-mode-only reference kernel (see module docstring)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, k = vals.shape
    tile = max(8, min(n, 512) // 8 * 8)
    if n % tile != 0:
        tile = 8
    assert n % tile == 0, (n, tile)

    def kernel(table_ref, vals_ref, ids_ref, out_ref):
        gathered = table_ref[ids_ref[:]]          # [tile, k] VMEM gather
        out_ref[:] = jnp.sum(vals_ref[:] * gathered, axis=-1)

    return pl.pallas_call(
        kernel,
        grid=(n // tile,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),            # full table
            pl.BlockSpec((tile, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile,), lambda i: (i,),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n,), vals.dtype),
        interpret=interpret,
    )(table, vals, ids)
