"""The GRR (gather-route-reduce) Pallas kernel: sparse contraction at
vector speed on TPU.

This is the compute half of the framework's sparse hot loop — the
replacement for XLA's scalar gather/scatter lowering of
``out[s] = Σ_e val_e · table[idx_e]`` (measured ~1 GB/s on v5e, ~800×
off the HBM roofline).  The layout half (how nonzeros are blocked,
placed, and routed) lives in ``data.grr``; this module only executes
the precomputed plan.

Per supertile (16384 nonzero slots, one grid step):

1. **gather** — one lane-gather ``take_along_axis(W, G1, axis=1)``
   pulls each slot's table value out of the supertile's 128×128 VMEM
   window (row s of the window IS ``table[gw·WIN + 128·s ...]`` — the
   ETL placed every element in the sublane matching its table index's
   window sub-tile, and ``G1`` carries the lane residue, pre-composed
   with the route's first stage; no window transpose needed).
2. **route** — two more lane-gathers with a transpose between
   (the classical 3-stage Clos form, switches precomputed by König
   edge-coloring — ``ops.crossbar``) move every product to its
   reduction slot.
3. **reduce** — capacity planes are contiguous 16-row blocks, so the
   per-segment sum is CAP static-slice adds; the [GROUP,128] partial
   accumulates into the output window, which Pallas keeps resident in
   VMEM across the supertiles of one segment-window run (grid ordered
   by (ow, gw); ``first_of_ow`` marks run starts).

The only dynamic-indexing primitive used is ``tpu.DynamicGather`` via
``take_along_axis`` on equal [128,128] shapes — the one fast irregular
data-movement op the TensorCore has.  Measured on v5e: ~7 Gslot/s
(vs ~0.06 Gnnz/s for the XLA scatter path).

Reference counterpart: the aggregator fold + treeAggregate hot loop
(SURVEY.md §2.2 [mount unavailable]); the reference's JVM scattered
writes have no TPU equivalent, hence this design.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

TILE = 128
SLOTS = TILE * TILE        # nonzero slots per supertile


def grr_contract_kernel(
    table_t: Array,        # [n_gw,128,128] f32 — windows, row s = table[gw*WIN+128s...]
    g1: Array,             # [n_st, 128, 128] i8 — gather ∘ route stage 1
    g2: Array,             # [n_st, 128, 128] i8 — route stage 2 (transposed)
    g3: Array,             # [n_st, 128, 128] i8 — route stage 3
    vals: Array,           # [n_st, 128, 128] f32 — values in final slot order
    gw_of_st: Array,       # [n_st] i32 — table-window id per supertile
    ow_of_st: Array,       # [n_st] i32 — output-window id per supertile
    first_of_ow: Array,    # [n_st] i32 — 1 at the first supertile of an ow run
    n_ow: int,
    cap: int,
    interpret: bool = False,
) -> Array:
    """Run the contraction plan; returns out2d [n_ow, 128//cap, 128].

    Flat segment s lives at ``out2d.reshape(-1)[s]`` (segment-window
    ow = s // (16384//cap), then row-major within the window).
    """
    n_st = vals.shape[0]
    group = TILE // cap

    def kernel(gw_ref, ow_ref, first_ref, wt_ref, g1_ref, g2_ref, g3_ref,
               v_ref, out_ref):
        st = pl.program_id(0)
        wt = wt_ref[0]
        x1 = jnp.take_along_axis(wt, g1_ref[0].astype(jnp.int32), axis=1)
        x2t = jnp.take_along_axis(x1.T, g2_ref[0].astype(jnp.int32), axis=1)
        x3 = jnp.take_along_axis(x2t.T, g3_ref[0].astype(jnp.int32), axis=1)
        c = x3 * v_ref[0]
        partial = c[0:group, :]
        for q in range(1, cap):
            partial = partial + c[q * group:(q + 1) * group, :]

        @pl.when(first_ref[st] == 1)
        def _start_run():
            out_ref[0] = partial

        @pl.when(first_ref[st] == 0)
        def _accumulate():
            out_ref[0] += partial

    stream = lambda: pl.BlockSpec(
        (1, TILE, TILE), lambda i, gw, ow, first: (i, 0, 0),
        memory_space=pltpu.VMEM,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n_st,),
        in_specs=[
            pl.BlockSpec((1, TILE, TILE),
                         lambda i, gw, ow, first: (gw[i], 0, 0),
                         memory_space=pltpu.VMEM),
            stream(), stream(), stream(), stream(),
        ],
        out_specs=pl.BlockSpec((1, group, TILE),
                               lambda i, gw, ow, first: (ow[i], 0, 0),
                               memory_space=pltpu.VMEM),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_ow, group, TILE), jnp.float32),
        interpret=interpret,
    )(gw_of_st, ow_of_st, first_of_ow, table_t, g1, g2, g3, vals)


DENSE_B = 4  # supertiles per grid step in the dense-grid kernel


def grr_contract_kernel_dense(
    table_t: Array,        # [n_gw,128,128] f32 — windows, row s = table[gw*WIN+128s...]
    g1: Array,             # [n_st_p, 128, 128] i8 — (gw-major full grid)
    g2: Array,
    g3: Array,
    vals: Array,           # [n_st_p, 128, 128] f32
    gwg: Array,            # [n_st_p // B] i32 — window id per B-group
    n_ow_p: int,
    cap: int,
    interpret: bool = False,
) -> Array:
    """Dense-grid execution: tiles ordered gw-major over the FULL
    (gw × ow_p) block grid (missing blocks are zero dummy tiles), B=4
    supertiles per grid step.  Emits per-tile partials; the ow reduction
    is a reshape-sum outside (``contract``).  Measured on v5e: 520
    ns/tile vs 650 for the revisiting kernel — bigger DMA blocks, one
    window fetch per gw run, and no out-block write-back stalls."""
    n_st_p = vals.shape[0]
    group = TILE // cap
    B = DENSE_B

    def kernel(gwg_ref, wt_ref, g1_ref, g2_ref, g3_ref, v_ref, out_ref):
        wt = wt_ref[0]
        for b in range(B):
            x1 = jnp.take_along_axis(wt, g1_ref[b].astype(jnp.int32), axis=1)
            x2t = jnp.take_along_axis(x1.T, g2_ref[b].astype(jnp.int32),
                                      axis=1)
            x3 = jnp.take_along_axis(x2t.T, g3_ref[b].astype(jnp.int32),
                                     axis=1)
            c = x3 * v_ref[b]
            partial = c[0:group, :]
            for q in range(1, cap):
                partial = partial + c[q * group:(q + 1) * group, :]
            out_ref[b] = partial

    stream = lambda: pl.BlockSpec(
        (B, TILE, TILE), lambda i, gwg: (i, 0, 0),
        memory_space=pltpu.VMEM,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_st_p // B,),
        in_specs=[
            pl.BlockSpec((1, TILE, TILE), lambda i, gwg: (gwg[i], 0, 0),
                         memory_space=pltpu.VMEM),
            stream(), stream(), stream(), stream(),
        ],
        out_specs=pl.BlockSpec((B, group, TILE), lambda i, gwg: (i, 0, 0),
                               memory_space=pltpu.VMEM),
    )
    parts = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_st_p, group, TILE), jnp.float32),
        interpret=interpret,
    )(gwg, table_t, g1, g2, g3, vals)
    # ow reduction: position in the full grid IS the (gw, ow) pair, so
    # the segment-sum collapses to a dense axis sum — no scatter.
    n_gw = n_st_p // n_ow_p
    return parts.reshape(n_gw, n_ow_p, group, TILE).sum(0)


def grr_contract_jnp_dense(
    table_t: Array, g1: Array, g2: Array, g3: Array, vals: Array,
    n_ow_p: int, cap: int,
) -> Array:
    """Pure-jnp execution of the dense-grid plan (CPU tests / semantic
    reference)."""
    group = TILE // cap
    i32 = jnp.int32
    n_st_p = vals.shape[0]
    n_gw = n_st_p // n_ow_p
    gw_of_st = jnp.repeat(jnp.arange(n_gw, dtype=i32), n_ow_p)
    wt = table_t[gw_of_st]
    x1 = jnp.take_along_axis(wt, g1.astype(i32), axis=2)
    x2t = jnp.take_along_axis(x1.transpose(0, 2, 1), g2.astype(i32), axis=2)
    x3 = jnp.take_along_axis(x2t.transpose(0, 2, 1), g3.astype(i32), axis=2)
    c = x3 * vals
    partial = c.reshape(n_st_p, cap, group, TILE).sum(1)
    return partial.reshape(n_gw, n_ow_p, group, TILE).sum(0)


def grr_contract_jnp(
    table_t: Array, g1: Array, g2: Array, g3: Array, vals: Array,
    gw_of_st: Array, ow_of_st: Array, n_ow: int, cap: int,
) -> Array:
    """Pure-jnp execution of the same plan (CPU tests, non-TPU backends,
    and the semantic reference the kernel is tested against)."""
    group = TILE // cap
    i32 = jnp.int32
    wt = table_t[gw_of_st]                                    # [n_st,128,128]
    x1 = jnp.take_along_axis(wt, g1.astype(i32), axis=2)
    x2t = jnp.take_along_axis(x1.transpose(0, 2, 1), g2.astype(i32), axis=2)
    x3 = jnp.take_along_axis(x2t.transpose(0, 2, 1), g3.astype(i32), axis=2)
    c = x3 * vals
    n_st = vals.shape[0]
    partial = c.reshape(n_st, cap, group, TILE).sum(1)
    return jax.ops.segment_sum(partial, ow_of_st, num_segments=n_ow)
