"""Regularization contexts (L1 / L2 / elastic net).

Reference counterpart: ``RegularizationContext`` /
``ElasticNetRegularizationContext`` / ``RegularizationType``
(photon-lib ``com.linkedin.photon.ml.optimization`` [expected path, mount
unavailable — see SURVEY.md]).

Semantics mirror the reference:

- the **L2 part** is smooth and folded directly into the objective's
  value / gradient / Hessian-vector product (weight ``alpha·λ`` ... for
  elastic net the split is ``l1 = α·λ``, ``l2 = (1−α)·λ``);
- the **L1 part** is non-smooth and is NOT part of the differentiable
  objective — it is handled by the optimizer (OWL-QN's orthant-wise
  projection), exactly as Breeze's OWLQN does for the reference.

The intercept column can be excluded from regularization via
``intercept_index`` (the reference excludes the intercept when
``addIntercept`` is on).
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

Array = jax.Array


class RegularizationType(str, enum.Enum):
    NONE = "NONE"
    L1 = "L1"
    L2 = "L2"
    ELASTIC_NET = "ELASTIC_NET"


_HALF = None


def _half():
    """0.5 for ``l2_value``, device-resident on the EAGER path.

    ``value_and_gradient`` adds the reg term outside the jitted chunk
    programs, and an eager ``0.5 * array`` implicitly uploads a fresh
    host scalar every evaluation — a per-pass host→device transfer the
    runtime transfer guard (``analysis.guards.no_implicit_transfers``)
    rightly rejects.  ``device_put`` is the explicit, planned spelling;
    lazy so importing this module never initializes a backend (the
    multi-host driver must call ``jax.distributed.initialize`` first).
    The cached constant is safe under any trace (a concrete device
    array is just a constant there), but CREATING it must not cache a
    tracer: under an abstract (jit) trace ``device_put`` returns a
    tracer, and under vmap's CONCRETE batching trace every op executes
    eagerly — so a plain-literal fallback would still upload
    implicitly (the swept ``_lane_reg`` path hits exactly this).
    First use under a trace therefore takes an UNCACHED explicit
    ``device_put``: allowed by the transfer guard, folded as a
    constant by abstract traces."""
    global _HALF
    if _HALF is not None:
        return _HALF
    if jax.core.trace_state_clean():
        _HALF = jax.device_put(np.float32(0.5))
        return _HALF
    return jax.device_put(np.float32(0.5))


@struct.dataclass
class RegularizationContext:
    """Split of the regularization weight into smooth (l2) and l1 parts.

    ``reg_mask`` (optional, [dim]) zeroes regularization on chosen
    coordinates (used to exempt the intercept).
    """

    l1_weight: Array  # scalar
    l2_weight: Array  # scalar
    reg_mask: Array | None = None  # [dim] or None (regularize everything)

    # -- constructors -------------------------------------------------------

    @staticmethod
    def none() -> "RegularizationContext":
        return RegularizationContext(
            l1_weight=jnp.asarray(0.0), l2_weight=jnp.asarray(0.0)
        )

    @staticmethod
    def l2(weight: float, reg_mask: Array | None = None) -> "RegularizationContext":
        return RegularizationContext(
            l1_weight=jnp.asarray(0.0),
            l2_weight=jnp.asarray(weight, jnp.float32),
            reg_mask=reg_mask,
        )

    @staticmethod
    def l1(weight: float, reg_mask: Array | None = None) -> "RegularizationContext":
        return RegularizationContext(
            l1_weight=jnp.asarray(weight, jnp.float32),
            l2_weight=jnp.asarray(0.0),
            reg_mask=reg_mask,
        )

    @staticmethod
    def elastic_net(
        weight: float, alpha: float, reg_mask: Array | None = None
    ) -> "RegularizationContext":
        """Reference convention: l1 = α·λ, l2 = (1−α)·λ."""
        return RegularizationContext(
            l1_weight=jnp.asarray(alpha * weight, jnp.float32),
            l2_weight=jnp.asarray((1.0 - alpha) * weight, jnp.float32),
            reg_mask=reg_mask,
        )

    # -- smooth (L2) part ---------------------------------------------------

    def _masked(self, w: Array) -> Array:
        return w if self.reg_mask is None else w * self.reg_mask

    def l2_value(self, w: Array) -> Array:
        wm = self._masked(w)
        return _half() * self.l2_weight * jnp.vdot(wm, wm)

    def l2_gradient(self, w: Array) -> Array:
        return self.l2_weight * self._masked(w)

    def l2_hessian_vector(self, v: Array) -> Array:
        return self.l2_weight * self._masked(v)

    def l2_hessian_diagonal(self, w: Array) -> Array:
        ones = jnp.ones_like(w)
        return self.l2_weight * self._masked(ones)

    # -- non-smooth (L1) part — optimizer-facing ----------------------------

    def l1_value(self, w: Array) -> Array:
        return self.l1_weight * jnp.sum(jnp.abs(self._masked(w)))


def exclude_intercept_mask(dim: int, intercept_index: int | None) -> Array | None:
    """[dim] mask that exempts the intercept coordinate, or None."""
    if intercept_index is None:
        return None
    return jnp.ones((dim,), jnp.float32).at[intercept_index].set(0.0)


@struct.dataclass
class SweptRegularization:
    """Per-lane regularization weights for a batched λ sweep.

    One lane per λ-grid point: ``l1_weights[l]`` / ``l2_weights[l]`` are
    the lane's split under the same reference convention as
    ``RegularizationContext`` (L2 → (0, λ); L1 → (λ, 0); elastic net →
    (α·λ, (1−α)·λ)).  The shared ``reg_mask`` (intercept exemption)
    stays on the base context — lanes differ only in weight.
    """

    l1_weights: Array  # [L]
    l2_weights: Array  # [L]

    @staticmethod
    def from_grid(
        regularization: "RegularizationType | str",
        weights,
        elastic_net_alpha: float = 0.5,
    ) -> "SweptRegularization":
        """λ grid [L] → per-lane (l1, l2) splits."""
        lam = jnp.asarray(np.asarray(weights, np.float32))
        reg = RegularizationType(regularization)
        if reg == RegularizationType.L2:
            l1, l2 = jnp.zeros_like(lam), lam
        elif reg == RegularizationType.L1:
            l1, l2 = lam, jnp.zeros_like(lam)
        elif reg == RegularizationType.ELASTIC_NET:
            l1 = elastic_net_alpha * lam
            l2 = (1.0 - elastic_net_alpha) * lam
        else:  # NONE
            l1, l2 = jnp.zeros_like(lam), jnp.zeros_like(lam)
        return SweptRegularization(l1_weights=l1, l2_weights=l2)

    @property
    def n_lanes(self) -> int:
        return self.l1_weights.shape[0]

    def has_l1(self) -> bool:
        """Concrete any-lane L1 presence (OWL-QN routing for the whole
        sweep; must be decided outside jit, like ``OptimizationProblem
        .has_l1``).  A zero-λ lane inside an L1 sweep rides the OWL-QN
        loop with an all-zero l1 vector."""
        return bool(np.any(np.asarray(self.l1_weights) != 0.0))

    def l1_vectors(self, dim: int, reg_mask: Array | None) -> Array:
        """Per-lane [L, dim] OWL-QN weight vectors (mask applied)."""
        vecs = jnp.broadcast_to(
            self.l1_weights[:, None].astype(jnp.float32),
            (self.n_lanes, dim),
        )
        if reg_mask is not None:
            vecs = vecs * reg_mask
        return vecs
