"""In-tile crossbar: arbitrary static permutation of a [T,128] VMEM tile.

The TPU's only fast irregular-data-movement primitive is the within-vreg
lane gather (``take_along_axis(x, idx, axis=1)`` on equal [S,128] shapes
→ one DynamicGather op).  Cross-row movement exists only as the regular
[128,128] transpose.  This module decomposes an arbitrary permutation of
a [128,128] tile into the classical three-stage Clos form

    out = L3 ∘ T ∘ L2 ∘ T ∘ L1

where L_i are lane permutations and T is the tile transpose: stage 1
moves each element within its source row to an intermediate lane (its
"color"), the transposed middle stage permutes within that color's row,
and stage 3 places elements in their destination lanes.  The routing
exists for every permutation by König's theorem: the (src_row, dst_row)
pairs form a 128-regular bipartite multigraph, and a proper 128-edge-
coloring (no vertex sees a color twice) gives conflict-free lanes.  The
coloring is computed by Euler splitting — O(m log 128), exact, in C++
(``native.pml_edge_color``) with a Python fallback.

This is a *routing network realized in data layout*: the switches are
precomputed on the host (the sparse design matrix is static across all
optimizer iterations), so at runtime the permutation costs three
DynamicGathers and two transposes per tile — no scatter, no per-element
control flow.  Reference counterpart: none; the reference's JVM fold
(SURVEY.md §2.2 aggregators) permutes implicitly through cheap scattered
writes, which TPUs do not have.

No reference code was available (mount empty, SURVEY.md banner); the
construction follows the public switching-network literature.
"""

from __future__ import annotations

import numpy as np

TILE = 128


def _edge_color_python(src: np.ndarray, dst: np.ndarray, n_left: int,
                       n_right: int, n_colors: int) -> np.ndarray:
    """Euler-split coloring, pure Python (small inputs / no toolchain)."""
    m = src.size
    color = np.zeros(m, np.int32)

    def split(edge_ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        # Walk Euler circuits, alternating edges between two halves.
        # Bipartite ⇒ circuits have even length ⇒ both halves see every
        # vertex equally often, keeping degrees even for recursion.
        adj: dict[int, list[int]] = {}
        other = {}
        for e in edge_ids:
            u, w = int(src[e]), n_left + int(dst[e])
            adj.setdefault(u, []).append(e)
            adj.setdefault(w, []).append(e)
            other[e] = (u, w)
        used = set()
        side = {}
        for e0 in edge_ids:
            if int(e0) in used:
                continue
            v = int(src[e0])
            s = 0
            while adj.get(v):
                e = adj[v].pop()
                if e in used:
                    continue
                used.add(e)
                side[e] = s
                s ^= 1
                u, w = other[e]
                v = w if v == u else u
        a = np.array([e for e in edge_ids if side[int(e)] == 0],
                     dtype=edge_ids.dtype)
        b = np.array([e for e in edge_ids if side[int(e)] == 1],
                     dtype=edge_ids.dtype)
        return a, b

    levels = int(n_colors).bit_length() - 1
    ranges = [np.arange(m, dtype=np.int64)]
    for level in range(levels):
        nxt = []
        bit = 1 << (levels - 1 - level)
        for ids in ranges:
            if ids.size == 0:
                continue
            a, b = split(ids)
            color[b] |= bit
            nxt.extend((a, b))
        ranges = nxt
    return color


def edge_color(src: np.ndarray, dst: np.ndarray, n_left: int,
               n_right: int, n_colors: int) -> np.ndarray:
    """Proper n_colors-edge-coloring of a bipartite multigraph whose
    vertex degrees are all divisible by n_colors (a power of two)."""
    from photon_ml_tpu.native import edge_color_native

    native = edge_color_native(src, dst, n_left, n_right, n_colors)
    if native is not None:
        return native
    return _edge_color_python(np.asarray(src, np.int64),
                              np.asarray(dst, np.int64),
                              n_left, n_right, n_colors)


def route_tile(dst_slot: np.ndarray) -> tuple[np.ndarray, np.ndarray,
                                              np.ndarray]:
    """Route one [128,128] tile permutation into (g1, g2, g3).

    Args:
      dst_slot: int array [128,128]; ``dst_slot[r, l]`` is the flat
        destination slot (dr*128+dl) of the element at source (r, l).
        Must be a bijection on 0..16383.

    Returns:
      (g1, g2, g3) int32 [128,128] lane-gather index arrays such that

        x1  = take_along_axis(x,    g1, axis=1)
        x2t = take_along_axis(x1.T, g2, axis=1)
        out = take_along_axis(x2t.T, g3, axis=1)

      applies the permutation: out[dr, dl] == x[r, l].
    """
    d = np.asarray(dst_slot, np.int64)
    if d.shape != (TILE, TILE):
        raise ValueError(f"expected [{TILE},{TILE}], got {d.shape}")
    flat = d.reshape(-1)
    if not np.array_equal(np.sort(flat), np.arange(TILE * TILE)):
        raise ValueError("dst_slot is not a bijection on the tile")

    src_row = np.repeat(np.arange(TILE, dtype=np.int32), TILE)
    src_lane = np.tile(np.arange(TILE, dtype=np.int32), TILE)
    dst_row = (flat // TILE).astype(np.int32)
    dst_lane = (flat % TILE).astype(np.int32)

    color = edge_color(src_row, dst_row, TILE, TILE, TILE)

    # Stage 1: x1[r, c] = x[r, lane of the edge with color c at row r].
    g1 = np.empty((TILE, TILE), np.int32)
    g1[src_row, color] = src_lane
    # Stage 2 (on x1.T): x2t[c, r2] = x1t[c, src row of the color-c edge
    # into dst row r2] — within color c the src→dst row map is a
    # perfect matching, so this is a true lane permutation.
    g2 = np.empty((TILE, TILE), np.int32)
    g2[color, dst_row] = src_row
    # Stage 3: out[r2, l2] = x2[r2, color of the edge landing at l2].
    g3 = np.empty((TILE, TILE), np.int32)
    g3[dst_row, dst_lane] = color
    return g1, g2, g3


def apply_route_numpy(x: np.ndarray, g1: np.ndarray, g2: np.ndarray,
                      g3: np.ndarray) -> np.ndarray:
    """Reference executor for tests (mirrors the kernel's micro-stages)."""
    x1 = np.take_along_axis(x, g1, axis=1)
    x2t = np.take_along_axis(x1.T, g2, axis=1)
    return np.take_along_axis(x2t.T, g3, axis=1)
