"""The GLM objective: fused value / gradient / Hessian-vector over a batch.

Reference counterparts (all [expected paths, mount unavailable — SURVEY.md]):
- ``ObjectiveFunction`` / ``DiffFunction`` / ``TwiceDiffFunction`` traits
  (photon-lib ``com.linkedin.photon.ml.function``),
- ``SingleNodeGLMLossFunction`` and the hot-loop aggregators
  ``ValueAndGradientAggregator`` / ``HessianVectorAggregator`` /
  ``HessianDiagonalAggregator`` (``...function.glm``).

Where the reference folds example-by-example in Scala, this objective is a
handful of fused array ops (margin contraction → elementwise loss → masked
reduce / transposed contraction), which XLA compiles onto the MXU/VPU as
one pipeline with no intermediate HBM round-trips.  The *distributed*
variant (reference ``DistributedGLMLossFunction`` + treeAggregate) is this
same objective wrapped in ``shard_map`` + ``psum`` — see
``photon_ml_tpu.parallel.distributed_objective``.

Everything is a pure function of ``(w, batch)`` so the same objective is
- jitted for the fixed-effect solve,
- vmapped over entity blocks for random-effect solves,
- shard_mapped over the device mesh for data parallelism.

Sign/weight conventions follow the reference: total value =
Σ_i weight_i·ℓ(margin_i, y_i) + ½·λ₂·‖w‖² (unnormalized by n; L1 handled by
OWL-QN, not here).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.data.normalization import NormalizationContext
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.ops.prior import GaussianPrior
from photon_ml_tpu.ops.regularization import RegularizationContext

Array = jax.Array


@struct.dataclass
class GLMObjective:
    """Bundle of (loss, regularization, normalization) over a batch.

    The batch is passed per-call (not stored) so one objective instance can
    serve many shards / entity blocks, and so batches can be donated.
    ``loss`` is static (hashable) metadata; reg/norm are pytrees of scalars
    and [dim] vectors that trace cleanly.
    """

    loss: PointwiseLoss = struct.field(pytree_node=False)
    reg: RegularizationContext
    norm: NormalizationContext
    # Optional Gaussian prior toward a previous model's coefficients
    # (incremental training, reference PriorDistribution — see ops/prior.py).
    prior: "GaussianPrior | None" = None

    # ---- internals --------------------------------------------------------

    def _margins(self, w: Array, batch: Batch) -> Array:
        w_raw = self.norm.model_to_raw(w)
        m = batch.margins(w_raw)
        if not self.norm.is_identity:
            m = m - self.norm.margin_correction(w)
        return m

    def _residual_to_grad(self, r: Array, batch: Batch) -> Array:
        """r (already masked+weighted, [n]) → model-space gradient [dim]."""
        g_raw = batch.xt_dot(r)
        return self.norm.grad_to_model(g_raw, jnp.sum(r))

    # ---- TwiceDiffFunction surface ---------------------------------------

    def value(self, w: Array, batch: Batch) -> Array:
        m = self._margins(w, batch)
        wl = batch.weights * batch.mask
        data_val = jnp.sum(wl * self.loss.loss(m, batch.labels))
        val = data_val + self.reg.l2_value(w)
        if self.prior is not None:
            val = val + self.prior.value(w)
        return val

    def value_and_gradient(self, w: Array, batch: Batch) -> tuple[Array, Array]:
        """The hot path: one fused pass for (value, gradient)."""
        m = self._margins(w, batch)
        wl = batch.weights * batch.mask
        val = jnp.sum(wl * self.loss.loss(m, batch.labels)) + self.reg.l2_value(w)
        r = wl * self.loss.d1(m, batch.labels)
        grad = self._residual_to_grad(r, batch) + self.reg.l2_gradient(w)
        if self.prior is not None:
            val = val + self.prior.value(w)
            grad = grad + self.prior.gradient(w)
        return val, grad

    def gradient(self, w: Array, batch: Batch) -> Array:
        return self.value_and_gradient(w, batch)[1]

    def hessian_vector(self, w: Array, v: Array, batch: Batch) -> Array:
        """Gauss–Newton/true HVP: X^T diag(wl·d2) X v  (+ λ₂ v).

        Under normalization, (Xv) uses the same margin algebra as the
        forward pass (factors fold into v, shifts become a scalar).
        """
        m = self._margins(w, batch)
        wl = batch.weights * batch.mask
        d2 = wl * self.loss.d2(m, batch.labels)
        v_raw = self.norm.model_to_raw(v)
        xv = batch.x_dot(v_raw)
        if not self.norm.is_identity:
            xv = xv - self.norm.margin_correction(v)
        r = d2 * xv
        out = self._residual_to_grad(r, batch) + self.reg.l2_hessian_vector(v)
        if self.prior is not None:
            out = out + self.prior.hessian_vector(v)
        return out

    def hessian_diagonal(self, w: Array, batch: Batch) -> Array:
        """diag(X^T diag(wl·d2) X) + λ₂ — for SIMPLE variance computation.

        Reference: ``HessianDiagonalAggregator``.  Exact for identity and
        factor-only normalization; with shifts the cross-terms are included
        via the expanded square (x_j − s_j)² = x_j² − 2·s_j·x_j + s_j².
        """
        m = self._margins(w, batch)
        wl = batch.weights * batch.mask
        d2 = wl * self.loss.d2(m, batch.labels)

        prior_diag = (self.prior.hessian_diagonal()
                      if self.prior is not None else 0.0)
        sq_batch = _elementwise_square_batch(batch)
        diag_raw = sq_batch.xt_dot(d2)          # Σ_i d2_i · x_ij²
        if self.norm.is_identity:
            return diag_raw + self.reg.l2_hessian_diagonal(w) + prior_diag

        f = (
            self.norm.factors
            if self.norm.factors is not None
            else jnp.ones_like(w)
        )
        diag = diag_raw * f * f
        if self.norm.shifts is not None:
            s = self.norm.shifts
            cross = batch.xt_dot(d2)            # Σ_i d2_i · x_ij
            total = jnp.sum(d2)                 # Σ_i d2_i
            diag = diag - 2.0 * f * f * s * cross + f * f * s * s * total
        return diag + self.reg.l2_hessian_diagonal(w) + prior_diag

    # ---- conveniences -----------------------------------------------------

    def predict_margins(self, w: Array, batch: Batch) -> Array:
        return self._margins(w, batch)

    def predict_means(self, w: Array, batch: Batch) -> Array:
        return self.loss.mean(self._margins(w, batch))


def _elementwise_square_batch(batch: Batch) -> Batch:
    """Batch with x_ij → x_ij² (same sparsity), for diagonal aggregation."""
    from photon_ml_tpu.data.batch import DenseBatch, SparseBatch

    if isinstance(batch, DenseBatch):
        return batch.replace(x=batch.x * batch.x)
    assert isinstance(batch, SparseBatch)
    cm = batch.colmajor.squared() if batch.colmajor is not None else None
    pair = batch.grr.squared() if batch.grr is not None else None
    return batch.replace(values=batch.values * batch.values, colmajor=cm,
                         grr=pair)


class ObjectiveFns(NamedTuple):
    """Plain-function view (for optimizers that take callables)."""

    value_and_grad: callable
    hvp: callable


def as_fns(obj: GLMObjective, batch: Batch) -> ObjectiveFns:
    return ObjectiveFns(
        value_and_grad=lambda w: obj.value_and_gradient(w, batch),
        hvp=lambda w, v: obj.hessian_vector(w, v, batch),
    )


# ---------------------------------------------------------------------------
# Swept (stacked-coefficient) surface: evaluate L λ-lanes against ONE
# shared batch.  The λ grid's dominant cost is moving the batch through
# the memory system (GRR plans stream at ~30% of HBM roofline; the
# chunked regime pays 6.5 s per full-data pass — PERF.md), so the sweep
# evaluates W [L, dim] with ``vmap(in_axes=(0, None))``: the batch is
# read once and every lane contracts against it.  Per-lane L2 weight
# rides as a [L] array (λ is a traced leaf, so one compiled program
# covers any grid).  GRR-plan batches get a ``lax.map`` lane loop
# instead — the Mosaic kernel has no batching rule, and the data is
# already resident so the loop still reads it from HBM, not the host.
# ---------------------------------------------------------------------------


def _lane_objective(obj: GLMObjective, l2_weight: Array) -> GLMObjective:
    """``obj`` with one lane's (traced scalar) L2 weight installed.

    Only the smooth L2 part varies inside a swept evaluation; per-lane
    L1 is the optimizer's business (OWL-QN), exactly as in the
    single-lane convention (module docstring).
    """
    return obj.replace(reg=obj.reg.replace(l2_weight=l2_weight))


def sweep_value_and_gradient(
    obj: GLMObjective, W: Array, batch: Batch,
    l2_weights: Array | None = None, use_map: bool = False,
) -> tuple[Array, Array]:
    """(W [L, dim], shared batch) → (values [L], gradients [L, dim]).

    ``l2_weights`` [L] installs a per-lane L2 weight (None keeps the
    objective's own, shared across lanes — the chunked inner sweep,
    whose reg is added outside the chunk loop).  ``use_map`` switches
    the lane axis from ``vmap`` to a ``lax.map`` loop (GRR plans /
    shard_mapped objectives, which have no batching rule).
    """
    if l2_weights is None:
        fn = lambda w: obj.value_and_gradient(w, batch)
        xs = W
    else:
        fn = lambda args: _lane_objective(obj, args[1]).value_and_gradient(
            args[0], batch)
        xs = (W, l2_weights)
    if use_map:
        return jax.lax.map(fn, xs)
    return jax.vmap(fn)(xs)


def sweep_value(
    obj: GLMObjective, W: Array, batch: Batch,
    l2_weights: Array | None = None, use_map: bool = False,
) -> Array:
    """Value-only lane sweep (line-search trials): W [L, dim] → [L]."""
    if l2_weights is None:
        fn = lambda w: obj.value(w, batch)
        xs = W
    else:
        fn = lambda args: _lane_objective(obj, args[1]).value(args[0], batch)
        xs = (W, l2_weights)
    if use_map:
        return jax.lax.map(fn, xs)
    return jax.vmap(fn)(xs)
