"""Gaussian prior toward a previous model: incremental-training loss.

Reference counterparts: ``PriorDistribution`` /
``PriorDistributionDiff`` mixins on the loss functions (photon-lib/api
``com.linkedin.photon.ml.function`` [expected paths, mount unavailable —
see SURVEY.md §2.2]): when warm-start training is given a prior model
with coefficient means AND variances, the new fit is regularized toward
the old coefficients with per-coordinate strength 1/σ²— Bayesian
incremental training — instead of (or on top of) plain L2 toward zero.

The penalty added to the objective is

    0.5 · λ_prior · Σ_j (w_j − μ_j)² / σ_j²

with derivatives λ_prior·(w−μ)/σ² (gradient), λ_prior·v/σ² (HVP) and
λ_prior/σ² (Hessian diagonal) — a diagonal quadratic, so it fuses into
the same device program as the data term.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

Array = jax.Array


@struct.dataclass
class GaussianPrior:
    """Diagonal Gaussian prior N(means, diag(variances)) on coefficients."""

    means: Array        # [dim]
    precisions: Array   # [dim] = 1/σ²  (precomputed; σ²>0 enforced upstream)
    weight: Array       # scalar λ_prior (reference incremental weight)

    @staticmethod
    def from_model(
        means: Array, variances: Array, weight: float = 1.0,
        min_variance: float = 1e-12,
    ) -> "GaussianPrior":
        v = jnp.maximum(jnp.asarray(variances, jnp.float32), min_variance)
        return GaussianPrior(
            means=jnp.asarray(means, jnp.float32),
            precisions=1.0 / v,
            weight=jnp.asarray(weight, jnp.float32),
        )

    def value(self, w: Array) -> Array:
        d = w - self.means
        return 0.5 * self.weight * jnp.vdot(d, self.precisions * d)

    def gradient(self, w: Array) -> Array:
        return self.weight * self.precisions * (w - self.means)

    def hessian_vector(self, v: Array) -> Array:
        return self.weight * self.precisions * v

    def hessian_diagonal(self) -> Array:
        return self.weight * self.precisions
