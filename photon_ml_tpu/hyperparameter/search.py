"""Hyperparameter search: rescaling, Expected Improvement, strategies.

Reference counterparts: ``VectorRescaling``, ``ExpectedImprovement``,
``RandomSearch``, ``GaussianProcessSearch`` (photon-lib
``com.linkedin.photon.ml.hyperparameter.search`` [expected paths, mount
unavailable — see SURVEY.md §2.7/§3.5]).

The search space is a box over named parameters, each linear- or
log-scaled into [0, 1] (the reference's rescaling).  ``RandomSearch``
proposes quasi-uniform points; ``GaussianProcessSearch`` fits a GP to
the observation history and proposes the EI-argmax over a random
candidate sweep (the reference samples candidates the same way).
Metrics where smaller is better (RMSE, losses) are negated internally
so the acquisition always maximizes.
"""

from __future__ import annotations

import dataclasses
import enum
import math

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.hyperparameter.gp import fit_gp
from photon_ml_tpu.hyperparameter.kernels import KernelType

Array = jax.Array


class ParamScale(str, enum.Enum):
    LINEAR = "LINEAR"
    LOG = "LOG"


@dataclasses.dataclass
class ParamRange:
    """One tunable dimension (reference search-space JSON entry)."""

    name: str
    low: float
    high: float
    scale: ParamScale = ParamScale.LOG

    def validate(self) -> None:
        if not self.low < self.high:
            raise ValueError(f"{self.name}: low must be < high")
        if self.scale == ParamScale.LOG and self.low <= 0:
            raise ValueError(f"{self.name}: LOG scale needs low > 0")

    def to_unit(self, v: float) -> float:
        if self.scale == ParamScale.LOG:
            return (math.log(v) - math.log(self.low)) / (
                math.log(self.high) - math.log(self.low))
        return (v - self.low) / (self.high - self.low)

    def from_unit(self, u: float) -> float:
        u = min(max(u, 0.0), 1.0)
        if self.scale == ParamScale.LOG:
            return math.exp(
                math.log(self.low)
                + u * (math.log(self.high) - math.log(self.low)))
        return self.low + u * (self.high - self.low)


@dataclasses.dataclass
class SearchSpace:
    """Named box; converts between config dicts and unit vectors."""

    params: list[ParamRange]

    def __post_init__(self):
        for p in self.params:
            p.validate()

    @property
    def dim(self) -> int:
        return len(self.params)

    def to_unit(self, config: dict) -> np.ndarray:
        return np.asarray([p.to_unit(config[p.name]) for p in self.params],
                          np.float32)

    def from_unit(self, u: np.ndarray) -> dict:
        return {p.name: p.from_unit(float(u[i]))
                for i, p in enumerate(self.params)}


def expected_improvement(mean: Array, std: Array, best: Array) -> Array:
    """EI for maximization: E[max(f − best, 0)] under N(mean, std²)."""
    z = (mean - best) / std
    cdf = 0.5 * (1.0 + jax.scipy.special.erf(z / jnp.sqrt(2.0)))
    pdf = jnp.exp(-0.5 * z * z) / jnp.sqrt(2.0 * jnp.pi)
    return (mean - best) * cdf + std * pdf


class RandomSearch:
    """Quasi-uniform proposals (reference ``RandomSearch``)."""

    # Random proposals are independent, so a batched evaluator (the
    # swept-λ GameEstimator) could take the whole trial budget at once
    # — but swept L-BFGS state scales O(m·L·dim) ([m, L, d] curvature
    # buffers), so an unbounded lane count would OOM wide problems
    # (d=10⁶ × L=100 ≈ 8 GB of (s, y) buffers alone).  Default to a
    # bounded batch; callers with headroom raise it via
    # TuningConfig.trial_batch.
    default_batch: int | None = 16

    def __init__(self, space: SearchSpace, seed: int = 0):
        self.space = space
        self._rng = np.random.default_rng(seed)

    def propose(self, history: list) -> dict:
        return self.space.from_unit(self._rng.uniform(size=self.space.dim))

    def propose_batch(self, history: list, q: int) -> list[dict]:
        """q independent proposals (batched trial evaluation)."""
        return [self.propose(history) for _ in range(q)]


class GaussianProcessSearch:
    """GP + EI proposals (reference ``GaussianProcessSearch``).

    ``history`` is a list of (config dict, metric); ``larger_is_better``
    flips loss-like metrics.  Falls back to random proposals until
    ``min_observations`` are available (the reference seeds the GP the
    same way).
    """

    def __init__(
        self,
        space: SearchSpace,
        larger_is_better: bool = True,
        kernel: KernelType = KernelType.MATERN52,
        n_candidates: int = 2048,
        min_observations: int = 3,
        seed: int = 0,
    ):
        self.space = space
        self.larger_is_better = larger_is_better
        self.kernel = kernel
        self.n_candidates = n_candidates
        self.min_observations = min_observations
        self._rng = np.random.default_rng(seed)
        self._random = RandomSearch(space, seed=seed + 1)

    # GP proposals condition on history, so batches stay small (a few
    # points per GP fit) — see ``propose_batch``.
    default_batch: int | None = 4

    def _ei_candidates(self, history: list):
        """One GP fit → (candidates [C, dim], EI [C]) shared by single
        and batched proposal."""
        x = np.stack([self.space.to_unit(cfg) for cfg, _ in history])
        y = np.asarray([m for _, m in history], np.float32)
        if not self.larger_is_better:
            y = -y
        gp = fit_gp(jnp.asarray(x), jnp.asarray(y), kind=self.kernel)
        cands = self._rng.uniform(
            size=(self.n_candidates, self.space.dim)).astype(np.float32)
        # Local refinement around the incumbent (reference slice-sample
        # spirit): half the candidates perturb the best-so-far point.
        best_x = x[int(np.argmax(y))]
        local = np.clip(
            best_x + 0.1 * self._rng.normal(
                size=(self.n_candidates // 2, self.space.dim)),
            0.0, 1.0,
        ).astype(np.float32)
        cands = np.vstack([cands, local])
        mean, std = gp.predict(jnp.asarray(cands))
        ei = expected_improvement(mean, std, jnp.max(jnp.asarray(y)))
        return cands, np.asarray(ei)

    def propose(self, history: list) -> dict:
        if len(history) < self.min_observations:
            return self._random.propose(history)
        cands, ei = self._ei_candidates(history)
        return self.space.from_unit(cands[int(np.argmax(ei))])

    def propose_batch(self, history: list, q: int,
                      min_dist: float = 0.05) -> list[dict]:
        """q proposals from ONE GP fit (batched trial evaluation).

        EI-ranked candidates with a greedy min-distance filter so the
        batch SPREADS over the acquisition surface instead of piling q
        near-duplicates onto the EI argmax (a cheap stand-in for
        constant-liar q-EI: no GP refit between picks, which is the
        point — one fit per round).  Before ``min_observations`` the
        batch is random, seeding the GP."""
        if len(history) < self.min_observations:
            return [self._random.propose(history) for _ in range(q)]
        cands, ei = self._ei_candidates(history)
        order = np.argsort(-ei)
        picked: list[np.ndarray] = []
        for i in order:
            if len(picked) == q:
                break
            c = cands[i]
            if any(np.linalg.norm(c - p) < min_dist for p in picked):
                continue
            picked.append(c)
        # Degenerate surfaces (every candidate inside min_dist of the
        # picks): fill with next-best regardless of spacing.
        for i in order:
            if len(picked) == q:
                break
            c = cands[i]
            if not any(np.array_equal(c, p) for p in picked):
                picked.append(c)
        return [self.space.from_unit(c) for c in picked]
