"""Gaussian-process regression over observed (config, evaluation) pairs.

Reference counterparts: ``GaussianProcessEstimator`` /
``GaussianProcessModel`` (photon-lib
``com.linkedin.photon.ml.hyperparameter.estimators`` [expected paths,
mount unavailable — see SURVEY.md §2.7]).

Exact GP with Cholesky solves — tuning histories are tens of points, so
the O(n³) factorization is trivial; everything is jittable jnp so the
posterior over thousands of candidate points is one fused device
program.  Kernel hyperparameters are chosen by maximizing the log
marginal likelihood over a small multi-start grid (the reference
similarly refits per observation round).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_ml_tpu.hyperparameter.kernels import (
    KernelType,
    kernel_fn,
)

Array = jax.Array


@dataclasses.dataclass
class GaussianProcessModel:
    """Posterior state: predict mean/std at new points."""

    x_train: Array          # [n, d] rescaled observations
    chol: Array             # [n, n] Cholesky of K + σ_n² I
    alpha: Array            # [n] (K + σ_n² I)⁻¹ (y − μ)
    y_mean: Array           # scalar target mean (centering)
    kind: KernelType
    amplitude: float
    lengthscale: float
    noise: float

    def predict(self, x: Array) -> tuple[Array, Array]:
        """Posterior (mean, std) at [m, d] candidate points."""
        k = kernel_fn(self.kind)
        k_star = k(self.x_train, x, self.amplitude, self.lengthscale)
        mean = self.y_mean + k_star.T @ self.alpha
        v = jax.scipy.linalg.solve_triangular(self.chol, k_star, lower=True)
        prior_var = self.amplitude**2
        var = jnp.maximum(prior_var - jnp.sum(v * v, axis=0), 1e-12)
        return mean, jnp.sqrt(var)


def _fit_fixed(x: Array, y: Array, kind: KernelType, amplitude,
               lengthscale, noise):
    k = kernel_fn(kind)
    n = x.shape[0]
    y_mean = jnp.mean(y)
    yc = y - y_mean
    gram = k(x, x, amplitude, lengthscale) + (noise**2 + 1e-8) * jnp.eye(n)
    chol = jnp.linalg.cholesky(gram)
    alpha = jax.scipy.linalg.cho_solve((chol, True), yc)
    # log marginal likelihood (up to constant)
    lml = (-0.5 * jnp.vdot(yc, alpha)
           - jnp.sum(jnp.log(jnp.diagonal(chol)))
           - 0.5 * n * jnp.log(2.0 * jnp.pi))
    return chol, alpha, y_mean, lml


def fit_gp(
    x: Array,
    y: Array,
    kind: KernelType = KernelType.MATERN52,
    lengthscales=(0.1, 0.2, 0.4, 0.8),
    noises=(1e-3, 1e-2, 1e-1),
) -> GaussianProcessModel:
    """Fit by marginal-likelihood model selection over a small grid.

    Amplitude is set to std(y) (empirical-Bayes scaling); lengthscale
    and noise are chosen by LML over the grid — robust at the <100-point
    scale of tuning runs, with no risk of gradient-ascent divergence.
    """
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    amplitude = float(jnp.std(y)) or 1.0

    best = None
    for ls in lengthscales:
        for nz in noises:
            chol, alpha, y_mean, lml = _fit_fixed(
                x, y, kind, amplitude, ls, nz)
            if best is None or float(lml) > best[0]:
                best = (float(lml), chol, alpha, y_mean, ls, nz)
    _, chol, alpha, y_mean, ls, nz = best
    return GaussianProcessModel(
        x_train=x, chol=chol, alpha=alpha, y_mean=y_mean, kind=kind,
        amplitude=amplitude, lengthscale=ls, noise=nz,
    )
