"""Stationary GP kernels: RBF and Matérn-5/2.

Reference counterparts: ``StationaryKernel``, ``RBF``, ``Matern52``
(photon-lib ``com.linkedin.photon.ml.hyperparameter.estimators.kernels``
[expected paths, mount unavailable — see SURVEY.md §2.7]).

Kernels are pure jittable functions over [n, d] point sets in the
rescaled [0, 1]^d search space; hyperparameters (amplitude, per-dim
lengthscales, noise) are explicit arguments so marginal-likelihood
optimization can differentiate through them.
"""

from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp

Array = jax.Array


class KernelType(str, enum.Enum):
    RBF = "RBF"
    MATERN52 = "MATERN52"


@dataclasses.dataclass(frozen=True)
class KernelParams:
    amplitude: float = 1.0       # signal variance σ_f²  (stored as σ_f)
    lengthscale: float = 0.25    # isotropic ℓ in the rescaled space
    noise: float = 1e-4          # observation noise σ_n² (stored as σ_n)


def _sq_dists(x1: Array, x2: Array, lengthscale) -> Array:
    """Pairwise squared distances of ℓ-scaled points: [n1, n2]."""
    a = x1 / lengthscale
    b = x2 / lengthscale
    aa = jnp.sum(a * a, axis=-1)[:, None]
    bb = jnp.sum(b * b, axis=-1)[None, :]
    return jnp.maximum(aa + bb - 2.0 * (a @ b.T), 0.0)


def rbf(x1: Array, x2: Array, amplitude, lengthscale) -> Array:
    r2 = _sq_dists(x1, x2, lengthscale)
    return amplitude**2 * jnp.exp(-0.5 * r2)


def matern52(x1: Array, x2: Array, amplitude, lengthscale) -> Array:
    r2 = _sq_dists(x1, x2, lengthscale)
    r = jnp.sqrt(r2 + 1e-12)
    s5r = jnp.sqrt(5.0) * r
    return amplitude**2 * (1.0 + s5r + 5.0 * r2 / 3.0) * jnp.exp(-s5r)


def kernel_fn(kind: KernelType):
    return rbf if kind == KernelType.RBF else matern52
