"""Hyperparameter tuner entry: iterate (propose → fit → observe).

Reference counterparts: ``HyperparameterTuner`` /
``HyperparameterTunerFactory`` (photon-lib
``com.linkedin.photon.ml.hyperparameter.tuner`` [expected paths, mount
unavailable — see SURVEY.md §2.7/§3.5]): the tuning loop wraps the full
``GameEstimator.fit`` — each trial trains a model with the proposed
configuration and reports the validation metric back to the search.
"""

from __future__ import annotations

import dataclasses
import enum

from photon_ml_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    RandomSearch,
    SearchSpace,
)


class TunerMode(str, enum.Enum):
    RANDOM = "RANDOM"
    BAYESIAN = "BAYESIAN"


@dataclasses.dataclass
class TrialResult:
    config: dict     # parameter name → value
    metric: float
    payload: object  # whatever evaluate_fn returned alongside the metric


class HyperparameterTuner:
    """Drive n trials of ``evaluate_fn`` over a search space.

    ``evaluate_fn(config) → (metric, payload)`` — typically a full GAME
    fit returning (validation metric, FitResult).
    """

    def __init__(
        self,
        space: SearchSpace,
        mode: TunerMode = TunerMode.BAYESIAN,
        larger_is_better: bool = True,
        seed: int = 0,
    ):
        self.space = space
        self.larger_is_better = larger_is_better
        if mode == TunerMode.RANDOM:
            self.search = RandomSearch(space, seed=seed)
        else:
            self.search = GaussianProcessSearch(
                space, larger_is_better=larger_is_better, seed=seed)

    def run(self, evaluate_fn, n_trials: int,
            run_logger=None) -> list[TrialResult]:
        history: list = []
        trials: list[TrialResult] = []
        for t in range(n_trials):
            config = self.search.propose(history)
            metric, payload = evaluate_fn(config)
            history.append((config, metric))
            trials.append(TrialResult(config=config, metric=float(metric),
                                      payload=payload))
            if run_logger is not None:
                run_logger.event("tuning_trial", trial=t, config=config,
                                 metric=float(metric))
        return trials

    def best(self, trials: list[TrialResult]) -> TrialResult:
        key = (max if self.larger_is_better else min)
        return key(trials, key=lambda t: t.metric)
