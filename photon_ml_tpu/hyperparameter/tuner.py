"""Hyperparameter tuner entry: iterate (propose → fit → observe).

Reference counterparts: ``HyperparameterTuner`` /
``HyperparameterTunerFactory`` (photon-lib
``com.linkedin.photon.ml.hyperparameter.tuner`` [expected paths, mount
unavailable — see SURVEY.md §2.7/§3.5]): the tuning loop wraps the full
``GameEstimator.fit`` — each trial trains a model with the proposed
configuration and reports the validation metric back to the search.
"""

from __future__ import annotations

import dataclasses
import enum

from photon_ml_tpu.telemetry import monitor as _mon
from photon_ml_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    RandomSearch,
    SearchSpace,
)


class TunerMode(str, enum.Enum):
    RANDOM = "RANDOM"
    BAYESIAN = "BAYESIAN"


@dataclasses.dataclass
class TrialResult:
    config: dict     # parameter name → value
    metric: float
    payload: object  # whatever evaluate_fn returned alongside the metric


class HyperparameterTuner:
    """Drive n trials of ``evaluate_fn`` over a search space.

    ``evaluate_fn(config) → (metric, payload)`` — typically a full GAME
    fit returning (validation metric, FitResult).
    """

    def __init__(
        self,
        space: SearchSpace,
        mode: TunerMode = TunerMode.BAYESIAN,
        larger_is_better: bool = True,
        seed: int = 0,
    ):
        self.space = space
        self.larger_is_better = larger_is_better
        if mode == TunerMode.RANDOM:
            self.search = RandomSearch(space, seed=seed)
        else:
            self.search = GaussianProcessSearch(
                space, larger_is_better=larger_is_better, seed=seed)

    def run(self, evaluate_fn, n_trials: int,
            run_logger=None) -> list[TrialResult]:
        history: list = []
        trials: list[TrialResult] = []
        for t in range(n_trials):
            config = self.search.propose(history)
            metric, payload = evaluate_fn(config)
            history.append((config, metric))
            trials.append(TrialResult(config=config, metric=float(metric),
                                      payload=payload))
            if run_logger is not None:
                run_logger.event("tuning_trial", trial=t, config=config,
                                 metric=float(metric))
            # Live tuning progress (ISSUE 10): trials done against the
            # budget, ETA from the observed trial rate.
            _mon.progress("tuner", len(trials), n_trials, unit="trials",
                          metric=float(metric))
        return trials

    def run_batched(self, evaluate_batch_fn, n_trials: int,
                    batch_size: int | None = None,
                    run_logger=None, restored=()) -> list[TrialResult]:
        """Drive trials in proposal BATCHES: each round proposes q
        configs (one GP fit / spread-EI pick for Bayesian, plain draws
        for random — ``propose_batch``) and hands the whole list to
        ``evaluate_batch_fn(configs) → [(metric, payload), ...]``, so a
        batched evaluator (the swept-λ ``GameEstimator``) trains the
        round as one fit.  ``batch_size`` None uses the strategy's
        ``default_batch`` (random: 16 — bounded, since swept solver
        state scales with lane count; GP: small rounds so later
        proposals condition on earlier observations).

        ``restored``: ``(config, metric, payload)`` triples from a
        checkpoint (ISSUE 9) — seeded into the observation history and
        the returned trials, so a resumed search proposes EXACTLY the
        rounds the interrupted run would have, without re-evaluating
        the completed ones."""
        history: list = []
        trials: list[TrialResult] = []
        for config, metric, payload in restored:
            history.append((config, metric))
            trials.append(TrialResult(config=dict(config),
                                      metric=float(metric),
                                      payload=payload))
        if trials and run_logger is not None:
            run_logger.event("tuning_restored", trials=len(trials))
        # Replay the restored rounds' PROPOSALS (discarding the
        # configs): the strategies draw from stateful RNGs that restart
        # at the seed in a new process, so without the replay a resumed
        # random search re-proposes round 0's configs instead of
        # continuing the stream.  Each replayed round proposes against
        # the history prefix it originally saw, which reproduces the
        # interrupted run's draws exactly (proposals are deterministic
        # given seed + history) and leaves every RNG where it left off.
        pos = 0
        while pos < len(trials) and pos < n_trials:
            q = batch_size or getattr(self.search, "default_batch",
                                      None) or (n_trials - pos)
            q = min(q, n_trials - pos)
            replayed = self.search.propose_batch(history[:pos], q)
            for cfg, t in zip(replayed, trials[pos:pos + q]):
                if cfg != t.config and run_logger is not None:
                    run_logger.event("tuning_replay_divergence",
                                     trial=pos, proposed=cfg,
                                     restored=t.config)
            pos += q
        while len(trials) < n_trials:
            q = batch_size or getattr(self.search, "default_batch",
                                      None) or (n_trials - len(trials))
            q = min(q, n_trials - len(trials))
            configs = self.search.propose_batch(history, q)
            outs = evaluate_batch_fn(configs)
            for config, (metric, payload) in zip(configs, outs):
                history.append((config, metric))
                trials.append(TrialResult(
                    config=config, metric=float(metric), payload=payload))
                if run_logger is not None:
                    run_logger.event(
                        "tuning_trial", trial=len(trials) - 1,
                        config=config, metric=float(metric))
            _mon.progress("tuner", len(trials), n_trials, unit="trials")
        return trials

    def best(self, trials: list[TrialResult]) -> TrialResult:
        key = (max if self.larger_is_better else min)
        return key(trials, key=lambda t: t.metric)
