"""Bayesian hyperparameter tuning: GP regression + Expected Improvement.

Reference: photon-lib ``com.linkedin.photon.ml.hyperparameter``
(SURVEY.md §2.7 — expected paths, mount unavailable).
"""

from photon_ml_tpu.hyperparameter.gp import GaussianProcessModel, fit_gp
from photon_ml_tpu.hyperparameter.kernels import KernelType
from photon_ml_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    ParamRange,
    ParamScale,
    RandomSearch,
    SearchSpace,
    expected_improvement,
)
from photon_ml_tpu.hyperparameter.tuner import (
    HyperparameterTuner,
    TrialResult,
    TunerMode,
)

__all__ = [
    "GaussianProcessModel", "fit_gp", "KernelType",
    "GaussianProcessSearch", "ParamRange", "ParamScale", "RandomSearch",
    "SearchSpace", "expected_improvement",
    "HyperparameterTuner", "TrialResult", "TunerMode",
]
