"""On-disk GRR plan cache: serialize compiled plans, keyed by content.

A compiled plan (``GrrPair`` / ``GrrDirection`` / ``GrrRangeSplit`` /
the sharded builder's list of pairs) is a pure function of the ELL
arrays, the table width, and the plan-affecting build options — so the
cache key is exactly that: a content fingerprint of (cols, vals, dim)
× a config key × the planner version.  Loading a hit replaces the
whole host build (the 123 s measured at the bench shape) with one
``np.load`` + device transfer.

Format: one uncompressed ``.npz`` per plan (arrays dominate — i8 route
planes and f32 value streams compress poorly and slowly) holding every
array leaf under a tree-path key, plus a JSON manifest (``__meta__``)
that records the node structure and static fields.  Writes go to a
``.tmp`` sibling and ``os.replace`` into place, so readers never see a
partial file; any load failure (truncated zip, missing keys, manifest
drift) returns None and the caller rebuilds — a cache must never be
able to make a run fail.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile

import numpy as np

logger = logging.getLogger(__name__)

# Serialization-format version: bump when the on-disk layout changes.
FORMAT_VERSION = 1

_DIR_ARRAYS = ("g1", "g2", "g3", "vals", "gw_of_st", "ow_of_st",
               "first_of_ow", "spill_idx", "spill_seg", "spill_val")
_DIR_STATIC = ("table_len", "n_segments", "cap", "n_gw", "n_ow",
               "dense_grid")


# ---------------------------------------------------------------------------
# Keys
# ---------------------------------------------------------------------------


def dataset_fingerprint(cols, vals, dim: int, extra: tuple = ()) -> str:
    """Content hash of the exact plan inputs.

    Hashes raw bytes (blake2b streams ~1 GB/s — sub-second at the bench
    shape, negligible against the build it replaces); shapes and dtypes
    are folded in so a reshape/retype can't collide.  ``extra`` lets
    callers fold in more arrays (per-shard inputs)."""
    h = hashlib.blake2b(digest_size=16)
    for a in (cols, vals) + tuple(extra):
        a = np.ascontiguousarray(a)
        h.update(str((a.shape, a.dtype.str)).encode())
        h.update(a.tobytes())
    h.update(str(int(dim)).encode())
    return h.hexdigest()


def plan_config_key(**options) -> str:
    """Hash of the plan-affecting build options (None-valued options
    included: the auto heuristics ARE part of plan semantics)."""
    blob = json.dumps({k: options[k] for k in sorted(options)},
                      sort_keys=True, default=str)
    return hashlib.blake2b(blob.encode(), digest_size=6).hexdigest()


def plan_cache_path(cache_dir: str, fingerprint: str,
                    config_key: str) -> str:
    """File path for a (dataset, config) plan under ``cache_dir``.

    The planner/builder version rides in the NAME (not the manifest) so
    a version bump is a clean miss — stale entries are never opened."""
    from photon_ml_tpu.data.grr import PLANNER_VERSION

    return os.path.join(
        cache_dir, "plans",
        f"grr-{fingerprint}-{config_key}"
        f"-v{FORMAT_VERSION}.{PLANNER_VERSION}.npz")


# ---------------------------------------------------------------------------
# Encode / decode
# ---------------------------------------------------------------------------


def _encode_node(node, prefix: str, arrays: dict):
    """Plan node → manifest fragment; array leaves land in ``arrays``
    as host numpy under tree-path keys."""
    from photon_ml_tpu.data.grr import GrrDirection, GrrPair, GrrRangeSplit

    if node is None:
        return None
    if isinstance(node, GrrDirection):
        for f in _DIR_ARRAYS:
            arrays[prefix + f] = np.asarray(getattr(node, f))
        meta = {"kind": "dir"}
        meta.update({f: getattr(node, f) for f in _DIR_STATIC})
        meta["overflow"] = _encode_node(node.overflow, prefix + "o.",
                                        arrays)
        return meta
    if isinstance(node, GrrRangeSplit):
        return {
            "kind": "split",
            "bounds": list(node.bounds),
            "table_len": node.table_len,
            "n_segments": node.n_segments,
            "parts": [_encode_node(p, f"{prefix}p{i}.", arrays)
                      for i, p in enumerate(node.parts)],
        }
    if isinstance(node, GrrPair):
        arrays[prefix + "hot_ids"] = np.asarray(node.hot_ids)
        arrays[prefix + "x_hot"] = np.asarray(node.x_hot)
        if node.mid_ids is not None:
            arrays[prefix + "mid_ids"] = np.asarray(node.mid_ids)
        return {
            "kind": "pair",
            "row": _encode_node(node.row_dir, prefix + "r.", arrays),
            "col": _encode_node(node.col_dir, prefix + "c.", arrays),
            "mid": _encode_node(node.col_mid, prefix + "m.", arrays),
            "has_mid_ids": node.mid_ids is not None,
        }
    raise TypeError(f"cannot serialize plan node {type(node)!r}")


def _decode_node(meta, prefix: str, arrays, place=None):
    """``arrays`` is dict-like and read LAZILY (an open NpzFile during
    load) — with ``place`` (e.g. ``jax.device_put``) each direction is
    handed off the moment its arrays are decoded, so the async
    host→device transfer of one direction overlaps the disk read of
    the next.  The overflow chain rides inside its top-level direction
    (placed as one subtree)."""
    from photon_ml_tpu.data.grr import GrrDirection, GrrPair, GrrRangeSplit

    if meta is None:
        return None
    kind = meta["kind"]
    if kind == "dir":
        kw = {f: arrays[prefix + f] for f in _DIR_ARRAYS}
        kw.update({f: meta[f] for f in _DIR_STATIC})
        kw["overflow"] = _decode_node(meta["overflow"], prefix + "o.",
                                      arrays)
        d = GrrDirection(**kw)
        return place(d) if place is not None else d
    if kind == "split":
        return GrrRangeSplit(
            parts=tuple(_decode_node(p, f"{prefix}p{i}.", arrays, place)
                        for i, p in enumerate(meta["parts"])),
            bounds=tuple(meta["bounds"]),
            table_len=meta["table_len"],
            n_segments=meta["n_segments"],
        )
    if kind == "pair":
        return GrrPair(
            row_dir=_decode_node(meta["row"], prefix + "r.", arrays,
                                 place),
            col_dir=_decode_node(meta["col"], prefix + "c.", arrays,
                                 place),
            hot_ids=arrays[prefix + "hot_ids"],
            x_hot=arrays[prefix + "x_hot"],
            mid_ids=(arrays[prefix + "mid_ids"]
                     if meta["has_mid_ids"] else None),
            col_mid=_decode_node(meta["mid"], prefix + "m.", arrays,
                                 place),
        )
    raise ValueError(f"unknown plan node kind {kind!r}")


# ---------------------------------------------------------------------------
# Save / load
# ---------------------------------------------------------------------------


def atomic_savez(path: str, meta, arrays: dict) -> None:
    """Write one uncompressed ``.npz`` holding ``arrays`` plus a JSON
    ``__meta__`` member, atomically (tmp sibling + ``os.replace``) —
    the shared write primitive of the plan cache AND the disk-backed
    chunk store (``data.chunk_store``): readers never see a partial
    file, and a crashed writer leaves at most a ``.tmp`` orphan."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __meta__=np.frombuffer(
                json.dumps(meta).encode(), np.uint8), **arrays)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:  # photon-lint: disable=swallowed-exception (tmp-orphan cleanup; the primary write error re-raises below)
            pass
        raise


def save_plan(path: str, plan) -> None:
    """Serialize a plan (or list of plans — the sharded builder's
    output) to ``path`` atomically.  Leaves must be host-reachable
    (numpy or device arrays; device leaves are pulled back — the
    in-repo builders save from their host copies, so no pull happens
    on the production path)."""
    arrays: dict = {}
    if isinstance(plan, (list, tuple)):
        meta = {"kind": "list",
                "items": [_encode_node(p, f"s{i}.", arrays)
                          for i, p in enumerate(plan)]}
    else:
        meta = _encode_node(plan, "", arrays)
    atomic_savez(path, meta, arrays)


def load_plan(path: str, place=None):
    """Deserialize a plan from ``path``, or None when the file is
    absent, truncated, or structurally stale — every failure mode
    means "rebuild", never "crash".

    Without ``place``, leaves are HOST numpy (the sharded builders'
    contract).  With ``place`` (e.g. ``jax.device_put``), each
    direction is placed AS IT IS DECODED, pipelining the disk read of
    later directions under the async transfer of earlier ones — the
    warm path's analog of the cold build's transfer/build overlap."""
    if not os.path.exists(path):
        return None
    z = None
    try:
        z = np.load(path, allow_pickle=False)
        meta = json.loads(bytes(z["__meta__"]).decode())
        if isinstance(meta, dict) and meta.get("kind") == "list":
            return [_decode_node(m, f"s{i}.", z, place)
                    for i, m in enumerate(meta["items"])]
        return _decode_node(meta, "", z, place)
    except Exception as e:  # corrupt/partial/stale: rebuild
        logger.warning("plan cache: unreadable entry %s (%r); rebuilding",
                       path, e)
        return None
    finally:
        if z is not None:
            z.close()
