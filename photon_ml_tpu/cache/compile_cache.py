"""JAX persistent compilation cache wiring.

The scale run pays ~1000 s of one-time compile+transfer and the
scoring sweep another 1037 s (PERF.md) — both re-paid on every run for
identical program shapes.  JAX ships a persistent compilation cache
(``jax_compilation_cache_dir``) that keys compiled executables by
(HLO, compile options, backend); enabling it makes those costs
once-per-program-shape instead of once-per-run.

``enable_compilation_cache`` is the single switch the drivers, the
estimator, and the bench all call.  It is idempotent, resolves its
default from ``PHOTON_ML_TPU_COMPILE_CACHE``, and degrades to a no-op
on JAX builds without the knobs — a cache must never be able to make a
run fail.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

ENV_VAR = "PHOTON_ML_TPU_COMPILE_CACHE"

_enabled_dir: str | None = None


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    ``cache_dir`` None falls back to ``$PHOTON_ML_TPU_COMPILE_CACHE``;
    if that is unset too, this is a no-op (returns None).  Compiled
    programs land under ``<cache_dir>/xla``.  The min-compile-time
    floor is dropped to 0.5 s so the solver/scoring programs (seconds
    to minutes of XLA time each) all persist without caching the
    dispatch-layer trivia.  Returns the directory in effect."""
    global _enabled_dir
    from photon_ml_tpu.config import read_env

    cache_dir = cache_dir or read_env(ENV_VAR)
    if not cache_dir:
        return None
    xla_dir = os.path.join(os.path.abspath(cache_dir), "xla")
    if _enabled_dir == xla_dir:
        return xla_dir
    try:
        import jax

        os.makedirs(xla_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", xla_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        # Some jax builds latch the cache state at the FIRST compile and
        # ignore later config changes; dropping the latched state makes
        # the next compile re-read the directory we just set.  Clears
        # only the persistent-cache handle, not the in-process jit cache.
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )

            _cc.reset_cache()
        except Exception:  # photon-lint: disable=swallowed-exception (older jax without reset_cache; stale in-process handle is harmless)
            pass
    except Exception as e:  # older jax / read-only fs: run uncached
        logger.warning(
            "persistent compilation cache unavailable (%r); compiles "
            "will not persist across runs", e)
        return None
    _enabled_dir = xla_dir
    logger.info("persistent compilation cache at %s", xla_dir)
    return xla_dir
