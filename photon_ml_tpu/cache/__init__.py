"""Persistent artifact cache: pay the cold path once per dataset.

The framework's dominant fixed costs are both *derivable* artifacts:

- **GRR plan ETL** — the compiled gather-route-reduce plan
  (``data.grr``) is a pure function of (cols, vals, dim) × the plan
  configuration; measured 123 s at the bench shape on a 1-core host
  (BENCH_r05), ~2 minutes of re-derivation per run for bytes that never
  change between runs.
- **XLA compilation** — the scale run pays ~1000 s of one-time
  compile+transfer and the scoring sweep another 1037 s (PERF.md),
  again identical across runs for identical program shapes.

Snap ML's 10×-over-Spark wins come largely from keeping data and
derived structures resident across iterations (PAPERS.md); this package
applies the same argument across *runs*: the second run of any workload
loads its plan from disk (``plan_cache``) and replays compiled XLA
programs from JAX's persistent compilation cache (``compile_cache``)
instead of re-deriving either.

Layout on disk (one directory, safe to delete wholesale)::

    <cache_dir>/
      plans/grr-<fp16>-<cfg12>-v<F>.<P>.npz   # serialized plans
      xla/...                                  # jax persistent cache

Keying (see ``plan_cache``): ``fp16`` is a content hash of the exact
ELL arrays + table width, ``cfg12`` hashes the plan-affecting build
options, ``F``/``P`` are the serialization-format and planner/builder
versions — any change to planner semantics bumps
``data.grr.PLANNER_VERSION`` and orphans old entries (they are
harmlessly ignored).  Corrupt or truncated files fall back to a fresh
build (tested).
"""

from photon_ml_tpu.cache.compile_cache import enable_compilation_cache
from photon_ml_tpu.cache.plan_cache import (
    atomic_savez,
    dataset_fingerprint,
    load_plan,
    plan_cache_path,
    plan_config_key,
    save_plan,
)

__all__ = [
    "atomic_savez",
    "dataset_fingerprint",
    "enable_compilation_cache",
    "load_plan",
    "plan_cache_path",
    "plan_config_key",
    "save_plan",
]
