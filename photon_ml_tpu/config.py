"""Typed configuration: the rebuild of the reference's Param plumbing.

Reference counterparts: the spark.ml ``Param``/``ParamMap`` objects +
Scopt CLI parsers on each driver (``GameTrainingDriver`` ~40 params,
``ScoptGameTrainingParametersParser``, coordinate-configuration strings
— photon-client ``com.linkedin.photon.ml.cli.game`` [expected paths,
mount unavailable — see SURVEY.md §2.8/§5.6]).

Design: one validated dataclass per concern, JSON in/out (the reference
passes coordinate configs as structured CLI strings; JSON is the honest
modern equivalent).  Validation happens in ``__post_init__``/
``validate`` — the reference's ``ParamValidators`` role.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
from typing import Any

from photon_ml_tpu.data.normalization import NormalizationType
from photon_ml_tpu.evaluation.evaluators import EvaluatorType
from photon_ml_tpu.models.glm import TaskType
from photon_ml_tpu.ops.regularization import RegularizationType
from photon_ml_tpu.optim.base import OptimizerType
from photon_ml_tpu.optim.variance import VarianceComputationType


# ---------------------------------------------------------------------------
# Sanctioned environment fallbacks.  Every env knob the package reads is
# registered HERE with its meaning, and read through ``read_env`` —
# scattered raw ``os.environ`` reads are invisible configuration, and
# the photon-lint ``env-read`` rule rejects them anywhere else.
# ---------------------------------------------------------------------------

SANCTIONED_ENV = {
    "PHOTON_ML_TPU_PLAN_CACHE": (
        "default on-disk GRR plan cache dir (data.grr cache_dir=None)"),
    "PHOTON_ML_TPU_COMPILE_CACHE": (
        "default persistent XLA compilation cache dir (cache"
        ".compile_cache)"),
    "PHOTON_ML_TPU_SPILL_DIR": (
        "default chunk-store spill dir (data.chunk_store"
        ".resolve_spill_dir)"),
    "PHOTON_ML_TPU_NATIVE": (
        "'0' forces the numpy ETL fallbacks (native bindings disabled)"),
    "PHOTON_ML_TPU_GRR": (
        "'0' forces the XLA fallback contraction off the Pallas kernel"),
    "PHOTON_ML_TPU_BENCH_CACHE": (
        "bench.py artifact cache dir override"),
    "JAX_COORDINATOR_ADDRESS": (
        "jax.distributed coordinator (multi-host init, training driver)"),
    "JAX_NUM_PROCESSES": "jax.distributed process count",
    "JAX_PROCESS_ID": "jax.distributed process id",
    "PHOTON_FLEET_NUM_HOSTS": (
        "local-fleet host count (parallel.fleet tcp transport — the "
        "fallback when jaxlib has no multiprocess CPU collectives)"),
    "PHOTON_FLEET_HOST_ID": "local-fleet host id (parallel.fleet)",
    "PHOTON_FLEET_COORDINATOR": (
        "local-fleet reduce coordinator host:port (parallel.fleet)"),
}


def read_env(name: str, default: str | None = None) -> str | None:
    """The one sanctioned ``os.environ`` read.

    Raises ``KeyError`` for an unregistered name — adding an env knob
    means registering it in ``SANCTIONED_ENV`` (with its meaning), so
    ``python -m photon_ml_tpu.analysis`` plus this registry is a
    complete inventory of the package's environment surface."""
    if name not in SANCTIONED_ENV:
        raise KeyError(
            f"env var {name!r} is not in config.SANCTIONED_ENV; "
            "register it (with a description) before reading it")
    return os.environ.get(name, default)


def _validate_monitor(cfg) -> None:
    """Shared live-monitoring knob validation (ISSUE 10) — both run
    configs carry the same monitor/monitor_every_s/status_port trio."""
    if cfg.monitor not in ("off", "on"):
        raise ValueError("monitor must be off|on")
    if cfg.monitor_every_s <= 0:
        raise ValueError("monitor_every_s must be positive")
    if cfg.status_port is not None and not (
            0 <= cfg.status_port <= 65535):
        raise ValueError("status_port must be in [0, 65535] "
                         "(0 = ephemeral)")


class CoordinateKind(str, enum.Enum):
    FIXED_EFFECT = "FIXED_EFFECT"
    RANDOM_EFFECT = "RANDOM_EFFECT"


@dataclasses.dataclass
class OptimizerSettings:
    """Per-coordinate optimizer configuration (reference
    ``FixedEffectOptimizationConfiguration`` /
    ``RandomEffectOptimizationConfiguration``)."""

    optimizer: OptimizerType = OptimizerType.LBFGS
    max_iters: int = 100
    tolerance: float = 1e-6
    regularization: RegularizationType = RegularizationType.L2
    reg_weight: float = 1.0
    elastic_net_alpha: float = 0.5  # only for ELASTIC_NET
    variance_type: VarianceComputationType = VarianceComputationType.NONE
    # Record per-solver-iteration (value, ‖g‖) history (reference
    # OptimizationStatesTracker, SURVEY §2.1/§5.5); the trace lands in
    # the run log's cd_coordinate events.  Costs two [max_iters+1]
    # arrays per solve.
    track_states: bool = False

    def validate(self) -> None:
        # Coerce a raw-string variance_type to the enum ONCE, loudly
        # rejecting typos — downstream checks (chunked FULL-variance
        # guard, compute_variances dispatch) then compare enums, and an
        # unknown string can't silently fall through to full_variances
        # (review finding).
        if not isinstance(self.variance_type, VarianceComputationType):
            self.variance_type = VarianceComputationType(
                str(self.variance_type).upper())
        if self.max_iters <= 0:
            raise ValueError("max_iters must be positive")
        if self.tolerance <= 0:
            raise ValueError("tolerance must be positive")
        if self.reg_weight < 0:
            raise ValueError("reg_weight must be non-negative")
        if not 0.0 <= self.elastic_net_alpha <= 1.0:
            raise ValueError("elastic_net_alpha must be in [0, 1]")
        if (self.optimizer == OptimizerType.TRON
                and self.regularization in (RegularizationType.L1,
                                            RegularizationType.ELASTIC_NET)):
            raise ValueError("TRON cannot handle L1/elastic-net; use LBFGS")


@dataclasses.dataclass
class CoordinateConfig:
    """One GAME coordinate (reference coordinate-configuration params)."""

    name: str
    kind: CoordinateKind
    feature_shard: str
    entity_key: str | None = None          # RANDOM_EFFECT only
    optimizer: OptimizerSettings = dataclasses.field(
        default_factory=OptimizerSettings
    )
    down_sampling_rate: float | None = None  # FIXED_EFFECT only

    def validate(self) -> None:
        self.optimizer.validate()
        if self.kind == CoordinateKind.RANDOM_EFFECT and not self.entity_key:
            raise ValueError(
                f"random-effect coordinate '{self.name}' needs entity_key"
            )
        if self.down_sampling_rate is not None:
            if self.kind != CoordinateKind.FIXED_EFFECT:
                raise ValueError("down-sampling applies to fixed effects")
            if not 0.0 < self.down_sampling_rate <= 1.0:
                raise ValueError("down_sampling_rate must be in (0, 1]")


@dataclasses.dataclass
class TuningConfig:
    """Hyperparameter-tuning run settings (reference tuning params +
    search-space JSON, SURVEY §2.7)."""

    n_trials: int = 10
    mode: str = "BAYESIAN"                 # BAYESIAN | RANDOM
    # coordinate name → {"low": float, "high": float, "scale": "LOG"|"LINEAR"}
    reg_weight_ranges: dict[str, dict] = dataclasses.field(
        default_factory=dict
    )
    seed: int = 0
    # Trials proposed (and, when the workload is swept-eligible,
    # TRAINED) per batch: the batched λ-sweep evaluates a whole
    # proposal round as one fit, amortizing the data stream across the
    # round's lanes.  None = strategy default (RANDOM: 16 at a time —
    # swept solver state is O(m·L·dim), so lanes stay bounded;
    # BAYESIAN: small rounds so later proposals condition on earlier
    # observations).
    trial_batch: int | None = None

    def validate(self) -> None:
        if self.n_trials <= 0:
            raise ValueError("n_trials must be positive")
        if self.mode not in ("BAYESIAN", "RANDOM"):
            raise ValueError("tuning mode must be BAYESIAN or RANDOM")
        if self.trial_batch is not None and self.trial_batch <= 0:
            raise ValueError("trial_batch must be positive when set")
        if not self.reg_weight_ranges:
            raise ValueError("tuning needs reg_weight_ranges")
        for name, r in self.reg_weight_ranges.items():
            if "low" not in r or "high" not in r:
                raise ValueError(f"range for '{name}' needs low and high")


@dataclasses.dataclass
class TrainingConfig:
    """Full training-run configuration (reference ``GameTrainingDriver``
    params; SURVEY §2.8)."""

    task_type: TaskType
    coordinates: list[CoordinateConfig]
    update_sequence: list[str]
    input_path: str = ""
    input_format: str = "auto"             # auto | jsonl | libsvm
    validation_path: str | None = None
    validation_fraction: float = 0.0       # split from input if no file
    output_dir: str = "output"
    index_dir: str | None = None           # prebuilt index maps (else scan)
    dense_feature_shards: list[str] = dataclasses.field(default_factory=list)
    n_iterations: int = 1
    normalization: NormalizationType = NormalizationType.NONE
    evaluators: list[EvaluatorType] = dataclasses.field(
        default_factory=lambda: [EvaluatorType.AUC]
    )
    # Hyperparameter grid: per-coordinate reg-weight lists, cartesian over
    # coordinates (reference GameOptimizationConfiguration grid).
    reg_weight_grid: dict[str, list[float]] = dataclasses.field(
        default_factory=dict
    )
    # Bayesian/random tuning over reg weights (replaces the grid when set).
    tuning: TuningConfig | None = None
    model_output_mode: str = "BEST"        # ALL | BEST | EXPLICIT
    warm_start_model_dir: str | None = None
    locked_coordinates: list[str] = dataclasses.field(default_factory=list)
    # Incremental training: regularize toward the warm-start model's
    # coefficients with strength prior_weight/σ² when it has variances
    # (reference PriorDistribution semantics).
    use_warm_start_as_prior: bool = False
    prior_weight: float = 1.0
    checkpoint_dir: str | None = None      # per-CD-iteration checkpoints
    resume: bool = False                   # resume from latest checkpoint
    # Checkpoint cadence (reliability.checkpoint, ISSUE 9):
    # checkpoint_every_sweeps gates the CD sweep-boundary snapshot
    # (coefficients + score planes + streamed-RE retirement state; the
    # final sweep always snapshots).  checkpoint_every_solver_iters > 0
    # additionally snapshots the streaming L-BFGS/OWL-QN loop state
    # (coefficients, (s,y,ρ) memory, swept lane buffers) every N solver
    # iterations AND the CD position at every coordinate boundary, so a
    # SIGKILL mid-solve resumes mid-solve; 0 keeps sweep-boundary-only
    # checkpoints (the pre-round-14 behavior).
    checkpoint_every_sweeps: int = 1
    checkpoint_every_solver_iters: int = 0
    intercept: bool = True
    seed: int = 0
    # Score the validation set with every evaluator after each CD sweep
    # (reference CoordinateDescent behavior, SURVEY §3.1); the trace
    # lands in FitResult.validation_history + run-log cd_validation
    # events.  Costs one validation transform per sweep.
    validate_per_iteration: bool = True
    # Sparse fixed-effect batch layout: AUTO picks the GRR compiled plan
    # (data/grr.py — the fast TPU path) on TPU backends and plain ELL
    # elsewhere; GRR/COLMAJOR/ELL force a specific layout.
    sparse_layout: str = "AUTO"
    # Device-mesh training (reference: the Spark cluster; SURVEY §3.1):
    # when set, fixed-effect batches are example-sharded over an
    # n_devices data mesh with the psum-reduced objective, and
    # random-effect bucket blocks are entity-sharded (strategy #2).
    # None = single device.
    n_devices: int | None = None
    # Chunk-accumulated (beyond-HBM-residency) fixed-effect training
    # (reference: Spark streams splits through executors, SURVEY §1
    # L1/§5.8; see data/chunked_batch.py): when set, sparse fixed
    # effects are compiled into ceil(n/chunk_rows) congruent chunk
    # batches streamed through HBM per objective evaluation, solved by
    # the host-driven streaming L-BFGS.  Composes with n_devices
    # (chunks × shards).  chunk_layout picks the per-chunk layout: AUTO
    # = GRR on TPU (kernel-speed steps, ~1.6 GB/10⁶ examples streamed)
    # else ELL (8 bytes/nnz — when transfer dominates).
    # chunk_max_resident chunks stay live in HBM across evaluations
    # (set ≥ n_chunks when the dataset fits; transfer then happens
    # once).
    chunk_rows: int | None = None
    chunk_layout: str = "AUTO"
    chunk_max_resident: int = 1
    # Out-of-core chunk store (data/chunk_store.py): spill_dir (default
    # $PHOTON_ML_TPU_SPILL_DIR; None = chunks stay host-resident)
    # activates the disk tier — chunk batches spill to atomic
    # content-keyed .npz files at build time, at most host_max_resident
    # decoded chunks stay live in host RAM (memory-mapped, LRU), and a
    # background prefetch thread overlaps disk read → host staging →
    # async device_put of chunks i+1..i+prefetch_depth under chunk i's
    # device compute.  Host RSS is then bounded by the WINDOW and the
    # trainable size by disk; spilled files double as a persistent
    # warm-ETL artifact (same data + config ⇒ the chunk compile is
    # skipped on the next run).  prefetch_depth=0 disables the thread
    # (chunks load synchronously from the store).
    spill_dir: str | None = None
    host_max_resident: int = 2
    prefetch_depth: int = 2
    # Out-of-core random-effect training (game/coordinates.py
    # StreamedRandomEffectCoordinate, ISSUE 5): when set, every
    # random-effect coordinate's entity blocks are split into
    # fixed-shape chunks of re_chunk_entities entities per size bucket,
    # spilled through the chunk store (same spill_dir /
    # host_max_resident window / prefetch_depth pipeline as chunked
    # fixed effects), and solved chunk-by-chunk by the vmapped masked
    # while_loop — HBM/host residency is bounded by the window instead
    # of the entity count.  Requires spill_dir (or
    # $PHOTON_ML_TPU_SPILL_DIR).  With a mesh (n_devices) the chunk
    # size rounds up to the device grid and every chunk entity-shards.
    re_chunk_entities: int | None = None
    # Converged-entity retirement (streamed REs only): between CD
    # sweeps, entities whose coefficients AND offsets moved less than
    # the solver tolerance are frozen (scores stay folded into totals)
    # and later sweeps solve only the active set; a retired entity
    # wakes if its offsets drift past the tolerance, so the final model
    # stays within solver tolerance of the retirement-off fit.
    re_retirement: bool = True
    # Fused CD super-sweep (game/fused_sweep.py, ISSUE 11): when true,
    # each coordinate-descent cycle is ONE streamed store pass that
    # accumulates the fixed effect's loss/grad/Hessian-diagonal
    # partials AND every random effect's per-entity statistics, then
    # solves all coordinates against cycle-START offsets (Jacobi
    # staleness) — ~1 data pass per cycle instead of C coordinates ×
    # solver iterations.  Per-cycle progress is one damped Newton step
    # per coordinate, so fused runs want MORE (cheap) cycles
    # (n_iterations) than per-coordinate runs; both converge to the
    # same block-stationary point.  Requires chunk_rows (the fixed
    # effect's chunk grid is the master cycle grid), exactly one
    # fixed-effect coordinate, smooth regularization (NONE/L2) on every
    # coordinate, no locked coordinates, and single-device execution.
    cd_fused: bool = False
    # Warm-path artifact caches (photon_ml_tpu.cache): plan_cache_dir
    # persists compiled GRR plans keyed by dataset fingerprint ×
    # plan-config × planner version, so the second run of a workload
    # skips the plan ETL (measured 123 s at the bench shape);
    # compilation_cache_dir points JAX's persistent compilation cache
    # at disk, so the ~1000 s scale-run compile and the 1037 s scoring
    # compile are paid once per program shape.  Both may also be set
    # via PHOTON_ML_TPU_PLAN_CACHE / PHOTON_ML_TPU_COMPILE_CACHE; the
    # same directory can serve both (plans/ and xla/ subtrees).
    plan_cache_dir: str | None = None
    compilation_cache_dir: str | None = None
    # When set, the driver's fit phase runs under jax.profiler.trace
    # and a TensorBoard/XProf device trace is written here (SURVEY §5.1).
    profile_dir: str | None = None
    # Pipeline telemetry (photon_ml_tpu.telemetry, ISSUE 7):
    # "off" (default) = the no-op singleton — zero events, zero extra
    # compiles, no measurable pass-time overhead; "metrics" = counters/
    # gauges/histograms + per-name span duration stats (the
    # telemetry_summary event); "trace" = metrics plus full span
    # retention, per-span run-log events, and a Chrome trace-event
    # trace.json (Perfetto-loadable) in telemetry_dir.  telemetry_dir
    # defaults to output_dir.  Analyze with
    # `python -m photon_ml_tpu.telemetry report <run_log.jsonl>`.
    telemetry: str = "off"
    telemetry_dir: str | None = None
    # Live run monitoring (photon_ml_tpu.telemetry.monitor, ISSUE 10):
    # "on" emits cadence-throttled `progress` events (phase, units
    # done/total, rolling throughput, ETA) from the CD loop, streaming
    # solvers, streamed-RE sweeps, and the tuner into the run log, and
    # evaluates the online anomaly rules (diverging loss, throughput
    # collapse, retry storms, ...) at the same cadence, emitting
    # structured `alert` events.  Follow live with
    # `python -m photon_ml_tpu.telemetry watch <run_log.jsonl>`.
    # "off" (default) is the no-op singleton: zero events, zero extra
    # compiles, no status thread.  monitor_every_s is the snapshot
    # cadence; status_port (0 = ephemeral) additionally serves
    # GET /status (JSON) and GET /metrics (Prometheus text) from a
    # localhost stdlib http.server thread — setting it implies
    # monitor="on".
    monitor: str = "off"
    monitor_every_s: float = 2.0
    status_port: int | None = None
    # Multi-host scale-out (SURVEY §5.8/§7 stage 9): when true, the
    # training driver calls jax.distributed.initialize() before any
    # backend use (coordinator/process env read from the standard JAX
    # env vars or cluster auto-detection).  The mesh then spans every
    # process's local devices, with XLA collectives riding ICI within a
    # slice and DCN across slices.  Single-process runs leave it false.
    distributed_init: bool = False

    def validate(self) -> None:
        names = [c.name for c in self.coordinates]
        if len(set(names)) != len(names):
            raise ValueError("duplicate coordinate names")
        for c in self.coordinates:
            c.validate()
        for s in self.update_sequence:
            if s not in names:
                raise ValueError(f"update_sequence entry '{s}' unknown")
        for s in self.locked_coordinates:
            if s not in names:
                raise ValueError(f"locked coordinate '{s}' unknown")
        if self.locked_coordinates and not self.warm_start_model_dir:
            raise ValueError(
                "locked_coordinates require warm_start_model_dir (locked "
                "coefficients come from the previous model)"
            )
        if self.use_warm_start_as_prior and not self.warm_start_model_dir:
            raise ValueError(
                "use_warm_start_as_prior requires warm_start_model_dir"
            )
        if self.resume and not self.checkpoint_dir:
            raise ValueError("resume requires checkpoint_dir")
        if self.checkpoint_every_sweeps < 1:
            raise ValueError("checkpoint_every_sweeps must be >= 1")
        if self.checkpoint_every_solver_iters < 0:
            raise ValueError(
                "checkpoint_every_solver_iters must be >= 0")
        if not 0.0 <= self.validation_fraction < 1.0:
            raise ValueError("validation_fraction must be in [0, 1)")
        if self.n_iterations <= 0:
            raise ValueError("n_iterations must be positive")
        if self.model_output_mode not in ("ALL", "BEST", "EXPLICIT"):
            raise ValueError("model_output_mode must be ALL|BEST|EXPLICIT")
        if self.sparse_layout not in ("AUTO", "GRR", "COLMAJOR", "ELL"):
            raise ValueError("sparse_layout must be AUTO|GRR|COLMAJOR|ELL")
        if self.telemetry not in ("off", "metrics", "trace"):
            raise ValueError("telemetry must be off|metrics|trace")
        _validate_monitor(self)
        if self.chunk_layout not in ("AUTO", "GRR", "ELL"):
            raise ValueError("chunk_layout must be AUTO|GRR|ELL")
        if self.host_max_resident < 1:
            raise ValueError("host_max_resident must be >= 1")
        if self.prefetch_depth < 0:
            raise ValueError("prefetch_depth must be >= 0")
        if (self.spill_dir is not None and self.chunk_rows is None
                and self.re_chunk_entities is None):
            raise ValueError(
                "spill_dir requires chunked training (chunk_rows) or "
                "streamed random effects (re_chunk_entities): only "
                "chunk batches spill to the disk tier")
        if self.re_chunk_entities is not None:
            if self.re_chunk_entities <= 0:
                raise ValueError("re_chunk_entities must be positive")
            from photon_ml_tpu.data.chunk_store import resolve_spill_dir

            if resolve_spill_dir(self.spill_dir) is None:
                raise ValueError(
                    "re_chunk_entities requires spill_dir (or "
                    "$PHOTON_ML_TPU_SPILL_DIR): streamed random-effect "
                    "training is store-backed")
        if self.chunk_rows is not None:
            if self.chunk_rows <= 0:
                raise ValueError("chunk_rows must be positive")
            if self.chunk_max_resident < 0:
                raise ValueError("chunk_max_resident must be >= 0")
            for c in self.coordinates:
                if (c.kind == CoordinateKind.FIXED_EFFECT
                        and c.down_sampling_rate is not None):
                    raise ValueError(
                        "down-sampling is not supported with chunked "
                        "training (chunk_rows)")
                if (c.kind == CoordinateKind.FIXED_EFFECT
                        and c.optimizer.variance_type
                        == VarianceComputationType.FULL):
                    raise ValueError(
                        "FULL variances materialize a [d, d] Hessian — "
                        "not supported with chunked training "
                        "(chunk_rows); use SIMPLE")
            if self.normalization != NormalizationType.NONE:
                raise ValueError(
                    "normalization requires resident feature statistics; "
                    "not supported with chunked training (chunk_rows)")
        if self.cd_fused:
            if self.chunk_rows is None:
                raise ValueError(
                    "cd_fused requires chunked training (chunk_rows): "
                    "the fixed effect's chunk grid is the fused cycle's "
                    "master grid")
            if self.locked_coordinates:
                raise ValueError(
                    "cd_fused does not support locked_coordinates (the "
                    "fused pass composes every coordinate's margins "
                    "from live coefficients)")
            if self.n_devices is not None:
                raise ValueError(
                    "cd_fused is single-device (the fused per-chunk "
                    "program is not mesh-sharded); drop n_devices")
            fixed = [c for c in self.coordinates
                     if c.name in self.update_sequence
                     and c.kind == CoordinateKind.FIXED_EFFECT]
            if len(fixed) != 1:
                raise ValueError(
                    "cd_fused requires exactly one fixed-effect "
                    f"coordinate in update_sequence (got {len(fixed)})")
            for c in self.coordinates:
                if (c.name in self.update_sequence
                        and c.optimizer.regularization
                        not in (RegularizationType.NONE,
                                RegularizationType.L2)):
                    raise ValueError(
                        "cd_fused requires smooth regularization "
                        "(NONE or L2) on every coordinate; "
                        f"'{c.name}' uses "
                        f"{c.optimizer.regularization.value} — the "
                        "Jacobi Newton solves have no proximal step")
        if self.n_devices is not None:
            if self.n_devices <= 0:
                raise ValueError("n_devices must be positive")
            for c in self.coordinates:
                if c.down_sampling_rate is not None:
                    raise ValueError(
                        "down-sampling is not supported with mesh "
                        "training (n_devices); the row subset would "
                        "cross shard boundaries"
                    )
        for name, grid in self.reg_weight_grid.items():
            if name not in names:
                raise ValueError(f"grid entry '{name}' unknown")
            if not grid:
                raise ValueError(f"empty grid for '{name}'")
        if self.tuning is not None:
            self.tuning.validate()
            if self.reg_weight_grid:
                raise ValueError("tuning and reg_weight_grid are exclusive")
            if not self.evaluators:
                raise ValueError("tuning needs at least one evaluator")
            for name in self.tuning.reg_weight_ranges:
                if name not in names:
                    raise ValueError(f"tuning range '{name}' unknown")


@dataclasses.dataclass
class ScoringConfig:
    """Scoring-run configuration (reference ``GameScoringDriver``)."""

    input_path: str
    model_dir: str
    output_path: str = "scores.npz"
    input_format: str = "auto"             # auto | jsonl | libsvm
    index_dir: str | None = None           # default: <model_dir>/../index_maps
    dense_feature_shards: list[str] = dataclasses.field(default_factory=list)
    evaluators: list[EvaluatorType] = dataclasses.field(default_factory=list)
    # JAX persistent compilation cache (see TrainingConfig): the 1037 s
    # scoring-program compile (PERF.md) is paid once per program shape.
    compilation_cache_dir: str | None = None
    # Streaming fused scoring (estimators.streaming_scorer, ISSUE 4):
    # score_chunk_rows activates the one-pass chunked pipeline — every
    # coordinate scored by ONE fused device program per fixed-shape
    # chunk, output sinks and evaluators fed chunk-wise (streaming
    # accumulators), so peak memory is bounded by the chunk window, not
    # the dataset.  None keeps the per-coordinate resident transform.
    # spill_dir (default $PHOTON_ML_TPU_SPILL_DIR, same env as
    # training) spills prepared score chunks to content-keyed .npz
    # files (memory-mapped back, LRU host_max_resident window; spilled
    # chunks double as a warm-scoring artifact across runs);
    # prefetch_depth runs the background disk→host→device prefetch
    # thread (0 = synchronous).
    score_chunk_rows: int | None = None
    spill_dir: str | None = None
    host_max_resident: int = 2
    prefetch_depth: int = 2
    # Pipeline telemetry (see TrainingConfig.telemetry): off | metrics
    # | trace; telemetry_dir defaults to the output file's directory.
    telemetry: str = "off"
    telemetry_dir: str | None = None
    # Live run monitoring (see TrainingConfig.monitor): progress/ETA
    # snapshots + online alerts from the streaming scorer; status_port
    # serves /status + /metrics (implies monitor="on").
    monitor: str = "off"
    monitor_every_s: float = 2.0
    status_port: int | None = None

    def validate(self) -> None:
        if self.score_chunk_rows is not None and self.score_chunk_rows <= 0:
            raise ValueError("score_chunk_rows must be positive")
        if self.telemetry not in ("off", "metrics", "trace"):
            raise ValueError("telemetry must be off|metrics|trace")
        _validate_monitor(self)
        if self.host_max_resident < 1:
            raise ValueError("host_max_resident must be >= 1")
        if self.prefetch_depth < 0:
            raise ValueError("prefetch_depth must be >= 0")
        if self.spill_dir is not None and self.score_chunk_rows is None:
            raise ValueError(
                "spill_dir requires streamed scoring (score_chunk_rows):"
                " only score chunks spill to the disk tier")


@dataclasses.dataclass
class ServingConfig:
    """Model-server configuration (ISSUE 12): the persistent online
    scoring process — ``python -m photon_ml_tpu.serving``."""

    # Model source: a checkpoint-manifest directory (model_manifest.npz
    # — the hot-swap unit) or a legacy metadata.json model dir; both go
    # through io.model_io.load_game_model (the shared loading path).
    model_dir: str
    # HTTP bind (127.0.0.1 only — front a proxy for external traffic);
    # port 0 asks the kernel for an ephemeral port (the bound port is
    # in ModelServer.port and the --info-file).
    host: str = "127.0.0.1"
    port: int = 0
    # Micro-batching: concurrent requests coalesce for up to
    # batch_deadline_ms, then dispatch as ONE fused device program call
    # padded to the smallest bucket ≥ the batch's row count.  Buckets
    # are the CLOSED shape set (default: powers of two up to
    # batch_rows) — every bucket is compiled at warm-up, so the steady
    # state pays zero compiles (guard-pinned).  Oversized requests
    # split across buckets.
    batch_rows: int = 64
    batch_buckets: list[int] | None = None
    batch_deadline_ms: float = 2.0
    max_queue: int = 1024
    request_timeout_s: float = 30.0
    # Sparse fixed-effect request rows densify to ELL at this per-row
    # capacity (part of the closed shape set); a request row with more
    # non-zeros answers 400 naming this knob.
    ell_row_capacity: int = 64
    # Feature shards served as dense vectors (same knob as
    # ScoringConfig); non-projected random-effect shards are dense
    # automatically — the model knows which those are.
    dense_feature_shards: list[str] = dataclasses.field(
        default_factory=list)
    # Random-effect coefficient store (serving.entity_store): with a
    # spill dir (default $PHOTON_ML_TPU_SPILL_DIR) coefficients live in
    # content-keyed chunked .npz files of entity_chunk entities,
    # memory-mapped back through an LRU host_max_resident window, with
    # a persistent entity-id → (chunk, row) index — host RSS is bounded
    # by the window, not the entity count, and a restart with the same
    # model reuses the files.  None keeps coefficients host-resident.
    spill_dir: str | None = None
    entity_chunk: int = 4096
    host_max_resident: int = 4
    # Hot model swap: poll the model dir's manifest at this cadence and
    # atomically switch to a newly published manifest between batches
    # (zero dropped requests; a corrupt manifest keeps the previous
    # good model).  0 disables the watcher.
    hot_swap_poll_s: float = 2.0
    # Persistent XLA compilation cache: bucket warm-up compiles are
    # paid once per program shape across server restarts.
    compilation_cache_dir: str | None = None
    # Telemetry/monitoring: the request path is instrumented (latency
    # histograms, queue-depth gauge, batch-fill counters) through a
    # telemetry session and the live monitor's alert rules (incl.
    # serve_tail_latency) — both ON by default: a server without
    # metrics is blind.  /status + /metrics ride the serving port.
    telemetry: str = "metrics"
    monitor: str = "on"
    monitor_every_s: float = 2.0
    status_port: int | None = None   # unused: /status rides the port
    log_path: str | None = None      # run-log JSONL (default: stderr)
    # --- Resilient fleet (ISSUE 13) ------------------------------------
    # replicas > 1 runs the supervised fleet: N replica ModelServer
    # subprocesses behind one health-routed frontend (serving.fleet /
    # serving.frontend).  The frontend binds `port`; replicas take
    # ephemeral ports and are restarted on crash or wedge.
    replicas: int = 1
    # Health probing: each replica's /healthz is polled every
    # probe_every_s with probe_timeout_s per probe; unhealthy_after
    # consecutive failed probes on a live process mark it wedged (it is
    # killed and restarted like a crash).
    probe_every_s: float = 0.5
    probe_timeout_s: float = 2.0
    unhealthy_after: int = 3
    # Restart policy: bounded exponential backoff between restarts of
    # the same replica (base doubling, capped), and a circuit breaker —
    # breaker_threshold restarts inside breaker_window_s opens the
    # breaker for breaker_reset_s (no restarts), after which ONE
    # half-open attempt either closes it (replica reaches ready) or
    # re-opens it.
    restart_backoff_s: float = 0.5
    restart_backoff_max_s: float = 10.0
    breaker_threshold: int = 5
    breaker_window_s: float = 30.0
    breaker_reset_s: float = 30.0
    # A (re)spawned replica that has not reached ready within this
    # budget counts as a failed start (killed, backoff applies).
    replica_ready_timeout_s: float = 300.0
    # Per-connection socket timeout on the HTTP cores (frontend and
    # replicas): a stalled client is disconnected instead of pinning a
    # handler thread forever.
    http_timeout_s: float = 30.0
    # --- Request tracing (ISSUE 14) ------------------------------------
    # End-to-end request tracing: per-request stage timestamps (+ the
    # shared micro-batch span), trace-id propagation across the fleet,
    # and tail-based sampling into a bounded ring buffer + request_trace
    # JSONL events.  "on" costs ≤2% on p50 (guard-pinned A/B, PERF.md
    # round 19); "off" is the pre-tracing request path bit for bit.
    trace: str = "on"
    # Tail threshold: a request slower than this is retained (sampled
    # as "tail"); every trace_sample_every-th request is retained
    # regardless (the deterministic floor; 0 disables the floor).
    trace_threshold_ms: float = 50.0
    trace_sample_every: int = 100
    # Retained traces kept in process memory (the /status view); every
    # retained trace is also a request_trace event on the run log.
    trace_buffer: int = 512

    def validate(self) -> None:
        if not self.model_dir:
            raise ValueError("serving needs model_dir")
        if not 0 <= self.port <= 65535:
            raise ValueError("port must be in [0, 65535] (0 = ephemeral)")
        if self.batch_rows <= 0:
            raise ValueError("batch_rows must be positive")
        if self.batch_buckets is not None:
            b = list(self.batch_buckets)
            if not b or any(int(x) <= 0 for x in b):
                raise ValueError("batch_buckets must be positive")
            if sorted(set(int(x) for x in b)) != [int(x) for x in b]:
                raise ValueError(
                    "batch_buckets must be strictly ascending")
            if int(b[-1]) != self.batch_rows:
                raise ValueError(
                    "batch_buckets must end at batch_rows (the largest "
                    "bucket IS the max micro-batch)")
        if self.batch_deadline_ms < 0:
            raise ValueError("batch_deadline_ms must be >= 0")
        if self.max_queue <= 0:
            raise ValueError("max_queue must be positive")
        if self.request_timeout_s <= 0:
            raise ValueError("request_timeout_s must be positive")
        if self.ell_row_capacity <= 0:
            raise ValueError("ell_row_capacity must be positive")
        if self.entity_chunk <= 0:
            raise ValueError("entity_chunk must be positive")
        if self.host_max_resident < 1:
            raise ValueError("host_max_resident must be >= 1")
        if self.hot_swap_poll_s < 0:
            raise ValueError("hot_swap_poll_s must be >= 0 (0 = off)")
        if self.telemetry not in ("off", "metrics", "trace"):
            raise ValueError("telemetry must be off|metrics|trace")
        _validate_monitor(self)
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        if self.probe_every_s <= 0:
            raise ValueError("probe_every_s must be positive")
        if self.probe_timeout_s <= 0:
            raise ValueError("probe_timeout_s must be positive")
        if self.unhealthy_after < 1:
            raise ValueError("unhealthy_after must be >= 1")
        if self.restart_backoff_s < 0:
            raise ValueError("restart_backoff_s must be >= 0")
        if self.restart_backoff_max_s < self.restart_backoff_s:
            raise ValueError(
                "restart_backoff_max_s must be >= restart_backoff_s")
        if self.breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        if self.breaker_window_s <= 0:
            raise ValueError("breaker_window_s must be positive")
        if self.breaker_reset_s <= 0:
            raise ValueError("breaker_reset_s must be positive")
        if self.replica_ready_timeout_s <= 0:
            raise ValueError("replica_ready_timeout_s must be positive")
        if self.http_timeout_s <= 0:
            raise ValueError("http_timeout_s must be positive")
        if self.trace not in ("on", "off"):
            raise ValueError("trace must be on|off")
        if self.trace_threshold_ms < 0:
            raise ValueError("trace_threshold_ms must be >= 0")
        if self.trace_sample_every < 0:
            raise ValueError(
                "trace_sample_every must be >= 0 (0 = no floor)")
        if self.trace_buffer < 1:
            raise ValueError("trace_buffer must be >= 1")

    def buckets(self) -> list[int]:
        """The closed micro-batch shape set, smallest first."""
        if self.batch_buckets is not None:
            return [int(b) for b in self.batch_buckets]
        out, b = [], 1
        while b < self.batch_rows:
            out.append(b)
            b *= 2
        out.append(self.batch_rows)
        return out


# ---------------------------------------------------------------------------
# JSON (de)serialization.  Enums serialize by value; nested dataclasses by
# field name — forgiving on input (unknown keys rejected, enums by name or
# value).
# ---------------------------------------------------------------------------

def _to_jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _to_jsonable(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, dict):
        return {k: _to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(v) for v in obj]
    return obj


def config_to_json(config) -> str:
    return json.dumps(_to_jsonable(config), indent=2)


def _build(cls, data: Any):
    if dataclasses.is_dataclass(cls) and isinstance(data, dict):
        fields = {f.name: f for f in dataclasses.fields(cls)}
        unknown = set(data) - set(fields)
        if unknown:
            raise ValueError(f"unknown config keys for {cls.__name__}: "
                             f"{sorted(unknown)}")
        kwargs = {}
        for k, v in data.items():
            kwargs[k] = _coerce(fields[k].type, v)
        return cls(**kwargs)
    return data


_ENUMS = {
    "TaskType": TaskType,
    "CoordinateKind": CoordinateKind,
    "OptimizerType": OptimizerType,
    "RegularizationType": RegularizationType,
    "NormalizationType": NormalizationType,
    "EvaluatorType": EvaluatorType,
    "VarianceComputationType": VarianceComputationType,
}


def _coerce(type_str, v):
    """Best-effort typed coercion from annotation strings (PEP 563)."""
    t = type_str if isinstance(type_str, str) else getattr(
        type_str, "__name__", str(type_str))
    if isinstance(v, list):
        if "CoordinateConfig" in t:
            return [_build(CoordinateConfig, c) for c in v]
        for name, enum_cls in _ENUMS.items():
            if name in t:
                return [enum_cls(e) if isinstance(e, str) else e for e in v]
        return v
    if isinstance(v, str):
        for name, enum_cls in _ENUMS.items():
            if name in t:
                try:
                    return enum_cls(v)
                except ValueError:
                    return enum_cls[v]
    if "OptimizerSettings" in t and isinstance(v, dict):
        return _build(OptimizerSettings, v)
    if "TuningConfig" in t and isinstance(v, dict):
        return _build(TuningConfig, v)
    return v


def training_config_from_json(text: str) -> TrainingConfig:
    cfg = _build(TrainingConfig, json.loads(text))
    cfg.validate()
    return cfg


def scoring_config_from_json(text: str) -> ScoringConfig:
    cfg = _build(ScoringConfig, json.loads(text))
    cfg.validate()
    return cfg


def serving_config_from_json(text: str) -> ServingConfig:
    cfg = _build(ServingConfig, json.loads(text))
    cfg.validate()
    return cfg


def load_training_config(path: str) -> TrainingConfig:
    with open(path) as f:
        return training_config_from_json(f.read())


def load_scoring_config(path: str) -> ScoringConfig:
    with open(path) as f:
        return scoring_config_from_json(f.read())


def load_serving_config(path: str) -> ServingConfig:
    with open(path) as f:
        return serving_config_from_json(f.read())
