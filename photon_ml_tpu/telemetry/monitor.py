"""Live run monitoring: progress snapshots, online alerts, and the
status endpoint (ISSUE 10).

Every observability layer so far (spans/metrics/trace, device cost,
convergence traces, bench history) is post-mortem — nothing tells an
operator what a RUNNING fit is doing, and the multi-hour streaming
workloads this repo is built for are exactly where a silent process is
unacceptable ("Distributed Function Minimization in Apache Spark",
PAPERS.md, monitors driver-side solver progress per iteration; PERF.md
records 1.5e7-example runs dying mid-flight with nothing watching).
This module is the live tier on top of the telemetry session:

- **Progress snapshots**: instrumented loops (the CD loop, the
  streaming L-BFGS/OWL-QN solvers, streamed-RE sweeps, the streaming
  scorer, the tuner) call ``monitor.progress(stage, done, total)``
  per unit of work; the monitor THROTTLES to a wall-clock cadence
  (``every_s``) so hot loops pay one module-global read when off and
  one dict update when on, and emits ``progress`` JSONL events
  carrying rolling throughput and an ETA derived from the observed
  chunk/sweep rates.
- **Online alert rules**, evaluated at snapshot cadence: non-finite or
  diverging loss, throughput collapse vs the stage's rolling median,
  prefetcher stall, retry storms (``store.retries``/``store.gave_up``),
  sink queue saturation, device-memory gauge growth.  Each rule
  LATCHES per (rule, stage) — an injected fault produces exactly one
  structured ``alert`` event, which surfaces in ``telemetry watch``,
  the status endpoint, and the report's Alerts section.
- **Status endpoint**: an opt-in stdlib ``http.server`` thread serving
  ``GET /status`` (JSON: phase, per-stage progress, ETA, alerts) and
  ``GET /metrics`` (Prometheus text exposition of the telemetry
  registry) — wired through ``TrainingConfig``/``ScoringConfig`` and
  ``--status-port`` on all three drivers.

Off by default via the same module-global null-singleton pattern as
the telemetry session: ``progress()`` with no active monitor is one
global read + early return, zero events, ZERO extra compiles
(guard-pinned — the monitor never touches jax).

Thread-safety (photon-lint ``unlocked-shared-write``): all monitor
state mutates under one lock; the status-server thread only reads
through locked snapshot methods; events go through the (internally
locked) ``RunLogger``.
"""

from __future__ import annotations

import contextlib
import json
import logging
import math
import re
import statistics
import threading
import time

from photon_ml_tpu import telemetry
# The status endpoint rides the SAME threaded HTTP core as the model
# server's request path (ISSUE 12): one server loop, one readiness
# state machine.  serving.http is stdlib-only, so no import cycle.
from photon_ml_tpu.serving.http import (
    READY,
    STOPPING,
    WARMING,
    HttpEndpoint,
    Readiness,
)

logger = logging.getLogger(__name__)

DEFAULT_EVERY_S = 2.0
# Rolling window for throughput/ETA and the alert rules' rate queries.
DEFAULT_WINDOW_S = 30.0
# Per-stage bounded history caps (snapshots are cadence-throttled, so
# these cover minutes of run at the default cadence).
_SAMPLE_CAP = 256
_RATE_HISTORY_CAP = 64

# Alert-rule thresholds; every one overridable per Monitor (the unit
# tests pin exactly which rules fire on synthetic streams).
DEFAULT_THRESHOLDS: dict = {
    # loss_diverging: finite loss worse than divergence_ratio x the
    # best loss this stage has seen (only defined for positive best).
    "divergence_ratio": 2.0,
    # throughput_collapse: current rate below collapse_fraction x the
    # median of the stage's previous snapshot rates, once at least
    # collapse_min_snapshots rates are on record.
    "collapse_fraction": 0.25,
    "collapse_min_snapshots": 4,
    # prefetch_stall: consumer blocked on the queue more than this
    # fraction of recent wall clock (rate of the seconds-counter), or
    # any hard stall timeout.
    "stall_wait_fraction": 0.75,
    # retry_storm: transient-I/O retries per second over the window,
    # or any store.gave_up.
    "retry_rate_per_s": 0.5,
    # sink_saturation: sink.queue_depth gauge at/above this depth for
    # this many consecutive snapshot evaluations (writer queue is 4
    # deep — sustained 3 means the sink tier is the bottleneck).
    "sink_queue_depth": 3,
    "sink_queue_streak": 2,
    # device_memory_growth: device.bytes_in_use grew by both this
    # ratio and this many MB since the monitor's first sample.
    "memory_growth_ratio": 1.5,
    "memory_growth_min_mb": 256.0,
    # serve_tail_latency (ISSUE 12): the serving tier's per-request
    # latency p99 (the bounded-reservoir rolling estimate over
    # serve.request_s) above this many seconds, once at least
    # serve_min_requests requests are on record — the online signal
    # that the micro-batcher/device path is falling behind its SLO.
    "serve_p99_s": 0.5,
    "serve_min_requests": 20,
    # serve_shed_rate (ISSUE 13): the shed fraction over the rolling
    # window — serve.shed / (serve.shed + serve.requests), both as
    # windowed rates — above this fraction, once at least
    # serve_shed_min_events (sheds + served) are on record.  Shedding
    # is the DESIGNED overload response (503 + Retry-After beats queue
    # collapse), but a sustained shed fraction means the fleet is
    # under-provisioned and an operator must see it.
    "serve_shed_fraction": 0.2,
    "serve_shed_min_events": 20,
    # serve_queue_wait (ISSUE 14): the batcher-is-the-bottleneck
    # signal — the queue-wait stage's p99 (from the request-tracing
    # tier's serve.stage.queue_wait_s histogram) exceeding this
    # fraction of the end-to-end request p99, once at least
    # queue_wait_min_requests requests are on record.  A tail
    # dominated by queue wait means requests are waiting on batch
    # formation/device capacity, not on the work itself — add
    # replicas or widen buckets rather than chasing the engine.
    "queue_wait_fraction": 0.5,
    "queue_wait_min_requests": 20,
}

_ACTIVE: "Monitor | None" = None
_ACTIVE_LOCK = threading.Lock()


def active() -> "Monitor | None":
    """The active monitor, or None when live monitoring is off."""
    return _ACTIVE


def progress(stage: str, done, total=None, unit: str = "units",
             **fields) -> None:
    """Report ``done`` (of ``total``) work units for ``stage``.  The
    hot-path contract: one module-global read + early return when
    monitoring is off; when on, emission is throttled to the monitor's
    wall-clock cadence, so per-chunk call sites pay dict bookkeeping,
    not I/O."""
    m = _ACTIVE
    if m is not None:
        m.progress(stage, done, total, unit, **fields)


def phase_begin(name: str) -> None:
    """Driver-phase entry hook (``RunLogger.timed`` calls this) — the
    status endpoint and ``watch`` report the innermost open phase."""
    m = _ACTIVE
    if m is not None:
        m.phase_begin(name)


def phase_end(name: str) -> None:
    m = _ACTIVE
    if m is not None:
        m.phase_end(name)


class Monitor:
    """One live-monitoring session (create via ``start()`` /
    ``maybe_monitor()`` — the module helpers dispatch to the single
    active monitor).

    ``run_logger``: the events channel (``progress`` / ``alert`` /
    ``monitor_summary`` JSONL lines); when None a pure stdlib-logging
    ``RunLogger`` is created and owned.  ``status_port`` spawns the
    HTTP status server (port 0 = ephemeral; the bound port is in
    ``status_port`` and logged as a ``status_server`` event).
    ``telemetry_session`` overrides the registry the alert rules read
    (tests); by default the rules look up the live session at
    evaluation time, and registry-backed rules simply stay inactive
    when telemetry is off.
    """

    def __init__(self, run_logger=None, every_s: float = DEFAULT_EVERY_S,
                 window_s: float = DEFAULT_WINDOW_S,
                 status_port: int | None = None,
                 alerts: bool = True,
                 thresholds: dict | None = None,
                 telemetry_session=None,
                 clock=time.monotonic):
        if every_s < 0:
            raise ValueError(f"every_s must be >= 0, got {every_s!r}")
        owns = False
        if run_logger is None:
            from photon_ml_tpu.utils.run_log import RunLogger

            run_logger = RunLogger(None)
            owns = True
        self._log = run_logger
        self._owns_logger = owns
        self.every_s = float(every_s)
        self.window_s = float(window_s)
        self._alerts_enabled = alerts
        self.thresholds = {**DEFAULT_THRESHOLDS, **(thresholds or {})}
        unknown = set(self.thresholds) - set(DEFAULT_THRESHOLDS)
        if unknown:
            raise ValueError(f"unknown alert thresholds: {sorted(unknown)}")
        self._session = telemetry_session
        self._clock = clock
        self._lock = threading.Lock()
        self._stages: dict[str, dict] = {}
        self._phases: list[str] = []
        self._alerts: list[dict] = []
        self._fired: set = set()
        self._snapshots = 0
        self._sink_high_streak = 0
        self._dev_first_bytes: float | None = None
        self._closed = False
        # Readiness for /healthz (ISSUE 12 satellite): the monitored
        # run is WARMING — plan build / XLA compile / first work unit
        # in progress — until the first progress snapshot arrives, then
        # READY.  The old endpoint answered an unconditional 200 from
        # the moment the socket bound; a probe now gets the same
        # warming→503 / ready→200 semantics as the model server.
        self.readiness = Readiness(
            WARMING, reason="no progress snapshot yet "
                            "(plan/compile or first work unit pending)")
        self._server: _StatusServer | None = None
        self.status_port: int | None = None
        if status_port is not None:
            self._server = _StatusServer(self, status_port)
        self.t0 = self._clock()

    # -- lifecycle ----------------------------------------------------------

    def _open(self) -> None:
        self._log.event("monitor_start", every_s=self.every_s)
        if self._server is not None:
            self._server.start()
            self.status_port = self._server.port
            self._log.event("status_server", port=self._server.port,
                            routes=["/status", "/metrics"])
            logger.info("status endpoint on http://127.0.0.1:%d/status",
                        self._server.port)

    def close(self) -> None:
        """Emit the summary event, stop the status server, deactivate.
        Idempotent."""
        global _ACTIVE
        if self._closed:
            return
        self._closed = True
        self.readiness.set(STOPPING, reason="monitor closing")
        if self._server is not None:
            self._server.close()
            self._server = None
        self._log.event("monitor_summary", **self.summary())
        if self._owns_logger:
            self._log.close()
        with _ACTIVE_LOCK:
            if _ACTIVE is self:
                _ACTIVE = None

    # -- phase tracking ------------------------------------------------------

    def phase_begin(self, name: str) -> None:
        with self._lock:
            self._phases.append(name)

    def phase_end(self, name: str) -> None:
        with self._lock:
            if name in self._phases:
                # Remove the innermost match (phases nest; a missed
                # begin must not corrupt the stack).
                for i in range(len(self._phases) - 1, -1, -1):
                    if self._phases[i] == name:
                        del self._phases[i]
                        break

    # -- progress ------------------------------------------------------------

    def mark_ready(self) -> None:
        """Flip /healthz to ready (200).  Progress snapshots do this
        implicitly — work flowing means the warm-up is behind us; the
        model server calls it explicitly after its bucket warm-up."""
        self.readiness.set(READY)

    def progress(self, stage: str, done, total=None,
                 unit: str = "units", **fields) -> None:
        now = self._clock()
        done = float(done)
        if self.readiness.state == WARMING:
            self.mark_ready()
        with self._lock:
            st = self._stages.get(stage)
            first = st is None
            if first:
                st = self._stages[stage] = {
                    "stage": stage, "done": done, "total": total,
                    "unit": unit, "rate": None, "eta_s": None,
                    "fields": {}, "samples": [], "rates": [],
                    "last_emit": -math.inf, "updated": now,
                    "first_loss": None, "best_loss": None,
                    "last_loss": None,
                }
            if done < st["done"]:
                # A new pass/sweep restarted the unit count: reset the
                # rate window so the rolling throughput never goes
                # negative across the seam.
                st["samples"] = []
            st["done"] = done
            st["total"] = None if total is None else float(total)
            st["unit"] = unit
            st["updated"] = now
            if fields:
                st["fields"].update(fields)
            loss = fields.get("loss")
            if loss is not None:
                loss = float(loss)
                st["last_loss"] = loss
                if math.isfinite(loss):
                    if st["first_loss"] is None:
                        st["first_loss"] = loss
                    if st["best_loss"] is None or loss < st["best_loss"]:
                        st["best_loss"] = loss
            st["samples"].append((now, done))
            cutoff = now - self.window_s
            samples = st["samples"]
            while len(samples) > 2 and samples[0][0] < cutoff:
                samples.pop(0)
            if len(samples) > _SAMPLE_CAP:
                # Every-other decimation keeping the just-appended
                # newest sample (``del samples[::2]`` would drop it and
                # lag the rolling rate by one update).
                del samples[1::2]
            complete = (st["total"] is not None
                        and done >= st["total"])
            if (not first and not complete
                    and now - st["last_emit"] < self.every_s):
                return               # throttled: no event, no alerts
            st["last_emit"] = now
            rate = None
            if len(samples) >= 2 and samples[-1][0] > samples[0][0]:
                rate = ((samples[-1][1] - samples[0][1])
                        / (samples[-1][0] - samples[0][0]))
            st["rate"] = rate
            eta = None
            if (st["total"] is not None and rate is not None and rate > 0
                    and st["total"] > done):
                eta = (st["total"] - done) / rate
            st["eta_s"] = eta
            if rate is not None:
                st["rates"].append(rate)
                del st["rates"][:-_RATE_HISTORY_CAP]
            self._snapshots += 1
            phase = self._phases[-1] if self._phases else None
            rec = {
                "stage": stage, "done": done, "unit": unit,
                **({"total": st["total"]}
                   if st["total"] is not None else {}),
                **({"rate": round(rate, 3)} if rate is not None else {}),
                **({"eta_s": round(eta, 1)} if eta is not None else {}),
                **({"phase": phase} if phase else {}),
                **fields,
            }
        t = self._session if self._session is not None \
            else telemetry.active()
        if stage == "serve" and t is not None:
            # Serve progress snapshots carry the stage-latency table
            # (ISSUE 14) so `telemetry watch` renders the serve stage
            # decomposition live — cadence-throttled with the event,
            # zero cost on the hot path.
            from photon_ml_tpu.serving import tracing as _tracing

            stage_tbl = _tracing.stage_summary(session=t)
            if stage_tbl:
                rec["stages_ms"] = stage_tbl
        self._log.event("progress", **rec)
        if t is not None:
            t.count("monitor.progress_events")
        self._evaluate_alerts(now)

    # -- alert rules ---------------------------------------------------------

    def _fire(self, rule: str, stage: str | None, message: str,
              severity: str = "warn", **context) -> None:
        key = (rule, stage)
        with self._lock:
            if key in self._fired:
                return
            self._fired.add(key)
            alert = {"rule": rule, "severity": severity,
                     "message": message, "t": round(self._log.now(), 6),
                     **({"stage": stage} if stage else {}), **context}
            self._alerts.append(alert)
        self._log.event("alert", rule=rule, severity=severity,
                        message=message,
                        **({"stage": stage} if stage else {}), **context)
        t = self._session if self._session is not None \
            else telemetry.active()
        if t is not None:
            t.count("monitor.alerts")
        logger.warning("ALERT [%s] %s%s: %s", severity, rule,
                       f" ({stage})" if stage else "", message)

    def _evaluate_alerts(self, now: float) -> None:
        """Run every rule against the current stage states and the
        telemetry registry.  Called at snapshot cadence (never from the
        throttled fast path), so rule cost is amortized to ~nothing."""
        if not self._alerts_enabled:
            return
        th = self.thresholds
        with self._lock:
            stages = [(s, dict(st, rates=list(st["rates"])))
                      for s, st in self._stages.items()]
        for stage, st in stages:
            loss = st["last_loss"]
            if loss is not None and not math.isfinite(loss):
                self._fire("loss_nonfinite", stage,
                           f"loss is {loss!r}; the solve is numerically "
                           "dead", severity="error", loss=loss)
            elif (loss is not None and st["best_loss"] is not None
                  and st["best_loss"] > 0
                  and loss > th["divergence_ratio"] * st["best_loss"]):
                self._fire(
                    "loss_diverging", stage,
                    f"loss {loss:.6g} is "
                    f"{loss / st['best_loss']:.2f}x the best seen "
                    f"({st['best_loss']:.6g}); the solve is diverging",
                    severity="error", loss=loss, best=st["best_loss"])
            rates = st["rates"]
            if (len(rates) > th["collapse_min_snapshots"]
                    and rates[-1] is not None):
                base = statistics.median(rates[:-1][-_RATE_HISTORY_CAP:])
                if base > 0 and rates[-1] < th["collapse_fraction"] * base:
                    self._fire(
                        "throughput_collapse", stage,
                        f"throughput {rates[-1]:.3g}/s is below "
                        f"{th['collapse_fraction']:.0%} of the rolling "
                        f"median {base:.3g}/s", rate=round(rates[-1], 3),
                        baseline=round(base, 3))
        t = self._session if self._session is not None \
            else telemetry.active()
        if t is None:
            return
        if t.counter("prefetch.stall_timeouts") > 0:
            self._fire("prefetch_stall", None,
                       "prefetch pipeline hit its stall deadline (see "
                       "stall_timeout_s); the disk/staging tier is "
                       "wedged", severity="error",
                       stall_timeouts=t.counter("prefetch.stall_timeouts"))
        else:
            wait_rate = t.rate("prefetch.consumer_wait_s", self.window_s)
            if (wait_rate is not None
                    and wait_rate > th["stall_wait_fraction"]):
                self._fire(
                    "prefetch_stall", None,
                    f"consumer blocked on the prefetch queue "
                    f"{wait_rate:.0%} of recent wall clock (threshold "
                    f"{th['stall_wait_fraction']:.0%}); the disk tier "
                    "is not keeping up",
                    blocked_fraction=round(wait_rate, 3))
        gave_up = t.counter("store.gave_up")
        retry_rate = t.rate("store.retries", self.window_s)
        if gave_up > 0:
            self._fire("retry_storm", None,
                       f"{gave_up} chunk-store I/O operation(s) "
                       "exhausted their retry budget",
                       severity="error", gave_up=gave_up)
        elif retry_rate is not None and retry_rate > th["retry_rate_per_s"]:
            self._fire("retry_storm", None,
                       f"transient I/O retries at {retry_rate:.2f}/s "
                       f"(threshold {th['retry_rate_per_s']:g}/s); the "
                       "spill-dir storage is degrading",
                       retries_per_s=round(retry_rate, 3))
        # serve_tail_latency (ISSUE 12): the serving tier's request
        # latency histogram, once enough requests are on record.  The
        # p99 comes from the bounded reservoir — a stride-decimated
        # rolling estimate of the stream, the same estimator /metrics
        # exposes — and the rule latches per (rule, stage) like every
        # other rule: one alert per incident, not one per snapshot.
        p99 = t.percentile("serve.request_s", 0.99)
        if (p99 is not None
                and t.counter("serve.requests") >= th["serve_min_requests"]
                and p99 > th["serve_p99_s"]):
            # Name the dominant stage (ISSUE 14): with request tracing
            # on, the serve.stage.* histograms say WHERE the tail goes
            # — the alert carries the first diagnostic step.
            from photon_ml_tpu.serving import tracing as _tracing

            dom = _tracing.dominant_stage(
                _tracing.stage_summary(session=t))
            self._fire(
                "serve_tail_latency", "serve",
                f"p99 request latency {p99 * 1e3:.1f} ms exceeds the "
                f"{th['serve_p99_s'] * 1e3:.0f} ms threshold; the "
                "serving tier is missing its tail SLO"
                + (f" (dominant stage: {dom[0]}, p99 {dom[1]:.1f} ms)"
                   if dom is not None else ""),
                p99_ms=round(p99 * 1e3, 2),
                threshold_ms=round(th["serve_p99_s"] * 1e3, 2),
                requests=t.counter("serve.requests"),
                **({"dominant_stage": dom[0],
                    "dominant_p99_ms": dom[1]} if dom is not None
                   else {}))
        # serve_queue_wait (ISSUE 14): queue wait dominating the
        # request tail IS the "batcher is the bottleneck" signal —
        # per-request wait vs shared compute is exactly the split the
        # tracing tier measures.  Latched like every rule.
        qw_p99 = t.percentile("serve.stage.queue_wait_s", 0.99)
        if (qw_p99 is not None and p99 is not None and p99 > 0
                and t.counter("serve.requests")
                >= th["queue_wait_min_requests"]
                and qw_p99 > th["queue_wait_fraction"] * p99):
            self._fire(
                "serve_queue_wait", "serve",
                f"p99 queue wait {qw_p99 * 1e3:.1f} ms is "
                f"{qw_p99 / p99:.0%} of the p99 request latency "
                f"{p99 * 1e3:.1f} ms (threshold "
                f"{th['queue_wait_fraction']:.0%}); the micro-batcher "
                "is the bottleneck — add replicas or raise batch "
                "capacity",
                queue_wait_p99_ms=round(qw_p99 * 1e3, 2),
                request_p99_ms=round(p99 * 1e3, 2),
                fraction=round(qw_p99 / p99, 3))
        # serve_shed_rate (ISSUE 13): the 429/503 shed fraction over
        # the rolling window.  Both legs come from the registry's
        # windowed counter rates, so one ancient burst of sheds cannot
        # fire the rule forever — and like every rule it latches: one
        # overload incident, one alert.
        shed_n = t.counter("serve.shed")
        served_n = t.counter("serve.requests")
        if shed_n + served_n >= th["serve_shed_min_events"]:
            shed_rate = t.rate("serve.shed", self.window_s)
            req_rate = t.rate("serve.requests", self.window_s)
            total_rate = (shed_rate or 0.0) + (req_rate or 0.0)
            if shed_rate is not None and total_rate > 0:
                frac = shed_rate / total_rate
                if frac > th["serve_shed_fraction"]:
                    self._fire(
                        "serve_shed_rate", "serve",
                        f"{frac:.0%} of scoring requests shed "
                        f"(429/503) over the window (threshold "
                        f"{th['serve_shed_fraction']:.0%}); the "
                        "serving tier is under-provisioned for the "
                        "offered load",
                        shed_fraction=round(frac, 3),
                        shed=shed_n, served=served_n)
        # replica_restarts (ISSUE 13): ANY replica restart latches —
        # the fleet healed itself, but an operator must know a replica
        # crashed or wedged (severity warn: the request path survived
        # by design).
        restarts = t.counter("fleet.replica_restarts")
        if restarts > 0:
            self._fire(
                "replica_restarts", None,
                f"{restarts} serving replica restart(s): a replica "
                "crashed or wedged and was restarted by the "
                "supervisor (see fleet_replica_* run-log events)",
                restarts=restarts)
        depth = t.gauge_value("sink.queue_depth")
        with self._lock:
            if (depth is not None
                    and depth["last"] >= th["sink_queue_depth"]):
                self._sink_high_streak += 1
            else:
                self._sink_high_streak = 0
            streak = self._sink_high_streak
        if depth is not None and streak >= th["sink_queue_streak"]:
            self._fire("sink_saturation", None,
                       f"sink queue depth {depth['last']:g} for "
                       f"{streak} consecutive snapshots; the output "
                       "sink is the bottleneck",
                       queue_depth=depth["last"])
        mem = t.gauge_value("device.bytes_in_use")
        if mem is not None:
            with self._lock:
                if self._dev_first_bytes is None:
                    self._dev_first_bytes = mem["last"]
                first = self._dev_first_bytes
            grown_mb = (mem["last"] - first) / 1e6
            if (first > 0
                    and mem["last"] > th["memory_growth_ratio"] * first
                    and grown_mb > th["memory_growth_min_mb"]):
                self._fire(
                    "device_memory_growth", None,
                    f"device memory grew {grown_mb:.0f} MB "
                    f"({mem['last'] / max(first, 1):.2f}x) since "
                    "monitoring started; a leak or an unbounded "
                    "residency", first_mb=round(first / 1e6, 1),
                    last_mb=round(mem["last"] / 1e6, 1))

    # -- snapshots for the endpoint / bench ----------------------------------

    def status(self) -> dict:
        """JSON-ready live snapshot: the ``/status`` body."""
        now = self._clock()
        with self._lock:
            stages = {}
            latest = None
            for name, st in self._stages.items():
                stages[name] = {
                    "done": st["done"], "total": st["total"],
                    "unit": st["unit"],
                    "rate": (None if st["rate"] is None
                             else round(st["rate"], 3)),
                    "eta_s": (None if st["eta_s"] is None
                              else round(st["eta_s"], 1)),
                    "age_s": round(now - st["updated"], 3),
                    **{k: v for k, v in st["fields"].items()
                       if isinstance(v, (int, float, str, bool))
                       or v is None},
                }
                if latest is None or st["updated"] > latest[1]:
                    latest = (name, st["updated"])
            out = {
                "phase": self._phases[-1] if self._phases else None,
                "uptime_s": round(now - self.t0, 1),
                "snapshots": self._snapshots,
                "stages": stages,
                "current_stage": latest[0] if latest else None,
                "eta_s": (stages[latest[0]]["eta_s"] if latest else None),
                "alerts": list(self._alerts),
            }
        fl = _fleet_status()
        if fl is not None:
            out["fleet"] = fl
        return out

    def summary(self) -> dict:
        """Run-end summary (the ``monitor_summary`` event body; bench
        arms embed it as their ``progress`` block)."""
        st = self.status()
        return {
            "snapshots": st["snapshots"],
            "stages": st["stages"],
            "alerts": st["alerts"],
        }


def _fleet_status() -> dict | None:
    """This host's slice of the fleet view for ``/status`` (ISSUE 16):
    identity + reduce/barrier counters.  Every host serves its own
    status endpoint; a fleet dashboard polls all of them and joins on
    ``host`` — the offline equivalent is ``telemetry fleet-report``
    over the per-host run logs.  None outside a fleet."""
    from photon_ml_tpu.parallel import fleet

    ctx = fleet.active()
    if ctx is None or not ctx.is_fleet:
        return None
    t = telemetry.active()
    out = {
        "host": ctx.host_id,
        "n_hosts": ctx.n_hosts,
        "transport": ctx.transport,
    }
    if t is not None:
        out.update({
            "reduces": t.counter("fleet.psums"),
            "chunks_streamed": t.counter("fleet.chunks_streamed"),
            "barrier_wait_s": round(
                float(t.counter("fleet.barrier_wait_s")), 3),
        })
    return out


# ---------------------------------------------------------------------------
# Status endpoint
# ---------------------------------------------------------------------------

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return "photon_" + _PROM_BAD.sub("_", name)


def _prom_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"')


def prometheus_text(monitor: "Monitor | None" = None,
                    session=None) -> str:
    """Prometheus text exposition (version 0.0.4) of the telemetry
    registry plus the monitor's progress/alert state.  Counters map to
    ``counter``, gauges to ``gauge`` (last value), histograms to
    ``summary`` (quantiles from the bounded reservoir)."""
    t = session if session is not None else telemetry.active()
    lines: list[str] = []
    if t is not None:
        s = t.summary()
        for name, v in s.get("counters", {}).items():
            pn = _prom_name(name + ("_total" if "." in name else ""))
            lines.append(f"# TYPE {pn} counter")
            lines.append(f"{pn} {v}")
        for name, g in s.get("gauges", {}).items():
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {g['last']}")
        stage_family = False
        for name, h in s.get("histograms", {}).items():
            if name.startswith("serve.stage.") and name.endswith("_s"):
                # The request-tracing stage histograms export as ONE
                # labeled family (ISSUE 14): a dashboard slices
                # photon_serve_stage_seconds{stage="queue_wait"}
                # against its siblings instead of discovering N
                # flat-named series.
                stage = _prom_label(name[len("serve.stage."):-2])
                pn = "photon_serve_stage_seconds"
                if not stage_family:
                    lines.append(f"# TYPE {pn} summary")
                    stage_family = True
                for q, key in ((0.5, "p50"), (0.95, "p95"),
                               (0.99, "p99")):
                    if h.get(key) is not None:
                        lines.append(
                            f'{pn}{{stage="{stage}",quantile="{q}"}} '
                            f'{h[key]}')
                lines.append(f'{pn}_count{{stage="{stage}"}} '
                             f"{h['count']}")
                lines.append(f'{pn}_sum{{stage="{stage}"}} {h["sum"]}')
                continue
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} summary")
            for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                if h.get(key) is not None:
                    lines.append(f'{pn}{{quantile="{q}"}} {h[key]}')
            lines.append(f"{pn}_count {h['count']}")
            lines.append(f"{pn}_sum {h['sum']}")
    if monitor is not None:
        st = monitor.status()
        lines.append("# TYPE photon_monitor_progress_done gauge")
        lines.append("# TYPE photon_monitor_progress_total gauge")
        lines.append("# TYPE photon_monitor_progress_rate gauge")
        for stage, ent in st["stages"].items():
            lbl = f'{{stage="{_prom_label(stage)}"}}'
            lines.append(f"photon_monitor_progress_done{lbl} "
                         f"{ent['done']}")
            if ent["total"] is not None:
                lines.append(f"photon_monitor_progress_total{lbl} "
                             f"{ent['total']}")
            if ent["rate"] is not None:
                lines.append(f"photon_monitor_progress_rate{lbl} "
                             f"{ent['rate']}")
        lines.append("# TYPE photon_monitor_alerts_total counter")
        lines.append(f"photon_monitor_alerts_total {len(st['alerts'])}")
    return "\n".join(lines) + "\n"


def status_routes(monitor: "Monitor") -> dict:
    """The monitor's observer routes for the shared HTTP core —
    ``/status`` (live JSON snapshot) + ``/metrics`` (Prometheus text).
    The model server mounts the same routes next to its ``/v1/score``
    request path, so the two surfaces cannot drift."""
    return {
        ("GET", "/status"): lambda body: (
            200, json.dumps(monitor.status()), "application/json"),
        ("GET", "/metrics"): lambda body: (
            200, prometheus_text(monitor), "text/plain; version=0.0.4"),
    }


class _StatusServer:
    """The opt-in observer endpoint: the shared ``HttpEndpoint`` core
    with the monitor's routes and readiness (``/healthz`` answers 503
    while the run is still warming, 200 once progress flows).  Binds
    127.0.0.1 only; port 0 asks the kernel for an ephemeral port — the
    bound one is in ``.port``."""

    def __init__(self, monitor: Monitor, port: int,
                 host: str = "127.0.0.1"):
        self._ep = HttpEndpoint(status_routes(monitor),
                                readiness=monitor.readiness,
                                port=port, host=host)
        self.port = self._ep.port

    def start(self) -> None:
        self._ep.start()

    def close(self) -> None:
        self._ep.close()


# ---------------------------------------------------------------------------
# Session management (the telemetry start/maybe_session pattern)
# ---------------------------------------------------------------------------


def start(run_logger=None, every_s: float = DEFAULT_EVERY_S,
          status_port: int | None = None, **kw) -> Monitor:
    """Activate the (one per process) live monitor."""
    global _ACTIVE
    m = Monitor(run_logger, every_s=every_s, status_port=status_port,
                **kw)
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            if m._server is not None:
                m._server.close()
            if m._owns_logger:
                m._log.close()
            raise RuntimeError("a monitor session is already active")
        _ACTIVE = m
    m._open()
    return m


@contextlib.contextmanager
def maybe_monitor(enabled: bool, run_logger=None,
                  status_port: int | None = None,
                  every_s: float = DEFAULT_EVERY_S, **kw):
    """Monitor context honoring the config knobs: disabled (and no
    status port — a requested endpoint implies monitoring) or an
    already-active monitor (the driver configured one) yields without
    creating anything; otherwise a monitor spans the block."""
    if (not enabled and status_port is None) or _ACTIVE is not None:
        yield _ACTIVE
        return
    m = start(run_logger, every_s=every_s, status_port=status_port, **kw)
    try:
        yield m
    finally:
        m.close()
