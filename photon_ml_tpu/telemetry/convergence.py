"""Solver convergence traces (ISSUE 8).

The optimization tier's per-iteration story — loss, gradient norm,
accepted step size, line-search trials — was locked inside device
programs (``StatesTracker`` arrays) or host solver logs; end-state
parity was the only convergence evidence.  "Parallel training of
linear models without compromising convergence" (PAPERS.md) makes the
per-iteration trace the first-class artifact of a solver comparison;
this module emits it through the telemetry tier:

- ``iteration(...)``: one ``convergence_iter`` JSONL event per
  host-driven (streaming) solver iteration — live, so a killed run's
  log still carries the partial trajectory.
- ``solve_trace(...)``: one ``convergence_trace`` event per completed
  solve, built from the ``StatesTracker`` planes (values / grad norms
  / step sizes / line-search trials; per-lane for swept or vmapped
  results with a small leading axis).
- ``re_sweep(...)``: one ``re_convergence`` event per streamed
  random-effect sweep — the solved/converged/retired/woken entity
  dynamics the retirement machinery was previously judged on only via
  end-state parity.

All entry points are no-ops when telemetry is off (the module-global
null-session contract: one read + early return, zero events).  The
counters they maintain (``conv.iterations``, ``conv.solves``,
``conv.solver_iterations``) are what ``telemetry report`` reconciles
against the ``solver.sweeps`` data-pass odometer — see
``report._convergence``: iteration counts and data passes can no
longer drift apart unnoticed.
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu import telemetry


def _round_list(arr, ndigits: int = 8) -> list:
    """Host list with bounded precision (JSONL size hygiene); NaN →
    None so the line stays strict-JSON parseable."""
    a = np.asarray(arr, np.float64)
    out = []
    for x in a.reshape(-1).tolist():
        out.append(None if x != x else round(x, ndigits))
    return out


def iteration(solver: str, label: str, it: int, value, grad_norm,
              step_size=None, ls_trials=None, lanes_active=None,
              lanes_done=None, delta=None, rho=None) -> None:
    """One host-driven solver iteration (streaming L-BFGS/OWL-QN/TRON).

    ``value``/``grad_norm`` may be scalars or per-lane arrays (swept
    solves); lane vectors are emitted in full — the grid is small by
    construction (a handful of λ points).  ``delta``/``rho`` are the
    trust-region radius and actual/predicted reduction ratio (ISSUE 17:
    the TRON radius trajectory is the convergence evidence the step
    norm alone cannot show — a collapsing δ means rejected steps even
    when the loss plane looks flat)."""
    t = telemetry.active()
    if t is None:
        return
    t.count("conv.iterations")
    fields = {"solver": solver, "label": label, "iteration": int(it)}
    v = np.asarray(value, np.float64).reshape(-1)
    g = np.asarray(grad_norm, np.float64).reshape(-1)
    if v.size == 1:
        fields["value"] = round(float(v[0]), 8)
        fields["grad_norm"] = float(g[0])
    else:
        fields["values"] = _round_list(v)
        fields["grad_norms"] = _round_list(g)
    if step_size is not None:
        fields["step_size"] = float(np.asarray(step_size).reshape(-1)[0])
    if ls_trials is not None:
        fields["ls_trials"] = int(ls_trials)
    if lanes_active is not None:
        fields["lanes_active"] = int(lanes_active)
    if lanes_done is not None:
        fields["lanes_done"] = int(lanes_done)
    if delta is not None:
        fields["delta"] = float(delta)
    if rho is not None:
        r = float(rho)
        fields["rho"] = None if r != r else round(r, 6)
    t._log.event("convergence_iter", **fields)


def solve_trace(solver: str, label: str, result) -> None:
    """One completed solve's full trajectory from its tracker planes.

    ``result`` is an ``OptimizationResult`` — scalar (one problem) or
    lane-batched (leading axis L, the swept solvers).  Per-entity
    vmapped random-effect results (thousands of lanes) should NOT come
    through here; their aggregate rides ``re_sweep``/``cd_coordinate``
    events instead."""
    t = telemetry.active()
    if t is None:
        return
    iters = np.asarray(result.iterations).reshape(-1)
    t.count("conv.solves")
    t.count("conv.solver_iterations", int(iters.sum()))
    fields = {"solver": solver, "label": label}
    lanes = iters.size
    if lanes == 1:
        fields["iterations"] = int(iters[0])
        fields["converged"] = bool(np.asarray(result.converged)
                                   .reshape(-1)[0])
    else:
        fields["lanes"] = lanes
        fields["iterations"] = [int(x) for x in iters.tolist()]
        fields["converged"] = [bool(x) for x in
                               np.asarray(result.converged)
                               .reshape(-1).tolist()]
    tracker = getattr(result, "tracker", None)
    if tracker is not None:
        count = np.asarray(tracker.count).reshape(-1)
        c = int(count.max()) if count.size else 0
        if c > 0:
            vals = np.asarray(tracker.values, np.float64)
            gns = np.asarray(tracker.grad_norms, np.float64)
            # Lane-batched planes are [L, max_iters+1]; keep slots
            # 0..c-1 (slot 0 = initial point).
            fields["values"] = _round_list(vals[..., :c])
            fields["grad_norms"] = _round_list(gns[..., :c])
            if tracker.step_sizes is not None:
                fields["step_sizes"] = _round_list(
                    np.asarray(tracker.step_sizes)[..., :c], 6)
            if tracker.ls_trials is not None:
                fields["ls_trials"] = _round_list(
                    np.asarray(tracker.ls_trials)[..., :c], 1)
    t._log.event("convergence_trace", **fields)


def re_retirement(coordinate: str, newly: int, total: int) -> None:
    """Retirement COMMIT (the CD between-sweeps hook): ``re_sweep``
    events sample the retired set as of sweep start, so the final
    sweep's commit would otherwise appear in no event (review
    finding)."""
    t = telemetry.active()
    if t is None:
        return
    t._log.event("re_retirement", coordinate=coordinate,
                 entities_newly_retired=int(newly),
                 entities_retired_total=int(total))


def re_sweep(coordinate: str, diag: dict) -> None:
    """One streamed random-effect sweep's entity dynamics (solved /
    converged / retired / woken counts + the iteration high-water)."""
    t = telemetry.active()
    if t is None:
        return
    t.count("conv.re_sweeps")
    keep = ("entities", "entities_solved", "entities_converged",
            "entities_retired", "entities_woken",
            "max_solver_iterations", "chunks_streamed")
    fields = {k: int(diag[k]) for k in keep if k in diag}
    t._log.event("re_convergence", coordinate=coordinate, **fields)
