"""Serve-report: cross-process request-latency decomposition (ISSUE 14).

``python -m photon_ml_tpu.telemetry serve-report <frontend_log>
<replica_logs...>`` joins the serving fleet's sampled request traces
BY TRACE ID across processes — the frontend's ``request_trace`` events
(routing / forward / retry-cost stages) against each replica's
(admission / queue-wait / serialize / write stages plus the linked
``batch_trace``'s shared assemble / store-lookup / dispatch / D2H
stages) — and prints the stage-level latency table the Spark-ML study
(PAPERS.md) argues is what actually finds a multi-stage pipeline's
bottleneck:

- **Stage table**: p50/p99/count per stage, split by basis — frontend
  stages over frontend records, request stages over replica records,
  batch stages over batch records.
- **Tail attribution**: every sampled TAIL request (above the
  recorder's threshold) is attributed to its DOMINANT stage — its own
  queue wait vs the linked batch's shared compute vs frontend retry
  cost — and the dominant-stage histogram names the fleet's bottleneck.
- **Retry cost**: requests with failed forward attempts, and the
  latency those failed attempts cost (the frontend's ``retry`` stage).
- **Join check**: the fraction of replica-side tail records with a
  matching frontend record.  A replica-side tail request is by
  construction at least as slow at the frontend, so with equal
  thresholds the join should be ~100%; below ``--join-threshold``
  (default 0.99) the report FAILS (rc 1) — trace propagation broke.
- ``--trace-out trace.json``: the joined timeline as a
  Perfetto-loadable Chrome trace with flow events
  (``telemetry.export.serve_trace_events``) — a request renders
  flowing frontend → replica → batcher → dispatch.

Single-process mode: pointing serve-report at one model server's log
(no frontend records) still prints the stage table and tail
attribution; the join check is N/A.  The last stdout line is one
machine-parseable JSON object (the repo's CLI contract); rc 1 when no
trace records are found or the join check fails.
"""

from __future__ import annotations

import json
import os
import sys

from photon_ml_tpu.serving.tracing import (
    ALL_STAGES,
    BATCH_STAGES,
    FRONTEND_STAGES,
    REQUEST_STAGES,
)
from photon_ml_tpu.telemetry.report import load_events

DEFAULT_JOIN_THRESHOLD = 0.99


def _percentile(sorted_vals: list, q: float):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def load_trace_files(paths: list[str]) -> list[dict]:
    """Each path → one process record: ``{name, path, requests,
    batches, roles}``.  Request/batch records are the ``TraceRecorder``
    JSONL event bodies; every segment of a stitched log contributes
    (a restarted replica's traces all count)."""
    processes = []
    for path in paths:
        events = load_events(path)
        requests = [ev for ev in events
                    if ev.get("event") == "request_trace"]
        batches = [ev for ev in events
                   if ev.get("event") == "batch_trace"]
        header = next((ev for ev in events
                       if ev.get("event") == "run_header"), None)
        name = os.path.basename(path)
        roles = sorted({r.get("role", "?") for r in requests})
        processes.append({
            "name": name, "path": path, "requests": requests,
            "batches": batches, "roles": roles,
            "run_id": (header or {}).get("run_id"),
        })
    return processes


def _attribution(rec: dict, batch: dict | None,
                 front: dict | None) -> dict:
    """One replica-side request's full stage attribution (ms): its own
    stages, the linked batch's shared stages, the joined frontend
    record's retry cost, and the residual neither claims."""
    out: dict = {}
    for stage, ms in (rec.get("stages_ms") or {}).items():
        out[stage] = out.get(stage, 0.0) + ms
    if batch is not None:
        for stage, ms in (batch.get("stages_ms") or {}).items():
            out[stage] = out.get(stage, 0.0) + ms
    total = float(rec.get("total_ms", 0.0))
    if front is not None:
        fr = (front.get("stages_ms") or {})
        if fr.get("retry"):
            out["retry"] = out.get("retry", 0.0) + fr["retry"]
        total = float(front.get("total_ms", total))
    residual = total - sum(out.values())
    if residual > 0:
        # Time neither a request stage nor the shared batch claims:
        # network + dispatcher-loop + handler scheduling.  Kept visible
        # so a creeping unattributed gap cannot hide.
        out["other"] = residual
    return out


def analyze(processes: list[dict],
            join_threshold: float = DEFAULT_JOIN_THRESHOLD) -> dict:
    """The decomposition over loaded trace files (pure; the CLI wraps
    it with rendering)."""
    frontend_by_trace: dict = {}
    replica_recs: list[tuple[int, dict]] = []
    frontend_recs: list[dict] = []
    for i, proc in enumerate(processes):
        for rec in proc["requests"]:
            if rec.get("role") == "frontend":
                frontend_recs.append(rec)
                frontend_by_trace.setdefault(rec.get("trace"), rec)
            else:
                replica_recs.append((i, rec))
    batch_by_proc = [
        {b.get("batch"): b for b in proc["batches"]}
        for proc in processes
    ]

    # Stage table: each stage over its natural basis.
    stage_vals: dict[str, list] = {}

    def fold(rec, stages):
        for stage in stages:
            ms = (rec.get("stages_ms") or {}).get(stage)
            if ms is not None:
                stage_vals.setdefault(stage, []).append(ms)

    for rec in frontend_recs:
        fold(rec, FRONTEND_STAGES)
    for _i, rec in replica_recs:
        fold(rec, REQUEST_STAGES)
    for proc in processes:
        for b in proc["batches"]:
            fold(b, BATCH_STAGES)
    stages_out = {}
    for stage in list(ALL_STAGES) + ["other"]:
        vals = sorted(stage_vals.get(stage, []))
        if vals:
            stages_out[stage] = {
                "count": len(vals),
                "p50_ms": round(_percentile(vals, 0.50), 3),
                "p99_ms": round(_percentile(vals, 0.99), 3),
                "max_ms": round(vals[-1], 3),
            }

    # Tail attribution + the cross-process join.
    tail = [(i, rec) for i, rec in replica_recs
            if rec.get("sampled") == "tail"]
    joined = 0
    dominant_counts: dict[str, int] = {}
    slowest: list[dict] = []
    for i, rec in tail:
        front = frontend_by_trace.get(rec.get("trace"))
        if front is not None:
            joined += 1
        batch = (batch_by_proc[i].get(rec.get("batch"))
                 if rec.get("batch") is not None else None)
        attr = _attribution(rec, batch, front)
        dom = max(attr, key=attr.get) if attr else "other"
        dominant_counts[dom] = dominant_counts.get(dom, 0) + 1
        slowest.append({
            "trace": rec.get("trace"),
            "total_ms": round(float((front or rec).get("total_ms", 0.0)),
                              3),
            "dominant": dom,
            "dominant_ms": round(attr.get(dom, 0.0), 3),
            "joined": front is not None,
            **({"retry_ms": round(attr["retry"], 3)}
               if attr.get("retry") else {}),
        })
    slowest.sort(key=lambda r: -r["total_ms"])

    # Retry cost (frontend records with failed forward attempts).
    retried = [r for r in frontend_recs
               if any(str(a.get("outcome", "")).startswith("connect_fail")
                      for a in r.get("attempts", ()))]
    retry_ms = sorted((r.get("stages_ms") or {}).get("retry", 0.0)
                      for r in retried)
    join_fraction = (round(joined / len(tail), 4) if tail
                     and frontend_recs else None)
    sampled_total = len(replica_recs) + len(frontend_recs)
    ok = sampled_total > 0 and (join_fraction is None
                                or join_fraction >= join_threshold)
    dominant = (max(dominant_counts, key=dominant_counts.get)
                if dominant_counts else None)
    return {
        "ok": ok,
        "processes": [{k: p[k] for k in
                       ("name", "run_id", "roles")}
                      | {"requests": len(p["requests"]),
                         "batches": len(p["batches"])}
                      for p in processes],
        "sampled_requests": sampled_total,
        "frontend_requests": len(frontend_recs),
        "replica_requests": len(replica_recs),
        "tail_requests": len(tail),
        "joined": joined,
        "join_fraction": join_fraction,
        "join_threshold": join_threshold,
        "stages": stages_out,
        "dominant_counts": dominant_counts,
        "dominant_stage": dominant,
        "retried_requests": len(retried),
        "retry_cost_ms": {
            "count": len(retry_ms),
            "total": round(sum(retry_ms), 3),
            "max": round(retry_ms[-1], 3) if retry_ms else None,
        },
        "slowest": slowest[:10],
    }


def run_serve_report(paths: list[str],
                     join_threshold: float = DEFAULT_JOIN_THRESHOLD,
                     trace_out: str | None = None, out=None) -> dict:
    """Load → analyze → print (tables + JSON last line); ``ok`` drives
    the exit code."""
    out = out or sys.stdout
    w = lambda s="": print(s, file=out)
    processes = load_trace_files(paths)
    result = analyze(processes, join_threshold=join_threshold)

    w(f"Serve trace report over {len(processes)} process log(s):")
    for p in result["processes"]:
        w(f"  {p['name']}: {p['requests']} request traces "
          f"({'/'.join(p['roles']) or 'none'}), {p['batches']} batch "
          f"traces")
    w()
    if result["sampled_requests"] == 0:
        w("No request_trace events found — tracing off, or the logs "
          "are not serving run logs.")
    if result["stages"]:
        w("Stage latency (sampled requests; batch stages once per "
          "micro-batch):")
        w(f"  {'stage':<14} {'count':>7} {'p50_ms':>9} {'p99_ms':>9} "
          f"{'max_ms':>9}")
        for stage, ent in result["stages"].items():
            w(f"  {stage:<14} {ent['count']:>7} {ent['p50_ms']:>9.3f} "
              f"{ent['p99_ms']:>9.3f} {ent['max_ms']:>9.3f}")
        w()
    if result["tail_requests"]:
        w(f"Tail attribution ({result['tail_requests']} tail "
          f"request(s)):")
        for stage, n in sorted(result["dominant_counts"].items(),
                               key=lambda kv: -kv[1]):
            w(f"  dominant {stage}: {n} "
              f"({n / result['tail_requests']:.0%})")
        for rec in result["slowest"][:5]:
            w(f"  {rec['trace']}: {rec['total_ms']} ms, dominant "
              f"{rec['dominant']} ({rec['dominant_ms']} ms)"
              + (f", retry {rec['retry_ms']} ms"
                 if rec.get("retry_ms") else "")
              + ("" if rec["joined"] else " [unjoined]"))
        w()
    if result["retried_requests"]:
        rc = result["retry_cost_ms"]
        w(f"Retry cost: {result['retried_requests']} request(s) with "
          f"failed forward attempts; {rc['total']} ms total, "
          f"{rc['max']} ms worst.")
        w()
    if result["join_fraction"] is not None:
        ok = result["join_fraction"] >= join_threshold
        w(f"Cross-process join: {result['joined']}/"
          f"{result['tail_requests']} tail requests matched a frontend "
          f"trace ({result['join_fraction']:.1%}) "
          f"{'>=' if ok else '<'} threshold {join_threshold:.0%} "
          f"-> {'PASS' if ok else 'FAIL'}")
        w()
    if trace_out is not None:
        from photon_ml_tpu.telemetry.export import write_serve_trace

        write_serve_trace(trace_out, processes)
        result["trace_out"] = trace_out
        w(f"Perfetto flow trace written to {trace_out} (load in "
          "https://ui.perfetto.dev).")
        w()
    print(json.dumps(result), file=out)
    return result
