"""Bench-history trajectory: ingest, aggregate, regression-gate (ISSUE 8).

``BENCH_r*.json`` records accumulate at the repo root — one per driver
round, one of which even recorded ``rc: 124`` — with no aggregation or
regression detection: a rows/s collapse of exactly the kind the bench
sections exist to catch would land silently.  This module turns a
directory (or explicit list) of bench records into a per-section,
per-metric TRAJECTORY and gates it:

- **Formats ingested** (all tolerated in one directory):
  the driver wrapper ``{"n", "cmd", "rc", "tail", "parsed"}`` (the
  repo's ``BENCH_r*.json``), the raw bench JSON-last-line record, and
  the ``--history-dir`` envelope ``{"schema", "kind": "bench_record",
  "argv", "record"}`` bench.py appends per run.  Files sort by name —
  the round order.
- **Metrics**: a fixed spec of (section, dotted path, direction) pairs
  covering the sections' numbers of record — throughput (examples/s,
  rows/s), pass-time and RSS ratios, overlap efficiency, warm-ETL
  speedup, retirement work fraction.  Missing values (older schemas,
  skipped sections) simply leave holes in the trajectory.
- **Regression detection**: each round's value is compared against a
  ROLLING BASELINE — the median of up to ``window`` preceding values —
  and flagged when it is worse (per the metric's direction) by more
  than ``tolerance`` (relative).  Any round whose wrapper recorded a
  nonzero rc is flagged unconditionally: a bench that died has no
  numbers to defend.
- **Output**: a markdown trajectory table + one JSON object as the
  last stdout line (the repo's CLI contract); exit code 1 on any
  regression or nonzero-rc round, 0 on a clean trajectory.
"""

from __future__ import annotations

import json
import os
import statistics

# A 20% worsening must gate (the bench contract test injects exactly
# that), so the default sits below it; host-jitter on the 2-core bench
# box measures ~±10% on pass times, comfortably inside.
DEFAULT_TOLERANCE = 0.15
DEFAULT_WINDOW = 3

# (section, dotted path into the bench record, direction).  Direction
# "higher" = a drop beyond tolerance regresses; "lower" = a rise does.
METRICS: tuple[tuple[str, str, str], ...] = (
    ("overall", "value", "higher"),                 # examples/s (GRR)
    ("overall", "step_ms_grr", "lower"),
    ("overall", "vs_baseline", "higher"),
    ("etl", "etl_grr_s", "lower"),
    ("cached", "cached.warm_speedup", "higher"),
    ("sweep", "sweep.speedup", "higher"),
    ("sweep", "sweep.pass_amortization", "higher"),
    ("stream", "stream.spilled.examples_per_sec", "higher"),
    ("stream", "stream.pass_time_ratio", "lower"),
    ("stream", "stream.spilled.rss_delta_mb", "lower"),
    ("stream", "stream.spilled.telemetry.overlap_efficiency", "higher"),
    ("score", "score.streamed.rows_per_sec", "higher"),
    ("score", "score.pass_time_ratio", "lower"),
    ("re", "re.streamed.rows_per_sec", "higher"),
    ("re", "re.sweep_time_ratio", "lower"),
    ("re", "re.retirement_work_fraction", "lower"),
    # Fused CD super-sweep (ISSUE 11): one store pass per cycle is THE
    # claim — passes/cycle creeping up, the fused pass slowing against
    # the legacy pass, or fused throughput dropping all gate.
    ("cd_fused", "cd_fused.passes_per_cycle_fused", "lower"),
    ("cd_fused", "cd_fused.pass_time_ratio", "lower"),
    ("cd_fused", "cd_fused.fused.rows_per_sec", "higher"),
    # Online serving (ISSUE 12): tail latency creeping up, sustained
    # throughput dropping, or micro-batch fill collapsing (the
    # batcher degenerating to single-row dispatches) all gate.
    ("serve", "serve.p99_ms", "lower"),
    ("serve", "serve.rows_per_sec", "higher"),
    ("serve", "serve.batch_fill", "higher"),
    # Resilient fleet (ISSUE 13): the SIGKILL arm's claims — failed
    # client requests must stay at zero (the retry-once contract) and
    # a killed replica's detect→respawn→re-warm→ready latency must not
    # creep.
    ("serve", "serve.failed_requests", "lower"),
    ("serve", "serve.restart_s", "lower"),
    # Request tracing (ISSUE 14): the stage medians the tracing tier
    # decomposes the tail into — queue wait creeping up means the
    # batcher is becoming the bottleneck, dispatch creeping up means
    # the device path regressed; both gate like every other metric.
    ("serve", "serve.queue_wait_ms", "lower"),
    ("serve", "serve.dispatch_ms", "lower"),
    # Multi-host out-of-core training (ISSUE 16): the sharded-streaming
    # claims — fleet throughput dropping, hosts stalling at the chunk
    # barrier, any host's peak RSS creeping toward its budget, or the
    # fleet-wide passes/cycle identity drifting above ~1 all gate.
    ("mesh_stream", "mesh_stream.rows_per_sec", "higher"),
    ("mesh_stream", "mesh_stream.barrier_wait_fraction", "lower"),
    ("mesh_stream", "mesh_stream.max_host_peak_rss_mb", "lower"),
    ("mesh_stream", "mesh_stream.passes_per_cycle", "lower"),
    # Streaming TRON (ISSUE 17): the second-order claim — total data
    # passes to tolerance creeping up (the pass advantage over
    # streaming L-BFGS eroding), streamed throughput dropping, or the
    # TRON arm's peak RSS growing (the HVP pass must stay as
    # store-bounded as the L-BFGS passes) all gate.
    ("tron", "tron.passes_to_tol", "lower"),
    ("tron", "tron.rows_per_sec", "higher"),
    ("tron", "tron.peak_rss_mb", "lower"),
)


def _dig(record: dict, path: str):
    cur = record
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur if isinstance(cur, (int, float)) else None


def load_round(path: str) -> dict:
    """One history file → ``{name, rc, record, header}``.

    Unreadable/unparseable files become rc-None rounds with no record
    (reported, never fatal — history is a forensic tool)."""
    name = os.path.basename(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return {"name": name, "rc": None, "record": None,
                "error": f"{type(e).__name__}: {e}", "header": None}
    if not isinstance(doc, dict):
        return {"name": name, "rc": None, "record": None,
                "error": "not a JSON object", "header": None}
    def _rc(value):
        # A wrapper that recorded "rc": null is the torn-run class
        # (BENCH_r05's cousin): normalize to None, which detect()
        # flags as a failed round instead of crashing the gate.
        if isinstance(value, bool) or not isinstance(value, int):
            return None
        return value

    if doc.get("kind") == "bench_record":        # --history-dir envelope
        header = {k: doc.get(k) for k in ("schema", "argv", "ts")
                  if k in doc}
        return {"name": name, "rc": _rc(doc.get("rc", 0)),
                "record": doc.get("record"), "header": header}
    if "rc" in doc and ("parsed" in doc or "tail" in doc):
        # Driver wrapper (the repo's BENCH_r*.json shape).
        return {"name": name, "rc": _rc(doc.get("rc", 0)),
                "record": doc.get("parsed"), "header": None}
    # Raw bench JSON-last-line record.
    return {"name": name, "rc": 0, "record": doc, "header": None}


def load_rounds(paths: list[str]) -> list[dict]:
    """Expand directories, sort by file name (round order), load."""
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(os.path.join(p, fn) for fn in sorted(os.listdir(p))
                         if fn.endswith(".json"))
        else:
            files.append(p)
    return [load_round(p) for p in files]


def trajectory(rounds: list[dict]) -> dict:
    """``{metric key: [value-or-None per round]}`` over the spec."""
    out: dict = {}
    for section, path, direction in METRICS:
        key = f"{section}:{path}"
        series = [(_dig(r["record"], path) if r["record"] else None)
                  for r in rounds]
        if any(v is not None for v in series):
            out[key] = {"direction": direction, "values": series}
    return out


def parse_known_bad(specs: list[str]) -> dict[str, str]:
    """``--known-bad ROUND=REASON`` flags → ``{round name: reason}``.

    The reason is REQUIRED (ISSUE 10): a waiver with no recorded "why"
    is how a real regression gets rubber-stamped next quarter — the
    gate echoes the reason in its markdown so the acknowledgment
    travels with every trajectory report."""
    out: dict[str, str] = {}
    for spec in specs:
        round_name, sep, reason = spec.partition("=")
        if not sep or not reason.strip() or not round_name.strip():
            raise ValueError(
                f"--known-bad needs ROUND=REASON (a reason is "
                f"required), got {spec!r}")
        out[round_name.strip()] = reason.strip()
    return out


def detect(rounds: list[dict], tolerance: float = DEFAULT_TOLERANCE,
           window: int = DEFAULT_WINDOW,
           known_bad: dict[str, str] | None = None) -> dict:
    """Regressions + failed rounds over the trajectory.

    A value regresses when it is worse than the rolling baseline (the
    median of up to ``window`` PRECEDING non-null values) by more than
    ``tolerance`` relative; the first valid value of a metric is its
    own baseline (never flagged).  Baselines at or below zero are
    skipped — a relative tolerance has no meaning there.

    ``known_bad`` (ISSUE 10, ``--known-bad ROUND=REASON``): rounds
    whose failure is already acknowledged (BENCH_r05's rc-124 budget
    timeout is the resident case) move from ``failed_rounds``/
    ``regressions`` to ``waived`` and stop failing the gate; the
    waived round's values STILL feed later baselines exactly as
    before — the waiver silences the verdict, not the data."""
    known_bad = known_bad or {}
    traj = trajectory(rounds)
    regressions = []
    for key, ent in traj.items():
        vals = ent["values"]
        higher = ent["direction"] == "higher"
        seen: list[float] = []
        for i, v in enumerate(vals):
            if v is None:
                continue
            if seen:
                base = statistics.median(seen[-window:])
                if base > 0:
                    change = (v - base) / base
                    if (-change if higher else change) > tolerance:
                        regressions.append({
                            "round": rounds[i]["name"],
                            "metric": key,
                            "value": v,
                            "baseline": round(base, 6),
                            "change": round(change, 4),
                            "direction": ent["direction"],
                        })
            seen.append(v)
    failed = [{"round": r["name"], "rc": r["rc"],
               **({"error": r["error"]} if r.get("error") else {})}
              for r in rounds if r["rc"] not in (0,)]
    waived = ([{**f, "reason": known_bad[f["round"]]}
               for f in failed if f["round"] in known_bad]
              + [{**reg, "reason": known_bad[reg["round"]]}
                 for reg in regressions if reg["round"] in known_bad])
    failed = [f for f in failed if f["round"] not in known_bad]
    regressions = [reg for reg in regressions
                   if reg["round"] not in known_bad]
    round_names = {r["name"] for r in rounds}
    unknown_waivers = sorted(set(known_bad) - round_names)
    return {
        "ok": not regressions and not failed,
        "rounds": [r["name"] for r in rounds],
        "trajectory": traj,
        "regressions": regressions,
        "failed_rounds": failed,
        "waived": waived,
        "unknown_waivers": unknown_waivers,
        "tolerance": tolerance,
        "window": window,
    }


def render_markdown(result: dict, out) -> None:
    """The human half of the contract: a per-metric trajectory table
    with the newest round last, regressions and dead rounds called
    out."""
    w = lambda s="": print(s, file=out)
    rounds = result["rounds"]
    w(f"# Bench history ({len(rounds)} rounds, tolerance "
      f"{result['tolerance']:.0%}, window {result['window']})")
    w()
    if rounds:
        w("| metric | dir | " + " | ".join(rounds) + " |")
        w("|---" * (len(rounds) + 2) + "|")
        for key, ent in result["trajectory"].items():
            cells = ["-" if v is None else f"{v:g}"
                     for v in ent["values"]]
            arrow = "↑" if ent["direction"] == "higher" else "↓"
            w(f"| {key} | {arrow} | " + " | ".join(cells) + " |")
        w()
    for fr in result["failed_rounds"]:
        w(f"**FAILED ROUND** {fr['round']}: rc={fr['rc']}"
          + (f" ({fr['error']})" if fr.get("error") else ""))
    for wv in result.get("waived", []):
        what = (f"rc={wv['rc']}" if "rc" in wv
                else f"{wv['metric']}: {wv['value']:g} "
                     f"({wv['change']:+.1%})")
        w(f"**WAIVED** {wv['round']} ({what}) — known bad: "
          f"{wv['reason']}")
    for name in result.get("unknown_waivers", []):
        w(f"**UNKNOWN WAIVER** --known-bad {name} matches no loaded "
          "round (typo, or the round was removed)")
    for reg in result["regressions"]:
        w(f"**REGRESSION** {reg['round']} {reg['metric']}: "
          f"{reg['value']:g} vs baseline {reg['baseline']:g} "
          f"({reg['change']:+.1%}, want "
          f"{'higher' if reg['direction'] == 'higher' else 'lower'})")
    if result["ok"]:
        w("Trajectory clean: no regressions, no failed rounds.")
    w()


def run_history(paths: list[str], tolerance: float = DEFAULT_TOLERANCE,
                window: int = DEFAULT_WINDOW, out=None,
                known_bad: dict[str, str] | None = None) -> dict:
    """Load → detect → print (markdown + JSON last line); returns the
    result dict (``ok`` drives the exit code)."""
    import sys

    out = out or sys.stdout
    rounds = load_rounds(paths)
    result = detect(rounds, tolerance=tolerance, window=window,
                    known_bad=known_bad)
    render_markdown(result, out)
    print(json.dumps(result), file=out)
    return result
