"""Device accounting: XLA program costs, device memory, rooflines (ISSUE 8).

The ROADMAP's scale math runs on two numbers that were previously
hand-reconstructed: device-memory residency ("~1.6 GB HBM per 1e6
examples" — the KDD sizing for the ~16-chip mesh) and per-program
bytes/FLOPs (PERF.md's roofline fractions).  This module turns both
into emitted data riding the telemetry session:

- **Program costs**: per-jitted-program XLA ``cost_analysis()`` (FLOPs,
  bytes accessed) + ``memory_analysis()`` (argument/output/temp bytes),
  captured once per session per program name at its first instrumented
  dispatch (``maybe_capture``).  The capture AOT-relowers the
  just-executed program — the pjit lowering cache means NO new
  "Compiling" record is emitted, so the compile-budget counters and
  guard tests are untouched (verified: ``jax.compiles`` stays 0 across
  a capture of a warm program).
- **Roofline estimate**: bytes-accessed over the platform's peak memory
  bandwidth — the analytic time floor the report compares against the
  measured per-chunk span.  Peaks are a small static table (v5e HBM is
  the measured platform of record; CPU gets a labeled nominal figure so
  the estimate is never silently null on the test backend).
- **Device memory**: ``Device.memory_stats()`` where the backend
  provides it (TPU/GPU), a ``jax.live_arrays()`` nbytes census as the
  CPU fallback — sampled at phase boundaries (every cat="phase" span
  open/close) into ``device.bytes_in_use`` gauges and a (ts, bytes)
  series for the trace counter track.

Everything is best-effort and session-gated: with telemetry off these
helpers cost one global read; capture/sampling failures degrade to a
missing block, never a broken run (the guard discipline).
"""

from __future__ import annotations

import logging
import sys

logger = logging.getLogger(__name__)

# Peak memory bandwidth per jax platform, GB/s.  "tpu" is the v5e HBM
# figure the bench's roofline_fraction already uses (bench.V5E_PEAK_GBPS);
# "cpu" is a labeled nominal (dual-channel DDR4) so CPU-backend runs and
# tests still emit a non-null estimate — the CPU number sizes nothing,
# it keeps the plumbing honest end to end.
PLATFORM_PEAK_GBPS = {
    "tpu": (819.0, "v5e HBM peak"),
    "gpu": (900.0, "nominal A100-class HBM"),
    "cpu": (25.6, "nominal dual-channel DDR4"),
}


def _jax():
    """The jax module if (and only if) something already imported it —
    device accounting must never force a backend into a host-only
    driver."""
    return sys.modules.get("jax")


def _platform() -> str | None:
    jax = _jax()
    if jax is None:
        return None
    try:
        return jax.devices()[0].platform
    except Exception:  # photon-lint: disable=swallowed-exception (backend probe; cost capture degrades to unlabeled platform)
        return None


def program_cost(fn, args, platform: str | None = None) -> dict | None:
    """FLOPs / bytes / memory / roofline estimate for jitted ``fn`` at
    ``args`` via AOT ``lower().compile()``.

    Call AFTER the program has executed once with congruent arguments:
    the lowering cache then serves the trace, no "Compiling" record is
    logged (compile budgets unaffected), and the XLA backend compile is
    a cache hit wherever the persistent compilation cache is wired.
    Returns None (logged at info) on any failure."""
    try:
        compiled = fn.lower(*args).compile()
        ca = compiled.cost_analysis()
    except Exception as e:       # pragma: no cover - backend-specific
        logger.info("device cost capture failed: %r", e)
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    flops = float(ca.get("flops", 0.0))
    byts = float(ca.get("bytes accessed", 0.0))
    out = {
        "flops": flops,
        "bytes_accessed": byts,
        "bytes_per_flop": (round(byts / flops, 4) if flops > 0 else None),
    }
    try:
        mem = compiled.memory_analysis()
        out["argument_bytes"] = int(mem.argument_size_in_bytes)
        out["output_bytes"] = int(mem.output_size_in_bytes)
        out["temp_bytes"] = int(mem.temp_size_in_bytes)
    except Exception:  # pragma: no cover - backend-specific  # photon-lint: disable=swallowed-exception (memory_analysis is optional per backend; cost rows just omit it)
        pass
    platform = platform or _platform()
    peak = PLATFORM_PEAK_GBPS.get(platform or "")
    if peak is not None and byts > 0:
        gbps, source = peak
        out["platform"] = platform
        out["peak_gbps"] = gbps
        out["peak_source"] = source
        out["roofline_est_ms"] = round(byts / (gbps * 1e9) * 1e3, 6)
    return out


def maybe_capture(name: str, fn, args, span: str | None = None) -> bool:
    """Session-scoped, once-per-name program-cost capture.

    Instrumentation sites call this right after a program's first
    dispatch in a sweep; the compile bridge's counter proves the
    capture itself compiled nothing new.  ``span`` names the stage span
    whose measured duration the report compares the roofline estimate
    against (e.g. ``chunk_compute``).  Returns True when THIS call
    performed the capture (callers exclude that dispatch from their
    per-program timing measures — it paid the XLA compile)."""
    from photon_ml_tpu import telemetry

    t = telemetry.active()
    if t is None:
        return False
    with t._lock:
        if name in t._device_programs:
            return False
        t._device_programs[name] = None   # reserve: capture once, ever
    cost = program_cost(fn, args)
    if cost is None:
        return True
    if span is not None:
        cost["span"] = span
    with t._lock:
        t._device_programs[name] = cost
    t._log.event("device_cost", program=name, **cost)
    return True


def memory_snapshot() -> dict | None:
    """Current device-memory occupancy: backend ``memory_stats()``
    summed over local devices, or a live-buffer nbytes census on
    backends (CPU) that expose none.  None when jax is absent or the
    backend is not initialized."""
    jax = _jax()
    if jax is None:
        return None
    try:
        devices = jax.local_devices()
    except Exception:  # photon-lint: disable=swallowed-exception (no initialized backend: the memory gauge simply has no source)
        return None
    in_use = peak = 0
    have_stats = False
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if ms:
            have_stats = True
            in_use += int(ms.get("bytes_in_use", 0))
            peak += int(ms.get("peak_bytes_in_use", 0))
    if have_stats:
        return {"source": "memory_stats", "bytes_in_use": in_use,
                "peak_bytes_in_use": peak, "devices": len(devices)}
    try:
        live = jax.live_arrays()
        return {"source": "live_arrays",
                "bytes_in_use": int(sum(int(getattr(a, "nbytes", 0))
                                        for a in live)),
                "buffers": len(live)}
    except Exception:  # pragma: no cover - jax-version edge  # photon-lint: disable=swallowed-exception (live_arrays census is best-effort; gauge degrades to absent)
        return None
