"""Fleet report: the per-host join of a multi-host training run
(ISSUE 16).

``python -m photon_ml_tpu.telemetry fleet-report <host_logs...>`` joins
each host's ``run_log.jsonl`` (one per ``host_NNN/`` output subdir)
into the aggregated fleet view that no single host's log can show:

- **Per-host rows**: chunks streamed, cross-host reductions
  (``fleet.psums``), barrier-wait seconds and the barrier-wait
  fraction of that host's streamed-pass time, peak RSS when the log
  carries it, and each host's own sweep odometer.
- **Barrier agreement**: every host MUST report the same reduction
  count — the chunk-synchronized schedule pads ragged shards with
  empty-chunk sentinels precisely so the barrier count cannot differ;
  a mismatch means a host skipped (or double-fired) a collective and
  the run only finished by luck.  Mismatch → rc 1.
- **Fleet-wide sweep odometer**: solver state is replicated (every
  host applies the same globally-reduced statistics), so per-host
  sweep odometers must agree host-to-host AND each must internally
  reconcile (the ``telemetry report`` identity: ``solver.sweeps ==
  streamed_solves + ls_trials + grad_recovery_sweeps + aux_sweeps +
  fused_cycle_sweeps``).  Any host failing its own identity, or any
  two hosts disagreeing, fails the report.
- **Resume forensics**: hosts whose stitched logs carry multiple run
  segments (a killed + restarted host) are flagged with their
  ``fleet.seq_restored`` count — the killed-host-resume audit trail.

The last stdout line is one machine-parseable JSON object (the repo's
CLI contract); exit code 1 when no fleet counters are found, the
barrier counts disagree, or the fleet-wide sweep odometer fails.
"""

from __future__ import annotations

import json
import os
import sys

from photon_ml_tpu.telemetry.report import (
    _convergence,
    _phases,
    load_events,
    split_segments,
)


def load_host_logs(paths: list[str]) -> list[dict]:
    """Each path → one host record.  The LAST run segment is the
    record of record (a restarted host appends with a fresh header);
    the segment count itself is the restart evidence."""
    hosts = []
    for path in paths:
        segments = split_segments(load_events(path))
        events = segments[-1]
        header = next((e for e in events
                       if e.get("event") == "run_header"), None)
        summary = None
        for ev in events:
            if ev.get("event") == "telemetry_summary":
                summary = ev
        counters = (summary or {}).get("counters", {})
        derived = (summary or {}).get("derived", {})
        host_id = (header or {}).get("fleet_host")
        if host_id is None:
            # Logs from before the header carried fleet identity (or
            # hand-assembled dirs): fall back to the host_NNN path
            # convention the driver shards output by.
            for part in reversed(os.path.normpath(path).split(os.sep)):
                if part.startswith("host_") and part[5:].isdigit():
                    host_id = int(part[5:])
                    break
        hosts.append({
            "name": os.path.basename(os.path.dirname(path)) or path,
            "path": path,
            "host": host_id,
            "n_hosts": (header or {}).get("fleet_hosts"),
            "transport": (header or {}).get("fleet_transport"),
            "run_id": (header or {}).get("run_id"),
            "segments": len(segments),
            "counters": counters,
            "derived": derived,
            "convergence": _convergence(events, counters),
            "phases": dict(_phases(events)),
            "peak_rss_mb": ((summary or {}).get("gauges", {})
                            .get("proc.rss_mb") or {}).get("max"),
        })
    hosts.sort(key=lambda h: (h["host"] is None, h["host"]))
    return hosts


def _host_row(h: dict) -> dict:
    c = h["counters"]
    wait_s = float(c.get("fleet.barrier_wait_s", 0.0))
    # Barrier wait is measured inside the streamed pass, so the pass
    # span total is its natural denominator; the fit phase is the
    # fallback for logs without span telemetry.
    pass_s = float(h["derived"].get("pass_span_total_s", 0.0)) or float(
        h["phases"].get("fit", 0.0))
    conv = h["convergence"] or {}
    return {
        "host": h["host"],
        "name": h["name"],
        "run_id": h["run_id"],
        "transport": h["transport"],
        "segments": h["segments"],
        "chunks_streamed": int(c.get("fleet.chunks_streamed", 0)),
        "reduces": int(c.get("fleet.psums", 0)),
        "barrier_wait_s": round(wait_s, 3),
        "barrier_wait_fraction": (round(wait_s / pass_s, 4)
                                  if pass_s > 0 else None),
        "seq_restored": int(c.get("fleet.seq_restored", 0)),
        "sweeps": conv.get("sweeps"),
        "passes_per_cycle": conv.get("passes_per_cycle"),
        "odometer_ok": conv.get("ok"),
        "peak_rss_mb": h["peak_rss_mb"],
    }


def analyze(hosts: list[dict]) -> dict:
    """The fleet join over loaded host logs (pure; the CLI wraps it
    with rendering)."""
    rows = [_host_row(h) for h in hosts]
    fleet_rows = [r for r in rows if r["reduces"] > 0]
    reduce_counts = sorted({r["reduces"] for r in fleet_rows})
    barrier_agreement = len(reduce_counts) <= 1
    odometers = sorted({(r["sweeps"], r["passes_per_cycle"])
                        for r in rows if r["sweeps"] is not None})
    odometer_agreement = len(odometers) <= 1
    odometer_ok = all(r["odometer_ok"] is not False for r in rows)
    restarted = [r["host"] for r in rows if r["segments"] > 1]
    expected = next((h["n_hosts"] for h in hosts
                     if h["n_hosts"] is not None), None)
    ok = (bool(fleet_rows) and barrier_agreement
          and odometer_agreement and odometer_ok
          and (expected is None or len(rows) == expected))
    return {
        "ok": ok,
        "hosts": rows,
        "n_hosts": len(rows),
        "expected_hosts": expected,
        "total_chunks_streamed": sum(r["chunks_streamed"] for r in rows),
        "reduces": reduce_counts[0] if barrier_agreement and reduce_counts
        else None,
        "barrier_agreement": barrier_agreement,
        "reduce_counts": reduce_counts,
        "odometer_agreement": odometer_agreement,
        "odometer_ok": odometer_ok,
        "fleet_sweeps": odometers[0][0] if odometer_agreement and odometers
        else None,
        "passes_per_cycle": (odometers[0][1]
                             if odometer_agreement and odometers else None),
        "max_barrier_wait_fraction": max(
            (r["barrier_wait_fraction"] or 0.0 for r in rows),
            default=0.0),
        "max_peak_rss_mb": max(
            (r["peak_rss_mb"] for r in rows
             if r["peak_rss_mb"] is not None), default=None),
        "restarted_hosts": restarted,
    }


def run_fleet_report(paths: list[str], out=None) -> dict:
    """Load → analyze → print (table + JSON last line); ``ok`` drives
    the exit code."""
    out = out or sys.stdout
    w = lambda s="": print(s, file=out)
    hosts = load_host_logs(paths)
    result = analyze(hosts)

    w(f"Fleet report over {len(hosts)} host log(s):")
    w(f"  {'host':>4} {'chunks':>7} {'reduces':>8} {'wait_s':>8} "
      f"{'wait%':>6} {'sweeps':>7} {'p/cyc':>6} {'rss_mb':>8} "
      f"{'segs':>5}")
    for r in result["hosts"]:
        wf = r["barrier_wait_fraction"]
        w(f"  {r['host'] if r['host'] is not None else '?':>4} "
          f"{r['chunks_streamed']:>7} {r['reduces']:>8} "
          f"{r['barrier_wait_s']:>8.3f} "
          f"{(f'{wf:.1%}' if wf is not None else '-'):>6} "
          f"{r['sweeps'] if r['sweeps'] is not None else '-':>7} "
          f"{r['passes_per_cycle'] if r['passes_per_cycle'] is not None else '-':>6} "
          f"{r['peak_rss_mb'] if r['peak_rss_mb'] is not None else '-':>8} "
          f"{r['segments']:>5}")
    w()
    if not any(r["reduces"] for r in result["hosts"]):
        w("No fleet counters found — these are not multi-host run "
          "logs, or the fleet never reduced.")
        w()
    if result["restarted_hosts"]:
        seqs = {r["host"]: r["seq_restored"] for r in result["hosts"]
                if r["segments"] > 1}
        w(f"Restarted host(s) {result['restarted_hosts']}: resumed "
          f"from per-host checkpoints (fleet.seq_restored per host: "
          f"{seqs}) while peers held the barrier.")
        w()
    w(f"Barrier agreement: reduce counts {result['reduce_counts']} "
      f"across hosts -> "
      f"{'PASS' if result['barrier_agreement'] else 'FAIL'}")
    w(f"Fleet sweep odometer: "
      + (f"{result['fleet_sweeps']} data passes on every host, "
         f"passes/cycle {result['passes_per_cycle']}"
         if result["odometer_agreement"] else
         "hosts DISAGREE (replicated solver state has drifted)")
      + f" -> {'PASS' if result['odometer_agreement'] and result['odometer_ok'] else 'FAIL'}")
    if (result["expected_hosts"] is not None
            and result["expected_hosts"] != result["n_hosts"]):
        w(f"MISSING HOSTS: headers declare {result['expected_hosts']} "
          f"hosts, {result['n_hosts']} log(s) given.")
    w()
    print(json.dumps(result), file=out)
    return result
