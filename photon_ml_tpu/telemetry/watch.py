"""Live run watch: follow a still-being-written ``run_log.jsonl``
(ISSUE 10).

``python -m photon_ml_tpu.telemetry watch <run_log.jsonl>`` renders a
refreshing status view of a RUNNING fit/score — the live counterpart
of ``telemetry report``'s post-mortem.  It reuses the report's event
loading (torn-tail tolerant: a live writer's partial final line is
skipped, not fatal) and ``run_header`` segment splitting (a resumed
run appends with a fresh header; the LAST segment is the live one),
then derives:

- **Phase**: the innermost driver phase still open
  (``phase_start`` without its ``phase_end``) — what the run is doing
  right now.
- **Progress**: the newest ``progress`` event per stage (done/total,
  unit, rolling rate, ETA) as emitted by the live monitor at snapshot
  cadence; the most recently updated stage leads the view and its ETA
  is the headline ``eta_s``.
- **Loss trajectory**: recent ``progress`` losses per stage plus the
  last swept ``convergence_iter``'s per-lane ``values`` (telemetry-on
  runs) — the per-lane view of a λ-grid solve.
- **Reliability**: heartbeat counts per stage, ``thread_exception``
  events, segment/torn-line counts — the liveness forensics, live.
- **Alerts**: every structured ``alert`` event so far (the monitor's
  online anomaly rules latch per rule×stage, so each appears once).

``--once`` prints a single snapshot and exits (the scripting mode);
either mode ends with one machine-parseable JSON object as the last
stdout line (the repo's CLI contract).  Follow mode refreshes every
``--interval`` seconds and exits when the run logs its ``done`` event
(or on Ctrl-C), then prints the final JSON line.
"""

from __future__ import annotations

import json
import sys
import time

from photon_ml_tpu.telemetry.report import load_events, split_segments

DEFAULT_INTERVAL_S = 2.0
# Recent-loss trajectory kept per stage (one point per snapshot-cadence
# progress event — minutes of run at the default cadence).
_LOSS_TRAJECTORY_CAP = 32


def snapshot(path: str) -> dict:
    """One JSON-ready snapshot of a (possibly live) run log."""
    all_events = load_events(path)
    segments = split_segments(all_events)
    events = segments[-1]

    header = next((e for e in events if e.get("event") == "run_header"),
                  None)
    open_phases: list[dict] = []
    phases_done: list[dict] = []
    stages: dict[str, dict] = {}
    losses: dict[str, list] = {}
    lanes: dict | None = None
    alerts: list[dict] = []
    beats: dict[str, int] = {}
    deaths: list[dict] = []
    done_event = None
    last_t = 0.0
    for ev in events:
        kind = ev.get("event")
        t = ev.get("t")
        if isinstance(t, (int, float)):
            last_t = max(last_t, float(t))
        if kind == "phase_start":
            open_phases.append({"phase": ev.get("phase", "?"),
                                "t": ev.get("t")})
        elif kind == "phase_end":
            name = ev.get("phase")
            for i in range(len(open_phases) - 1, -1, -1):
                if open_phases[i]["phase"] == name:
                    del open_phases[i]
                    break
            phases_done.append({"phase": name,
                                "duration_s": ev.get("duration_s")})
        elif kind == "progress":
            stage = ev.get("stage", "?")
            stages[stage] = {k: v for k, v in ev.items()
                             if k not in ("event",)}
            if ev.get("loss") is not None:
                traj = losses.setdefault(stage, [])
                traj.append(ev["loss"])
                del traj[:-_LOSS_TRAJECTORY_CAP]
        elif kind == "convergence_iter" and "values" in ev:
            # Swept solve: the per-lane loss vector (telemetry-on runs).
            lanes = {"label": ev.get("label", ""),
                     "iteration": ev.get("iteration"),
                     "values": ev.get("values")}
        elif kind == "alert":
            alerts.append({k: v for k, v in ev.items()
                           if k not in ("event",)})
        elif kind == "heartbeat":
            beats[ev.get("stage", "?")] = beats.get(
                ev.get("stage", "?"), 0) + 1
        elif kind == "thread_exception":
            deaths.append({"stage": ev.get("stage"),
                           "error": ev.get("error"),
                           "thread": ev.get("thread")})
        elif kind == "done":
            done_event = ev

    current = None
    for name, st in stages.items():
        if current is None or (st.get("t") or 0) > (
                stages[current].get("t") or 0):
            current = name
    # Serve stage table (ISSUE 14): the newest serve progress event
    # carries the request-tracing tier's per-stage p50/p99 — watch
    # renders the live latency decomposition, and the dominant stage
    # is the one with the largest p99.
    serve_stages = (stages.get("serve") or {}).get("stages_ms") or None
    dominant = None
    if serve_stages:
        best = max(((s, e.get("p99_ms")) for s, e in serve_stages.items()
                    if e.get("p99_ms") is not None),
                   key=lambda kv: kv[1], default=None)
        if best is not None:
            dominant = {"stage": best[0], "p99_ms": best[1]}
    torn = sum(1 for ev in all_events
               if ev.get("event") == "_malformed_line")
    return {
        "log": path,
        "live": done_event is None,
        "segments": len(segments),
        "run_id": (header or {}).get("run_id"),
        "phase": (open_phases[-1]["phase"] if open_phases else None),
        "phases_done": phases_done,
        "stages": stages,
        "current_stage": current,
        "eta_s": (stages[current].get("eta_s")
                  if current is not None else None),
        "loss": (stages[current].get("loss")
                 if current is not None else None),
        "losses": losses,
        "lanes": lanes,
        "serve_stages": serve_stages,
        "serve_dominant": dominant,
        "alerts": alerts,
        "heartbeats": beats,
        "thread_exceptions": deaths,
        "torn_lines": torn,
        "last_event_t": round(last_t, 3),
        "events": len(events),
    }


def _fmt_eta(eta) -> str:
    if eta is None:
        return "-"
    eta = float(eta)
    if eta >= 3600:
        return f"{eta / 3600:.1f}h"
    if eta >= 60:
        return f"{eta / 60:.1f}m"
    return f"{eta:.0f}s"


def render(snap: dict, out=None) -> None:
    """The human half: one status view of a snapshot."""
    out = out or sys.stdout
    w = lambda s="": print(s, file=out)
    state = "RUNNING" if snap["live"] else "FINISHED"
    head = f"run {snap['run_id'] or '?'} [{state}]"
    if snap["segments"] > 1:
        head += f" (segment {snap['segments']} of a resumed run)"
    w(head)
    w(f"  phase: {snap['phase'] or '-'}   last event t="
      f"{snap['last_event_t']}s   events: {snap['events']}"
      + (f"   torn lines: {snap['torn_lines']}"
         if snap["torn_lines"] else ""))
    if snap["stages"]:
        w("  progress:")
        w(f"    {'stage':<18} {'done':>10} {'total':>10} {'unit':<8} "
          f"{'rate/s':>9} {'eta':>6}  loss")
        for name, st in sorted(snap["stages"].items(),
                               key=lambda kv: -(kv[1].get("t") or 0)):
            total = st.get("total")
            rate = st.get("rate")
            loss = st.get("loss")
            marker = " <- current" if name == snap["current_stage"] else ""
            w(f"    {name:<18} {st.get('done', 0):>10g} "
              f"{(f'{total:g}' if total is not None else '-'):>10} "
              f"{st.get('unit', '?'):<8} "
              f"{(f'{rate:g}' if rate is not None else '-'):>9} "
              f"{_fmt_eta(st.get('eta_s')):>6}  "
              f"{(f'{loss:.6g}' if loss is not None else '-')}"
              f"{marker}")
    for stage, traj in snap["losses"].items():
        if len(traj) > 1:
            w(f"  loss[{stage}]: "
              + " -> ".join(f"{v:.6g}" for v in traj[-6:]))
    if snap["lanes"]:
        vals = snap["lanes"]["values"]
        w(f"  lanes[{snap['lanes']['label'] or 'swept'}] iter "
          f"{snap['lanes']['iteration']}: "
          + " ".join(f"{v:.6g}" for v in vals))
    if snap.get("serve_stages"):
        w("  serve stages (request tracing):")
        w(f"    {'stage':<14} {'count':>7} {'p50_ms':>9} {'p99_ms':>9}")
        for stage, ent in snap["serve_stages"].items():
            p50 = ent.get("p50_ms")
            p99 = ent.get("p99_ms")
            w(f"    {stage:<14} {ent.get('count', 0):>7} "
              f"{(f'{p50:.3f}' if p50 is not None else '-'):>9} "
              f"{(f'{p99:.3f}' if p99 is not None else '-'):>9}")
        dom = snap.get("serve_dominant")
        if dom:
            w(f"    dominant stage: {dom['stage']} "
              f"(p99 {dom['p99_ms']:.3f} ms)")
    if snap["heartbeats"]:
        w("  heartbeats: " + ", ".join(
            f"{s}={n}" for s, n in sorted(snap["heartbeats"].items())))
    for d in snap["thread_exceptions"]:
        w(f"  DIED {d['stage']}: {d['error']} (thread {d['thread']})")
    if snap["alerts"]:
        w("  ALERTS:")
        for a in snap["alerts"]:
            stage = f" ({a['stage']})" if a.get("stage") else ""
            w(f"    [{a.get('severity', 'warn')}] "
              f"{a.get('rule', '?')}{stage}: {a.get('message', '')}")
    else:
        w("  alerts: none")


def watch(path: str, once: bool = False,
          interval_s: float = DEFAULT_INTERVAL_S,
          max_wait_s: float | None = None, out=None) -> dict:
    """Render ``path`` until its run finishes (or ``--once``); the
    returned snapshot is also printed as the JSON last line.

    ``max_wait_s`` bounds follow mode for scripted callers: a log that
    stops growing without a ``done`` event (a killed run) must not
    watch forever."""
    out = out or sys.stdout
    if interval_s <= 0:
        raise ValueError(f"interval_s must be positive, got {interval_s!r}")
    snap = snapshot(path)
    render(snap, out)
    if not once:
        deadline = (time.monotonic() + max_wait_s
                    if max_wait_s is not None else None)
        try:
            while snap["live"]:
                if deadline is not None and time.monotonic() >= deadline:
                    break
                time.sleep(interval_s)
                snap = snapshot(path)
                # ANSI home+clear between refreshes keeps the view in
                # place on a terminal; piped output just accumulates
                # frames (the JSON line is still last).
                if out is sys.stdout and sys.stdout.isatty():
                    print("\x1b[H\x1b[2J", end="", file=out)
                render(snap, out)
        except KeyboardInterrupt:  # photon-lint: disable=swallowed-exception (operator detach: the final JSON line still prints)
            pass
    print(json.dumps(snap), file=out)
    return snap
