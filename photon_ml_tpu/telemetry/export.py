"""Chrome trace-event export: the session timeline as ``trace.json``.

The output is the Trace Event Format's JSON-object form —
``{"traceEvents": [...], "displayTimeUnit": "ms"}`` — with complete
("ph": "X") events for spans, instant ("ph": "i") events for compile
records, counter ("ph": "C") events for the RSS series, and thread-name
metadata ("ph": "M") so Perfetto / ``chrome://tracing`` label each
pipeline thread (main solver loop, ``photon-chunk-prefetch``,
``photon-score-writer``, ``photon-telemetry-rss``).  Timestamps are
microseconds on the session RunLogger's monotonic clock, so a span's
``ts``/1e6 equals the matching JSONL event's ``t``.

Serve-trace export (ISSUE 14): ``serve_trace_events`` renders the
request-tracing tier's sampled ``request_trace``/``batch_trace``
records — one Chrome pid per serving process, request spans on a
"requests" track and the shared micro-batch spans on a "batcher"
track, with FLOW events (``ph: s``/``f``) joining a frontend request
span to the replica-side span it caused (by trace id) and a replica
request span to its micro-batch span (by batch id) — so Perfetto
renders a request flowing frontend → replica → batcher → dispatch.
Timestamps are wall-clock anchored (each record's single ``wall_t``
stamp), so processes on one host line up to clock-sync precision.
"""

from __future__ import annotations

import json
import os

# Request-side stages laid out from the span START in this order; the
# tail stages anchor to the span END (the shared batch work sits in
# the gap, linked by the batch flow arrow).
_REQ_HEAD_STAGES = ("route", "retry", "forward", "admission",
                    "queue_wait")
_REQ_TAIL_STAGES = ("serialize", "write")
_BATCH_STAGES = ("assemble", "store_lookup", "dispatch", "d2h")


def _us(seconds: float) -> int:
    return int(round(seconds * 1e6))


def trace_events(spans: list[dict], thread_names: dict,
                 instants: list, rss_series: list,
                 device_series: list = (),
                 pid: int | None = None) -> list[dict]:
    """The traceEvents list (exposed separately for tests)."""
    pid = os.getpid() if pid is None else pid
    events: list[dict] = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": "photon-ml-tpu"}},
    ]
    for tid, name in sorted(thread_names.items()):
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": name}})
    for rec in spans:
        ev = {"ph": "X", "name": rec["name"], "cat": rec["cat"],
              "pid": pid, "tid": rec["tid"], "ts": _us(rec["ts"]),
              "dur": max(1, _us(rec["dur"]))}
        args = dict(rec.get("args") or {})
        if rec.get("failed"):
            args["failed"] = True
        if args:
            ev["args"] = args
        events.append(ev)
    for ts, tid, name, cat, args in instants:
        ev = {"ph": "i", "name": name, "cat": cat, "pid": pid,
              "tid": tid, "ts": _us(ts), "s": "t"}
        if args:
            ev["args"] = args
        events.append(ev)
    for ts, mb in rss_series:
        events.append({"ph": "C", "name": "proc.rss_mb", "pid": pid,
                       "tid": 0, "ts": _us(ts),
                       "args": {"rss_mb": round(mb, 1)}})
    for ts, nbytes in device_series:
        # Device-memory counter track (ISSUE 8): phase-boundary samples
        # of backend memory_stats / live-buffer census, in MB so the
        # track shares a readable scale with proc.rss_mb.
        events.append({"ph": "C", "name": "device.mem_mb", "pid": pid,
                       "tid": 0, "ts": _us(ts),
                       "args": {"mem_mb": round(nbytes / 1e6, 2)}})
    events.sort(key=lambda e: e.get("ts", 0))
    return events


def serve_trace_events(processes: list[dict]) -> list[dict]:
    """Chrome trace events for serve-trace records (exposed for tests).

    ``processes``: ``[{"name", "requests": [request_trace bodies],
    "batches": [batch_trace bodies]}, ...]`` — the JSONL event dicts
    the ``TraceRecorder`` writes.  Process i becomes Chrome pid i+1;
    tid 1 is the request track, tid 2 the batcher track."""
    recs = [r for p in processes for r in p.get("requests", ())]
    recs += [b for p in processes for b in p.get("batches", ())]
    if not recs:
        return []
    t_origin = min(float(r.get("wall_t", 0.0)) for r in recs)

    def ts_us(rec) -> int:
        return _us(float(rec.get("wall_t", 0.0)) - t_origin)

    # Frontend request spans by trace id: the flow-arrow sources.
    frontend: dict = {}
    for i, proc in enumerate(processes):
        for rec in proc.get("requests", ()):
            if rec.get("role") == "frontend":
                frontend.setdefault(rec.get("trace"), (i + 1, ts_us(rec)))

    events: list[dict] = []
    for i, proc in enumerate(processes):
        pid = i + 1
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0,
                       "args": {"name": proc.get("name", f"proc{pid}")}})
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": 1, "args": {"name": "requests"}})
        if proc.get("batches"):
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": 2, "args": {"name": "batcher"}})
        for rec in proc.get("requests", ()):
            ts = ts_us(rec)
            dur = max(1, _us(float(rec.get("total_ms", 0.0)) / 1e3))
            trace = rec.get("trace")
            args = {k: v for k, v in rec.items()
                    if k not in ("event", "t", "wall_t")}
            events.append({"ph": "X", "name": "request", "cat": "serve",
                           "pid": pid, "tid": 1, "ts": ts, "dur": dur,
                           "args": args})
            # Stage sub-slices: head stages laid out from the span
            # start, tail stages anchored to its end — the gap is the
            # shared batch work the flow arrow points at.
            stages = rec.get("stages_ms") or {}
            cursor = ts
            for stage in _REQ_HEAD_STAGES:
                if stage in stages:
                    sdur = max(1, _us(stages[stage] / 1e3))
                    events.append({"ph": "X", "name": stage,
                                   "cat": "serve_stage", "pid": pid,
                                   "tid": 1, "ts": cursor, "dur": sdur})
                    cursor += sdur
            tail_cursor = ts + dur
            for stage in reversed(_REQ_TAIL_STAGES):
                if stage in stages:
                    sdur = max(1, _us(stages[stage] / 1e3))
                    tail_cursor -= sdur
                    events.append({"ph": "X", "name": stage,
                                   "cat": "serve_stage", "pid": pid,
                                   "tid": 1,
                                   "ts": max(cursor, tail_cursor),
                                   "dur": sdur})
            role = rec.get("role")
            if role != "frontend" and trace in frontend:
                # The cross-process join: frontend hop → replica work.
                f_pid, f_ts = frontend[trace]
                events.append({"ph": "s", "id": str(trace),
                               "name": "request_flow", "cat": "serve",
                               "pid": f_pid, "tid": 1, "ts": f_ts + 1})
                events.append({"ph": "f", "bp": "e", "id": str(trace),
                               "name": "request_flow", "cat": "serve",
                               "pid": pid, "tid": 1, "ts": ts + 1})
            if role != "frontend" and rec.get("batch") is not None:
                events.append({"ph": "s",
                               "id": f"{trace}:b{rec['batch']}",
                               "name": "batch_flow", "cat": "serve",
                               "pid": pid, "tid": 1, "ts": ts + 2})
        for rec in proc.get("batches", ()):
            ts = ts_us(rec)
            dur = max(1, _us(float(rec.get("total_ms", 0.0)) / 1e3))
            args = {k: v for k, v in rec.items()
                    if k not in ("event", "t", "wall_t")}
            events.append({"ph": "X", "name": f"batch {rec.get('batch')}",
                           "cat": "serve", "pid": pid, "tid": 2,
                           "ts": ts, "dur": dur, "args": args})
            cursor = ts
            stages = rec.get("stages_ms") or {}
            for stage in _BATCH_STAGES:
                if stage in stages:
                    sdur = max(1, _us(stages[stage] / 1e3))
                    events.append({"ph": "X", "name": stage,
                                   "cat": "serve_stage", "pid": pid,
                                   "tid": 2, "ts": cursor, "dur": sdur})
                    cursor += sdur
            # Every member request that linked this batch emitted an
            # "s" with this id; one "f" on the batch span binds them.
            for rq in proc.get("requests", ()):
                if rq.get("batch") == rec.get("batch"):
                    events.append({"ph": "f", "bp": "e",
                                   "id": f"{rq.get('trace')}:"
                                         f"b{rec.get('batch')}",
                                   "name": "batch_flow", "cat": "serve",
                                   "pid": pid, "tid": 2, "ts": ts + 1})
    events.sort(key=lambda e: e.get("ts", 0))
    return events


def write_serve_trace(path: str, processes: list[dict]) -> None:
    """Write the serve-trace Perfetto file atomically (tmp + rename)."""
    doc = {"traceEvents": serve_trace_events(processes),
           "displayTimeUnit": "ms"}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def write_trace(path: str, spans: list[dict], thread_names: dict,
                instants: list, rss_series: list,
                device_series: list = ()) -> None:
    """Write ``trace.json`` atomically (tmp + rename — a killed run
    leaves the previous trace readable, never a truncated one)."""
    doc = {"traceEvents": trace_events(spans, thread_names, instants,
                                       rss_series, device_series),
           "displayTimeUnit": "ms"}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
