"""Chrome trace-event export: the session timeline as ``trace.json``.

The output is the Trace Event Format's JSON-object form —
``{"traceEvents": [...], "displayTimeUnit": "ms"}`` — with complete
("ph": "X") events for spans, instant ("ph": "i") events for compile
records, counter ("ph": "C") events for the RSS series, and thread-name
metadata ("ph": "M") so Perfetto / ``chrome://tracing`` label each
pipeline thread (main solver loop, ``photon-chunk-prefetch``,
``photon-score-writer``, ``photon-telemetry-rss``).  Timestamps are
microseconds on the session RunLogger's monotonic clock, so a span's
``ts``/1e6 equals the matching JSONL event's ``t``.
"""

from __future__ import annotations

import json
import os


def _us(seconds: float) -> int:
    return int(round(seconds * 1e6))


def trace_events(spans: list[dict], thread_names: dict,
                 instants: list, rss_series: list,
                 device_series: list = (),
                 pid: int | None = None) -> list[dict]:
    """The traceEvents list (exposed separately for tests)."""
    pid = os.getpid() if pid is None else pid
    events: list[dict] = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": "photon-ml-tpu"}},
    ]
    for tid, name in sorted(thread_names.items()):
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": name}})
    for rec in spans:
        ev = {"ph": "X", "name": rec["name"], "cat": rec["cat"],
              "pid": pid, "tid": rec["tid"], "ts": _us(rec["ts"]),
              "dur": max(1, _us(rec["dur"]))}
        args = dict(rec.get("args") or {})
        if rec.get("failed"):
            args["failed"] = True
        if args:
            ev["args"] = args
        events.append(ev)
    for ts, tid, name, cat, args in instants:
        ev = {"ph": "i", "name": name, "cat": cat, "pid": pid,
              "tid": tid, "ts": _us(ts), "s": "t"}
        if args:
            ev["args"] = args
        events.append(ev)
    for ts, mb in rss_series:
        events.append({"ph": "C", "name": "proc.rss_mb", "pid": pid,
                       "tid": 0, "ts": _us(ts),
                       "args": {"rss_mb": round(mb, 1)}})
    for ts, nbytes in device_series:
        # Device-memory counter track (ISSUE 8): phase-boundary samples
        # of backend memory_stats / live-buffer census, in MB so the
        # track shares a readable scale with proc.rss_mb.
        events.append({"ph": "C", "name": "device.mem_mb", "pid": pid,
                       "tid": 0, "ts": _us(ts),
                       "args": {"mem_mb": round(nbytes / 1e6, 2)}})
    events.sort(key=lambda e: e.get("ts", 0))
    return events


def write_trace(path: str, spans: list[dict], thread_names: dict,
                instants: list, rss_series: list,
                device_series: list = ()) -> None:
    """Write ``trace.json`` atomically (tmp + rename — a killed run
    leaves the previous trace readable, never a truncated one)."""
    doc = {"traceEvents": trace_events(spans, thread_names, instants,
                                       rss_series, device_series),
           "displayTimeUnit": "ms"}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
