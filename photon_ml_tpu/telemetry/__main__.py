"""Telemetry CLI: ``python -m photon_ml_tpu.telemetry
<report|history|watch|serve-report>``.

``report <log>`` prints the per-phase / stage-span / overlap /
convergence / device / reconciliation report for a run's
``run_log.jsonl`` (see ``telemetry.report``); exit code 1 when the
span-vs-wall-clock reconciliation or the convergence sweep-odometer
check fails.

``history <dir-or-files...>`` ingests bench round records (the repo's
``BENCH_r*.json`` wrappers, raw bench JSON-last-line records, or
``bench.py --history-dir`` envelopes) into per-section metric
trajectories and gates them against a rolling baseline (see
``telemetry.history``); exit code 1 on any regression or on any round
with a nonzero rc not waived via ``--known-bad``.

``watch <log>`` follows a LIVE, still-being-written run log (ISSUE
10): a refreshing status view — phase, per-stage progress/ETA, loss
trajectory, reliability counters, active alerts — that exits when the
run logs ``done`` (or ``--once`` for a single snapshot); see
``telemetry.watch``.

``serve-report <logs...>`` joins the serving fleet's sampled request
traces across processes by trace id (ISSUE 14) into a stage-level
latency-decomposition table (p50/p99 per stage, retry cost, dominant
stage per tail request) and optionally exports a Perfetto flow trace
(``--trace-out``); exit code 1 when no trace records are found or the
cross-process join falls below ``--join-threshold``; see
``telemetry.serve_report``.

``fleet-report <host_logs...>`` joins a multi-host training run's
per-host ``run_log.jsonl`` files (ISSUE 16) into one fleet view:
per-host chunks streamed / reductions / barrier-wait / peak RSS rows,
the barrier-agreement check (every host must count the same
reductions), and the fleet-wide sweep odometer (replicated solver
state ⇒ per-host odometers must agree and each must reconcile); exit
code 1 on any disagreement; see ``telemetry.fleet_report``.

All subcommands print one machine-parseable JSON object as the last
stdout line (the repo's CLI contract).
"""

from __future__ import annotations

import argparse
import sys

from photon_ml_tpu.telemetry import fleet_report as fleet_report_mod
from photon_ml_tpu.telemetry import serve_report as serve_report_mod
from photon_ml_tpu.telemetry import watch as watch_mod
from photon_ml_tpu.telemetry.history import (
    DEFAULT_TOLERANCE,
    DEFAULT_WINDOW,
    parse_known_bad,
    run_history,
)
from photon_ml_tpu.telemetry.report import report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.telemetry",
        description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser(
        "report", help="per-phase wall-clock tables, prefetcher overlap "
                       "efficiency, convergence + device accounting, "
                       "and the reconciliation checks")
    rp.add_argument("log", help="path to a run_log.jsonl")
    rp.add_argument("--threshold", type=float, default=0.9,
                    help="reconciliation pass threshold (default 0.9)")
    hp = sub.add_parser(
        "history", help="bench-record trajectory: aggregate rounds, "
                        "gate regressions against a rolling baseline")
    hp.add_argument("paths", nargs="+",
                    help="history directory or individual bench JSON "
                         "files (BENCH_r*.json wrappers, raw records, "
                         "or --history-dir envelopes)")
    hp.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="relative worsening vs the rolling baseline "
                         "that counts as a regression (default "
                         f"{DEFAULT_TOLERANCE})")
    hp.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="rolling-baseline width in preceding rounds "
                         f"(default {DEFAULT_WINDOW})")
    hp.add_argument("--known-bad", action="append", default=[],
                    metavar="ROUND=REASON",
                    help="waive an acknowledged bad round (e.g. "
                         "BENCH_r05.json=rc-124 budget timeout, see "
                         "PERF.md): its rc/regressions stop failing "
                         "the gate; the reason is REQUIRED and echoed "
                         "in the markdown output. Repeatable.")
    wp = sub.add_parser(
        "watch", help="follow a live run_log.jsonl: phase, per-stage "
                      "progress/ETA, loss trajectory, alerts; exits "
                      "when the run logs its done event")
    wp.add_argument("log", help="path to a (possibly still-being-"
                                "written) run_log.jsonl")
    wp.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (scripting mode; "
                         "the JSON last line is the snapshot)")
    wp.add_argument("--interval", type=float,
                    default=watch_mod.DEFAULT_INTERVAL_S,
                    help="refresh cadence in seconds (default "
                         f"{watch_mod.DEFAULT_INTERVAL_S})")
    wp.add_argument("--max-wait-s", type=float, default=None,
                    help="give up following after this many seconds "
                         "without a done event (a killed run's log "
                         "stops growing but never finishes)")
    sp = sub.add_parser(
        "serve-report",
        help="join frontend + replica request traces by trace id into "
             "a cross-process stage-latency decomposition (p50/p99 "
             "per stage, retry cost, dominant stage per tail request)")
    sp.add_argument("logs", nargs="+",
                    help="serving run logs (the frontend's and each "
                         "replica's run_log JSONL; one server's log "
                         "also works — the join check is then N/A)")
    sp.add_argument("--join-threshold", type=float,
                    default=serve_report_mod.DEFAULT_JOIN_THRESHOLD,
                    help="minimum fraction of replica-side tail "
                         "requests that must match a frontend trace "
                         "(default "
                         f"{serve_report_mod.DEFAULT_JOIN_THRESHOLD})")
    sp.add_argument("--trace-out", default=None,
                    help="also write a Perfetto-loadable Chrome trace "
                         "with cross-process flow events here")
    fp = sub.add_parser(
        "fleet-report",
        help="join a multi-host training run's per-host run logs into "
             "one fleet view: per-host chunk/reduce/barrier-wait rows, "
             "the barrier-agreement check, and the fleet-wide sweep "
             "odometer")
    fp.add_argument("logs", nargs="+",
                    help="per-host run logs (each host_NNN/ output "
                         "subdir's run_log.jsonl)")
    args = p.parse_args(argv)
    if args.cmd == "fleet-report":
        result = fleet_report_mod.run_fleet_report(args.logs)
        return 0 if result["ok"] else 1
    if args.cmd == "serve-report":
        result = serve_report_mod.run_serve_report(
            args.logs, join_threshold=args.join_threshold,
            trace_out=args.trace_out)
        return 0 if result["ok"] else 1
    if args.cmd == "watch":
        snap = watch_mod.watch(args.log, once=args.once,
                               interval_s=args.interval,
                               max_wait_s=args.max_wait_s)
        return 0 if not snap["thread_exceptions"] else 1
    if args.cmd == "history":
        try:
            waivers = parse_known_bad(args.known_bad)
        except ValueError as e:
            p.error(str(e))
        result = run_history(args.paths, tolerance=args.tolerance,
                             window=args.window, known_bad=waivers)
        return 0 if result["ok"] else 1
    result = report(args.log, threshold=args.threshold)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
