"""Telemetry CLI: ``python -m photon_ml_tpu.telemetry report <log>``.

Prints the per-phase / stage-span / overlap / reconciliation report
for a run's ``run_log.jsonl`` (see ``telemetry.report``); the last
stdout line is one machine-parseable JSON object and the exit code is
1 when the span-vs-wall-clock reconciliation check fails.
"""

from __future__ import annotations

import argparse
import sys

from photon_ml_tpu.telemetry.report import report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.telemetry",
        description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser(
        "report", help="per-phase wall-clock tables, prefetcher overlap "
                       "efficiency, and the span reconciliation check")
    rp.add_argument("log", help="path to a run_log.jsonl")
    rp.add_argument("--threshold", type=float, default=0.9,
                    help="reconciliation pass threshold (default 0.9)")
    args = p.parse_args(argv)
    result = report(args.log, threshold=args.threshold)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
