"""Telemetry CLI: ``python -m photon_ml_tpu.telemetry <report|history>``.

``report <log>`` prints the per-phase / stage-span / overlap /
convergence / device / reconciliation report for a run's
``run_log.jsonl`` (see ``telemetry.report``); exit code 1 when the
span-vs-wall-clock reconciliation or the convergence sweep-odometer
check fails.

``history <dir-or-files...>`` ingests bench round records (the repo's
``BENCH_r*.json`` wrappers, raw bench JSON-last-line records, or
``bench.py --history-dir`` envelopes) into per-section metric
trajectories and gates them against a rolling baseline (see
``telemetry.history``); exit code 1 on any regression or on any round
with a nonzero rc.

Both subcommands print one machine-parseable JSON object as the last
stdout line (the repo's CLI contract).
"""

from __future__ import annotations

import argparse
import sys

from photon_ml_tpu.telemetry.history import (
    DEFAULT_TOLERANCE,
    DEFAULT_WINDOW,
    run_history,
)
from photon_ml_tpu.telemetry.report import report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.telemetry",
        description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser(
        "report", help="per-phase wall-clock tables, prefetcher overlap "
                       "efficiency, convergence + device accounting, "
                       "and the reconciliation checks")
    rp.add_argument("log", help="path to a run_log.jsonl")
    rp.add_argument("--threshold", type=float, default=0.9,
                    help="reconciliation pass threshold (default 0.9)")
    hp = sub.add_parser(
        "history", help="bench-record trajectory: aggregate rounds, "
                        "gate regressions against a rolling baseline")
    hp.add_argument("paths", nargs="+",
                    help="history directory or individual bench JSON "
                         "files (BENCH_r*.json wrappers, raw records, "
                         "or --history-dir envelopes)")
    hp.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="relative worsening vs the rolling baseline "
                         "that counts as a regression (default "
                         f"{DEFAULT_TOLERANCE})")
    hp.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="rolling-baseline width in preceding rounds "
                         f"(default {DEFAULT_WINDOW})")
    args = p.parse_args(argv)
    if args.cmd == "history":
        result = run_history(args.paths, tolerance=args.tolerance,
                             window=args.window)
        return 0 if result["ok"] else 1
    result = report(args.log, threshold=args.threshold)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
