"""Telemetry report: per-phase tables, overlap efficiency, convergence
and device accounting, and the reconciliation checks over a
``run_log.jsonl``.

``python -m photon_ml_tpu.telemetry report <run_log.jsonl>`` prints:

- **Header**: the ``run_header`` event (run id, argv, jax version,
  platform, telemetry mode) when present — absent in pre-ISSUE-8 logs,
  which stay fully readable.
- **Phases**: the RunLogger ``phase_start``/``phase_end`` wall-clock
  table (driver ETL / fit / save phases).
- **Stage spans**: per-name duration stats from the
  ``telemetry_summary`` event (count, total, mean, share of the
  busiest thread's wall clock).
- **Prefetcher**: overlap efficiency — the fraction of streamed pass
  time the consumer was NOT blocked on the prefetch queue (1.0 = the
  disk+staging tier fully hidden under device compute) — plus producer
  stall and LRU hit/load counters.
- **Convergence** (ISSUE 8): per-solver iteration totals from the
  ``convergence_iter``/``convergence_trace`` events, streamed-RE
  solved/retired dynamics, and the SWEEP-ODOMETER RECONCILIATION —
  every streamed data pass must be claimed by exactly one accounting
  bucket (``solver.sweeps == streamed_solves + ls_trials +
  grad_recovery_sweeps + aux_sweeps + hvp_sweeps``), so solver
  iteration counts and data passes cannot drift apart unnoticed.  A
  violated identity fails the report (rc 1).
- **Device** (ISSUE 8): per-program FLOPs / bytes accessed from the
  captured XLA cost analyses, the analytic roofline estimate, and the
  measured per-dispatch span time it implies a fraction of — PERF.md's
  hand math, emitted.
- **Liveness**: heartbeat counts per stage and any thread_exception
  events (the hung-run forensic trail).
- **Reconciliation**: for each thread with trace spans, the fraction
  of wall clock (first depth-0 span start → last depth-0 span end)
  covered by depth-0 spans.  The check passes when the busiest thread
  covers at least ``--threshold`` (default 0.9) — i.e. the stage spans
  actually account for where the time went.

The last stdout line is one machine-parseable JSON object (the repo's
CLI contract); exit code is 1 when the span reconciliation OR the
convergence sweep-odometer check fails.
"""

from __future__ import annotations

import json
import sys


def load_events(path: str) -> list[dict]:
    """Parse a run log, tolerating a torn tail: a killed run (the
    report's primary forensic case) can leave a partial final line —
    malformed lines are skipped, not fatal."""
    out = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                out.append({"event": "_malformed_line"})
    return out


def split_segments(events: list[dict]) -> list[list[dict]]:
    """Split a (possibly stitched) log into per-process segments at
    ``run_header`` events.  A resumed driver run APPENDS to the
    interrupted run's log with a fresh header (ISSUE 9), and each
    segment's clock restarts at zero — so spans/phases/counters must
    reconcile per segment, never across the stitch."""
    segs: list[list[dict]] = [[]]
    for ev in events:
        if ev.get("event") == "run_header" and segs[-1]:
            segs.append([])
        segs[-1].append(ev)
    return segs


def _convergence(events: list[dict], counters: dict) -> dict | None:
    """Convergence reconciliation (ISSUE 8): per-solver iteration
    totals and the sweep-odometer identity.

    Every chunk sweep (``solver.sweeps``) is claimed by an accounting
    bucket: the per-solve initial evaluation
    (``solver.streamed_solves``), a line-search/trial-point evaluation
    (``solver.ls_trials``), a gradient-recovery pass
    (``solver.grad_recovery_sweeps``), an auxiliary pass
    (``solver.aux_sweeps`` — Hessian diagonals, variance passes), or a
    TRON CG Hessian-vector pass (``solver.hvp_sweeps``).  The check FAILS
    when the claimed evaluations exceed the data passes (negative
    ``unattributed`` — a solver claiming passes it never streamed is
    impossible accounting, i.e. drift) or, with streamed solves
    present, when the live per-iteration event count disagrees with
    the ``solver.iterations`` counter (a solver iterating without
    emitting, or vice versa — wiring drift).  POSITIVE unattributed
    sweeps stay informational: direct objective evaluations outside
    any solve (benches, notebooks, a final-loss log line) are
    legitimate data passes no solve claims, and the report prints
    their count so a creeping gap is still visible.

    Returns None when the log carries no convergence signal at all
    (pre-ISSUE-8 logs, telemetry off)."""
    iters_by_solver: dict = {}
    trust_region: dict = {}
    traces = 0
    re_by_coord: dict = {}
    for ev in events:
        kind = ev.get("event")
        if kind == "convergence_iter":
            key = (ev.get("solver", "?"), ev.get("label", ""))
            iters_by_solver[key] = iters_by_solver.get(key, 0) + 1
            if ev.get("delta") is not None:
                # TRON radius/ratio trajectory (ISSUE 17): a collapsing
                # δ means rejected steps even when the loss plane looks
                # flat — surfaced per solver in the Convergence section.
                tr = trust_region.setdefault(
                    key, {"delta": [], "rho": [], "rejected": 0})
                tr["delta"].append(float(ev["delta"]))
                if ev.get("rho") is not None:
                    tr["rho"].append(float(ev["rho"]))
                if not ev.get("step_size"):
                    tr["rejected"] += 1
        elif kind == "convergence_trace":
            traces += 1
        elif kind == "re_convergence":
            d = re_by_coord.setdefault(
                ev.get("coordinate", "?"),
                {"sweeps": 0, "solved": [], "retired": 0, "woken": 0})
            d["sweeps"] += 1
            d["solved"].append(ev.get("entities_solved"))
            d["retired"] = max(d["retired"],
                               ev.get("entities_retired") or 0)
            d["woken"] += ev.get("entities_woken", 0)
        elif kind == "re_retirement":
            # Commit-time totals: re_convergence samples as of sweep
            # start, so the LAST commit only appears here.
            d = re_by_coord.setdefault(
                ev.get("coordinate", "?"),
                {"sweeps": 0, "solved": [], "retired": 0, "woken": 0})
            d["retired"] = max(d["retired"],
                               ev.get("entities_retired_total") or 0)
    sweeps = counters.get("solver.sweeps")
    solves = counters.get("solver.streamed_solves", 0)
    resumed = counters.get("solver.resumed_solves", 0)
    ls = counters.get("solver.ls_trials", 0)
    grad_rec = counters.get("solver.grad_recovery_sweeps", 0)
    aux = counters.get("solver.aux_sweeps", 0)
    fused = counters.get("solver.fused_cycle_sweeps", 0)
    hvp = counters.get("solver.hvp_sweeps", 0)
    if (not iters_by_solver and not traces and not re_by_coord
            and sweeps is None):
        return None
    # ISSUE 17: TRON's CG inner-loop passes claim their own bucket
    # (`solver.hvp_sweeps`); resumed solves claim ZERO passes (the
    # initial evaluation was streamed — and counted — by the
    # interrupted predecessor segment), but they still run iterations,
    # so the iteration/counter cross-check must engage for them too.
    expected = solves + ls + grad_rec + aux + fused + hvp
    unattributed = (sweeps or 0) - expected
    # Data passes per CD cycle (ISSUE 11): the fused super-sweep's
    # deliverable is this ratio dropping from ~C (coordinates × solver
    # iterations) to ~1 (one fused pass per cycle + the final score
    # pass).  None when the run had no CD loop (plain solver benches).
    cycles = counters.get("cd.cycles", 0)
    passes_per_cycle = (round((sweeps or 0) / cycles, 3) if cycles
                        else None)
    iter_events = sum(iters_by_solver.values())
    ok = unattributed >= 0
    if solves or resumed:
        # The live per-iteration events and the counter must agree —
        # an instrumented solver that iterates without emitting (or
        # vice versa) is wiring drift.  Resume-only segments (mid-CG
        # resume: zero fresh solves) are checked too.
        ok = ok and iter_events == counters.get("solver.iterations", 0)
    # Data passes per (fresh) solve: the TRON-vs-L-BFGS comparison's
    # headline ratio — how many streamed passes one fit cost.
    passes_per_solve = (round((sweeps or 0) / solves, 3) if solves
                        else None)
    return {
        "ok": ok,
        "sweeps": sweeps or 0,
        "streamed_solves": solves,
        "resumed_solves": resumed,
        "ls_trials": ls,
        "grad_recovery_sweeps": grad_rec,
        "aux_sweeps": aux,
        "fused_cycle_sweeps": fused,
        "hvp_sweeps": hvp,
        "unattributed_sweeps": unattributed,
        "cd_cycles": cycles,
        "passes_per_cycle": passes_per_cycle,
        "passes_per_solve": passes_per_solve,
        "trust_region": {f"{s}:{lbl}" if lbl else s: d
                         for (s, lbl), d in sorted(trust_region.items())},
        "iterations": {f"{s}:{lbl}" if lbl else s: n
                       for (s, lbl), n in sorted(iters_by_solver.items())},
        "iteration_events": iter_events,
        "solver_iterations_counter": counters.get("solver.iterations", 0),
        "traces": traces,
        "re": re_by_coord,
    }


def _device(summary: dict | None) -> dict | None:
    """Device-accounting table: captured program costs joined against a
    MEASURED per-dispatch time (the roofline estimate vs measured
    comparison).

    The measure of record is the per-program dispatch histogram
    (``device.dispatch_s.<name>``) — the shared ``chunk_compute`` span
    pools every chunk program's dispatches, so its mean is only used as
    a fallback when exactly ONE captured program claims it (otherwise a
    solve that runs both the fused and the value-only program would
    overstate the expensive one's roofline fraction and understate the
    cheap one's)."""
    programs = ((summary or {}).get("device") or {}).get("programs")
    if not programs:
        return None
    spans = (summary or {}).get("spans", {})
    hists = (summary or {}).get("histograms", {})
    span_claims: dict = {}
    for cost in programs.values():
        sp = cost.get("span")
        if sp:
            span_claims[sp] = span_claims.get(sp, 0) + 1
    out = {}
    for name, cost in sorted(programs.items()):
        row = dict(cost)
        measured_ms = None
        h = hists.get(f"device.dispatch_s.{name}")
        if h and h.get("count"):
            measured_ms = 1e3 * h["mean"]
        else:
            st = spans.get(cost.get("span", ""), None)
            if (st and st["count"]
                    and span_claims.get(cost.get("span")) == 1):
                measured_ms = 1e3 * st["total_s"] / st["count"]
        if measured_ms is not None:
            row["measured_span_ms"] = round(measured_ms, 3)
            est = cost.get("roofline_est_ms")
            if est and measured_ms > 0:
                row["roofline_fraction"] = round(est / measured_ms, 4)
        out[name] = row
    mem = ((summary or {}).get("device") or {}).get("memory")
    return {"programs": out, **({"memory": mem} if mem else {})}


def _phases(events: list[dict]) -> list[tuple[str, float]]:
    out = []
    for ev in events:
        if ev.get("event") == "phase_end":
            out.append((ev.get("phase", "?"),
                        float(ev.get("duration_s", 0.0))))
    return out


def reconcile(events: list[dict]) -> dict:
    """Per-thread depth-0 span coverage of that thread's wall clock.

    Depth-0 spans on one thread cannot overlap (they come off a stack),
    so covered time is a plain sum; wall clock is last end − first
    start.  Returns ``{threads: {name: {...}}, coverage, thread}``
    where ``coverage`` is the busiest (most covered seconds) thread's
    fraction — the reconciliation number of record."""
    per_tid: dict = {}
    for ev in events:
        if ev.get("event") != "span" or ev.get("depth", 0) != 0:
            continue
        tid = ev.get("tid", 0)
        ts, dur = float(ev["ts"]), float(ev["dur"])
        ent = per_tid.setdefault(
            tid, {"thread": ev.get("thread", str(tid)), "covered_s": 0.0,
                  "start": ts, "end": ts + dur, "spans": 0})
        ent["covered_s"] += dur
        ent["start"] = min(ent["start"], ts)
        ent["end"] = max(ent["end"], ts + dur)
        ent["spans"] += 1
    threads = {}
    best = None
    for tid, ent in per_tid.items():
        wall = max(ent["end"] - ent["start"], 1e-9)
        cov = min(1.0, ent["covered_s"] / wall)
        threads[ent["thread"]] = {
            "spans": ent["spans"],
            "covered_s": round(ent["covered_s"], 3),
            "wall_s": round(wall, 3),
            "coverage": round(cov, 4),
        }
        if best is None or ent["covered_s"] > best[1]:
            best = (ent["thread"], ent["covered_s"], cov)
    return {
        "threads": threads,
        "thread": best[0] if best else None,
        "coverage": round(best[2], 4) if best else None,
    }


def report(path: str, threshold: float = 0.9, out=None) -> dict:
    """Print the report for ``path``; returns the JSON summary dict."""
    out = out or sys.stdout
    all_events = load_events(path)
    segments = split_segments(all_events)
    # The LAST segment is the report of record (a resumed run's own
    # events); earlier segments are the interrupted predecessors — a
    # torn tail there is expected, not a finding.
    events = segments[-1]
    summary = None
    for ev in events:
        if ev.get("event") == "telemetry_summary":
            summary = ev         # last one wins (append-mode logs)

    w = lambda s="": print(s, file=out)
    if len(segments) > 1:
        resumes = sum(1 for ev in events if ev.get("event") == "cd_resume")
        w(f"Stitched log: {len(segments)} run segments (resumed run); "
          f"reporting the last segment"
          + (f", which resumed from a checkpoint" if resumes else "")
          + ".")
        w()
    header = next((e for e in events if e.get("event") == "run_header"),
                  None)
    if header is not None:
        w(f"Run {header.get('run_id', '?')} (schema "
          f"{header.get('schema', '?')}): "
          f"jax={header.get('jax', '-')} "
          f"platforms={header.get('jax_platforms', '-')} "
          f"telemetry={header.get('telemetry', '-')}")
        argv = header.get("argv")
        if argv:
            w(f"  argv: {' '.join(str(a) for a in argv)}")
        w()

    phases = _phases(events)
    if phases:
        w("Phases (run log):")
        w(f"  {'phase':<28} {'wall_s':>10}")
        for name, dur in phases:
            w(f"  {name:<28} {dur:>10.3f}")
        w()

    spans = (summary or {}).get("spans", {})
    if spans:
        total_all = sum(st["total_s"] for st in spans.values())
        w("Stage spans:")
        w(f"  {'name':<24} {'cat':<8} {'count':>7} {'total_s':>10} "
          f"{'mean_ms':>9} {'share':>7}")
        for name, st in sorted(spans.items(),
                               key=lambda kv: -kv[1]["total_s"]):
            mean_ms = 1e3 * st["total_s"] / max(st["count"], 1)
            share = st["total_s"] / total_all if total_all else 0.0
            w(f"  {name:<24} {st['cat']:<8} {st['count']:>7} "
              f"{st['total_s']:>10.3f} {mean_ms:>9.2f} {share:>6.1%}")
        w()

    derived = (summary or {}).get("derived", {})
    counters = (summary or {}).get("counters", {})
    overlap = derived.get("overlap_efficiency")
    if overlap is not None:
        w("Prefetcher:")
        w(f"  consumer blocked {counters.get('prefetch.consumer_wait_s', 0.0):.3f} s"
          f" of {derived.get('pass_span_total_s', 0.0):.3f} s streamed pass time"
          f" ({derived.get('consumer_blocked_fraction', 0.0):.1%})"
          f" -> overlap efficiency {overlap:.1%}")
        if "producer_stall_fraction" in derived:
            w(f"  producer stalled on a full queue "
            f"{counters.get('prefetch.producer_stall_s', 0.0):.3f} s "
              f"({derived['producer_stall_fraction']:.1%} of pass time)")
        hits = counters.get("store.hits")
        loads = counters.get("store.loads")
        if hits is not None or loads is not None:
            w(f"  chunk source: {hits or 0} LRU window hits, "
              f"{loads or 0} disk loads, "
              f"{counters.get('store.rebuilds', 0)} rebuilds")
        w()

    fleet_reduces = counters.get("fleet.psums")
    if fleet_reduces:
        # One host's view of a multi-host run; `telemetry fleet-report`
        # joins every host's log into the fleet-wide table.
        w("Fleet (this host's shard):")
        w(f"  {counters.get('fleet.chunks_streamed', 0)} chunks "
          f"streamed, {fleet_reduces} cross-host reductions, "
          f"{counters.get('fleet.barrier_wait_s', 0.0):.3f} s waiting "
          "at chunk barriers"
          + (f", {counters.get('fleet.seq_restored')} reduce-seq "
             "restore(s) (resumed host)"
             if counters.get("fleet.seq_restored") else ""))
        w()

    conv = _convergence(events, counters)
    if conv is not None:
        w("Convergence:")
        for key, n in conv["iterations"].items():
            w(f"  {key}: {n} iterations")
        for coord, d in conv["re"].items():
            solved = [s for s in d["solved"] if s is not None]
            w(f"  re '{coord}': {d['sweeps']} sweeps, solved/sweep "
              f"{solved}, retired {d['retired']}, woken {d['woken']}")
        for key, d in conv["trust_region"].items():
            deltas, rhos = d["delta"], d["rho"]
            line = (f"  {key} trust region: δ {deltas[0]:.3g} -> "
                    f"{deltas[-1]:.3g} over {len(deltas)} iters")
            if rhos:
                line += (f", ρ in [{min(rhos):.3g}, {max(rhos):.3g}]"
                         f", {d['rejected']} rejected")
            w(line)
        w(f"  sweep odometer: {conv['sweeps']} data passes = "
          f"{conv['streamed_solves']} solve inits + "
          f"{conv['ls_trials']} ls trials + "
          f"{conv['grad_recovery_sweeps']} grad recoveries + "
          f"{conv['aux_sweeps']} aux + "
          f"{conv['hvp_sweeps']} hvp + "
          f"{conv['fused_cycle_sweeps']} fused cycles + "
          f"{conv['unattributed_sweeps']} unattributed "
          f"-> {'PASS' if conv['ok'] else 'FAIL'}")
        if conv["resumed_solves"]:
            w(f"  resumed solves: {conv['resumed_solves']} (zero-pass "
              "inits — streamed by the interrupted segment)")
        if conv["passes_per_cycle"] is not None:
            w(f"  passes/cycle: {conv['passes_per_cycle']} "
              f"({conv['sweeps']} passes / {conv['cd_cycles']} CD "
              "cycles)")
        if conv["passes_per_solve"] is not None:
            w(f"  passes/solve: {conv['passes_per_solve']} "
              f"({conv['sweeps']} passes / {conv['streamed_solves']} "
              "solves)")
        w()

    device = _device(summary)
    if device is not None:
        w("Device programs (XLA cost analysis):")
        w(f"  {'program':<22} {'GFLOPs':>9} {'MB':>9} {'roof_ms':>8} "
          f"{'meas_ms':>8} {'frac':>6}")
        for name, row in device["programs"].items():
            gf = (row.get("flops") or 0.0) / 1e9
            mb = (row.get("bytes_accessed") or 0.0) / 1e6
            est = row.get("roofline_est_ms")
            meas = row.get("measured_span_ms")
            frac = row.get("roofline_fraction")
            w(f"  {name:<22} {gf:>9.3f} {mb:>9.2f} "
              f"{est if est is not None else '-':>8} "
              f"{meas if meas is not None else '-':>8} "
              f"{frac if frac is not None else '-':>6}")
        mem = device.get("memory")
        if mem:
            w(f"  memory: {mem.get('bytes_in_use', 0)/1e6:.1f} MB in "
              f"use ({mem.get('source')}, {mem.get('samples')} "
              "phase-boundary samples)")
        w()

    torn = sum(1 for ev in all_events
               if ev.get("event") == "_malformed_line")
    if torn:
        w(f"NOTE: {torn} malformed line(s) skipped (torn tail — a "
          "run segment died mid-write).")
        w()

    alerts = [{k: v for k, v in ev.items() if k != "event"}
              for ev in events if ev.get("event") == "alert"]
    if alerts:
        w("Alerts (live monitor, ISSUE 10):")
        for a in alerts:
            stage = f" ({a['stage']})" if a.get("stage") else ""
            w(f"  [{a.get('severity', 'warn')}] {a.get('rule', '?')}"
              f"{stage} at t={a.get('t', '?')}: {a.get('message', '')}")
        w()

    beats: dict = {}
    deaths = []
    for ev in events:
        if ev.get("event") == "heartbeat":
            beats[ev.get("stage", "?")] = beats.get(
                ev.get("stage", "?"), 0) + 1
        elif ev.get("event") == "thread_exception":
            deaths.append(ev)
    if beats or deaths:
        w("Liveness:")
        for stage, n in sorted(beats.items()):
            w(f"  {stage}: {n} heartbeats")
        for ev in deaths:
            w(f"  DIED {ev.get('stage')}: {ev.get('error')} "
              f"(thread {ev.get('thread')}, t={ev.get('t')})")
        w()

    recon = reconcile(events)
    ok = True
    if recon["coverage"] is not None:
        w("Reconciliation (depth-0 spans vs wall clock, per thread):")
        for name, ent in sorted(recon["threads"].items()):
            w(f"  {name}: {ent['covered_s']:.3f} s covered of "
              f"{ent['wall_s']:.3f} s wall ({ent['coverage']:.1%}, "
              f"{ent['spans']} spans)")
        ok = recon["coverage"] >= threshold
        w(f"  busiest thread '{recon['thread']}' coverage "
          f"{recon['coverage']:.1%} "
          f"{'>=' if ok else '<'} threshold {threshold:.0%} "
          f"-> {'PASS' if ok else 'FAIL'}")
        w()
    elif summary is None:
        w("No telemetry_summary event found (telemetry was off, or the "
          "run died before close).")
        w()

    if conv is not None and not conv["ok"]:
        w("CONVERGENCE FAIL: solver iteration accounting does not "
          "reconcile with the solver.sweeps odometer (see above).")
        w()
        ok = False

    result = {
        "ok": ok,
        "segments": len(segments),
        "run_id": (header or {}).get("run_id"),
        "convergence": conv,
        "device": device,
        "phases": {name: dur for name, dur in phases},
        "overlap_efficiency": overlap,
        "consumer_blocked_fraction": derived.get(
            "consumer_blocked_fraction"),
        "reconciliation": recon["coverage"],
        "reconciliation_thread": recon["thread"],
        "reconciliation_threads": recon["threads"],
        "counters": counters,
        "alerts": alerts,
        "heartbeats": beats,
        "thread_exceptions": len(deaths),
        "mode": (summary or {}).get("mode"),
    }
    print(json.dumps(result), file=out)
    return result
