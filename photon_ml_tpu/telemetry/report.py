"""Telemetry report: per-phase tables, overlap efficiency, and the
span-vs-wall-clock reconciliation check over a ``run_log.jsonl``.

``python -m photon_ml_tpu.telemetry report <run_log.jsonl>`` prints:

- **Phases**: the RunLogger ``phase_start``/``phase_end`` wall-clock
  table (driver ETL / fit / save phases).
- **Stage spans**: per-name duration stats from the
  ``telemetry_summary`` event (count, total, mean, share of the
  busiest thread's wall clock).
- **Prefetcher**: overlap efficiency — the fraction of streamed pass
  time the consumer was NOT blocked on the prefetch queue (1.0 = the
  disk+staging tier fully hidden under device compute) — plus producer
  stall and LRU hit/load counters.
- **Liveness**: heartbeat counts per stage and any thread_exception
  events (the hung-run forensic trail).
- **Reconciliation**: for each thread with trace spans, the fraction
  of wall clock (first depth-0 span start → last depth-0 span end)
  covered by depth-0 spans.  The check passes when the busiest thread
  covers at least ``--threshold`` (default 0.9) — i.e. the stage spans
  actually account for where the time went.

The last stdout line is one machine-parseable JSON object (the repo's
CLI contract); exit code is 1 when the reconciliation check fails.
"""

from __future__ import annotations

import json
import sys


def load_events(path: str) -> list[dict]:
    """Parse a run log, tolerating a torn tail: a killed run (the
    report's primary forensic case) can leave a partial final line —
    malformed lines are skipped, not fatal."""
    out = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                out.append({"event": "_malformed_line"})
    return out


def _phases(events: list[dict]) -> list[tuple[str, float]]:
    out = []
    for ev in events:
        if ev.get("event") == "phase_end":
            out.append((ev.get("phase", "?"),
                        float(ev.get("duration_s", 0.0))))
    return out


def reconcile(events: list[dict]) -> dict:
    """Per-thread depth-0 span coverage of that thread's wall clock.

    Depth-0 spans on one thread cannot overlap (they come off a stack),
    so covered time is a plain sum; wall clock is last end − first
    start.  Returns ``{threads: {name: {...}}, coverage, thread}``
    where ``coverage`` is the busiest (most covered seconds) thread's
    fraction — the reconciliation number of record."""
    per_tid: dict = {}
    for ev in events:
        if ev.get("event") != "span" or ev.get("depth", 0) != 0:
            continue
        tid = ev.get("tid", 0)
        ts, dur = float(ev["ts"]), float(ev["dur"])
        ent = per_tid.setdefault(
            tid, {"thread": ev.get("thread", str(tid)), "covered_s": 0.0,
                  "start": ts, "end": ts + dur, "spans": 0})
        ent["covered_s"] += dur
        ent["start"] = min(ent["start"], ts)
        ent["end"] = max(ent["end"], ts + dur)
        ent["spans"] += 1
    threads = {}
    best = None
    for tid, ent in per_tid.items():
        wall = max(ent["end"] - ent["start"], 1e-9)
        cov = min(1.0, ent["covered_s"] / wall)
        threads[ent["thread"]] = {
            "spans": ent["spans"],
            "covered_s": round(ent["covered_s"], 3),
            "wall_s": round(wall, 3),
            "coverage": round(cov, 4),
        }
        if best is None or ent["covered_s"] > best[1]:
            best = (ent["thread"], ent["covered_s"], cov)
    return {
        "threads": threads,
        "thread": best[0] if best else None,
        "coverage": round(best[2], 4) if best else None,
    }


def report(path: str, threshold: float = 0.9, out=None) -> dict:
    """Print the report for ``path``; returns the JSON summary dict."""
    out = out or sys.stdout
    events = load_events(path)
    summary = None
    for ev in events:
        if ev.get("event") == "telemetry_summary":
            summary = ev         # last one wins (append-mode logs)

    w = lambda s="": print(s, file=out)
    phases = _phases(events)
    if phases:
        w("Phases (run log):")
        w(f"  {'phase':<28} {'wall_s':>10}")
        for name, dur in phases:
            w(f"  {name:<28} {dur:>10.3f}")
        w()

    spans = (summary or {}).get("spans", {})
    if spans:
        total_all = sum(st["total_s"] for st in spans.values())
        w("Stage spans:")
        w(f"  {'name':<24} {'cat':<8} {'count':>7} {'total_s':>10} "
          f"{'mean_ms':>9} {'share':>7}")
        for name, st in sorted(spans.items(),
                               key=lambda kv: -kv[1]["total_s"]):
            mean_ms = 1e3 * st["total_s"] / max(st["count"], 1)
            share = st["total_s"] / total_all if total_all else 0.0
            w(f"  {name:<24} {st['cat']:<8} {st['count']:>7} "
              f"{st['total_s']:>10.3f} {mean_ms:>9.2f} {share:>6.1%}")
        w()

    derived = (summary or {}).get("derived", {})
    counters = (summary or {}).get("counters", {})
    overlap = derived.get("overlap_efficiency")
    if overlap is not None:
        w("Prefetcher:")
        w(f"  consumer blocked {counters.get('prefetch.consumer_wait_s', 0.0):.3f} s"
          f" of {derived.get('pass_span_total_s', 0.0):.3f} s streamed pass time"
          f" ({derived.get('consumer_blocked_fraction', 0.0):.1%})"
          f" -> overlap efficiency {overlap:.1%}")
        if "producer_stall_fraction" in derived:
            w(f"  producer stalled on a full queue "
            f"{counters.get('prefetch.producer_stall_s', 0.0):.3f} s "
              f"({derived['producer_stall_fraction']:.1%} of pass time)")
        hits = counters.get("store.hits")
        loads = counters.get("store.loads")
        if hits is not None or loads is not None:
            w(f"  chunk source: {hits or 0} LRU window hits, "
              f"{loads or 0} disk loads, "
              f"{counters.get('store.rebuilds', 0)} rebuilds")
        w()

    torn = sum(1 for ev in events if ev.get("event") == "_malformed_line")
    if torn:
        w(f"NOTE: {torn} malformed line(s) skipped (torn tail — the "
          "run likely died mid-write).")
        w()

    beats: dict = {}
    deaths = []
    for ev in events:
        if ev.get("event") == "heartbeat":
            beats[ev.get("stage", "?")] = beats.get(
                ev.get("stage", "?"), 0) + 1
        elif ev.get("event") == "thread_exception":
            deaths.append(ev)
    if beats or deaths:
        w("Liveness:")
        for stage, n in sorted(beats.items()):
            w(f"  {stage}: {n} heartbeats")
        for ev in deaths:
            w(f"  DIED {ev.get('stage')}: {ev.get('error')} "
              f"(thread {ev.get('thread')}, t={ev.get('t')})")
        w()

    recon = reconcile(events)
    ok = True
    if recon["coverage"] is not None:
        w("Reconciliation (depth-0 spans vs wall clock, per thread):")
        for name, ent in sorted(recon["threads"].items()):
            w(f"  {name}: {ent['covered_s']:.3f} s covered of "
              f"{ent['wall_s']:.3f} s wall ({ent['coverage']:.1%}, "
              f"{ent['spans']} spans)")
        ok = recon["coverage"] >= threshold
        w(f"  busiest thread '{recon['thread']}' coverage "
          f"{recon['coverage']:.1%} "
          f"{'>=' if ok else '<'} threshold {threshold:.0%} "
          f"-> {'PASS' if ok else 'FAIL'}")
        w()
    elif summary is None:
        w("No telemetry_summary event found (telemetry was off, or the "
          "run died before close).")
        w()

    result = {
        "ok": ok,
        "phases": {name: dur for name, dur in phases},
        "overlap_efficiency": overlap,
        "consumer_blocked_fraction": derived.get(
            "consumer_blocked_fraction"),
        "reconciliation": recon["coverage"],
        "reconciliation_thread": recon["thread"],
        "reconciliation_threads": recon["threads"],
        "counters": counters,
        "heartbeats": beats,
        "thread_exceptions": len(deaths),
        "mode": (summary or {}).get("mode"),
    }
    print(json.dumps(result), file=out)
    return result
