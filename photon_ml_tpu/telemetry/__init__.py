"""Pipeline telemetry: spans, metrics, and trace export (ISSUE 7).

The out-of-core tier (rounds 8-10) is a multi-threaded pipeline — disk
reader, host stager, async device dispatch, sink writer — whose
performance story was previously reconstructed by hand from bench
deltas.  Attributing time to STAGES, not end-to-end timing, is what
finds the next lever (PAPERS.md: the Spark-ML stage-attribution study;
Snap ML's pipelined hierarchy is only tunable if stall/overlap at each
level is measurable).  This package makes the pipeline observable:

- **Span tracer**: nested, thread-aware spans (``telemetry.span``)
  recorded per-thread and merged at close.  One streamed fit yields a
  timeline of prefetcher disk reads, host staging, device compute, and
  sink writes across threads.
- **Metrics registry**: counters / gauges / histograms (bounded
  reservoirs) — LRU hits vs disk loads, prefetch stall vs consumer
  wait seconds, sweeps odometer, line-search trials, sink queue depth,
  XLA compile events (bridged from ``analysis.guards``' listener), and
  a background RSS sampler.
- **Export**: everything writes through the existing
  ``utils.run_log.RunLogger`` JSONL (``telemetry_summary`` + per-span
  ``span`` events in trace mode) and — in ``trace`` mode — a Chrome
  trace-event ``trace.json`` loadable in Perfetto / ``chrome://tracing``.
- **Report**: ``python -m photon_ml_tpu.telemetry report
  <run_log.jsonl>`` prints per-phase wall-clock tables, prefetcher
  overlap efficiency (fraction of streamed pass time the consumer was
  blocked on the queue), and a reconciliation check that stage spans
  account for the measured wall clock.

Modes (``TrainingConfig.telemetry`` / ``ScoringConfig.telemetry``):

- ``off`` (default): the module-level helpers are no-ops against a
  null singleton — zero events, zero extra compiles, no measurable
  overhead on the per-chunk hot paths (a global read + early return).
- ``metrics``: counters/gauges/histograms active; finished spans fold
  into bounded per-name duration stats (no per-span retention).
- ``trace``: ``metrics`` plus full span retention and ``trace.json``.

Thread-safety contract (photon-lint ``unlocked-shared-write``): all
shared registries mutate under one lock; per-span hot state lives on a
``threading.local``; heartbeat / exception events go straight through
the (internally locked) ``RunLogger``.
"""

from __future__ import annotations

import bisect
import contextlib
import logging
import os
import threading
import time

logger = logging.getLogger(__name__)

MODES = ("off", "metrics", "trace")

# Span names that represent one full streamed data pass — the basis for
# the prefetcher overlap-efficiency derivation (consumer blocked time /
# total streamed pass time).
PASS_SPANS = ("sweep", "per_example_pass", "score_pass", "re_sweep",
              "fused_cycle_pass")

# Bounded-reservoir cap for histograms and sampled gauges: when full,
# the reservoir decimates to every-other sample and doubles its stride
# (deterministic — no RNG in the telemetry path).
_RESERVOIR_CAP = 1024

# Counter rate() support (ISSUE 10): per-counter (ts, cumulative)
# samples older than the horizon are dropped at cap-time cleanup — the
# monitor's alert rules only ever ask about trailing windows of tens
# of seconds.
_RATE_HORIZON_S = 300.0
_RATE_SERIES_CAP = 4096


class _NullSpan:
    """The off-path span: a shared, stateless context manager."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def _reservoir_quantile(sorted_reservoir: list, q: float):
    """Nearest-rank quantile of a SORTED reservoir — the one place the
    bounded-error contract's rank arithmetic lives (``percentile()``
    and the summary's p50/p95/p99 must agree by construction)."""
    if not sorted_reservoir:
        return None
    idx = min(len(sorted_reservoir) - 1,
              int(round(q * (len(sorted_reservoir) - 1))))
    return sorted_reservoir[idx]

# The active session (None = telemetry off).  Module-global by design:
# instrumentation sites are deep library code (prefetch threads, chunk
# stores) that cannot thread a handle through every call.
_ACTIVE: "Telemetry | None" = None
_ACTIVE_LOCK = threading.Lock()


def active() -> "Telemetry | None":
    """The active session, or None when telemetry is off."""
    return _ACTIVE


def span(name: str, cat: str = "app", **args):
    """Context manager timing a nested, thread-aware span.  A no-op
    singleton when telemetry is off (the hot-path contract)."""
    t = _ACTIVE
    if t is None:
        return _NULL_SPAN
    return _Span(t, name, cat, args or None)


def count(name: str, n=1) -> None:
    """Increment counter ``name`` (int or float increments)."""
    t = _ACTIVE
    if t is not None:
        t.count(name, n)


def gauge(name: str, value) -> None:
    """Set gauge ``name`` (last/min/max retained; sampled in trace)."""
    t = _ACTIVE
    if t is not None:
        t.gauge(name, value)


def observe(name: str, value) -> None:
    """Fold ``value`` into histogram ``name`` (count/sum/min/max +
    bounded reservoir)."""
    t = _ACTIVE
    if t is not None:
        t.observe(name, value)


def heartbeat(stage: str, **fields) -> None:
    """Immediate liveness event from a pipeline thread (hung-run
    diagnosability: a stalled fit shows which stage stopped)."""
    t = _ACTIVE
    if t is not None:
        t.heartbeat(stage, **fields)


def device_memory(tag: str | None = None) -> None:
    """Sample the device-memory gauge now (ISSUE 8 device accounting).
    Phase spans sample automatically at open/close; call this at extra
    boundaries worth a data point (e.g. after dataset placement)."""
    t = _ACTIVE
    if t is not None:
        t.sample_device_memory(tag)


def thread_exception(stage: str, error: BaseException, **fields) -> None:
    """Immediate death event from a pipeline thread (written before
    the error rides the queue to the consumer)."""
    t = _ACTIVE
    if t is not None:
        t.thread_exception(stage, error, **fields)


class _Span:
    """One live span; produced by ``span()`` when a session is active.

    Start/duration use ``time.perf_counter`` (monotonic — the
    naked-clock rule); the recorded ``ts`` is on the session's
    RunLogger clock so span timestamps line up with the JSONL ``t``
    field."""

    __slots__ = ("_t", "name", "cat", "args", "ts", "t0", "depth")

    def __init__(self, t: "Telemetry", name: str, cat: str, args):
        self._t = t
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        tl = self._t._local
        stack = getattr(tl, "stack", None)
        if stack is None:
            stack = tl.stack = []
            self._t._register_thread()
        self.depth = len(stack)
        stack.append(self)
        if self.cat == "phase":
            # Phase boundaries are the device-memory sampling points
            # (ISSUE 8): cheap (a handful per run) and aligned with the
            # phases the report attributes residency to.
            self._t.sample_device_memory(self.name)
        self.ts = self._t.now()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self.t0
        self._t._local.stack.pop()
        self._t._finish_span(self, dur, failed=exc_type is not None)
        if self.cat == "phase":
            self._t.sample_device_memory(self.name)
        return False


class _RssSampler:
    """Background RSS sampler: ``/proc/self/status`` VmRSS at a fixed
    period into the ``proc.rss_mb`` gauge (+ a (ts, mb) series for the
    trace counter track).  Worker/caller shared state lives under one
    lock (photon-lint thread contract); ``Event`` stops the thread."""

    def __init__(self, t: "Telemetry", period_s: float):
        self._t = t
        self._period = period_s
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._samples: list = []
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="photon-telemetry-rss")

    @staticmethod
    def _rss_mb() -> float | None:
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1]) / 1024.0
        except OSError:  # photon-lint: disable=swallowed-exception (/proc absent off-Linux; the sampler simply never starts)
            return None
        return None

    def _run(self) -> None:
        while not self._stop.is_set():
            mb = self._rss_mb()
            if mb is not None:
                self._t.gauge("proc.rss_mb", mb)
                with self._lock:
                    self._samples.append((self._t.now(), mb))
                    if len(self._samples) > _RESERVOIR_CAP:
                        del self._samples[::2]
            self._stop.wait(self._period)

    def start(self) -> None:
        if self._rss_mb() is not None:   # /proc present
            self._thread.start()

    def close(self) -> list:
        self._stop.set()
        if self._thread.is_alive():
            # The sampler loop wakes at most _period after the stop
            # event sets; the bounded join is belt-and-braces
            # (photon-lint eternal-wait).
            self._thread.join(timeout=max(5.0, self._period * 2))
        with self._lock:
            return list(self._samples)


class _CompileBridge(logging.Handler):
    """Bridges XLA compile events into the metrics registry.

    Listens exactly like ``analysis.guards.count_compiles`` (same
    record pattern from the jax logger under ``jax.log_compiles``):
    each compiled program bumps the ``jax.compiles`` counter and — in
    trace mode — lands as an instant event on the compiling thread's
    track, so a mid-sweep retrace is visible in the timeline."""

    def __init__(self, t: "Telemetry"):
        super().__init__(level=logging.DEBUG)
        self._t = t

    def emit(self, record: logging.LogRecord) -> None:
        from photon_ml_tpu.analysis.guards import _COMPILE_RE

        try:
            m = _COMPILE_RE.match(record.getMessage())
        except Exception:  # photon-lint: disable=swallowed-exception (a guard must never break the run)
            return
        if m:
            self._t.count("jax.compiles")
            self._t._instant("xla_compile", "jax", {"program": m.group(1)})


class Telemetry:
    """One telemetry session: tracer + metrics registry + exporters.

    Create through ``start()`` / ``maybe_session()`` — the module-level
    helpers dispatch to the single active session.  ``close()`` merges
    per-thread spans, writes the ``telemetry_summary`` (+ per-span
    events and ``trace.json`` in trace mode), and deactivates.
    """

    def __init__(self, mode: str, run_logger, telemetry_dir: str | None,
                 heartbeat_s: float = 5.0, rss_period_s: float = 0.25,
                 owns_logger: bool = False):
        if mode not in ("metrics", "trace"):
            raise ValueError(f"telemetry mode {mode!r} not in "
                             "('metrics', 'trace')")
        self.mode = mode
        self.dir = telemetry_dir
        self.heartbeat_s = float(heartbeat_s)
        self._rss_period_s = float(rss_period_s)
        self._log = run_logger
        self._owns_logger = owns_logger
        self._lock = threading.Lock()
        self._local = threading.local()
        self._counters: dict = {}
        self._counter_series: dict = {}   # name -> [(ts, cumulative)]
        self._gauges: dict = {}
        self._hists: dict = {}
        self._span_stats: dict = {}
        self._thread_spans: dict = {}     # tid -> [span records]
        self._thread_names: dict = {}     # tid -> thread name
        self._instants: list = []         # (ts, tid, name, cat, args)
        self._device_programs: dict = {}  # name -> cost dict (device.py)
        self._dev_series: list = []       # (ts, bytes_in_use) samples
        self._dev_memory_source: str | None = None
        self._sampler: _RssSampler | None = None
        self._bridge: _CompileBridge | None = None
        self._jax_stack: contextlib.ExitStack | None = None
        self._closed = False

    # -- clock --------------------------------------------------------------

    def now(self) -> float:
        """Seconds on the session RunLogger's monotonic clock (span
        timestamps line up with JSONL event ``t`` fields)."""
        return self._log.now()

    # -- lifecycle ----------------------------------------------------------

    def _open(self) -> None:
        self._log.event("telemetry_start", mode=self.mode,
                        **({"dir": self.dir} if self.dir else {}))
        self._sampler = _RssSampler(self, self._rss_period_s)
        self._sampler.start()
        # Compile bridge: best-effort (jax may be absent in a host-only
        # driver); uses the guards listener's record pattern.
        try:
            import jax

            self._jax_stack = contextlib.ExitStack()
            self._jax_stack.enter_context(jax.log_compiles())
            self._bridge = _CompileBridge(self)
            jax_logger = logging.getLogger("jax")
            self._bridge_old_level = jax_logger.level
            jax_logger.addHandler(self._bridge)
            # Records are emitted at WARNING; an app that raised the
            # effective level above it would silently mute the bridge.
            if jax_logger.getEffectiveLevel() > logging.WARNING:
                jax_logger.setLevel(logging.WARNING)
        except Exception as e:   # pragma: no cover - jax-less hosts
            logger.info("telemetry: compile bridge unavailable (%r)", e)
            self._bridge = None
            self._jax_stack = None

    def close(self) -> None:
        """Merge, export, deactivate.  Idempotent."""
        global _ACTIVE
        if self._closed:
            return
        self._closed = True
        rss_series = self._sampler.close() if self._sampler else []
        if self._bridge is not None:
            jax_logger = logging.getLogger("jax")
            jax_logger.removeHandler(self._bridge)
            jax_logger.setLevel(self._bridge_old_level)
            self._bridge = None
        if self._jax_stack is not None:
            self._jax_stack.close()
            self._jax_stack = None

        summary = self.summary()
        self._log.event("telemetry_summary", **summary)
        if self.mode == "trace":
            with self._lock:
                merged = [dict(rec, tid=tid,
                               thread=self._thread_names.get(tid, str(tid)))
                          for tid, recs in self._thread_spans.items()
                          for rec in recs]
            merged.sort(key=lambda r: r["ts"])
            for rec in merged:
                self._log.event("span", **rec)
            if self.dir is not None:
                from photon_ml_tpu.telemetry.export import write_trace

                os.makedirs(self.dir, exist_ok=True)
                path = os.path.join(self.dir, "trace.json")
                with self._lock:
                    names = dict(self._thread_names)
                    instants = list(self._instants)
                    dev_series = list(self._dev_series)
                write_trace(path, merged, names, instants, rss_series,
                            device_series=dev_series)
                self._log.event("trace_written", path=path,
                                spans=len(merged))
        if self._owns_logger:
            self._log.close()
        with _ACTIVE_LOCK:
            if _ACTIVE is self:
                _ACTIVE = None

    # -- metrics ------------------------------------------------------------

    def count(self, name: str, n=1) -> None:
        now = self.now()
        with self._lock:
            v = self._counters.get(name, 0) + n
            self._counters[name] = v
            # Rolling (ts, cumulative) series behind rate().  Per
            # increment the series pays ONE append; pruning is deferred
            # to the cap — one batched front-drop of horizon-stale
            # entries, then every-other decimation (keeping the
            # just-appended newest sample) — so a hot per-chunk counter
            # amortizes the cleanup to O(1) instead of a per-call
            # memmove.  Stale front entries before a cleanup only cost
            # memory (bounded by the cap): rate() walks from the back
            # and never reads past its window.
            s = self._counter_series.get(name)
            if s is None:
                s = self._counter_series[name] = []
            s.append((now, v))
            if len(s) >= _RATE_SERIES_CAP:
                cutoff = now - _RATE_HORIZON_S
                k = min(bisect.bisect_left(s, (cutoff,)), len(s) - 2)
                if k > 0:
                    del s[:k]
                if len(s) >= _RATE_SERIES_CAP:
                    del s[1::2]

    def counter(self, name: str, default=0):
        """Current cumulative value of counter ``name``."""
        with self._lock:
            return self._counters.get(name, default)

    def gauge_value(self, name: str) -> dict | None:
        """Snapshot of gauge ``name`` ({last, min, max}) or None."""
        with self._lock:
            g = self._gauges.get(name)
            return None if g is None else dict(g)

    def rate(self, name: str, window_s: float = 30.0,
             now: float | None = None) -> float | None:
        """Rolling-window rate of counter ``name`` in units/second
        (ISSUE 10): the live-monitoring tier needs throughput-per-
        second, not lifetime totals — a run that was fast an hour ago
        and is stalled NOW has a healthy lifetime average.

        The rate is ``Δvalue / Δt`` between the newest sample and the
        oldest sample inside the trailing ``window_s`` (anchored at
        ``now`` on the session clock when given, else at the newest
        sample).  Error contract (pinned by the bounded-error unit
        test): samples are exact (every ``count()`` records one), so
        within the horizon the only approximation is decimation under
        the series cap — the retained every-other subsample still
        brackets the window to within one inter-sample gap, i.e. the
        reported rate is the exact mean rate over an interval that
        differs from the requested window by at most two sample
        spacings.  None when fewer than two samples exist (or the
        counter is unknown)."""
        if window_s <= 0:
            raise ValueError(f"window_s must be positive, got {window_s!r}")
        with self._lock:
            s = self._counter_series.get(name)
            if not s or len(s) < 2:
                return None
            anchor = s[-1][0] if now is None else float(now)
            cutoff = anchor - window_s
            base = None
            for ts, v in reversed(s):
                if ts < cutoff:
                    break
                base = (ts, v)
            if base is None or base[0] >= s[-1][0]:
                base = s[-2]
            dt = anchor - base[0]
            if dt <= 0:
                return None
            return (s[-1][1] - base[1]) / dt

    def gauge(self, name: str, value) -> None:
        value = float(value)
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                self._gauges[name] = {"last": value, "min": value,
                                      "max": value}
            else:
                g["last"] = value
                g["min"] = min(g["min"], value)
                g["max"] = max(g["max"], value)

    def observe(self, name: str, value) -> None:
        value = float(value)
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = {
                    "count": 0, "sum": 0.0, "min": value, "max": value,
                    "reservoir": [], "stride": 1}
            h["count"] += 1
            h["sum"] += value
            h["min"] = min(h["min"], value)
            h["max"] = max(h["max"], value)
            if (h["count"] - 1) % h["stride"] == 0:
                h["reservoir"].append(value)
                if len(h["reservoir"]) >= _RESERVOIR_CAP:
                    del h["reservoir"][::2]
                    h["stride"] *= 2

    def histogram_quantiles(self, prefix: str, qs: tuple
                            ) -> dict:
        """{name: {count, quantiles: [per q]}} for every histogram
        whose name starts with ``prefix`` (ISSUE 14): the serving
        tier's stage table polls a handful of ``serve.stage.*``
        histograms per /status request — this sorts ONLY the matching
        reservoirs under the lock instead of snapshotting the whole
        registry the way ``summary()`` does."""
        out = {}
        with self._lock:
            # Copy under the lock, sort OUTSIDE it — the whole point
            # is not stalling request-path observe() calls.
            matching = [(name, h["count"], list(h["reservoir"]))
                        for name, h in self._hists.items()
                        if name.startswith(prefix)]
        for name, count, res in matching:
            res.sort()
            out[name] = {
                "count": count,
                "quantiles": [_reservoir_quantile(res, q)
                              for q in qs],
            }
        return out

    def percentile(self, name: str, q: float) -> float | None:
        """Quantile ``q`` in [0, 1] of histogram ``name`` from its
        bounded reservoir (ISSUE 8 satellite).

        Error contract: the reservoir is a deterministic every-stride-th
        subsample of the observation stream, so the estimate is the true
        q-quantile of a subsample of size R ≥ _RESERVOIR_CAP/2 once the
        stream exceeds the cap — rank error ≤ 1/R of the distribution
        (≤ ~0.2 percentile points at the 1024 cap), pinned by the
        bounded-error contract test.  None for an unknown name."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q!r} not in [0, 1]")
        with self._lock:
            h = self._hists.get(name)
            if h is None or not h["reservoir"]:
                return None
            res = sorted(h["reservoir"])
        return _reservoir_quantile(res, q)

    # -- device accounting (telemetry.device) --------------------------------

    def sample_device_memory(self, tag: str | None = None) -> None:
        """Device-memory gauge sample: backend ``memory_stats()`` or the
        live-buffer census (see ``telemetry.device.memory_snapshot``).
        Called at every phase-span boundary; a no-op when jax is absent
        or the backend exposes nothing.  Each sample also lands as a
        ``device_memory`` JSONL event carrying ``tag`` (the phase name,
        or an explicit label like the estimator's ``datasets_placed``),
        so a specific boundary's footprint is recoverable from the log
        — the gauge and trace series are anonymous by construction."""
        from photon_ml_tpu.telemetry import device as _device

        snap = _device.memory_snapshot()
        if snap is None:
            return
        self.gauge("device.bytes_in_use", snap["bytes_in_use"])
        if "peak_bytes_in_use" in snap:
            self.gauge("device.peak_bytes_in_use",
                       snap["peak_bytes_in_use"])
        with self._lock:
            self._dev_memory_source = snap["source"]
            self._dev_series.append((self.now(), snap["bytes_in_use"]))
            if len(self._dev_series) > _RESERVOIR_CAP:
                del self._dev_series[::2]
        self._log.event("device_memory",
                        **({"tag": tag} if tag else {}), **snap)

    # -- spans --------------------------------------------------------------

    def span(self, name: str, cat: str = "app", **args) -> _Span:
        return _Span(self, name, cat, args or None)

    def _register_thread(self) -> None:
        tid = threading.get_ident()
        with self._lock:
            self._thread_spans.setdefault(tid, [])
            self._thread_names[tid] = threading.current_thread().name

    def _finish_span(self, sp: _Span, dur: float, failed: bool) -> None:
        key = sp.name
        with self._lock:
            st = self._span_stats.get(key)
            if st is None:
                st = self._span_stats[key] = {
                    "cat": sp.cat, "count": 0, "total_s": 0.0,
                    "min_s": dur, "max_s": dur}
            st["count"] += 1
            st["total_s"] += dur
            st["min_s"] = min(st["min_s"], dur)
            st["max_s"] = max(st["max_s"], dur)
            if self.mode == "trace":
                rec = {"name": sp.name, "cat": sp.cat,
                       "ts": round(sp.ts, 6), "dur": round(dur, 6),
                       "depth": sp.depth}
                if sp.args:
                    rec["args"] = sp.args
                if failed:
                    rec["failed"] = True
                self._thread_spans[threading.get_ident()].append(rec)

    def _instant(self, name: str, cat: str, args=None) -> None:
        if self.mode != "trace":
            return
        with self._lock:
            self._instants.append(
                (self.now(), threading.get_ident(), name, cat, args))
            if len(self._instants) > 4 * _RESERVOIR_CAP:
                del self._instants[::2]

    # -- liveness events ----------------------------------------------------

    def heartbeat(self, stage: str, **fields) -> None:
        self._log.event("heartbeat", stage=stage,
                        thread=threading.current_thread().name, **fields)

    def thread_exception(self, stage: str, error: BaseException,
                         **fields) -> None:
        self._log.event("thread_exception", stage=stage,
                        thread=threading.current_thread().name,
                        error=repr(error), **fields)

    # -- summary ------------------------------------------------------------

    @staticmethod
    def _derived(counters: dict, spans: dict) -> dict:
        """Cross-metric derivations from SNAPSHOT dicts (never the live
        registries — summary() is called on live sessions, and pipeline
        threads keep inserting span-stat keys): prefetcher overlap
        efficiency = 1 − (consumer blocked on the queue / total
        streamed pass time).  ~1.0 means the prefetch pipeline fully
        hid the disk+staging tier under device compute."""
        out: dict = {}
        blocked = counters.get("prefetch.consumer_wait_s")
        basis = sum(st["total_s"] for name, st in spans.items()
                    if name in PASS_SPANS)
        if blocked is not None and basis > 0:
            frac = min(1.0, float(blocked) / basis)
            out["consumer_blocked_fraction"] = round(frac, 4)
            out["overlap_efficiency"] = round(1.0 - frac, 4)
            out["pass_span_total_s"] = round(basis, 3)
        stall = counters.get("prefetch.producer_stall_s")
        if stall is not None and basis > 0:
            out["producer_stall_fraction"] = round(
                min(1.0, float(stall) / basis), 4)
        return out

    def summary(self) -> dict:
        """JSON-ready snapshot of every registry (the
        ``telemetry_summary`` event body; bench arms embed it)."""
        with self._lock:
            counters = {k: (round(v, 6) if isinstance(v, float) else v)
                        for k, v in sorted(self._counters.items())}
            gauges = {k: {f: round(x, 3) for f, x in v.items()}
                      for k, v in sorted(self._gauges.items())}
            hists = {}
            for k, h in sorted(self._hists.items()):
                res = sorted(h["reservoir"])

                def pct(q, res=res):
                    v = _reservoir_quantile(res, q)
                    return None if v is None else round(v, 6)

                hists[k] = {"count": h["count"],
                            "sum": round(h["sum"], 6),
                            "min": round(h["min"], 6),
                            "max": round(h["max"], 6),
                            "mean": round(h["sum"] / max(h["count"], 1), 6),
                            "p50": pct(0.50), "p95": pct(0.95),
                            "p99": pct(0.99)}
            spans = {k: {"cat": st["cat"], "count": st["count"],
                         "total_s": round(st["total_s"], 6),
                         "min_s": round(st["min_s"], 6),
                         "max_s": round(st["max_s"], 6)}
                     for k, st in sorted(self._span_stats.items())}
            programs = {k: v for k, v in self._device_programs.items()
                        if v is not None}
            dev_source = self._dev_memory_source
            dev_samples = len(self._dev_series)
        out = {"mode": self.mode, "counters": counters, "gauges": gauges,
               "histograms": hists, "spans": spans,
               "derived": self._derived(counters, spans)}
        if programs or dev_source is not None:
            device = {}
            if programs:
                device["programs"] = programs
            if dev_source is not None:
                device["memory"] = {
                    "source": dev_source, "samples": dev_samples,
                    **{k: gauges[f"device.{k}"]["last"]
                       for k in ("bytes_in_use", "peak_bytes_in_use")
                       if f"device.{k}" in gauges},
                    **{f"{k}_max": gauges[f"device.{k}"]["max"]
                       for k in ("bytes_in_use",)
                       if f"device.{k}" in gauges}}
            out["device"] = device
        return out


def start(mode: str, telemetry_dir: str | None = None, run_logger=None,
          heartbeat_s: float = 5.0,
          rss_period_s: float = 0.25) -> Telemetry:
    """Activate a telemetry session (the one per process).

    ``run_logger``: the events channel; when None a ``RunLogger`` is
    created at ``<telemetry_dir>/run_log.jsonl`` (or a pure
    stdlib-logging sink when ``telemetry_dir`` is also None) and owned
    (closed) by the session."""
    global _ACTIVE
    if mode not in MODES:
        raise ValueError(f"telemetry mode {mode!r} not in {MODES}")
    if mode == "off":
        raise ValueError("start() needs an active mode; gate 'off' at "
                         "the caller (see maybe_session)")
    owns = False
    if run_logger is None:
        from photon_ml_tpu.utils.run_log import RunLogger

        path = (os.path.join(telemetry_dir, "run_log.jsonl")
                if telemetry_dir else None)
        run_logger = RunLogger(path, run_info={"telemetry": mode})
        owns = True
    t = Telemetry(mode, run_logger, telemetry_dir,
                  heartbeat_s=heartbeat_s, rss_period_s=rss_period_s,
                  owns_logger=owns)
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            if owns:
                run_logger.close()
            raise RuntimeError("a telemetry session is already active")
        _ACTIVE = t
    t._open()
    return t


@contextlib.contextmanager
def maybe_session(mode: str | None, telemetry_dir: str | None = None,
                  run_logger=None, **kw):
    """Session context honoring the config knob: ``off``/None (or an
    already-active session — the driver configured one) yields without
    creating anything; otherwise a session spans the block."""
    if mode in (None, "off") or _ACTIVE is not None:
        yield _ACTIVE
        return
    t = start(mode, telemetry_dir, run_logger, **kw)
    try:
        yield t
    finally:
        t.close()
