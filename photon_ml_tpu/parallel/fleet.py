"""Multi-host chunk-synchronized fleet streaming (ISSUE 16).

The two scale mechanisms that bound the KDD2012-scale run — the chunk
store/prefetch pipeline (host RSS) and the mesh-sharded GRR path (HBM)
— previously only composed inside ONE process.  This module is the
cross-process layer: the chunk store's chunk sequence is partitioned
across processes ("hosts"), each host opens/spills/prefetches only its
shard from a per-host spill directory, and the streaming objectives
reduce their per-chunk partials across the fleet on a
chunk-synchronized schedule.  Snap ML's hierarchical parallelism
(cluster → node → accelerator, pipelined loading at every level) is
the blueprint (PAPERS.md).

Pieces:

- ``FleetContext`` — (host_id, n_hosts, transport) for this process.
  ``initialize_from_env`` builds it from ``jax.distributed`` state
  (``transport="psum"``) or from the ``PHOTON_FLEET_*`` env trio
  (``transport="tcp"`` — the local-fleet fallback for CPU backends
  whose jaxlib has no multiprocess collectives, see
  ``MULTIPROC_UNSUPPORTED_MARKER``).
- ``shard_chunk_ids`` — contiguous per-host chunk shard, padded with
  ``EMPTY_CHUNK`` sentinels to a COMMON step count, so every host
  issues the same number of per-chunk reductions and collectives never
  deadlock on ragged shards (sentinel steps contribute exact zeros).
- ``FleetReducer`` — the per-chunk allreduce.  ``psum`` transport runs
  one cached jitted ``shard_map``/``lax.psum`` program over a
  one-device-per-process mesh (the small partial pytree is the ONLY
  thing that crosses hosts — chunk programs stay process-local, so the
  GRR/pallas per-chunk pipeline needs no sharding).  ``tcp`` transport
  is a star allreduce through a ``ReduceCoordinator`` (run by the
  launcher), summing contributions in host-id order — deterministic,
  so killed-host replay is bitwise-stable.
- ``ReduceCoordinator`` — the launcher-side reduction server.  Results
  are cached per sequence number: a host killed mid-sweep resumes from
  its per-host checkpoint, replays its reduce sequence, and fast-
  forwards through cached totals until it rejoins the live barrier —
  the rest of the fleet just waits at the chunk barrier, it is never
  restarted.

Thread contract (photon-lint ``unlocked-shared-write``): coordinator
state mutates under one condition-variable lock; client sockets are
owned by the calling (driver) thread.  All waits are bounded
(``stall_timeout_s``) — a torn fleet ends in ONE actionable error,
never a hang.
"""

from __future__ import annotations

import contextlib
import dataclasses
import io
import json
import logging
import os
import socket
import threading
import time

import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.config import read_env
from photon_ml_tpu.reliability import faults

logger = logging.getLogger(__name__)

# The sentinel chunk id padding ragged shards to a common step count.
# A sentinel step computes no chunk — it contributes an exact-zero
# partial so the fleet's per-chunk reduction count stays identical on
# every host.
EMPTY_CHUNK = -1

# Mesh axis name for the cross-process partial reduction (distinct from
# the intra-process DATA_AXIS/ENTITY_AXIS meshes — the reduce mesh has
# exactly one device per process).
HOSTS_AXIS = "hosts"

# The jaxlib CPU backend's "no multiprocess collectives" marker: the
# single capability probe every 2-process CPU test and the bench's
# transport selection key off (ISSUE 16 satellite — previously an
# ad-hoc string scattered through the mesh tests).
MULTIPROC_UNSUPPORTED_MARKER = "Multiprocess computations aren't implemented"

# Default bound on any fleet barrier wait: a killed host stalls its
# peers AT the barrier (that is the protocol — the fleet is never
# restarted), but a fleet that lost a host forever must end in one
# actionable error, never a hang.
DEFAULT_STALL_TIMEOUT_S = 600.0

# Reduce-result cache depth on the coordinator: a replaying host can
# fast-forward at most this many sequence numbers past its checkpoint.
# Solver/CD checkpoints land every iteration (a handful of sweeps ×
# chunks apart), so 4096 covers multiple checkpoint intervals at any
# realistic chunk grid.
_RESULT_CACHE_CAP = 4096


class FleetBarrierError(RuntimeError):
    """A fleet reduction could not complete (torn fleet, dead
    coordinator, stalled peer past the timeout)."""


@dataclasses.dataclass(frozen=True)
class FleetContext:
    """This process's position in the training fleet.

    ``transport``: ``"psum"`` (jax.distributed collectives) or
    ``"tcp"`` (the local-fleet star allreduce via ``coordinator``,
    ``host:port``)."""

    host_id: int
    n_hosts: int
    transport: str = "psum"
    coordinator: str | None = None

    def __post_init__(self):
        if self.n_hosts < 1:
            raise ValueError("n_hosts must be >= 1")
        if not 0 <= self.host_id < self.n_hosts:
            raise ValueError(
                f"host_id {self.host_id} not in [0, {self.n_hosts})")
        if self.transport not in ("psum", "tcp"):
            raise ValueError("transport must be psum|tcp")
        if self.transport == "tcp" and self.n_hosts > 1 \
                and not self.coordinator:
            raise ValueError("tcp transport needs coordinator host:port")

    @property
    def is_fleet(self) -> bool:
        return self.n_hosts > 1


def shard_chunk_ids(n_chunks: int, host_id: int, n_hosts: int
                    ) -> tuple[list[int], list[int]]:
    """Contiguous chunk shard for one host + its padded schedule.

    Returns ``(local_ids, schedule)``: ``local_ids`` are the real chunk
    ids this host owns; ``schedule`` is ``local_ids`` followed by
    ``EMPTY_CHUNK`` sentinels up to the COMMON per-host step count
    ``ceil(n_chunks / n_hosts)``.  Real chunks come FIRST so the
    prefetch pipeline never idles behind a sentinel; a host past the
    end of a ragged grid gets an all-sentinel schedule (its partials
    are exact zeros every step)."""
    if n_chunks < 0:
        raise ValueError("n_chunks must be >= 0")
    if not 0 <= host_id < n_hosts:
        raise ValueError(f"host_id {host_id} not in [0, {n_hosts})")
    steps = -(-n_chunks // n_hosts) if n_chunks else 0
    lo = min(host_id * steps, n_chunks)
    hi = min(lo + steps, n_chunks)
    local = list(range(lo, hi))
    return local, local + [EMPTY_CHUNK] * (steps - len(local))


def host_dir(base: str, ctx: "FleetContext | None") -> str:
    """Per-host subdirectory of ``base`` (spill/checkpoint/output
    sharding by process id); ``base`` unchanged outside a fleet."""
    if ctx is None or not ctx.is_fleet:
        return base
    return os.path.join(base, f"host_{ctx.host_id:03d}")


# ---------------------------------------------------------------------------
# Active-context plumbing (the telemetry/checkpoint pattern: deep library
# code — chunk builders, streaming sweeps — cannot thread a handle
# through every call).
# ---------------------------------------------------------------------------

_ACTIVE: FleetContext | None = None
_REDUCER: "FleetReducer | None" = None
_ACTIVE_LOCK = threading.Lock()


def active() -> FleetContext | None:
    """The active fleet context, or None (single-host run)."""
    return _ACTIVE


def reducer() -> "FleetReducer | None":
    """The process-wide reducer for the active context (lazily built),
    or None outside a fleet."""
    global _REDUCER
    ctx = _ACTIVE
    if ctx is None or not ctx.is_fleet:
        return None
    with _ACTIVE_LOCK:
        if _REDUCER is None or _REDUCER.ctx is not ctx:
            _REDUCER = FleetReducer(ctx)
        return _REDUCER


@contextlib.contextmanager
def session(ctx: FleetContext | None):
    """Expose ``ctx`` as the active fleet for the block (tests/bench
    workers); None yields a no-op."""
    global _ACTIVE, _REDUCER
    if ctx is None:
        yield None
        return
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            raise RuntimeError("a fleet session is already active")
        _ACTIVE = ctx
    try:
        yield ctx
    finally:
        with _ACTIVE_LOCK:
            red, _REDUCER = _REDUCER, None
            _ACTIVE = None
        if red is not None:
            red.close()


def initialize_from_env() -> FleetContext | None:
    """Build + activate the fleet context for this process, or None.

    Order: an initialized ``jax.distributed`` multi-process runtime
    wins (``transport="psum"`` — the production path); otherwise the
    ``PHOTON_FLEET_NUM_HOSTS`` / ``PHOTON_FLEET_HOST_ID`` /
    ``PHOTON_FLEET_COORDINATOR`` env trio selects the local-fleet tcp
    transport.  Idempotent: an already-active context is returned
    as-is."""
    global _ACTIVE
    if _ACTIVE is not None:
        return _ACTIVE
    ctx = None
    try:
        import jax

        if jax.process_count() > 1:
            ctx = FleetContext(host_id=jax.process_index(),
                               n_hosts=jax.process_count(),
                               transport="psum")
    except Exception as e:  # pragma: no cover - jax-less hosts
        logger.info("fleet: jax process probe unavailable (%r)", e)
    if ctx is None:
        n = read_env("PHOTON_FLEET_NUM_HOSTS")
        if n is None or int(n) <= 1:
            return None
        ctx = FleetContext(
            host_id=int(read_env("PHOTON_FLEET_HOST_ID", "0") or 0),
            n_hosts=int(n),
            transport="tcp",
            coordinator=read_env("PHOTON_FLEET_COORDINATOR"),
        )
    with _ACTIVE_LOCK:
        if _ACTIVE is None:
            _ACTIVE = ctx
        ctx = _ACTIVE
    logger.info("fleet: host %d of %d (transport=%s)",
                ctx.host_id, ctx.n_hosts, ctx.transport)
    return ctx


# ---------------------------------------------------------------------------
# Wire codec (tcp transport): one JSON header line + one npz payload.
# Pickle-free by design — the coordinator ingests bytes from N worker
# processes; npz with allow_pickle=False bounds the parse surface.
# ---------------------------------------------------------------------------


def _encode_leaves(leaves: list[np.ndarray]) -> bytes:
    bio = io.BytesIO()
    np.savez(bio, *[np.asarray(lf) for lf in leaves])
    return bio.getvalue()


def _decode_leaves(payload: bytes) -> list[np.ndarray]:
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        return [np.asarray(z[f"arr_{i}"]) for i in range(len(z.files))]


def _send_msg(sock: socket.socket, header: dict, payload: bytes) -> None:
    head = json.dumps({**header, "nbytes": len(payload)}).encode() + b"\n"
    sock.sendall(head + payload)


def _recv_exact(fh, n: int) -> bytes:
    buf = fh.read(n)
    if len(buf) != n:
        raise FleetBarrierError(
            f"fleet connection closed mid-message ({len(buf)}/{n} bytes)")
    return buf


def _recv_msg(fh) -> tuple[dict, bytes]:
    line = fh.readline()
    if not line:
        raise EOFError("fleet connection closed")
    header = json.loads(line.decode())
    return header, _recv_exact(fh, int(header.get("nbytes", 0)))


# ---------------------------------------------------------------------------
# ReduceCoordinator: the launcher-side star-allreduce server.
# ---------------------------------------------------------------------------


class ReduceCoordinator:
    """Star allreduce for the tcp local-fleet transport.

    Runs in the LAUNCHER (bench parent / test harness / a dedicated
    supervisor) — deliberately outside any worker, so killing a worker
    host never takes the reduction plane with it.  Each reduce sequence
    number completes when all ``n_hosts`` contributions arrive; the
    total (summed in host-id order — deterministic float order) is
    broadcast to every waiter and cached, so a restarted host replaying
    from its per-host checkpoint fast-forwards through cached totals
    (duplicate contributions for a completed seq are answered from
    cache, never re-summed)."""

    def __init__(self, n_hosts: int, host: str = "127.0.0.1",
                 port: int = 0,
                 stall_timeout_s: float = DEFAULT_STALL_TIMEOUT_S):
        if n_hosts < 1:
            raise ValueError("n_hosts must be >= 1")
        self.n_hosts = int(n_hosts)
        self.stall_timeout_s = float(stall_timeout_s)
        self._cond = threading.Condition()
        self._pending: dict[int, dict[int, list[np.ndarray]]] = {}
        self._done: dict[int, list[np.ndarray]] = {}
        self._done_order: list[int] = []
        self._closed = False
        self.reduces = 0          # completed sequence numbers
        self.replays = 0          # cache-answered duplicate requests
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.5)
        self.port = self._srv.getsockname()[1]
        self.address = f"{host}:{self.port}"
        self._threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="photon-fleet-coordinator")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:  # photon-lint: disable=swallowed-exception (the accept poll tick; loop re-checks _closed)
                continue
            except OSError:  # photon-lint: disable=swallowed-exception (server socket closed under us: the shutdown path)
                break
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="photon-fleet-conn")
            t.start()
            with self._cond:
                self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            with conn, conn.makefile("rb") as fh:
                while True:
                    try:
                        header, payload = _recv_msg(fh)
                    # photon-lint: disable=swallowed-exception (worker hung up, possibly SIGKILLed; its restart replays the seq)
                    except (EOFError, FleetBarrierError, ValueError,
                            OSError):
                        return
                    total = self._reduce_one(int(header["host"]),
                                             int(header["seq"]),
                                             _decode_leaves(payload))
                    if total is None:
                        return  # coordinator closed / barrier torn
                    _send_msg(conn, {"seq": int(header["seq"])},
                              _encode_leaves(total))
        except OSError:  # photon-lint: disable=swallowed-exception (peer death mid-reply; the worker side raises its own barrier error)
            return

    def _reduce_one(self, host: int, seq: int,
                    leaves: list[np.ndarray]) -> list[np.ndarray] | None:
        deadline = time.monotonic() + self.stall_timeout_s
        with self._cond:
            if seq in self._done:
                self.replays += 1
                return self._done[seq]
            # Overwrite semantics per (seq, host): a replaying host's
            # duplicate contribution for a still-pending seq replaces
            # (never double-counts) its earlier one — the values are
            # bitwise-identical by determinism anyway.
            self._pending.setdefault(seq, {})[host] = leaves
            if len(self._pending[seq]) == self.n_hosts:
                contrib = self._pending.pop(seq)
                total = contrib[0]
                for h in range(1, self.n_hosts):
                    total = [np.add(a, b) for a, b in
                             zip(total, contrib[h])]
                self._done[seq] = total
                self._done_order.append(seq)
                self.reduces += 1
                if len(self._done_order) > _RESULT_CACHE_CAP:
                    self._done.pop(self._done_order.pop(0), None)
                self._cond.notify_all()
                return total
            while seq not in self._done and not self._closed:
                if not self._cond.wait(
                        timeout=min(1.0, self.stall_timeout_s)):
                    if time.monotonic() > deadline:
                        return None
            return self._done.get(seq)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            threads = list(self._threads)
        try:
            self._srv.close()
        except OSError:  # photon-lint: disable=swallowed-exception (already closed)
            pass
        self._accept_thread.join(timeout=5.0)
        for t in threads:
            t.join(timeout=5.0)


# ---------------------------------------------------------------------------
# FleetReducer: the per-chunk allreduce, both transports.
# ---------------------------------------------------------------------------


class FleetReducer:
    """Per-chunk partial-pytree allreduce for one fleet process.

    ``reduce(tree)`` returns the fleet-wide sum with the SAME tree
    structure; every host must call it in the same order (the
    chunk-synchronized schedule guarantees the alignment).  ``seq`` is
    the monotonically increasing reduction counter — it rides in the
    per-host solver checkpoints so a resumed host replays the exact
    sequence (tcp transport replay is answered from the coordinator's
    result cache).

    Wall time spent inside ``reduce`` (transfer + peer wait) is the
    chunk-barrier cost; it accumulates in ``barrier_wait_s`` and the
    ``fleet.barrier_wait_s`` telemetry counter.
    """

    def __init__(self, ctx: FleetContext,
                 stall_timeout_s: float = DEFAULT_STALL_TIMEOUT_S):
        self.ctx = ctx
        self.seq = 0
        self.barrier_wait_s = 0.0
        self.stall_timeout_s = float(stall_timeout_s)
        self._sock: socket.socket | None = None
        self._fh = None
        self._psum_cache: dict = {}
        self._mesh = None

    # -- psum transport ------------------------------------------------------

    def _hosts_mesh(self):
        """1-D mesh with exactly ONE device per process — the partial
        pytree's reduction plane.  Chunk programs never touch it."""
        if self._mesh is None:
            import jax
            from jax.sharding import Mesh

            by_proc: dict[int, object] = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, d)
            if len(by_proc) != self.ctx.n_hosts:
                raise FleetBarrierError(
                    f"jax reports {len(by_proc)} processes, fleet "
                    f"context says {self.ctx.n_hosts}")
            devs = [by_proc[p] for p in sorted(by_proc)]
            self._mesh = Mesh(np.asarray(devs), (HOSTS_AXIS,))
        return self._mesh

    def _psum_program(self, key, n_leaves: int):
        prog = self._psum_cache.get(key)
        if prog is None:
            import jax
            import jax.numpy as jnp
            from jax.sharding import PartitionSpec as P

            from photon_ml_tpu.parallel.distributed_objective import (
                _shard_map,
            )

            mesh = self._hosts_mesh()

            def red(*xs):
                return tuple(jax.lax.psum(jnp.squeeze(x, 0), HOSTS_AXIS)
                             for x in xs)

            # photon-lint: disable=jit-in-function (memoized in self._psum_cache keyed on leaf shapes/dtypes; one compile per pytree signature)
            prog = jax.jit(_shard_map(
                red, mesh=mesh,
                in_specs=(P(HOSTS_AXIS),) * n_leaves,
                out_specs=(P(),) * n_leaves))
            self._psum_cache[key] = prog
        return prog

    def _psum_reduce(self, leaves: list):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._hosts_mesh()
        dev0 = mesh.devices.flat[self.ctx.host_id]
        placed = []
        shapes = []
        for lf in leaves:
            lf = jnp.asarray(lf)
            shapes.append((lf.shape, lf.dtype.name))
            local = jax.device_put(lf[None], dev0)
            placed.append(jax.make_array_from_single_device_arrays(
                (self.ctx.n_hosts, *lf.shape),
                NamedSharding(mesh, P(HOSTS_AXIS)), [local]))
        prog = self._psum_program(tuple(shapes), len(leaves))
        out = prog(*placed)
        jax.block_until_ready(out)
        # Replicated outputs → this process's local single-device view,
        # so downstream per-chunk programs stay process-local.
        return [r.addressable_data(0) for r in out]

    # -- tcp transport -------------------------------------------------------

    def _connect(self) -> None:
        host, port = self.ctx.coordinator.rsplit(":", 1)
        deadline = time.monotonic() + self.stall_timeout_s
        delay = 0.05
        while True:
            try:
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=self.stall_timeout_s)
                self._sock.setsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY, 1)
                self._fh = self._sock.makefile("rb")
                return
            except OSError as e:
                if time.monotonic() > deadline:
                    raise FleetBarrierError(
                        f"fleet coordinator {self.ctx.coordinator} "
                        f"unreachable: {e}") from e
                time.sleep(delay)
                delay = min(delay * 2, 1.0)

    def _tcp_reduce(self, leaves: list) -> list[np.ndarray]:
        if self._sock is None:
            self._connect()
        try:
            _send_msg(self._sock,
                      {"host": self.ctx.host_id, "seq": self.seq},
                      _encode_leaves([np.asarray(lf) for lf in leaves]))
            header, payload = _recv_msg(self._fh)
        except (OSError, EOFError) as e:
            raise FleetBarrierError(
                f"fleet reduce seq={self.seq} failed (coordinator "
                f"{self.ctx.coordinator}): {e}") from e
        if int(header.get("seq", -1)) != self.seq:
            raise FleetBarrierError(
                f"fleet reduce got seq {header.get('seq')} for "
                f"request seq {self.seq} (protocol skew)")
        return _decode_leaves(payload)

    # -- the public reduce ---------------------------------------------------

    def reduce(self, tree):
        """Fleet-wide sum of ``tree`` (any pytree of arrays/scalars).
        Single-host contexts return the tree unchanged (and count
        nothing) — callers never branch on fleet-ness."""
        if not self.ctx.is_fleet:
            return tree
        import jax

        faults.fire("fleet.reduce", seq=self.seq)
        leaves, treedef = jax.tree.flatten(tree)
        t0 = time.perf_counter()
        if self.ctx.transport == "psum":
            out = self._psum_reduce(leaves)
        else:
            out = self._tcp_reduce(leaves)
        dt = time.perf_counter() - t0
        self.seq += 1
        self.barrier_wait_s += dt
        telemetry.count("fleet.psums")
        telemetry.count("fleet.barrier_wait_s", dt)
        return jax.tree.unflatten(treedef, out)

    def close(self) -> None:
        if self._fh is not None:
            with contextlib.suppress(OSError):
                self._fh.close()
            self._fh = None
        if self._sock is not None:
            with contextlib.suppress(OSError):
                self._sock.close()
            self._sock = None


# ---------------------------------------------------------------------------
# Capability probe: can THIS jaxlib run real 2-process CPU collectives?
# ---------------------------------------------------------------------------

_PROBE_WORKER = r'''
import os, sys
os.environ.pop("JAX_PLATFORMS", None)
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
    num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
    process_id=int(os.environ["JAX_PROCESS_ID"]))
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map as shard_map
    kw = {"check_vma": False}
except ImportError:
    from jax.experimental.shard_map import shard_map
    kw = {"check_rep": False}
mesh = Mesh(np.asarray(jax.devices()[:jax.process_count()]), ("hosts",))
arr = jax.make_array_from_single_device_arrays(
    (jax.process_count(),), NamedSharding(mesh, P("hosts")),
    [jax.device_put(jnp.ones((1,)), jax.local_devices()[0])])
out = jax.jit(shard_map(lambda x: jax.lax.psum(x[0], "hosts"),
                        mesh=mesh, in_specs=P("hosts"),
                        out_specs=P(), **kw))(arr)
assert float(np.asarray(out.addressable_data(0))) == jax.process_count()
print("FLEET_PROBE_OK", flush=True)
'''

_PROBE_RESULT: bool | None = None


def probe_cpu_multiprocess_collectives(timeout_s: float = 120.0) -> bool:
    """Whether this environment can run REAL 2-process CPU collectives
    (jax.distributed + cross-process psum).  Spawns two tiny probe
    workers once per process and caches the verdict — the bench's
    transport selection and the 2-process tests' skip guard share this
    single probe instead of ad-hoc marker scans."""
    global _PROBE_RESULT
    if _PROBE_RESULT is not None:
        return _PROBE_RESULT
    import subprocess
    import sys
    import tempfile

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    with tempfile.TemporaryDirectory(prefix="fleet_probe_") as tmp:
        script = os.path.join(tmp, "probe_worker.py")
        with open(script, "w") as f:
            f.write(_PROBE_WORKER)
        procs = []
        for pid in range(2):
            env = dict(os.environ)  # photon-lint: disable=env-read (whole-environment copy for a subprocess, not a config knob read)
            env.pop("JAX_PLATFORMS", None)
            env.update({
                "JAX_COORDINATOR_ADDRESS": f"localhost:{port}",
                "JAX_NUM_PROCESSES": "2",
                "JAX_PROCESS_ID": str(pid),
                "XLA_FLAGS": "",
            })
            procs.append(subprocess.Popen(
                [sys.executable, script], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out or "")
    ok = (all(p.returncode == 0 for p in procs)
          and all("FLEET_PROBE_OK" in o for o in outs)
          and not any(MULTIPROC_UNSUPPORTED_MARKER in o for o in outs))
    if not ok:
        logger.info("fleet probe: 2-process CPU collectives unavailable "
                    "(rc=%s)", [p.returncode for p in procs])
    _PROBE_RESULT = ok
    return ok
