"""The distributed GLM objective: shard_map + psum over the device mesh.

Reference counterpart — THE north-star component (BASELINE.json):
``DistributedGLMLossFunction`` / ``DistributedObjectiveFunction``
(photon-api ``com.linkedin.photon.ml.function.glm`` [expected path, mount
unavailable — see SURVEY.md §2.2]).  The reference's pattern per L-BFGS
iteration is:

    broadcast(w) → per-partition aggregator fold → treeAggregate partials

Here the whole pattern is one ``shard_map``ped function: ``w`` arrives
replicated (broadcast ≡ no-op), each device runs the SAME fused
``GLMObjective`` pipeline on its resident batch shard, and partial
(value, gradient, HVP) sums meet in a ``lax.psum`` — an ICI allreduce on
real hardware, which is the latency-critical hop the reference pays
driver↔executor round-trips for.

Exactness: every data-side quantity the objective computes is a linear
reduction over examples (including normalization's model-space algebra,
which is linear in (X^T r, Σr)), so per-shard partials + psum equal the
single-device result to float-summation reordering.  Regularization is
example-independent and is added OUTSIDE the psum, once.

The optimizers consume this through the same ``(value_and_grad, hvp)``
callables as the local objective — distribution is invisible to them
(see ``optim.problem`` docstring).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.parallel.mesh import DATA_AXIS, batch_spec

Array = jax.Array

# jax >= 0.6 exposes shard_map at top level with the replication check
# spelled ``check_vma``; older builds ship it under jax.experimental
# with the same semantics as ``check_rep``.
try:
    from jax import shard_map as _shard_map_impl
    _CHECK_KW = "check_vma"
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _CHECK_KW = "check_rep"


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **{_CHECK_KW: check_vma})


def _vma(batch) -> bool:
    """Whether shard_map may validate varying-mesh-axes for this batch.

    Only the GRR layout must disable it: pallas_call (the GRR kernel)
    cannot annotate vma on its out_shape, which vma checking requires of
    everything inside a shard_map.  Every other layout (colmajor/ELL/
    dense) keeps the validation on, so replication bugs on those paths
    still fail loudly (advisor finding).
    """
    return getattr(batch, "grr", None) is None


@struct.dataclass
class DistributedGLMObjective:
    """GLMObjective over a batch sharded on the mesh's data axis.

    Same ``TwiceDiffFunction`` surface as ``GLMObjective`` —
    ``OptimizationProblem`` and the solvers cannot tell them apart.
    ``mesh`` is static; the inner objective's reg/norm arrays trace.
    """

    objective: GLMObjective
    mesh: Mesh = struct.field(pytree_node=False)

    @property
    def _data_obj(self) -> GLMObjective:
        """The inner objective stripped of regularization: reg must be
        added once, outside the psum, not per-shard."""
        return self.objective.replace(reg=RegularizationContext.none())

    # Each method shard_maps a closure running the LOCAL fused pipeline and
    # psumming the [dim]-or-scalar partials.  w is replicated (in_spec P()),
    # batch leaves are example-sharded (P('data')).

    def value(self, w: Array, batch: Batch) -> Array:
        def local(w, batch):
            return jax.lax.psum(self._data_obj.value(w, batch), DATA_AXIS)

        val = _shard_map(
            local, mesh=self.mesh, in_specs=(P(), batch_spec()),
            out_specs=P(), check_vma=_vma(batch),
        )(w, batch)
        return val + self.objective.reg.l2_value(w)

    def value_and_gradient(self, w: Array, batch: Batch) -> tuple[Array, Array]:
        def local(w, batch):
            v, g = self._data_obj.value_and_gradient(w, batch)
            return jax.lax.psum((v, g), DATA_AXIS)

        v, g = _shard_map(
            local, mesh=self.mesh, in_specs=(P(), batch_spec()),
            out_specs=(P(), P()), check_vma=_vma(batch),
        )(w, batch)
        reg = self.objective.reg
        return v + reg.l2_value(w), g + reg.l2_gradient(w)

    def gradient(self, w: Array, batch: Batch) -> Array:
        return self.value_and_gradient(w, batch)[1]

    def hessian_vector(self, w: Array, v: Array, batch: Batch) -> Array:
        def local(w, v, batch):
            return jax.lax.psum(
                self._data_obj.hessian_vector(w, v, batch), DATA_AXIS
            )

        hv = _shard_map(
            local, mesh=self.mesh, in_specs=(P(), P(), batch_spec()),
            out_specs=P(), check_vma=_vma(batch),
        )(w, v, batch)
        return hv + self.objective.reg.l2_hessian_vector(v)

    def hessian_diagonal(self, w: Array, batch: Batch) -> Array:
        def local(w, batch):
            return jax.lax.psum(
                self._data_obj.hessian_diagonal(w, batch), DATA_AXIS
            )

        hd = _shard_map(
            local, mesh=self.mesh, in_specs=(P(), batch_spec()),
            out_specs=P(), check_vma=_vma(batch),
        )(w, batch)
        return hd + self.objective.reg.l2_hessian_diagonal(w)

    # Scoring: no reduction — per-example outputs stay sharded in place.
    def predict_margins(self, w: Array, batch: Batch) -> Array:
        return _shard_map(
            lambda w, b: self._data_obj.predict_margins(w, b),
            mesh=self.mesh, in_specs=(P(), batch_spec()),
            out_specs=batch_spec(), check_vma=_vma(batch),
        )(w, batch)

    def x_dot(self, v: Array, batch: Batch) -> Array:
        """Raw X·v per example (coordinate scoring).  Must run under
        shard_map: a per-shard layout (GRR plan / colmajor) indexes only
        its device's rows, so the contraction is shard-local."""
        return _shard_map(
            lambda v, b: b.x_dot(v),
            mesh=self.mesh, in_specs=(P(), batch_spec()),
            out_specs=batch_spec(), check_vma=_vma(batch),
        )(v, batch)
