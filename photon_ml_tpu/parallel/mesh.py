"""Device-mesh utilities: the TPU replacement for the Spark cluster.

Reference counterpart: Spark's runtime substrate — executors, torrent
broadcast, hash partitioning (SURVEY.md §5.8 [reference mount
unavailable]).  The mapping:

- executor set            → ``jax.sharding.Mesh`` over TPU chips (ICI)
- ``broadcast(w)``        → replicated sharding ``P()`` (a no-op: every
                            chip holds w; XLA keeps it resident in HBM)
- ``partitionBy`` shuffle → a one-time host-side layout into batch shards
                            (``shard_batch``), then static placement
- ``treeAggregate``       → ``lax.psum`` over the mesh axis, riding ICI

Axis names: ``"data"`` for example-parallelism (fixed effect) and
``"entity"`` for entity-sharded random effects.  Multi-host scale-out
uses the same meshes over ``jax.distributed``-initialized device sets —
collectives then span DCN between slices with no code change.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.data.batch import Batch

DATA_AXIS = "data"
ENTITY_AXIS = "entity"


def _make_mesh(axis: str, n_devices: int | None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}"
            )
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def data_parallel_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all)."""
    return _make_mesh(DATA_AXIS, n_devices)


def entity_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh named for entity sharding (random effects): bucket
    blocks [E_b, cap, p] shard their leading (entity) axis here —
    the reference's parallelism strategy #2 (SURVEY §2.3)."""
    return _make_mesh(ENTITY_AXIS, n_devices)


def shard_entity_blocks(blocks: list, mesh: Mesh) -> list:
    """Pad each bucket's entity count to the mesh size and shard the
    leading axis on ENTITY_AXIS.  Padding entities carry zero
    data/mask, so their (vmapped) solves converge immediately and their
    coefficients are never gathered.  Per-device entity counts are
    exactly balanced by construction."""
    n_dev = mesh.devices.size
    out = []
    for b in blocks:
        e = b.shape[0]
        e_pad = padded_rows(max(e, 1), n_dev)
        if e_pad != e:
            b = jnp.pad(b, ((0, e_pad - e),) + ((0, 0),) * (b.ndim - 1))
        out.append(jax.device_put(b, NamedSharding(mesh, P(ENTITY_AXIS))))
    return out


def place_entity_chunk(arrays: dict, mesh: Mesh | None) -> dict:
    """Host entity-chunk leaves (name → [C, ...] ndarray) → device,
    entity-axis sharded when a mesh is given — the streamed random-
    effect coordinate's per-chunk placement (ISSUE 5).  ``C`` must be a
    multiple of the mesh size (the streamed builder rounds
    ``re_chunk_entities`` up), so every device holds an equal slice of
    the chunk's vmapped solve lanes; padding entities carry zero mask
    and converge immediately, exactly as in ``shard_entity_blocks``."""
    if mesh is None:
        return jax.device_put(arrays)
    n_dev = mesh.devices.size
    for k, a in arrays.items():
        if a.shape[0] % n_dev != 0:
            raise ValueError(
                f"entity chunk leaf '{k}' has {a.shape[0]} entities, "
                f"not divisible by mesh size {n_dev}; round the chunk "
                "size up to the mesh grid")
    sharding = NamedSharding(mesh, P(ENTITY_AXIS))
    return {k: jax.device_put(np.ascontiguousarray(a), sharding)
            for k, a in arrays.items()}


def batch_spec() -> P:
    """PartitionSpec sharding the example axis (every Batch leaf has the
    example dimension leading)."""
    return P(DATA_AXIS)


def replicated_spec() -> P:
    return P()


def shard_batch(batch: Batch, mesh: Mesh) -> Batch:
    """Place a host-built batch onto the mesh, example-axis sharded.

    The batch must already be padded so n divides the mesh size
    (``make_*_batch(pad_to=...)``); padding rows are masked, so shard
    imbalance costs nothing but the pad FLOPs.  This is the rebuild's
    "shuffle": it happens once, before training, not per-iteration.
    """
    from photon_ml_tpu.data.batch import SparseBatch

    if isinstance(batch, SparseBatch) and (
        batch.colmajor is not None or batch.grr is not None
    ):
        raise ValueError(
            "cannot shard a SparseBatch whose colmajor/GRR layout was "
            "built globally: its index arrays reference the whole "
            "batch, but each device shard sees only its local "
            "residuals.  Build with shard_sparse_batch(...) instead, "
            "which constructs per-shard layouts."
        )
    n = batch.n_padded
    n_dev = mesh.devices.size
    if n % n_dev != 0:
        raise ValueError(
            f"batch rows {n} not divisible by mesh size {n_dev}; "
            f"build the batch with pad_to=ceil(n/{n_dev})*{n_dev}"
        )
    sharding = NamedSharding(mesh, batch_spec())
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


def shard_sparse_batch(
    rows,
    dim: int,
    labels: np.ndarray,
    mesh: Mesh,
    weights: np.ndarray | None = None,
    offsets: np.ndarray | None = None,
    row_capacity: int | None = None,
    col_major: bool = True,
    col_capacity: int | None = None,
    layout: str | None = None,
    cache_dir: str | None = None,
):
    """Host-side ETL: split examples across the mesh, build one
    SparseBatch per device — each with the fast-contraction layout of
    *its own* rows — and assemble the global example-sharded arrays.

    This is the rebuild of the reference's one-time ``partitionBy``
    shuffle (SURVEY.md §5.8): after this call every optimizer iteration
    is pure compute + one ``psum``; no per-step data movement.  The
    per-shard layout is what keeps the gradient contraction scatter-free
    under data parallelism: each device computes ``Xᵀ_shard r_shard``
    locally and the partial [dim] gradients are combined by the same
    ``psum`` that already reduces the loss.

    ``layout`` selects the per-shard contraction layout:
    - ``"grr"`` — per-device compiled GRR plans run by the Mosaic
      kernel (``data.grr.build_sharded_grr_pairs``): the fast TPU path,
      now also the distributed path (BASELINE.json north star);
    - ``"colmajor"`` (default, = ``col_major=True``) — per-shard
      transposed-ELL copies;
    - ``"ell"`` (= ``col_major=False``) — plain ELL shards.

    ``cache_dir``: on-disk GRR plan cache (``photon_ml_tpu.cache``) for
    the per-shard plans — the one-time "shuffle" becomes one-time per
    DATASET, not per run.
    """
    from photon_ml_tpu.data.batch import make_sparse_batch
    from photon_ml_tpu.data.colmajor import build_colmajor, choose_capacity
    from photon_ml_tpu.data.grr import collect_spill_warnings
    from photon_ml_tpu.data.sparse_rows import SparseRows

    if layout is None:
        layout = "colmajor" if col_major else "ell"
    if layout not in ("grr", "colmajor", "ell"):
        raise ValueError(f"unknown layout {layout!r}")
    col_major = layout == "colmajor"

    n = len(labels)
    n_dev = mesh.devices.size
    per = padded_rows(n, n_dev) // n_dev
    if row_capacity is not None:
        k = row_capacity
    elif isinstance(rows, SparseRows):
        k = max(rows.max_nnz, 1)
    else:
        k = max((len(c) for c, _ in rows), default=1)

    weights = np.ones(n) if weights is None else np.asarray(weights)
    offsets = np.zeros(n) if offsets is None else np.asarray(offsets)

    shards = []
    # One spill-warning aggregation scope over the whole sharded build
    # (per-shard batch builds + the sharded plan set below): one
    # summary line per build, never one per shard sub-plan (ISSUE 4
    # satellite; MULTICHIP_r05's tail printed 15+).
    with collect_spill_warnings():
        for i in range(n_dev):
            lo, hi = i * per, min((i + 1) * per, n)
            shards.append(
                make_sparse_batch(
                    rows[lo:hi],
                    dim,
                    np.asarray(labels)[lo:hi],
                    weights=weights[lo:hi],
                    offsets=offsets[lo:hi],
                    row_capacity=k,
                    pad_to=per,
                )
            )

        if col_major:
            if col_capacity is None:
                if isinstance(rows, SparseRows):
                    all_cols = rows.cols
                else:
                    all_cols = (
                        np.concatenate([np.asarray(c) for c, _ in rows])
                        if len(rows) else np.zeros(0, np.int64)
                    )
                counts = np.bincount(all_cols, minlength=dim)
                col_capacity = choose_capacity(counts)
            # Per-shard virtual-row counts (cheap bincounts) → common
            # padded shape, so build_colmajor emits equal-shape shards
            # directly.
            shard_counts = [
                np.bincount(
                    np.asarray(b.col_ids).reshape(-1)[
                        np.asarray(b.values).reshape(-1) != 0
                    ],
                    minlength=dim,
                )
                for b in shards
            ]
            from photon_ml_tpu.ops.kernels import vrow_pad

            v_max = max(
                int((-(-c // col_capacity)).sum()) for c in shard_counts
            )
            v_max = vrow_pad(v_max, None)
            shards = [
                b.replace(colmajor=build_colmajor(
                    np.asarray(b.col_ids), np.asarray(b.values), dim,
                    capacity=col_capacity, pad_vrows_to=v_max,
                ))
                for b in shards
            ]
        elif layout == "grr":
            from photon_ml_tpu.data.grr import build_sharded_grr_pairs

            pairs = build_sharded_grr_pairs(
                [np.asarray(b.col_ids) for b in shards],
                [np.asarray(b.values) for b in shards],
                dim,
                cache_dir=cache_dir,
            )
            shards = [b.replace(grr=p) for b, p in zip(shards, pairs)]

    devices = list(mesh.devices.flat)
    sharding = NamedSharding(mesh, batch_spec())

    def assemble(*leaves):
        placed = [jax.device_put(lf, d) for lf, d in zip(leaves, devices)]
        gshape = (n_dev * leaves[0].shape[0],) + tuple(leaves[0].shape[1:])
        return jax.make_array_from_single_device_arrays(
            gshape, sharding, placed
        )

    return jax.tree.map(assemble, *shards)


def replicate(x, mesh: Mesh):
    """Replicate an array (the coefficient 'broadcast')."""
    return jax.device_put(x, NamedSharding(mesh, P()))


def padded_rows(n: int, n_devices: int) -> int:
    """Smallest multiple of n_devices ≥ n."""
    return ((n + n_devices - 1) // n_devices) * n_devices
