"""Device-mesh utilities: the TPU replacement for the Spark cluster.

Reference counterpart: Spark's runtime substrate — executors, torrent
broadcast, hash partitioning (SURVEY.md §5.8 [reference mount
unavailable]).  The mapping:

- executor set            → ``jax.sharding.Mesh`` over TPU chips (ICI)
- ``broadcast(w)``        → replicated sharding ``P()`` (a no-op: every
                            chip holds w; XLA keeps it resident in HBM)
- ``partitionBy`` shuffle → a one-time host-side layout into batch shards
                            (``shard_batch``), then static placement
- ``treeAggregate``       → ``lax.psum`` over the mesh axis, riding ICI

Axis names: ``"data"`` for example-parallelism (fixed effect) and
``"entity"`` for entity-sharded random effects.  Multi-host scale-out
uses the same meshes over ``jax.distributed``-initialized device sets —
collectives then span DCN between slices with no code change.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.data.batch import Batch

DATA_AXIS = "data"
ENTITY_AXIS = "entity"


def data_parallel_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}"
            )
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (DATA_AXIS,))


def batch_spec() -> P:
    """PartitionSpec sharding the example axis (every Batch leaf has the
    example dimension leading)."""
    return P(DATA_AXIS)


def replicated_spec() -> P:
    return P()


def shard_batch(batch: Batch, mesh: Mesh) -> Batch:
    """Place a host-built batch onto the mesh, example-axis sharded.

    The batch must already be padded so n divides the mesh size
    (``make_*_batch(pad_to=...)``); padding rows are masked, so shard
    imbalance costs nothing but the pad FLOPs.  This is the rebuild's
    "shuffle": it happens once, before training, not per-iteration.
    """
    n = batch.n_padded
    n_dev = mesh.devices.size
    if n % n_dev != 0:
        raise ValueError(
            f"batch rows {n} not divisible by mesh size {n_dev}; "
            f"build the batch with pad_to=ceil(n/{n_dev})*{n_dev}"
        )
    sharding = NamedSharding(mesh, batch_spec())
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


def replicate(x, mesh: Mesh):
    """Replicate an array (the coefficient 'broadcast')."""
    return jax.device_put(x, NamedSharding(mesh, P()))


def padded_rows(n: int, n_devices: int) -> int:
    """Smallest multiple of n_devices ≥ n."""
    return ((n + n_devices - 1) // n_devices) * n_devices
