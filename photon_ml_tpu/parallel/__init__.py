"""Distribution: device meshes, sharded batches, the distributed objective.

Reference: Spark runtime + ``DistributedGLMLossFunction`` (SURVEY.md
§2.2/§5.8 — expected paths, mount unavailable).
"""

from photon_ml_tpu.parallel.distributed_objective import DistributedGLMObjective
from photon_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    ENTITY_AXIS,
    batch_spec,
    data_parallel_mesh,
    padded_rows,
    replicate,
    shard_batch,
    shard_sparse_batch,
)

__all__ = [
    "DistributedGLMObjective",
    "DATA_AXIS",
    "ENTITY_AXIS",
    "batch_spec",
    "data_parallel_mesh",
    "padded_rows",
    "replicate",
    "shard_batch",
    "shard_sparse_batch",
]
