"""Coefficients: means (+ optional variances) of a linear model.

Reference counterpart: ``Coefficients``
(photon-api ``com.linkedin.photon.ml.model.Coefficients`` [expected path,
mount unavailable — see SURVEY.md]).  Breeze vectors become JAX arrays;
the container stays a pytree so it flows through jit/vmap/sharding (a
``RandomEffectModel`` holds a *batched* Coefficients with a leading
entity axis).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

Array = jax.Array


@struct.dataclass
class Coefficients:
    """means [.., dim] and optional variances [.., dim] (reference:
    variances from the Hessian diagonal, VarianceComputationType)."""

    means: Array
    variances: Array | None = None

    @staticmethod
    def zeros(dim: int, dtype=jnp.float32) -> "Coefficients":
        return Coefficients(means=jnp.zeros((dim,), dtype))

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def norm(self) -> Array:
        return jnp.linalg.norm(self.means, axis=-1)
