"""GAME model containers: fixed-effect + random-effect + composite.

Reference counterparts: ``GameModel``, ``FixedEffectModel``,
``RandomEffectModel`` (photon-api ``com.linkedin.photon.ml.model``
[expected paths, mount unavailable — see SURVEY.md §2.5]).

Mapping to TPU-resident state:

- ``FixedEffectModel``: broadcast Breeze vector → replicated [dim] array.
- ``RandomEffectModel``: ``RDD[(REId, Coefficients)]`` → per-bucket
  dense coefficient blocks [E_b, d_re] (the entity axis is shardable
  over the mesh's entity axis), plus host-side id metadata from the
  ``EntityGrouping``.
- ``GameModel``: ordered coordinate → model map (order = update order).
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.models.coefficients import Coefficients

if TYPE_CHECKING:  # import would cycle through the game package at runtime
    from photon_ml_tpu.game.dataset import EntityGrouping
    from photon_ml_tpu.game.projector import SubspaceProjection

Array = jax.Array


@dataclasses.dataclass
class FixedEffectModel:
    """Global coefficients for one feature shard.

    ``intercept``: the last coefficient is an intercept the estimator
    appended — scorers append a 1s column to raw features to match.
    """

    coefficients: Coefficients
    feature_shard: str = "global"
    intercept: bool = False

    @property
    def dim(self) -> int:
        return self.coefficients.dim


@dataclasses.dataclass
class RandomEffectModel:
    """Per-entity coefficients, stored as size-bucketed blocks.

    ``coefficient_blocks[b]`` is [E_b, p_b] for bucket b of the
    grouping; ``grouping`` maps original entity ids to (bucket, slot).
    When the coordinate used a subspace projection, ``projection``
    carries each entity's local→global feature map and p_b varies per
    bucket.  Entities never seen in training score zero (the reference's
    behavior for missing REIds: only the other coordinates apply).
    """

    coefficient_blocks: list[Array]
    grouping: EntityGrouping
    feature_shard: str
    variance_blocks: list[Array] | None = None
    projection: "SubspaceProjection | None" = None
    # Which GameDataset.entity_ids column tags examples for this model
    # (reference REId key, e.g. "userId"); None → the coordinate name.
    entity_key: str | None = None

    @property
    def n_entities(self) -> int:
        return self.grouping.n_total_entities

    def coefficients_for(self, entity_id) -> np.ndarray | None:
        """Host-side per-entity lookup, in the entity's LOCAL space
        (model inspection / serialization)."""
        idx = self.grouping.entity_index().get(int(entity_id))
        if idx is None:
            return None
        b, s = idx
        return np.asarray(self.coefficient_blocks[b][s])

    def global_coefficients_for(self, entity_id) -> np.ndarray | None:
        """Per-entity coefficients scattered into the global feature
        space (projection inverted; identity when unprojected)."""
        idx = self.grouping.entity_index().get(int(entity_id))
        if idx is None:
            return None
        b, s = idx
        local = np.asarray(self.coefficient_blocks[b][s])
        if self.projection is None:
            return local
        fids = self.projection.feature_ids[b][s]
        out = np.zeros(self.projection.global_dim, local.dtype)
        valid = fids >= 0
        out[fids[valid]] = local[valid]
        return out

    def all_coefficients(self) -> Array:
        """[E_total, d_re] in global entity order (unique-id sorted) —
        the gatherable form scoring uses.  Unprojected models only (all
        buckets share one width)."""
        if self.projection is not None:
            raise ValueError(
                "all_coefficients is width-uniform; use "
                "global_coefficients_for on projected models"
            )
        dim = self.coefficient_blocks[0].shape[-1]
        out = jnp.zeros((self.n_entities, dim),
                        self.coefficient_blocks[0].dtype)
        for b, blk in enumerate(self.coefficient_blocks):
            global_idx = np.where(self.grouping.entity_bucket == b)[0]
            # Blocks may carry trailing padding entities (entity-mesh
            # sharding pads E_b to the device count); real entities
            # occupy the leading slots.
            out = out.at[jnp.asarray(global_idx)].set(blk[: len(global_idx)])
        return out


@dataclasses.dataclass
class GameModel:
    """Ordered coordinate name → component model (reference ``GameModel``)."""

    models: dict  # name → FixedEffectModel | RandomEffectModel

    def __getitem__(self, name: str):
        return self.models[name]

    def __contains__(self, name: str) -> bool:
        return name in self.models

    @property
    def coordinate_names(self) -> list[str]:
        return list(self.models.keys())
