"""Model containers: Coefficients, GLMs, GAME models.

Reference: photon-api ``com.linkedin.photon.ml.model`` /
``...supervised.model`` (SURVEY.md §2.5 — expected paths, mount
unavailable).
"""

from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import GeneralizedLinearModel, TaskType

__all__ = [
    "Coefficients",
    "FixedEffectModel",
    "GameModel",
    "RandomEffectModel",
    "GeneralizedLinearModel",
    "TaskType",
]
