"""Generalized linear models: per-task scoring on top of Coefficients.

Reference counterparts: ``GeneralizedLinearModel`` and its per-task
subclasses ``LogisticRegressionModel`` / ``LinearRegressionModel`` /
``PoissonRegressionModel`` / ``SmoothedHingeLossLinearSVMModel``
(photon-api ``com.linkedin.photon.ml.supervised.model`` [expected paths,
mount unavailable — see SURVEY.md]).

The Scala subclass-per-task hierarchy collapses into one pytree
parameterized by ``TaskType``: the task selects the pointwise loss (and
thus the mean/link function), which is exactly what distinguished the
subclasses.  ``compute_score`` is the margin (dot product); mean-space
prediction applies the link — matching the reference's score vs mean
split used by scoring and evaluators.
"""

from __future__ import annotations

import enum

import jax
from flax import struct

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.ops.losses import PointwiseLoss, get_loss

Array = jax.Array


class TaskType(str, enum.Enum):
    """Reference ``TaskType`` enum."""

    LOGISTIC_REGRESSION = "LOGISTIC_REGRESSION"
    LINEAR_REGRESSION = "LINEAR_REGRESSION"
    POISSON_REGRESSION = "POISSON_REGRESSION"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "SMOOTHED_HINGE_LOSS_LINEAR_SVM"

    @property
    def loss(self) -> PointwiseLoss:
        return get_loss(self.value)


@struct.dataclass
class GeneralizedLinearModel:
    """A trained GLM: coefficients + task type (static)."""

    coefficients: Coefficients
    task: TaskType = struct.field(pytree_node=False)

    def compute_score(self, batch: Batch) -> Array:
        """Margins x·w + offset (reference ``computeScore``): the raw
        score coordinate descent and loss evaluators consume."""
        return batch.margins(self.coefficients.means)

    def compute_mean(self, batch: Batch) -> Array:
        """Mean-space prediction: link(margin) — sigmoid / identity / exp."""
        return self.task.loss.mean(self.compute_score(batch))
