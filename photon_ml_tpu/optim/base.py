"""Optimizer substrate: configs, results, convergence, state tracking.

Reference counterparts: ``Optimizer`` / ``OptimizerConfig`` /
``OptimizerState`` / ``OptimizationStatesTracker``
(photon-lib ``com.linkedin.photon.ml.optimization`` [expected paths, mount
unavailable — see SURVEY.md]).

The reference's ``Optimizer`` is a JVM iteration loop with mutable history;
here every solver is a **pure function** ``(objective fns, w0, config) →
OptimizationResult`` whose loop is a ``lax.while_loop``.  That makes one
solver serve all three execution contexts the framework needs:

- **jit** for the fixed-effect solve (one big problem),
- **vmap** for random-effect solves (thousands of small problems at once —
  the reference's per-entity Scala loops become one batched program), and
- **shard_map** transparently, because the objective callables close over
  sharded batches and psum internally.

vmap semantics: ``lax.while_loop`` under vmap iterates until *every* lane's
predicate is false, so each solver carries a ``converged`` flag and guards
its update with ``jnp.where`` — converged lanes coast unchanged while
stragglers finish (SURVEY.md §7 "masked while_loop semantics").

Convergence mirrors the reference's two criteria: relative gradient-norm
tolerance (``‖g‖ ≤ tol·max(1,‖g₀‖)``) and relative loss-change tolerance.
``OptimizationStatesTracker`` history is kept as fixed-shape [max_iters+1]
arrays written with ``.at[i].set`` — static shapes, jit/vmap friendly.
"""

from __future__ import annotations

import enum
from typing import Callable

import jax
import jax.numpy as jnp
from flax import struct

Array = jax.Array

# Objective callables: value_and_grad(w) -> (f, g);  hvp(w, v) -> Hv.
ValueAndGrad = Callable[[Array], tuple[Array, Array]]
Hvp = Callable[[Array, Array], Array]


class OptimizerType(str, enum.Enum):
    """Reference ``OptimizerType`` enum (LBFGS / TRON; OWL-QN is selected
    automatically when L1 regularization is present, as in the reference)."""

    LBFGS = "LBFGS"
    TRON = "TRON"


@struct.dataclass
class OptimizerConfig:
    """Solver hyperparameters (reference ``OptimizerConfig``).

    All fields are static Python numbers so a config change retriggers
    compilation (shapes depend on ``max_iters`` / ``lbfgs_memory``).
    """

    max_iters: int = struct.field(pytree_node=False, default=100)
    # ‖g‖₂ ≤ tolerance · max(1, ‖g₀‖₂)  (Breeze/reference-style relative
    # gradient convergence).
    tolerance: float = struct.field(pytree_node=False, default=1e-7)
    # |f_k − f_{k−1}| ≤ rel_tolerance · max(1, |f_k|).
    rel_tolerance: float = struct.field(pytree_node=False, default=0.0)
    # L-BFGS two-loop memory (Breeze default m=10).
    lbfgs_memory: int = struct.field(pytree_node=False, default=10)
    # Backtracking line search: shrink factor / Armijo c1 / max halvings.
    ls_shrink: float = struct.field(pytree_node=False, default=0.5)
    ls_c1: float = struct.field(pytree_node=False, default=1e-4)
    ls_max_steps: int = struct.field(pytree_node=False, default=30)
    # TRON inner CG: max iterations and forcing tolerance ‖r‖ ≤ cg_tol·‖g‖.
    cg_max_iters: int = struct.field(pytree_node=False, default=50)
    cg_tolerance: float = struct.field(pytree_node=False, default=0.1)
    # Record per-iteration (value, grad_norm) history.
    track_states: bool = struct.field(pytree_node=False, default=True)


@struct.dataclass
class StatesTracker:
    """Fixed-shape per-iteration history (reference
    ``OptimizationStatesTracker``): ``values[i]`` / ``grad_norms[i]`` hold
    the state after iteration i (slot 0 = initial point); ``count`` is the
    number of valid slots.  Unwritten slots are NaN.

    ``step_sizes[i]`` / ``ls_trials[i]`` (ISSUE 8 convergence traces)
    record the accepted line-search step and the number of objective
    trials iteration i paid (TRON records the step NORM and the inner-CG
    iteration count instead — the analogous per-iteration cost).  Both
    planes are optional pytree leaves: a ``None`` stays ``None`` through
    every ``record``/``tree.map`` so pre-existing direct constructions
    (the swept streaming solver assembles trackers by hand) keep their
    treedef."""

    values: Array      # [max_iters + 1]
    grad_norms: Array  # [max_iters + 1]
    count: Array       # int32 scalar
    step_sizes: Array | None = None  # [max_iters + 1] accepted α (TRON: ‖p‖)
    ls_trials: Array | None = None   # [max_iters + 1] trials (TRON: CG iters)

    @staticmethod
    def create(max_iters: int) -> "StatesTracker":
        nan = jnp.full((max_iters + 1,), jnp.nan, jnp.float32)
        return StatesTracker(values=nan, grad_norms=nan,
                             count=jnp.asarray(0, jnp.int32),
                             step_sizes=nan, ls_trials=nan)

    def record(self, i: Array, value: Array, grad_norm: Array,
               step_size: Array | None = None,
               ls_trials: Array | None = None) -> "StatesTracker":
        def _set(plane, x):
            if plane is None:
                return None
            if x is None:
                return plane
            return plane.at[i].set(
                jnp.asarray(x, jnp.float32).astype(jnp.float32))
        return StatesTracker(
            values=self.values.at[i].set(value.astype(jnp.float32)),
            grad_norms=self.grad_norms.at[i].set(grad_norm.astype(jnp.float32)),
            count=jnp.maximum(self.count, i.astype(jnp.int32) + 1),
            step_sizes=_set(self.step_sizes, step_size),
            ls_trials=_set(self.ls_trials, ls_trials),
        )


@struct.dataclass
class OptimizationResult:
    """What a solve returns — the reference's final ``OptimizerState`` plus
    its tracker, as one pytree (vmap gives these a leading batch dim)."""

    w: Array            # [dim] solution
    value: Array        # scalar final objective value
    grad_norm: Array    # scalar final ‖g‖₂
    iterations: Array   # int32 iterations executed
    converged: Array    # bool: tolerance met (vs iteration-capped)
    tracker: StatesTracker


def grad_converged(g_norm: Array, g0_norm: Array, tolerance: float) -> Array:
    return g_norm <= tolerance * jnp.maximum(1.0, g0_norm)


def loss_converged(f_new: Array, f_old: Array, rel_tolerance: float) -> Array:
    if rel_tolerance <= 0.0:
        return jnp.asarray(False)
    return jnp.abs(f_new - f_old) <= rel_tolerance * jnp.maximum(
        jnp.abs(f_new), 1.0
    )
