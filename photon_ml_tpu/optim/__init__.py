"""Optimizers: L-BFGS / OWL-QN / TRON as jittable+vmappable JAX solvers.

Reference: photon-lib ``com.linkedin.photon.ml.optimization`` (SURVEY.md
§2.1 — expected paths, mount unavailable).
"""

from photon_ml_tpu.optim.base import (
    OptimizationResult,
    OptimizerConfig,
    OptimizerType,
    StatesTracker,
)
from photon_ml_tpu.optim.lbfgs import (
    lbfgs_solve,
    lbfgs_solve_swept,
    owlqn_solve,
    owlqn_solve_swept,
)
from photon_ml_tpu.optim.problem import OptimizationProblem, solve_batched
from photon_ml_tpu.optim.streaming import (
    ChunkedGLMObjective,
    streaming_lbfgs_solve,
    streaming_lbfgs_solve_swept,
)
from photon_ml_tpu.optim.tron import tron_solve

__all__ = [
    "OptimizationResult",
    "OptimizerConfig",
    "OptimizerType",
    "StatesTracker",
    "lbfgs_solve",
    "lbfgs_solve_swept",
    "owlqn_solve",
    "owlqn_solve_swept",
    "tron_solve",
    "OptimizationProblem",
    "solve_batched",
    "ChunkedGLMObjective",
    "streaming_lbfgs_solve",
    "streaming_lbfgs_solve_swept",
]
