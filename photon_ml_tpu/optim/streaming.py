"""Streaming (chunk-accumulated) objective + host-driven L-BFGS/OWL-QN.

Reference counterpart: the per-iteration Spark round —
``broadcast(w) → per-partition aggregator fold → treeAggregate`` —
whose partitions never co-reside in memory (SURVEY.md §2.2, §5.8
[expected structure, mount unavailable]).  Here the "partitions" are
the congruent device-program chunks of ``data.chunked_batch``: each
objective evaluation replays ONE compiled per-chunk program K times,
double-buffering the host→device transfer of chunk i+1 under chunk i's
compute, and accumulates (value, gradient, HVP, Hessian-diagonal)
partials on device.  Exact: every data-side quantity is a linear
reduction over examples; regularization and the Gaussian prior are
example-independent and added once, outside the chunk loop.

The resident solvers (``optim.lbfgs`` / ``optim.tron``) run their whole
optimize loop as one device program — impossible when each objective
evaluation needs host-side chunk swaps.  ``streaming_lbfgs_solve`` is
the host-driven mirror of ``lbfgs_solve``: the same two-loop recursion,
Armijo backtracking (with the OWL-QN orthant projection and
pseudo-gradient), curvature-guarded (s, y) updates, and convergence
tests, but with a Python outer loop calling a host-level
``value_and_grad``.  Per-iteration [dim]-vector math dispatches eagerly
(a handful of cached device ops — microseconds of compute); the data
passes dominate, exactly as in the reference's driver loop.

λ-sweep amortization: the data passes are also λ-INDEPENDENT (reg is
added outside the chunk loop), so ``value_and_gradient_swept`` feeds L
stacked coefficient lanes from ONE double-buffered chunk sweep and
``streaming_lbfgs_solve_swept`` runs the whole regularization grid as
one masked-lane solve — data passes per solver iteration drop from L
to ~1 (see ``ops.objective`` swept surface).
"""

from __future__ import annotations

import hashlib
import logging
import queue
import threading
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import telemetry
from photon_ml_tpu.reliability import checkpoint as _ckpt
from photon_ml_tpu.reliability import faults as _faults
from photon_ml_tpu.telemetry import convergence as _conv
from photon_ml_tpu.telemetry import device as _device
from photon_ml_tpu.telemetry import monitor as _mon
from photon_ml_tpu.data.chunked_batch import ChunkedBatch
from photon_ml_tpu.ops.objective import (
    GLMObjective,
    sweep_value,
    sweep_value_and_gradient,
)
from photon_ml_tpu.ops.regularization import (
    RegularizationContext,
    SweptRegularization,
)
from photon_ml_tpu.optim.base import (
    OptimizationResult,
    OptimizerConfig,
    StatesTracker,
    grad_converged,
    loss_converged,
)
from photon_ml_tpu.optim.lbfgs import _pseudo_gradient
from photon_ml_tpu.optim.tron import (
    _DELTA_MIN,
    _ETA0,
    _SIGMA1,
    _SIGMA3,
    _boundary_tau,
)

logger = logging.getLogger(__name__)

Array = jax.Array

_CURVATURE_EPS = 1e-10

# Consumer-side stall deadline (seconds) for the prefetch pipeline: a
# wedged disk (or a producer thread killed without a sentinel) turns
# into ONE actionable error after this long, never an eternal
# ``q.get`` (ISSUE 9).  Generous by design — a healthy chunk read is
# milliseconds, so ten minutes means the disk tier is truly gone.
DEFAULT_STALL_TIMEOUT_S = 600.0


def _fleet_reducer():
    """The active fleet's per-chunk allreduce, or None (single host).
    Lazy import: ``parallel`` pulls mesh machinery this module only
    needs when a mesh (or fleet) is actually in play."""
    from photon_ml_tpu.parallel import fleet

    return fleet.reducer()


def _place_chunk(chunk, mesh):
    """Host chunk → device: plain device_put, or example-sharded
    assembly of the per-device sub-batches onto the mesh."""
    if mesh is None:
        return jax.device_put(chunk)
    from jax.sharding import NamedSharding

    from photon_ml_tpu.parallel.mesh import batch_spec

    devices = list(mesh.devices.flat)
    sharding = NamedSharding(mesh, batch_spec())

    def asm(*leaves):
        placed = [jax.device_put(lf, d) for lf, d in zip(leaves, devices)]
        gshape = ((len(devices) * leaves[0].shape[0],)
                  + tuple(leaves[0].shape[1:]))
        return jax.make_array_from_single_device_arrays(
            gshape, sharding, placed)

    return jax.tree.map(asm, *chunk)


class ChunkPrefetcher:
    """Background disk → host → device pipeline stage.

    One thread walks the sweep's chunk order ahead of the consumer:
    ``load(i)`` pulls the host pieces (the chunk store's disk read /
    LRU window), ``place`` starts the ASYNC host→device transfer, and
    the (host, device) pair lands in a bounded queue of depth
    ``depth`` — so chunk i's device compute overlaps chunk
    i+1..i+depth's disk reads AND transfers, the third pipeline level
    in front of the classic device double-buffer.  The host reference
    rides in the queue item until the consumer takes it, so the LRU
    window can never free arrays out from under an in-flight copy.

    Generic over the chunk source since ISSUE 4 (``load``/``place``
    callables + optional ``store`` for reader accounting): the training
    objective feeds it ``ChunkedBatch.chunk`` + the mesh-aware
    placement, the streaming scorer its score-chunk store reader +
    plain ``device_put``.

    Determinism: the queue preserves the thread's (sweep) order and
    ``next(expect)`` asserts it — the chunk visit order the parity and
    ``sweeps``-odometer contracts rely on cannot be reordered by the
    pipeline.  The thread registers as a store reader so
    ``ChunkStore.assert_quiesced`` can prove no use-after-evict.
    """

    _SENTINEL = object()

    def __init__(self, load, place, depth: int, store=None,
                 stall_timeout_s: float | None = None):
        self._load = load
        self._place = place
        self._store = store
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stall_timeout_s = (DEFAULT_STALL_TIMEOUT_S
                                if stall_timeout_s is None
                                else float(stall_timeout_s))

    def start(self, order) -> None:
        if self._store is not None:
            self._store.begin_read()
        self._thread = threading.Thread(
            target=self._run, args=(list(order),), daemon=True,
            name="photon-chunk-prefetch")
        self._thread.start()

    def _put(self, item) -> bool:
        t = telemetry.active()
        if t is None:
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.05)
                    return True
                except queue.Full:  # photon-lint: disable=swallowed-exception (bounded poll; the loop re-checks the stop flag each lap)
                    continue
            return False
        # Telemetry-on path: account full-queue stall time (a full
        # queue means the producer is AHEAD — informational, not a
        # problem) and emit liveness heartbeats while blocked, so a
        # hung consumer shows as a stalled-but-alive producer.
        start = time.perf_counter()
        beat = start
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                stalled = time.perf_counter() - start
                if stalled > 0.01:   # an actual full-queue wait
                    t.count("prefetch.producer_stall_s", stalled)
                return True
            except queue.Full:
                now = time.perf_counter()
                if now - beat >= t.heartbeat_s:
                    t.heartbeat("prefetch-producer", state="queue_full",
                                stalled_s=round(now - start, 3))
                    beat = now
        return False

    def _run(self, order) -> None:
        t = telemetry.active()
        last_beat = time.perf_counter()
        try:
            for i in order:
                if self._stop.is_set():
                    return
                with telemetry.span("prefetch_load", cat="prefetch",
                                    chunk=i):
                    _faults.fire("prefetch.load", chunk=i)
                    host = self._load(i)             # disk -> host
                with telemetry.span("prefetch_place", cat="prefetch",
                                    chunk=i):
                    _faults.fire("prefetch.place", chunk=i)
                    buf = self._place(host)          # host -> device
                if t is not None:
                    t.count("prefetch.chunks_produced")
                    t.gauge("prefetch.queue_depth", self._q.qsize())
                    now = time.perf_counter()
                    if now - last_beat >= t.heartbeat_s:
                        t.heartbeat("prefetch-producer", chunk=i)
                        last_beat = now
                if not self._put((i, host, buf)):
                    return
        except BaseException as e:
            # Death event FIRST (hung-run forensics: the JSONL shows
            # which stage died even if the consumer never drains the
            # sentinel), then the error RIDES THE QUEUE to the
            # consumer: an attribute would be an unlocked cross-thread
            # write (photon-lint unlocked-shared-write); the queue's
            # internal lock gives the happens-before edge for free.
            telemetry.thread_exception("prefetch-producer", e)
            logger.warning("chunk prefetch thread died: %r", e)
            self._put((self._SENTINEL, e, None))
        finally:
            if self._store is not None:
                self._store.end_read()

    def next(self, expect: int):
        """The next placed chunk; raises the producer's error, and
        asserts the deterministic order.  The wait is a BOUNDED poll,
        never an eternal ``q.get`` (ISSUE 9): a producer thread that
        died without delivering (killed, lost without a sentinel)
        raises one actionable error immediately, and a wedged disk
        read trips ``stall_timeout_s`` into an actionable timeout.
        With telemetry active the blocking wait is accounted
        (``prefetch.consumer_wait_s`` — the numerator of the
        overlap-efficiency derivation) and heartbeats flow while
        starved, so a hung producer shows as a waiting-but-alive
        consumer."""
        t = telemetry.active()
        start = time.perf_counter()
        beat = start
        while True:
            try:
                i, host, buf = self._q.get(timeout=0.05)
                break
            except queue.Empty:
                now = time.perf_counter()
                thread = self._thread
                if ((thread is None or not thread.is_alive())
                        and self._q.empty()):
                    telemetry.count("reliability.actionable_errors")
                    raise RuntimeError(
                        f"prefetch producer died without delivering "
                        f"chunk {expect} (thread gone, queue empty, no "
                        "in-band error); see the run log's "
                        "thread_exception / heartbeat events for the "
                        "stage that stopped")
                if now - start > self.stall_timeout_s:
                    telemetry.count("prefetch.stall_timeouts")
                    telemetry.count("reliability.actionable_errors")
                    raise TimeoutError(
                        f"prefetch pipeline stalled {now - start:.1f}s "
                        f"waiting for chunk {expect} (stall_timeout_s="
                        f"{self.stall_timeout_s:g}): the disk/staging "
                        "tier is wedged — check spill-dir health; the "
                        "producer thread is still alive, so its "
                        "heartbeat events name the stuck stage")
                if t is not None and now - beat >= t.heartbeat_s:
                    t.heartbeat("prefetch-consumer",
                                state="queue_empty", expect=expect,
                                waiting_s=round(now - start, 3))
                    beat = now
        if t is not None:
            t.count("prefetch.consumer_wait_s",
                    time.perf_counter() - start)
            t.count("prefetch.chunks_consumed")
        if i is self._SENTINEL:
            raise host   # the producer's exception, delivered in-band
        if i != expect:
            raise AssertionError(
                f"prefetch order violated: got chunk {i}, "
                f"expected {expect}")
        del host   # consumer now owns the device buffer
        return buf

    def close(self, join_timeout_s: float = 10.0) -> None:
        """Quiesce: stop the producer, drain, join — with a DEADLINE.
        A producer wedged inside a blocking ``load`` (hung disk/NFS)
        cannot observe the stop flag, and close() runs while the stall
        TimeoutError unwinds — an unbounded join would re-hang the run
        the deadline just turned into an error (review finding).  The
        thread is a daemon, so abandoning it is safe.  Idempotent."""
        t = self._thread
        if t is None:
            return
        self._stop.set()
        deadline = time.monotonic() + join_timeout_s
        while t.is_alive() and time.monotonic() < deadline:
            try:
                self._q.get_nowait()   # unblock a full-queue producer
            except queue.Empty:
                t.join(timeout=0.05)
        if t.is_alive():
            logger.warning(
                "prefetch thread did not exit within %.1fs (blocked "
                "in a chunk load?); abandoning daemon thread",
                join_timeout_s)
            telemetry.count("prefetch.abandoned_threads")
        self._thread = None


# Historical name (round 8); the class went public when the streaming
# scorer started reusing it.
_ChunkPrefetcher = ChunkPrefetcher


def prefetch_stream(load, place, order, depth: int, store=None):
    """Yield ``(i, placed)`` for every ``i`` in ``order`` through the
    three-tier prefetch pipeline (disk read → host staging → async
    device_put, ``depth`` chunks ahead), or synchronously when
    ``depth <= 0`` — the one entry point for consumers that drive a
    chunk sweep themselves instead of owning a ``ChunkedGLMObjective``
    (the streamed random-effect coordinate's per-bucket solves, ISSUE
    5).  The prefetcher is always closed (and the store reader
    released) when the generator exits, including on error or early
    ``break`` — quiescence is structural, not a caller obligation."""
    order = list(order)
    if depth <= 0:
        if store is not None:
            store.begin_read()
        try:
            for i in order:
                yield i, place(load(i))
        finally:
            if store is not None:
                store.end_read()
        return
    pf = ChunkPrefetcher(load, place, depth, store=store)
    pf.start(order)
    try:
        for i in order:
            yield i, pf.next(i)
    finally:
        pf.close()


# ---------------------------------------------------------------------------
# Per-chunk device programs, jitted at MODULE level so every
# ChunkedGLMObjective instance shares one compile cache: λ-grid /
# tuning points build a fresh objective per point, and per-instance jit
# wrappers would recompile the identical program once per point (the
# objective rides as a pytree ARGUMENT — its reg/norm arrays, λ
# included, are traced leaves, never HLO constants).
# ---------------------------------------------------------------------------

_jit_vg = jax.jit(lambda o, w, b: o.value_and_gradient(w, b))
_jit_val = jax.jit(lambda o, w, b: o.value(w, b))
_jit_hvp = jax.jit(lambda o, w, v, b: o.hessian_vector(w, v, b))
_jit_hd = jax.jit(lambda o, w, b: o.hessian_diagonal(w, b))
_jit_margins = jax.jit(lambda o, w, b: o.predict_margins(w, b))
_jit_xdot_obj = jax.jit(lambda o, w, b: o.x_dot(w, b))
_jit_xdot = jax.jit(lambda w, b: b.x_dot(w))


@partial(jax.jit, static_argnums=(3,))
def _jit_vg_swept(o, W, b, lane_map):
    return sweep_value_and_gradient(o, W, b, use_map=lane_map)


@partial(jax.jit, static_argnums=(3,))
def _jit_val_swept(o, W, b, lane_map):
    return sweep_value(o, W, b, use_map=lane_map)


@jax.jit
def _swept_direction(PG, W, S_buf, Y_buf, Rho, head, count, l1):
    """Per-lane two-loop recursion + safeguards as ONE device program
    (the host-driven swept solver dispatches this once per iteration;
    eagerly it would be ~2·m·L fancy-indexed ops per step).

    Returns (D [L, d], Xi [L, d] | None): the per-lane descent
    directions and, when ``l1`` is given (OWL-QN), the search orthants.
    """
    m, L, d = S_buf.shape
    lanes = jnp.arange(L)
    q = PG
    alphas = []
    for j in range(m):
        idx = (head - 1 - j) % m
        valid = j < count
        s_j, y_j = S_buf[idx, lanes], Y_buf[idx, lanes]
        a = Rho[idx, lanes] * jnp.sum(s_j * q, axis=-1)
        a = jnp.where(valid, a, 0.0)
        q = q - a[:, None] * y_j
        alphas.append((a, idx, valid))
    newest = (head - 1) % m
    y_new = Y_buf[newest, lanes]
    gamma = jnp.where(
        count > 0,
        1.0 / jnp.maximum(
            Rho[newest, lanes] * jnp.sum(y_new * y_new, axis=-1),
            _CURVATURE_EPS),
        1.0,
    )
    r = gamma[:, None] * q
    for a, idx, valid in reversed(alphas):
        s_j, y_j = S_buf[idx, lanes], Y_buf[idx, lanes]
        beta = Rho[idx, lanes] * jnp.sum(y_j * r, axis=-1)
        upd = s_j * (a - beta)[:, None]
        r = r + jnp.where(valid[:, None], upd, 0.0)
    D = -r
    Xi = None
    if l1 is not None:
        D = jnp.where(D * -PG > 0.0, D, 0.0)
        Xi = jnp.where(W != 0.0, jnp.sign(W), jnp.sign(-PG))
    bad = jnp.sum(PG * D, axis=-1) >= 0.0
    D = jnp.where(bad[:, None], -PG, D)
    return D, Xi


@jax.jit
def _swept_push(S_buf, Y_buf, Rho, head, count, s, y, good):
    """Masked per-lane circular-buffer push of curvature pairs — one
    device program per iteration."""
    L = head.shape[0]
    lanes = jnp.arange(L)
    sy = jnp.sum(s * y, axis=-1)
    S_buf = S_buf.at[head, lanes].set(
        jnp.where(good[:, None], s, S_buf[head, lanes]))
    Y_buf = Y_buf.at[head, lanes].set(
        jnp.where(good[:, None], y, Y_buf[head, lanes]))
    Rho = Rho.at[head, lanes].set(
        jnp.where(good, 1.0 / jnp.maximum(sy, _CURVATURE_EPS),
                  Rho[head, lanes]))
    m = S_buf.shape[0]
    head = jnp.where(good, (head + 1) % m, head)
    count = jnp.where(good, jnp.minimum(count + 1, m), count)
    return S_buf, Y_buf, Rho, head, count


class ChunkedGLMObjective:
    """``GLMObjective`` surface over a ``ChunkedBatch``.

    Methods take only ``w`` (the batch is owned): the streaming solver
    cannot donate or close over a resident batch, so the usual
    ``(w, batch)`` calling convention has no meaning here.

    ``max_resident`` chunks stay live on device across evaluations
    (datasets that fit entirely set it ≥ n_chunks and pay the transfer
    once — the resident and streaming regimes are one code path);
    beyond it, chunks are re-placed each pass, double-buffered.

    When the batch carries a spill store (``data.chunk_store`` — the
    disk tier), each sweep runs a background ``_ChunkPrefetcher``
    instead: disk read → host staging → async device_put of chunks
    i+1..i+``prefetch_depth`` overlap chunk i's device compute, and the
    chunk visit order (hence float-summation order and the ``sweeps``
    odometer) is exactly the resident path's.

    ``sweeps`` counts full chunk sweeps since construction — the
    data-pass odometer the bench's ``sweep`` section reads to show the
    L → 1 passes-per-iteration amortization.
    """

    def __init__(self, objective: GLMObjective, batch: ChunkedBatch,
                 max_resident: int = 1, prefetch_depth: int = 2):
        self.objective = objective
        self.batch = batch
        self.max_resident = max_resident
        self.prefetch_depth = prefetch_depth
        self.sweeps = 0
        self._cache: dict = {}
        self._active_prefetcher: _ChunkPrefetcher | None = None
        inner = objective.replace(
            reg=RegularizationContext.none(), prior=None)
        self._mesh = batch.mesh
        if self._mesh is not None:
            from photon_ml_tpu.parallel import DistributedGLMObjective

            self._inner = DistributedGLMObjective(
                objective=inner, mesh=self._mesh)
        else:
            self._inner = inner
        # Swept evaluations lane-loop (lax.map) instead of vmapping when
        # the per-chunk program has no batching rule: GRR chunk plans
        # (Pallas kernel) and shard_mapped mesh objectives.  The chunk
        # still streams ONCE either way — the amortization is the
        # transfer, not the read.
        self._lane_map = batch.layout == "grr" or self._mesh is not None

    # -- chunk residency ---------------------------------------------------

    def invalidate(self) -> None:
        """Drop device copies (after ``ChunkedBatch.set_offsets``).

        The prefetch pipeline is quiesced FIRST, and the store must
        prove it (``assert_quiesced``): freeing buffers while the
        background thread is mid device_put on an LRU-windowed chunk
        would be a use-after-evict race."""
        pf = self._active_prefetcher
        if pf is not None:
            pf.close()
            self._active_prefetcher = None
        if self.batch.store is not None:
            self.batch.store.assert_quiesced()
        self._cache.clear()

    def capture_device_cost(self, w: Array) -> None:
        """Explicit device-cost capture of the per-chunk value+gradient
        program against chunk 0 (ISSUE 8).  Bench arms call this right
        after warmup so the capture's AOT relower lands OUTSIDE the
        timed sweeps; the in-sweep capture then finds the name already
        resolved.  2-D ``w`` captures the swept program.  No-op without
        an active telemetry session or with an empty batch."""
        if telemetry.active() is None or self.batch.n_chunks == 0:
            return
        owned = self.batch.owned_chunk_ids
        if not owned:   # all-sentinel fleet host: nothing to capture
            return
        store = self.batch.store
        if store is not None:
            store.begin_read()
        try:
            b = _place_chunk(self.batch.chunk(owned[0]), self._mesh)
        finally:
            if store is not None:
                store.end_read()
        w = jnp.asarray(w, jnp.float32)
        if w.ndim == 2:
            _device.maybe_capture(
                "chunk_vg_swept", _jit_vg_swept,
                (self._inner, w, b, self._lane_map),
                span="chunk_compute")
        else:
            _device.maybe_capture("chunk_vg", _jit_vg,
                                  (self._inner, w, b),
                                  span="chunk_compute")

    def _get(self, i: int):
        if i in self._cache:
            return self._cache[i]
        b = _place_chunk(self.batch.chunk(i), self._mesh)
        if len(self._cache) < self.max_resident:
            self._cache[i] = b
        return b

    def _chunk_stream(self):
        """Device chunks in deterministic order 0..K-1, pipelined.

        Spill-store batches run the three-tier prefetch thread (disk →
        host window → async device_put, ``prefetch_depth`` deep);
        resident batches keep the classic device double-buffer (the
        transfer of chunk i+1 dispatches before chunk i's compute).

        Yields ``(chunk_id, device_chunk)`` in this host's schedule
        order.  Fleet hosts visit only their shard; sentinel steps
        (``fleet.EMPTY_CHUNK`` — ragged-shard padding so every host
        takes the same number of chunk barriers) yield
        ``(EMPTY_CHUNK, None)`` and stream nothing."""
        sched = self.batch.chunk_schedule
        real = [i for i in sched if i >= 0]
        if not sched:
            return
        if self.batch.store is not None and self.prefetch_depth > 0 \
                and real:
            pf = ChunkPrefetcher(
                self.batch.chunk,
                lambda host: _place_chunk(host, self._mesh),
                self.prefetch_depth, store=self.batch.store)
            self._active_prefetcher = pf
            pf.start(real)
            try:
                for i in sched:
                    yield (i, pf.next(i)) if i >= 0 else (i, None)
            finally:
                pf.close()
                self._active_prefetcher = None
            return
        nxt = self._get(real[0]) if real else None
        pos = 0
        for i in sched:
            if i < 0:
                yield i, None
                continue
            cur = nxt
            pos += 1
            if pos < len(real):
                nxt = self._get(real[pos])  # async transfer under compute
            yield i, cur

    def _sweep(self, per_chunk, combine, cost=None, zero=None):
        """Stream this host's chunk schedule through ``per_chunk``,
        pipelined.

        Out-of-core batches add BACKPRESSURE: chunk i-1's accumulate is
        fenced before chunk i dispatches, so the async dispatch queue
        holds one chunk's buffers + temporaries instead of all K —
        without it a K-chunk pass keeps every placed chunk live until
        its compute retires, un-bounding exactly the memory the store
        exists to bound.  On a device backend the chunk programs
        serialize on the accelerator anyway (the prefetch thread keeps
        transfers ahead regardless), so the fence costs a dispatch
        bubble, not overlap.

        ``cost``: optional ``(name, jit_fn, chunk → args)`` device-cost
        capture spec (ISSUE 8) — resolved once per session per name on
        the FIRST chunk, right after its dispatch (the lowering cache is
        then warm, so the capture relowers without a new compile
        record).

        ``zero``: the sentinel partial (``() → same pytree shape as
        ``per_chunk``'s result, all zeros``).  Fleet runs REQUIRE it —
        a host's sentinel steps and all-sentinel hosts contribute exact
        zeros to the per-chunk fleet reduction, so ragged shards never
        skew the barrier count.  Outside a fleet it is never called.

        Fleet runs reduce each chunk partial across hosts (the
        chunk-synchronized barrier) and every host accumulates the
        SAME global totals — solver state stays replicated, so the
        solvers above this line are fleet-oblivious."""
        self.sweeps += 1
        telemetry.count("solver.sweeps")
        fred = _fleet_reducer()
        if fred is not None and zero is None:
            raise ValueError(
                "fleet sweep needs a zero() sentinel template")
        bounded = self.batch.store is not None
        # Per-program dispatch times are only MEANINGFUL on the bounded
        # (spilled) path, where the backpressure fence makes each
        # iteration's wall time cover a chunk's device compute; the
        # resident path dispatches asynchronously (tens of µs observed
        # regardless of program cost), which would make the report's
        # roofline fractions nonsense.
        timed = (cost is not None and bounded
                 and telemetry.active() is not None)
        acc = None
        steps = len(self.batch.chunk_schedule)
        with telemetry.span("sweep", cat="solver",
                            chunks=self.batch.n_chunks):
            for ci, (cid, cur) in enumerate(self._chunk_stream()):
                # The span covers the backpressure fence too: that wait
                # IS the previous chunk's device compute retiring.
                t0 = time.perf_counter() if timed else None
                with telemetry.span("chunk_compute", cat="device"):
                    if bounded and acc is not None:
                        jax.block_until_ready(acc)
                    out = per_chunk(cur) if cid >= 0 else zero()
                # Live chunk progress (ISSUE 10): the monitor derives
                # rolling chunk throughput + a within-sweep ETA; a
                # no-op global read when monitoring is off, throttled
                # to its wall-clock cadence when on.
                _mon.progress("train.sweep", ci + 1, steps,
                              unit="chunks")
                newly_captured = False
                if acc is None and cost is not None and cid >= 0:
                    name, fn, mk_args = cost
                    newly_captured = _device.maybe_capture(
                        name, fn, mk_args(cur), span="chunk_compute")
                if fred is not None:
                    # Chunk barrier: this step's partial summed across
                    # the fleet (each host contributed a DIFFERENT
                    # chunk, or zeros past its ragged shard).
                    out = fred.reduce(out)
                    if cid >= 0:
                        telemetry.count("fleet.chunks_streamed")
                if timed and not newly_captured and cid >= 0:
                    # Per-PROGRAM dispatch histogram: the shared
                    # "chunk_compute" span pools every chunk program's
                    # dispatches, so the device report joins each
                    # captured cost against this name-keyed measure
                    # instead (review finding: a pooled mean overstates
                    # the expensive program and understates the cheap
                    # one whenever a solve runs both).  The capture
                    # chunk — this program's first dispatch, which pays
                    # the XLA compile — is excluded from the measure.
                    telemetry.observe("device.dispatch_s." + cost[0],
                                      time.perf_counter() - t0)
                acc = out if acc is None else combine(acc, out)
        return acc

    # -- TwiceDiffFunction surface (batch owned) ---------------------------

    def value(self, w: Array) -> Array:
        w = jnp.asarray(w, jnp.float32)
        val = self._sweep(lambda b: _jit_val(self._inner, w, b),
                          lambda a, x: a + x,
                          cost=("chunk_value", _jit_val,
                                lambda b: (self._inner, w, b)),
                          zero=lambda: jnp.zeros((), jnp.float32))
        val = val + self.objective.reg.l2_value(w)
        if self.objective.prior is not None:
            val = val + self.objective.prior.value(w)
        return val

    def value_and_gradient(self, w: Array) -> tuple[Array, Array]:
        w = jnp.asarray(w, jnp.float32)
        f, g = self._sweep(
            lambda b: _jit_vg(self._inner, w, b),
            lambda a, x: (a[0] + x[0], a[1] + x[1]),
            cost=("chunk_vg", _jit_vg, lambda b: (self._inner, w, b)),
            zero=lambda: (jnp.zeros((), jnp.float32),
                          jnp.zeros_like(w)))
        reg = self.objective.reg
        f = f + reg.l2_value(w)
        g = g + reg.l2_gradient(w)
        if self.objective.prior is not None:
            f = f + self.objective.prior.value(w)
            g = g + self.objective.prior.gradient(w)
        return f, g

    def gradient(self, w: Array) -> Array:
        return self.value_and_gradient(w)[1]

    def hessian_vector(self, w: Array, v: Array) -> Array:
        w = jnp.asarray(w, jnp.float32)
        v = jnp.asarray(v, jnp.float32)
        # Auxiliary pass (not a line-search evaluation): the report's
        # sweep-odometer reconciliation accounts it separately.
        telemetry.count("solver.aux_sweeps")
        hv = self._sweep(lambda b: _jit_hvp(self._inner, w, v, b),
                         lambda a, x: a + x,
                         zero=lambda: jnp.zeros_like(w))
        hv = hv + self.objective.reg.l2_hessian_vector(v)
        if self.objective.prior is not None:
            hv = hv + self.objective.prior.hessian_vector(v)
        return hv

    def hessian_diagonal(self, w: Array) -> Array:
        w = jnp.asarray(w, jnp.float32)
        telemetry.count("solver.aux_sweeps")
        hd = self._sweep(lambda b: _jit_hd(self._inner, w, b),
                         lambda a, x: a + x,
                         zero=lambda: jnp.zeros_like(w))
        hd = hd + self.objective.reg.l2_hessian_diagonal(w)
        if self.objective.prior is not None:
            hd = hd + self.objective.prior.hessian_diagonal()
        return hd

    def hvp_pass(self, w: Array, v: Array) -> Array:
        """One chunk-accumulated H(w)·v data pass for Steihaug CG
        (ISSUE 17).

        Same math as ``hessian_vector`` — each chunk's J^T D J v
        partial is one module-jitted device program, fleet psum-reduced
        per chunk, with the L2/prior curvature added ONCE outside the
        chunk loop (example-independent, so the pass stays exact) — but
        accounted under ``solver.hvp_sweeps``: CG inner-loop passes are
        the quantity the TRON-vs-L-BFGS comparison is ABOUT, so the
        sweep odometer attributes them to their own bucket instead of
        folding them into ``aux_sweeps`` (variance/diagnostic passes).
        """
        w = jnp.asarray(w, jnp.float32)
        v = jnp.asarray(v, jnp.float32)
        telemetry.count("solver.hvp_sweeps")
        hv = self._sweep(lambda b: _jit_hvp(self._inner, w, v, b),
                         lambda a, x: a + x,
                         cost=("chunk_hvp", _jit_hvp,
                               lambda b: (self._inner, w, v, b)),
                         zero=lambda: jnp.zeros_like(w))
        hv = hv + self.objective.reg.l2_hessian_vector(v)
        if self.objective.prior is not None:
            hv = hv + self.objective.prior.hessian_vector(v)
        return hv

    # -- swept (stacked λ-lane) surface ------------------------------------

    def _lane_reg(self, W: Array, reg: SweptRegularization | None,
                  method: str) -> Array:
        """Per-lane L2/prior term via the named context method —
        [L(, d)].  ``reg`` None applies the objective's own weight to
        every lane."""
        ctx = self.objective.reg
        if reg is None:
            out = jax.vmap(getattr(ctx, method))(W)
        else:
            out = jax.vmap(
                lambda w, l2: getattr(ctx.replace(l2_weight=l2), method)(w)
            )(W, reg.l2_weights)
        return out

    def value_swept(self, W: Array,
                    reg: SweptRegularization | None = None) -> Array:
        """[L, d] stacked lanes → [L] values from ONE chunk sweep."""
        W = jnp.asarray(W, jnp.float32)
        val = self._sweep(
            lambda b: _jit_val_swept(self._inner, W, b, self._lane_map),
            lambda a, x: a + x,
            cost=("chunk_value_swept", _jit_val_swept,
                  lambda b: (self._inner, W, b, self._lane_map)),
            zero=lambda: jnp.zeros((W.shape[0],), jnp.float32))
        val = val + self._lane_reg(W, reg, "l2_value")
        if self.objective.prior is not None:
            val = val + jax.vmap(self.objective.prior.value)(W)
        return val

    def value_and_gradient_swept(
        self, W: Array, reg: SweptRegularization | None = None,
    ) -> tuple[Array, Array]:
        """[L, d] stacked lanes → ([L], [L, d]) from ONE double-buffered
        chunk sweep: the λ grid's L data passes collapse to one, since
        the per-chunk partials are λ-independent and per-lane reg is
        added here, outside the chunk loop."""
        W = jnp.asarray(W, jnp.float32)
        f, g = self._sweep(
            lambda b: _jit_vg_swept(self._inner, W, b, self._lane_map),
            lambda a, x: (a[0] + x[0], a[1] + x[1]),
            cost=("chunk_vg_swept", _jit_vg_swept,
                  lambda b: (self._inner, W, b, self._lane_map)),
            zero=lambda: (jnp.zeros((W.shape[0],), jnp.float32),
                          jnp.zeros_like(W)))
        f = f + self._lane_reg(W, reg, "l2_value")
        g = g + self._lane_reg(W, reg, "l2_gradient")
        if self.objective.prior is not None:
            f = f + jax.vmap(self.objective.prior.value)(W)
            g = g + jax.vmap(self.objective.prior.gradient)(W)
        return f, g

    def _per_example(self, fn) -> np.ndarray:
        """Concatenate a per-chunk per-example quantity over all chunks
        — [n] host array (n·f32 stays bounded; only plans/features were
        too big for residency).  Each chunk's D2H copy is STARTED
        asynchronously as soon as its compute is dispatched, so copies
        overlap the next chunk's compute; the blocking ``np.asarray``
        conversions happen once at the end, when most bytes have
        already landed (a serial per-chunk ``np.asarray`` would fence
        every chunk).  The chunk feed is the same pipelined
        ``_chunk_stream`` the objective sweeps use — spill-store
        batches prefetch disk→host→device here too (scoring sweeps are
        a full data pass like any other)."""
        pending = []
        bounded = self.batch.store is not None
        fred = _fleet_reducer()
        telemetry.count("solver.per_example_passes")
        steps = len(self.batch.chunk_schedule)
        with telemetry.span("per_example_pass", cat="solver",
                            chunks=self.batch.n_chunks):
            for ci, (cid, cur) in enumerate(self._chunk_stream()):
                if cid < 0:   # ragged-shard sentinel: nothing to score
                    _mon.progress("train.pass", ci + 1, steps,
                                  unit="chunks")
                    continue
                with telemetry.span("chunk_compute", cat="device"):
                    if bounded and pending:
                        # Backpressure (see _sweep): chunk i-1's compute
                        # must retire before chunk i dispatches, or
                        # every placed chunk stays live in the dispatch
                        # queue.  Only the [rows]-sized margins are
                        # fenced — their async D2H copies keep
                        # overlapping later chunks' compute.
                        jax.block_until_ready(pending[-1][0])
                    m = fn(cur)
                try:
                    m.copy_to_host_async()
                except AttributeError:  # photon-lint: disable=swallowed-exception (backends without async D2H: the device_get below copies synchronously)
                    pass
                lo, hi = self.batch.chunk_slice(cid)
                pending.append((m, cid, hi - lo))
                _mon.progress("train.pass", ci + 1, steps,
                              unit="chunks")
            if fred is None:
                if not pending:
                    return np.zeros(0, np.float32)
                # device_get, not np.asarray: the harvest is a PLANNED
                # device-to-host copy, and the explicit spelling keeps
                # it allowed under guards.no_implicit_transfers (the
                # async copies above already landed most bytes; this
                # just materializes).
                return np.concatenate(
                    [jax.device_get(m)[:rows] for m, _, rows in pending])
            # Fleet: scatter this host's chunk slices into the full
            # [n] plane and sum across hosts ONCE at the end (each
            # example is owned by exactly one host, so the sum IS the
            # concatenation) — per-example planes take one barrier per
            # pass, not one per chunk.
            full = np.zeros(self.batch.n, np.float32)
            for m, cid, rows in pending:
                lo, _hi = self.batch.chunk_slice(cid)
                full[lo:lo + rows] = jax.device_get(m)[:rows]
            return np.asarray(fred.reduce(full))

    def predict_margins(self, w: Array) -> np.ndarray:
        """Per-example margins (offsets included) over all chunks."""
        w = jnp.asarray(w, jnp.float32)
        return self._per_example(
            lambda b: _jit_margins(self._inner, w, b))

    def x_dot(self, w: Array) -> np.ndarray:
        """Raw X·w per example (offset-free scoring, the GAME
        ``CoordinateDataScores`` convention)."""
        w = jnp.asarray(w, jnp.float32)
        if self._mesh is not None:
            return self._per_example(
                lambda b: _jit_xdot_obj(self._inner, w, b))
        return self._per_example(lambda b: _jit_xdot(w, b))


def _tracker_state(tracker) -> dict:
    """StatesTracker → checkpoint tree (None planes pass through)."""
    return {"values": tracker.values, "grad_norms": tracker.grad_norms,
            "count": tracker.count, "step_sizes": tracker.step_sizes,
            "ls_trials": tracker.ls_trials}


def _restore_tracker(st: dict):
    from photon_ml_tpu.optim.base import StatesTracker

    opt = lambda a: None if a is None else jnp.asarray(a, jnp.float32)
    return StatesTracker(
        values=jnp.asarray(st["values"], jnp.float32),
        grad_norms=jnp.asarray(st["grad_norms"], jnp.float32),
        count=jnp.asarray(st["count"], jnp.int32),
        step_sizes=opt(st.get("step_sizes")),
        ls_trials=opt(st.get("ls_trials")),
    )


def _fleet_seq() -> int:
    """The fleet reducer's reduction counter for checkpoint trees
    (-1 outside a fleet).  A resumed host restores it and REPLAYS its
    reduce sequence — the coordinator answers already-completed
    sequence numbers from its result cache, so the replay fast-forwards
    to the live barrier the rest of the fleet is blocked on."""
    red = _fleet_reducer()
    return -1 if red is None else int(red.seq)


def _restore_fleet_seq(seq) -> None:
    if seq is None or int(seq) < 0:
        return
    red = _fleet_reducer()
    if red is not None:
        red.seq = int(seq)
        telemetry.count("fleet.seq_restored")


def _solver_checkpoint(solver_name: str, label: str):
    """(checkpointer, scoped label) when an active checkpoint session
    has mid-solve cadence enabled, else (None, None) — the solvers'
    one hook into ``reliability.checkpoint`` (ISSUE 9)."""
    ck = _ckpt.active()
    if ck is None or ck.every_solver_iters <= 0:
        return None, None
    name = solver_name + (f":{label}" if label else "")
    return ck, ck.solver_label(name)


def _solver_fingerprint(m: int, *arrays) -> str:
    """Identity stamp for a mid-solve snapshot: the warm start and l1
    weights pin the (objective, position) lineage — a resumed process
    reconstructs both bitwise from the CD/stage checkpoints, while an
    edited config (new λ grid at the same lane count, changed warm
    path) produces different bytes, so a stale snapshot is REJECTED
    instead of silently adopted (review finding: the scope label alone
    cannot tell two configs apart).  ``m`` guards the (s, y) buffer
    geometry."""
    h = hashlib.blake2b(digest_size=16)
    h.update(str(int(m)).encode())
    for a in arrays:
        if a is None:
            h.update(b"|none")
        else:
            arr = np.asarray(a, np.float32)
            h.update(f"|{arr.shape}".encode())
            h.update(arr.tobytes())
    return h.hexdigest()


def streaming_lbfgs_solve(
    value_and_grad,
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    l1_weight=None,
    value_fn=None,
    label: str = "",
) -> OptimizationResult:
    """Host-driven L-BFGS / OWL-QN over an expensive (streamed)
    ``value_and_grad`` — the chunked mirror of ``optim.lbfgs
    .lbfgs_solve`` (same math, same convergence semantics; the outer
    loop is Python because each evaluation swaps chunks through HBM).

    ``value_fn`` (optional, ``w → f``) makes backtracking cheaper: the
    FIRST line-search trial keeps the fused value+gradient pass (the
    steady state accepts α=1, so the common case stays one pass per
    iteration), later trials run value-only passes, and the gradient is
    computed once on the accepted point — every rejected backtrack
    stops paying the gradient half of its pass.
    """
    m = config.lbfgs_memory
    w = jnp.asarray(w0, jnp.float32)
    owlqn = l1_weight is not None
    solver_name = "streaming_owlqn" if owlqn else "streaming_lbfgs"
    l1 = (jnp.broadcast_to(jnp.asarray(l1_weight, w.dtype), w.shape)
          if owlqn else None)

    def l1_term(w_):
        return jnp.sum(l1 * jnp.abs(w_)) if owlqn else 0.0

    def full_value_grad(w_):
        f, g = value_and_grad(w_)
        return f + l1_term(w_), g

    full_value = (None if value_fn is None
                  else (lambda w_: value_fn(w_) + l1_term(w_)))

    def pgrad(g_, w_):
        return _pseudo_gradient(g_, w_, l1) if owlqn else g_

    ck, ck_label = _solver_checkpoint(solver_name, label)
    fp = _solver_fingerprint(m, w, l1) if ck is not None else None
    restored = ck.load_solver(ck_label) if ck is not None else None
    if restored is not None and restored.get("fp") != fp:
        logger.warning(
            "streaming lbfgs '%s': solver snapshot ignored — "
            "objective/warm-start fingerprint mismatch (config changed "
            "since the interrupted run?)", label)
        restored = None
    if restored is not None:
        # Mid-solve resume (ISSUE 9): the loop re-enters at the exact
        # iteration boundary the snapshot captured — committed point,
        # value, gradient, and the full (s, y, ρ) memory — so the
        # continuation is the run the kill interrupted.  The initial
        # fused evaluation is NOT repaid (and not counted: the resumed
        # process never streamed it).
        telemetry.count("solver.resumed_solves")
        w = jnp.asarray(restored["w"], jnp.float32)
        f = jnp.asarray(restored["f"], jnp.float32)
        g = jnp.asarray(restored["g"], jnp.float32)
        pg = pgrad(g, w)
        g0_norm = float(restored["g0_norm"])
        s_hist = [jnp.asarray(s, jnp.float32)
                  for s in restored["s_hist"]]
        y_hist = [jnp.asarray(y, jnp.float32)
                  for y in restored["y_hist"]]
        rho_hist = [float(r) for r in restored["rho_hist"]]
        tracker = _restore_tracker(restored["tracker"])
        converged = bool(restored["converged"])
        it = int(restored["it"])
        _restore_fleet_seq(restored.get("fleet_seq"))
        logger.info("streaming lbfgs '%s': resumed at iteration %d",
                    label, it)
    else:
        # Sweep-odometer accounting (ISSUE 8): the initial fused
        # evaluation below is the one data pass neither an ls_trial nor
        # a recovery counter claims — one tick per solve closes the
        # identity
        #   solver.sweeps == streamed_solves + ls_trials
        #                    + grad_recovery_sweeps + aux_sweeps
        # that `telemetry report` reconciles.
        telemetry.count("solver.streamed_solves")
        f, g = full_value_grad(w)
        pg = pgrad(g, w)
        g0_norm = float(jnp.linalg.norm(pg))
        tracker = StatesTracker.create(config.max_iters)
        if config.track_states:
            tracker = tracker.record(jnp.asarray(0, jnp.int32), f,
                                     jnp.asarray(g0_norm))
        s_hist = []   # newest first
        y_hist = []
        rho_hist = []
        converged = bool(grad_converged(jnp.asarray(g0_norm),
                                        jnp.asarray(g0_norm),
                                        config.tolerance))
        it = 0
    while not converged and it < config.max_iters:
        # Two-loop recursion over the (s, y) history.
        q = pg
        alphas = []
        for s, y, rho in zip(s_hist, y_hist, rho_hist):
            a = rho * jnp.vdot(s, q)
            alphas.append(a)
            q = q - a * y
        if s_hist:
            y_new = y_hist[0]
            gamma = 1.0 / jnp.maximum(
                rho_hist[0] * jnp.vdot(y_new, y_new), _CURVATURE_EPS)
        else:
            gamma = 1.0
        r = gamma * q
        for (s, y, rho), a in zip(reversed(list(zip(s_hist, y_hist,
                                                    rho_hist))),
                                  reversed(alphas)):
            beta = rho * jnp.vdot(y, r)
            r = r + s * (a - beta)
        d = -r
        if owlqn:
            d = jnp.where(d * -pg > 0.0, d, 0.0)
            xi = jnp.where(w != 0.0, jnp.sign(w), jnp.sign(-pg))
        # Steepest-descent safeguard on numerical breakdown.
        if float(jnp.vdot(pg, d)) >= 0.0:
            d = -pg

        # Backtracking Armijo (modified condition under the orthant
        # projection — identical to optim.lbfgs._line_search).
        # Backtracking mirror of optim.lbfgs._line_search: on Armijo
        # accept the trial commits; after ls_max_steps backtracks the
        # LAST trial commits anyway (the resident while_loop exits with
        # it), and in both cases only a STRICT decrease counts as
        # progress (ok = f_new < f0) — a zero-decrease step means
        # progress is below f32 measurement precision and the solve
        # stall-terminates rather than grinds.
        alpha = 1.0
        g_try = None
        trials = 0
        for step in range(config.ls_max_steps + 1):
            # The step the committed trial actually used: on a range
            # exhaustion the loop tail shrinks ``alpha`` AFTER building
            # w_try, so recording ``alpha`` there would understate the
            # terminal stall-edge step by one shrink factor.
            alpha_used = alpha
            w_try = w + alpha * d
            if owlqn:
                w_try = jnp.where(jnp.sign(w_try) == xi, w_try, 0.0)
            telemetry.count("solver.ls_trials")
            trials += 1
            if step == 0 or full_value is None:
                f_try, g_try = full_value_grad(w_try)
            else:
                f_try, g_try = full_value(w_try), None
            if float(f_try) <= float(
                    f + config.ls_c1 * jnp.vdot(pg, w_try - w)):
                break
            alpha *= config.ls_shrink
        if g_try is None and float(f_try) < float(f):
            # Accepted (or committed) a value-only trial that will
            # take effect: one fused pass recovers its gradient.  A
            # stall (no strict decrease — the common terminal
            # iteration) keeps the old state, so its gradient would be
            # discarded work: skip the pass and terminate below.
            telemetry.count("solver.grad_recovery_sweeps")
            f_try, g_try = full_value_grad(w_try)
        elif g_try is None:
            g_try = g   # stalled: state is not committed below
        w_new, f_new, g_new = w_try, f_try, g_try
        ls_ok = float(f_new) < float(f)
        if ls_ok:
            s = w_new - w
            y = g_new - g
            sy = float(jnp.vdot(s, y))
            if sy > _CURVATURE_EPS * float(
                    jnp.linalg.norm(s) * jnp.linalg.norm(y)):
                s_hist.insert(0, s)
                y_hist.insert(0, y)
                rho_hist.insert(0, 1.0 / max(sy, _CURVATURE_EPS))
                del s_hist[m:], y_hist[m:], rho_hist[m:]

        pg_new = pgrad(g_new, w_new)
        g_norm = jnp.linalg.norm(pg_new)
        conv = bool(grad_converged(g_norm, jnp.asarray(g0_norm),
                                   config.tolerance)) or bool(
            loss_converged(f_new, f, config.rel_tolerance))
        stalled = not ls_ok   # no measurable decrease possible
        it += 1
        telemetry.count("solver.iterations")
        if config.track_states:
            tracker = tracker.record(jnp.asarray(it, jnp.int32),
                                     f_new, g_norm,
                                     step_size=jnp.asarray(
                                         alpha_used if ls_ok else 0.0),
                                     ls_trials=jnp.asarray(
                                         float(trials)))
        _conv.iteration(solver_name, label, it, float(f_new),
                        float(g_norm),
                        step_size=(alpha_used if ls_ok else 0.0),
                        ls_trials=trials)
        # Live solver progress (ISSUE 10): iteration count against the
        # budget plus the loss the online divergence rules watch.
        _mon.progress("solver" + (f".{label}" if label else ""),
                      it, config.max_iters, unit="iters",
                      loss=float(f_new), grad_norm=float(g_norm))
        logger.info("streaming lbfgs iter %d: f=%.6f |pg|=%.3e%s", it,
                    float(f_new), float(g_norm),
                    " (stalled)" if stalled else "")
        if ls_ok:
            w, f, g, pg = w_new, f_new, g_new, pg_new
        converged = conv or stalled
        if ck is not None:
            # Iteration-boundary snapshot (cadence-gated): everything
            # the resumed loop needs to continue bit-for-bit.
            ck.maybe_save_solver(ck_label, it, {
                "fp": fp,
                "w": w, "f": f, "g": g, "g0_norm": float(g0_norm),
                "s_hist": list(s_hist), "y_hist": list(y_hist),
                "rho_hist": [float(r) for r in rho_hist],
                "converged": bool(converged),
                "tracker": _tracker_state(tracker),
                "fleet_seq": _fleet_seq(),
            })

    if ck is not None:
        ck.clear_solver(ck_label)   # superseded by the result
    pg_f = pgrad(g, w)
    result = OptimizationResult(
        w=w,
        value=f,
        grad_norm=jnp.linalg.norm(pg_f),
        iterations=jnp.asarray(it, jnp.int32),
        converged=jnp.asarray(converged),
        tracker=tracker,
    )
    _conv.solve_trace(solver_name, label, result)
    return result


def streaming_tron_solve(
    value_and_grad,
    hvp,
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    hessian_diag=None,
    label: str = "",
) -> OptimizationResult:
    """Host-driven trust-region Newton over a chunk-streamed objective
    — the out-of-core mirror of ``optim.tron.tron_solve`` (ISSUE 17).

    Same math as the resident solver (Steihaug CG inside the Lin–Moré
    radius schedule, identical accept/shrink constants), but both loops
    run on the host because every Hessian-vector product is a full
    chunk-streamed data pass: ``hvp(w, v)`` is
    ``ChunkedGLMObjective.hvp_pass`` — one module-jitted per-chunk
    program accumulating J^T D J v partials, fleet psum-reduced per
    chunk, accounted under ``solver.hvp_sweeps``.

    ``hessian_diag`` (optional, ``w → diag H(w)``) enables Jacobi
    preconditioning: one aux pass at the warm start buys the diagonal,
    CG then runs in the scaled space p̂ = D^{1/2} p with the trust
    region measuring ‖p̂‖ (the LIBLINEAR 2.20 convention) — this
    collapses the CG iteration count on badly feature-scaled problems,
    exactly the ill-conditioned regime TRON exists for.  The
    preconditioner is FROZEN for the whole solve (any fixed SPD scaling
    is a valid preconditioner; freshness affects CG speed, never the
    answer) and rides the snapshot tree so resumes stay bitwise.

    The predicted reduction is recovered incrementally from the CG
    residual (prered = ½(p̂ᵀr̂ − ĝᵀp̂), with r̂ kept consistent on the
    boundary exits) — no dedicated H·p̂ pass, so an outer iteration
    costs exactly ``cg_iters`` HVP passes plus one trial evaluation.

    Mid-CG resume (ISSUE 9 semantics): with solver-iteration
    checkpointing enabled a snapshot is cut after every CG step — the
    CG basis vectors (p̂, r̂, d̂, rs), trust radius, and outer (w, f, g)
    all ride the state tree, fingerprinted like the L-BFGS snapshots —
    so a SIGKILL inside the inner loop resumes at the exact HVP
    boundary and reproduces the uninterrupted fit bitwise.
    """
    w = jnp.asarray(w0, jnp.float32)
    solver_name = "streaming_tron"

    ck, ck_label = _solver_checkpoint(solver_name, label)
    fp = (_solver_fingerprint(config.cg_max_iters, w)
          if ck is not None else None)
    restored = ck.load_solver(ck_label) if ck is not None else None
    if restored is not None and restored.get("fp") != fp:
        logger.warning(
            "streaming tron '%s': solver snapshot ignored — "
            "objective/warm-start fingerprint mismatch (config changed "
            "since the interrupted run?)", label)
        restored = None
    cg_state = None
    if restored is not None:
        # Mid-solve resume: the loop re-enters at the exact snapshot
        # boundary — outer point, radius, and (mid-CG) the basis
        # vectors — so the continuation is the run the kill
        # interrupted.  The initial fused evaluation (and the
        # preconditioner pass) are NOT repaid and not counted.
        telemetry.count("solver.resumed_solves")
        w = jnp.asarray(restored["w"], jnp.float32)
        f = jnp.asarray(restored["f"], jnp.float32)
        g = jnp.asarray(restored["g"], jnp.float32)
        delta = float(restored["delta"])
        g0_norm = float(restored["g0_norm"])
        scale = (None if restored.get("scale") is None
                 else jnp.asarray(restored["scale"], jnp.float32))
        tracker = _restore_tracker(restored["tracker"])
        converged = bool(restored["converged"])
        it = int(restored["it"])
        steps = int(restored["steps"])
        cg = restored.get("cg")
        if cg is not None:
            cg_state = (jnp.asarray(cg["p"], jnp.float32),
                        jnp.asarray(cg["r"], jnp.float32),
                        jnp.asarray(cg["d"], jnp.float32),
                        jnp.asarray(cg["rs"], jnp.float32),
                        int(cg["cg_it"]))
        _restore_fleet_seq(restored.get("fleet_seq"))
        logger.info(
            "streaming tron '%s': resumed at iteration %d%s", label, it,
            f" (mid-CG, step {cg_state[4]})" if cg_state else "")
    else:
        # Sweep-odometer accounting (ISSUE 8): the initial fused
        # evaluation is the one pass the streamed_solves tick claims;
        # CG passes ride hvp_sweeps, trial evaluations ride ls_trials,
        # and the preconditioner diagonal rides aux_sweeps — together
        # they close the identity `telemetry report` reconciles.
        telemetry.count("solver.streamed_solves")
        f, g = value_and_grad(w)
        scale = None
        if hessian_diag is not None:
            diag = hessian_diag(w)
            scale = 1.0 / jnp.sqrt(jnp.maximum(
                jnp.asarray(diag, jnp.float32), 1e-12))
        g0_norm = float(jnp.linalg.norm(g))
        delta = float(jnp.linalg.norm(g if scale is None else scale * g))
        tracker = StatesTracker.create(config.max_iters)
        if config.track_states:
            tracker = tracker.record(jnp.asarray(0, jnp.int32), f,
                                     jnp.asarray(g0_norm))
        converged = bool(grad_converged(jnp.asarray(g0_norm),
                                        jnp.asarray(g0_norm),
                                        config.tolerance))
        it = 0
        steps = 0

    def save(cg):
        """Cadence-gated snapshot at the current (outer, CG) boundary.
        ``steps`` counts HVP passes + outer commits, so the configured
        ``every_solver_iters`` cadence lands INSIDE long CG solves."""
        if ck is None:
            return
        ck.maybe_save_solver(ck_label, steps, {
            "fp": fp, "w": w, "f": f, "g": g,
            "delta": float(delta), "g0_norm": float(g0_norm),
            "scale": scale, "it": it, "steps": steps,
            "converged": bool(converged),
            "tracker": _tracker_state(tracker),
            "fleet_seq": _fleet_seq(),
            "cg": cg,
        })

    while not converged and it < config.max_iters:
        g_hat = g if scale is None else scale * g
        tol_cg = config.cg_tolerance * float(jnp.linalg.norm(g_hat))
        if cg_state is not None:
            p, r, d, rs, cg_it = cg_state
            cg_state = None
        else:
            p = jnp.zeros_like(g_hat)
            r = -g_hat
            d = r
            rs = jnp.vdot(r, r)
            cg_it = 0
        # -- Steihaug-CG inner loop: one chunked HVP pass per step ----
        while (cg_it < config.cg_max_iters
               and float(jnp.sqrt(rs)) > tol_cg):
            hd = (hvp(w, d) if scale is None
                  else scale * hvp(w, scale * d))
            dhd = jnp.vdot(d, hd)
            cg_it += 1
            steps += 1
            if float(dhd) <= 0.0:
                # Negative/zero curvature: march to the boundary, and
                # keep the residual consistent (r̂ ← r̂ − τ·Ĥd̂) so the
                # incremental predicted-reduction identity below stays
                # exact without a dedicated H·p̂ pass.
                tau = _boundary_tau(p, d, delta)
                p = p + tau * d
                r = r - tau * hd
                break
            alpha = rs / jnp.maximum(dhd, 1e-30)
            p_try = p + alpha * d
            if float(jnp.linalg.norm(p_try)) >= delta:
                tau = _boundary_tau(p, d, delta)
                p = p + tau * d
                r = r - tau * hd
                break
            p = p_try
            r = r - alpha * hd
            rs_new = jnp.vdot(r, r)
            beta = rs_new / jnp.maximum(rs, 1e-30)
            d = r + beta * d
            rs = rs_new
            save({"p": p, "r": r, "d": d, "rs": rs, "cg_it": cg_it})

        predicted = float(0.5 * (jnp.vdot(p, r) - jnp.vdot(g_hat, p)))
        step = p if scale is None else scale * p
        w_try = w + step
        # Trial-point evaluation: accounted like a line-search trial
        # (accept/reject against the model's predicted reduction).
        telemetry.count("solver.ls_trials")
        f_new, g_new = value_and_grad(w_try)
        f_prev = f
        actual = float(f) - float(f_new)
        rho = actual / max(predicted, 1e-30)
        accept = (rho > _ETA0) and (actual > 0.0)
        p_norm = float(jnp.linalg.norm(p))   # trust-region (scaled) norm
        # Radius update (Lin & Moré simplified schedule, as resident):
        if rho < _SIGMA1:
            delta = min(delta, p_norm) * _SIGMA1
        elif rho > 0.75:
            delta = max(delta, _SIGMA3 * p_norm / 2.0)
        delta = max(delta, _DELTA_MIN)

        if accept:
            w, f, g = w_try, f_new, g_new
        g_norm = float(jnp.linalg.norm(g))
        conv = bool(grad_converged(jnp.asarray(g_norm),
                                   jnp.asarray(g0_norm),
                                   config.tolerance))
        if accept and bool(loss_converged(f_new, f_prev,
                                          config.rel_tolerance)):
            conv = True
        # Numerical-precision stop (mirrors the resident solver): when
        # the model predicts less reduction than f32 can measure on
        # |f|, further iterations only reject steps and shrink Δ.
        if predicted <= 1e-6 * max(abs(float(f_prev)), 1.0):
            conv = True
        stalled = delta <= _DELTA_MIN
        it += 1
        steps += 1
        telemetry.count("solver.iterations")
        if config.track_states:
            tracker = tracker.record(
                jnp.asarray(it, jnp.int32), f, jnp.asarray(g_norm),
                step_size=jnp.asarray(p_norm if accept else 0.0),
                ls_trials=jnp.asarray(float(cg_it)))
        _conv.iteration(solver_name, label, it, float(f), g_norm,
                        step_size=(p_norm if accept else 0.0),
                        ls_trials=cg_it, delta=delta, rho=rho)
        # Live solver progress (ISSUE 10): the `train.tron` monitor
        # stage — iteration count against the budget plus the loss the
        # online divergence rules watch.
        _mon.progress("train.tron" + (f".{label}" if label else ""),
                      it, config.max_iters, unit="iters",
                      loss=float(f), grad_norm=g_norm)
        logger.info(
            "streaming tron iter %d: f=%.6f |g|=%.3e delta=%.3e "
            "rho=%.3f cg=%d%s", it, float(f), g_norm, delta, rho,
            cg_it, "" if accept else " (rejected)")
        converged = conv
        save(None)
        if stalled:
            break

    if ck is not None:
        ck.clear_solver(ck_label)   # superseded by the result
    result = OptimizationResult(
        w=w,
        value=f,
        grad_norm=jnp.linalg.norm(g),
        iterations=jnp.asarray(it, jnp.int32),
        converged=jnp.asarray(converged),
        tracker=tracker,
    )
    _conv.solve_trace(solver_name, label, result)
    return result


def streaming_lbfgs_solve_swept(
    value_and_grad_swept,
    value_swept,
    w0s: Array,
    config: OptimizerConfig = OptimizerConfig(),
    l1_weights=None,
    label: str = "",
) -> OptimizationResult:
    """Host-driven batched-lane L-BFGS / OWL-QN: the whole λ grid as
    ONE streamed solve.

    The chunked mirror of ``optim.lbfgs.lbfgs_solve_swept``: all
    per-lane state (coefficients, (s, y) circular buffers, line-search
    step sizes, convergence flags) carries a leading lane axis L and
    every update is masked per lane, so converged lanes coast while
    stragglers finish — and EVERY objective evaluation is one shared
    chunk sweep feeding all L lanes (``value_and_grad_swept``:
    ``W [L, d] → (F [L], G [L, d])`` including per-lane smooth reg).
    Data passes per solver iteration drop from L (sequential fits) to
    ~1: one fused value+gradient sweep when every searching lane
    accepts α=1 (the steady state), plus one shared value-only sweep
    per extra backtracking trial (``value_swept``) and one gradient
    recovery sweep on iterations where some lane accepted late.

    ``l1_weights``: None, [L] per-lane scalars, or [L, d] per-lane
    vectors — any non-None activates OWL-QN semantics on every lane.

    Returns a batched ``OptimizationResult`` (leading dim L), like a
    vmapped resident solve.
    """
    m = config.lbfgs_memory
    W = jnp.asarray(w0s, jnp.float32)
    L, d = W.shape
    owlqn = l1_weights is not None
    solver_name = ("streaming_owlqn_swept" if owlqn
                   else "streaming_lbfgs_swept")
    if owlqn:
        l1 = jnp.asarray(l1_weights, W.dtype)
        l1 = jnp.broadcast_to(l1.reshape(L, -1), (L, d))

    def l1_term(W_):
        return jnp.sum(l1 * jnp.abs(W_), axis=-1) if owlqn else 0.0

    def full_vg(W_):
        F_, G_ = value_and_grad_swept(W_)
        return F_ + l1_term(W_), G_

    def full_val(W_):
        return value_swept(W_) + l1_term(W_)

    def pgrad(G_, W_):
        return _pseudo_gradient(G_, W_, l1) if owlqn else G_

    ck, ck_label = _solver_checkpoint(solver_name, label)
    fp = (_solver_fingerprint(m, W, l1 if owlqn else None)
          if ck is not None else None)
    restored = ck.load_solver(ck_label) if ck is not None else None
    if restored is not None and restored.get("fp") != fp:
        logger.warning(
            "streaming swept lbfgs '%s': solver snapshot ignored — "
            "objective/warm-start fingerprint mismatch (λ grid or "
            "warm path changed since the interrupted run?)", label)
        restored = None
    if restored is not None:
        # Mid-solve resume of the whole masked-lane state (ISSUE 9):
        # λ-sweep lane coefficients, per-lane (s, y, ρ) circular
        # buffers, convergence masks, tracker planes.
        telemetry.count("solver.resumed_solves")
        W = jnp.asarray(restored["W"], jnp.float32)
        F = jnp.asarray(restored["F"], jnp.float32)
        G = jnp.asarray(restored["G"], jnp.float32)
        g0_norm = jnp.asarray(restored["g0_norm"], jnp.float32)
        done = jnp.asarray(restored["done"], bool)
        converged = jnp.asarray(restored["converged"], bool)
        iters = jnp.asarray(restored["iters"], jnp.int32)
        S_buf = jnp.asarray(restored["S_buf"], W.dtype)
        Y_buf = jnp.asarray(restored["Y_buf"], W.dtype)
        Rho = jnp.asarray(restored["Rho"], W.dtype)
        head = jnp.asarray(restored["head"], jnp.int32)
        count = jnp.asarray(restored["count"], jnp.int32)
        t_vals = jnp.asarray(restored["t_vals"], jnp.float32)
        t_gn = jnp.asarray(restored["t_gn"], jnp.float32)
        it = int(restored["it"])
        _restore_fleet_seq(restored.get("fleet_seq"))
        logger.info("streaming swept lbfgs '%s': resumed at iteration "
                    "%d (%d/%d lanes done)", label, it,
                    int(jnp.sum(done)), L)
    else:
        # One tick per solve for the initial fused sweep — see the
        # odometer identity note in streaming_lbfgs_solve.
        telemetry.count("solver.streamed_solves")
        F, G = full_vg(W)
        PG = pgrad(G, W)
        g0_norm = jnp.linalg.norm(PG, axis=-1)                    # [L]
        done = grad_converged(g0_norm, g0_norm, config.tolerance)  # [L]
        converged = done
        iters = jnp.zeros((L,), jnp.int32)

        S_buf = jnp.zeros((m, L, d), W.dtype)
        Y_buf = jnp.zeros((m, L, d), W.dtype)
        Rho = jnp.zeros((m, L), W.dtype)
        head = jnp.zeros((L,), jnp.int32)
        count = jnp.zeros((L,), jnp.int32)

        t_vals = jnp.full((L, config.max_iters + 1), jnp.nan,
                          jnp.float32)
        t_gn = jnp.full((L, config.max_iters + 1), jnp.nan, jnp.float32)
        if config.track_states:
            t_vals = t_vals.at[:, 0].set(F)
            t_gn = t_gn.at[:, 0].set(g0_norm)

        it = 0
    while not bool(jnp.all(done)) and it < config.max_iters:
        active = jnp.logical_not(done)
        PG = pgrad(G, W)

        # Per-lane two-loop recursion + OWL-QN projections, one
        # dispatch (module-level jit).
        D, Xi = _swept_direction(PG, W, S_buf, Y_buf, Rho, head, count,
                                 l1 if owlqn else None)

        def project(W_try):
            if not owlqn:
                return W_try
            return jnp.where(jnp.sign(W_try) == Xi, W_try, 0.0)

        def armijo(W_t, F_t):
            return F_t <= F + config.ls_c1 * jnp.sum(
                PG * (W_t - W), axis=-1)

        # Batched backtracking: one SHARED sweep per trial serves every
        # still-searching lane.  Trial 0 is the fused value+gradient
        # sweep (steady state: all lanes accept α=1 → one pass per
        # iteration for the whole grid); later trials are value-only.
        alpha = jnp.ones((L,), W.dtype)
        W_try = project(W + alpha[:, None] * D)
        telemetry.count("solver.ls_trials")
        trials = 1
        F1, G1 = full_vg(W_try)
        ok = armijo(W_try, F1)
        accepted = ok | done
        commit0 = ok & active
        W_acc = jnp.where(commit0[:, None], W_try, W)
        F_acc = jnp.where(commit0, F1, F)
        G_acc = jnp.where(commit0[:, None], G1, G)
        grad_known = accepted          # lanes whose G_acc is current
        W_last, F_last = W_try, F1
        for _ in range(config.ls_max_steps):
            if bool(jnp.all(accepted)):
                break
            alpha = jnp.where(accepted, alpha, alpha * config.ls_shrink)
            W_try = project(W + alpha[:, None] * D)
            # Accepted lanes re-evaluate at their committed point (the
            # sweep is shared; their rows are simply ignored).
            W_eval = jnp.where(accepted[:, None], W_acc, W_try)
            telemetry.count("solver.ls_trials")
            trials += 1
            F_eval = full_val(W_eval)
            ok = armijo(W_eval, F_eval) & jnp.logical_not(accepted)
            W_acc = jnp.where(ok[:, None], W_try, W_acc)
            F_acc = jnp.where(ok, F_eval, F_acc)
            accepted = accepted | ok
            still = jnp.logical_not(accepted)
            W_last = jnp.where(still[:, None], W_try, W_last)
            F_last = jnp.where(still, F_eval, F_last)
        # Never-accepted lanes commit the LAST trial (resident
        # semantics); only a strict decrease counts as progress below.
        hold = accepted | jnp.logical_not(active)
        W_new = jnp.where(hold[:, None], W_acc, W_last)
        F_new = jnp.where(hold, F_acc, F_last)
        # Gradient recovery is only owed to lanes that BOTH moved past
        # trial 0 and will actually commit (strict decrease) — a lane
        # that exhausted its backtracks without progress stalls and
        # keeps its old state, so paying a sweep for its gradient would
        # be discarded work (stall iterations are common right at each
        # lane's convergence edge).
        need_grad = (jnp.logical_not(grad_known | done)
                     & (F_new < F) & active)
        if bool(jnp.any(need_grad)):
            # One shared sweep recovers every lane's gradient at its
            # committed point.
            telemetry.count("solver.grad_recovery_sweeps")
            F_new, G_new = full_vg(W_new)
        else:
            G_new = G_acc

        ls_ok = (F_new < F) & active
        s = W_new - W
        y = G_new - G
        sy = jnp.sum(s * y, axis=-1)
        good = ls_ok & (
            sy > _CURVATURE_EPS * jnp.linalg.norm(s, axis=-1)
            * jnp.linalg.norm(y, axis=-1))
        S_buf, Y_buf, Rho, head, count = _swept_push(
            S_buf, Y_buf, Rho, head, count, s, y, good)

        PG_new = pgrad(G_new, W_new)
        g_norm = jnp.linalg.norm(PG_new, axis=-1)
        conv = jnp.logical_or(
            grad_converged(g_norm, g0_norm, config.tolerance),
            loss_converged(F_new, F, config.rel_tolerance),
        )
        stalled = jnp.logical_not(ls_ok) & active
        it += 1
        telemetry.count("solver.iterations")
        iters = jnp.where(active, it, iters)
        if config.track_states:
            t_vals = t_vals.at[:, it].set(
                jnp.where(active, F_new, t_vals[:, it]))
            t_gn = t_gn.at[:, it].set(
                jnp.where(active, g_norm, t_gn[:, it]))
        # Commit per lane: line-search progress updates state; stalled
        # lanes keep theirs (and terminate, as in the resident solver).
        W = jnp.where(ls_ok[:, None], W_new, W)
        F = jnp.where(ls_ok, F_new, F)
        G = jnp.where(ls_ok[:, None], G_new, G)
        finished = active & (conv | stalled)
        converged = converged | finished
        done = done | finished
        _conv.iteration(solver_name, label, it, F, g_norm,
                        ls_trials=trials,
                        lanes_active=int(jnp.sum(active)),
                        lanes_done=int(jnp.sum(done)))
        _mon.progress("solver" + (f".{label}" if label else ""),
                      it, config.max_iters, unit="iters",
                      loss=float(jnp.min(F)),
                      lanes_done=int(jnp.sum(done)), lanes=L)
        logger.info(
            "streaming swept lbfgs iter %d: %d/%d lanes done, "
            "f_best=%.6f", it, int(jnp.sum(done)), L,
            float(jnp.min(F)))
        if ck is not None:
            ck.maybe_save_solver(ck_label, it, {
                "fp": fp,
                "W": W, "F": F, "G": G, "g0_norm": g0_norm,
                "done": done, "converged": converged, "iters": iters,
                "S_buf": S_buf, "Y_buf": Y_buf, "Rho": Rho,
                "head": head, "count": count,
                "t_vals": t_vals, "t_gn": t_gn,
                "fleet_seq": _fleet_seq(),
            })

    if ck is not None:
        ck.clear_solver(ck_label)   # superseded by the result
    PG_f = pgrad(G, W)
    tracker = StatesTracker(
        values=t_vals, grad_norms=t_gn,
        count=(iters + 1 if config.track_states
               else jnp.zeros((L,), jnp.int32)),
    )
    result = OptimizationResult(
        w=W,
        value=F,
        grad_norm=jnp.linalg.norm(PG_f, axis=-1),
        iterations=iters,
        converged=converged,
        tracker=tracker,
    )
    _conv.solve_trace(solver_name, label, result)
    return result
